# tpud container image (reference: Dockerfile:1-40 — multi-arch runtime
# image; the CUDA base becomes a slim Python base since the TPU runtime
# needs no userspace driver stack in the monitoring container).
FROM python:3.12-slim

# monitoring tools used by components (lspci, lsmod equivalents)
RUN apt-get update \
    && apt-get install -y --no-install-recommends pciutils kmod curl \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /opt/tpud
COPY pyproject.toml README.md ./
COPY gpud_tpu ./gpud_tpu
RUN pip install --no-cache-dir .

# state under a hostPath mount in k8s (see deployments/helm)
ENV TPUD_DATA_DIR=/var/lib/tpud
VOLUME ["/var/lib/tpud"]

EXPOSE 15132
ENTRYPOINT ["python", "-m", "gpud_tpu"]
CMD ["run"]
