"""Lightweight line coverage built on ``sys.monitoring`` (PEP 669).

The sandbox image ships no ``coverage`` package, so this module provides
the minimal subset the test pyramid needs: which executable lines of
``gpud_tpu`` ran during a test session. It mirrors the role of the
reference's ``go test -cover`` CI step (reference: .github/workflows —
coverage gates on pkg/), implemented the CPython-3.12 way: LINE events
are disabled per-location after the first hit, so steady-state overhead
is near zero.

Usage (standalone)::

    python -m gpud_tpu.tools.cov report cov.json         # summary table
    python -m gpud_tpu.tools.cov report cov.json -m gpud_tpu/cli.py

or via the pytest hook in tests/conftest.py: ``TPUD_COV=out.json pytest``.
"""

from __future__ import annotations

import io
import json
import os
import sys
from dataclasses import dataclass, field

_TOOL_ID = sys.monitoring.COVERAGE_ID


class LineCollector:
    """Records the first execution of each (file, line) under ``root``."""

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root) + os.sep
        self.hits: dict[str, set[int]] = {}
        self._active = False

    # -- sys.monitoring plumbing ------------------------------------------
    def _on_line(self, code, lineno):  # noqa: ANN001 - monitoring signature
        fname = code.co_filename
        if fname.startswith(self.root) and not fname.endswith(
            os.sep + "cov.py"
        ):
            self.hits.setdefault(fname, set()).add(lineno)
        # one hit per location is all coverage needs; disabling keeps the
        # interpreter at full speed afterwards
        return sys.monitoring.DISABLE

    def start(self) -> None:
        if self._active:
            return
        owner = sys.monitoring.get_tool(_TOOL_ID)
        if owner == "tpud-cov":
            # another collector in this process already owns the id (e.g. a
            # conftest imported twice under two module names) — defer to it
            return
        if owner is not None:
            # a foreign profiler/debugger owns COVERAGE_ID: degrade to
            # no-coverage rather than crashing the host process
            sys.stderr.write(
                f"tpud-cov: tool id owned by {owner!r}; coverage disabled\n"
            )
            return
        sys.monitoring.use_tool_id(_TOOL_ID, "tpud-cov")
        sys.monitoring.register_callback(
            _TOOL_ID, sys.monitoring.events.LINE, self._on_line
        )
        sys.monitoring.set_events(_TOOL_ID, sys.monitoring.events.LINE)
        self._active = True

    def stop(self) -> None:
        if not self._active:
            return
        sys.monitoring.set_events(_TOOL_ID, sys.monitoring.events.NO_EVENTS)
        sys.monitoring.register_callback(
            _TOOL_ID, sys.monitoring.events.LINE, None
        )
        sys.monitoring.free_tool_id(_TOOL_ID)
        self._active = False

    def dump(self, path: str) -> None:
        data = {f: sorted(lines) for f, lines in sorted(self.hits.items())}
        with open(path, "w", encoding="utf-8") as fh:
            json.dump({"root": self.root, "hits": data}, fh)


# -- static side: which lines COULD run -----------------------------------

def executable_lines(path: str) -> set[int]:
    """All line numbers that carry bytecode in ``path`` (incl. nested
    functions/classes), via recursive ``co_lines`` walk."""
    with open(path, "rb") as fh:
        src = fh.read()
    try:
        top = compile(src, path, "exec")
    except SyntaxError:
        return set()
    lines: set[int] = set()
    stack = [top]
    while stack:
        code = stack.pop()
        for _start, _end, lineno in code.co_lines():
            if lineno is not None:
                lines.add(lineno)
        for const in code.co_consts:
            if isinstance(const, type(top)):
                stack.append(const)
    return lines


def _is_noise_line(text: str) -> bool:
    t = text.strip()
    # co_lines marks def/class headers and bare string (docstring) lines as
    # executable; a module whose functions never ran still "covers" them.
    # Keep them — they are executable — but drop obvious non-statements.
    return t == "" or t.startswith("#")


@dataclass
class FileReport:
    path: str
    total: int
    hit: int
    missing: list[int] = field(default_factory=list)

    @property
    def pct(self) -> float:
        return 100.0 * self.hit / self.total if self.total else 100.0


def build_report(cov_json: str) -> list[FileReport]:
    with open(cov_json, encoding="utf-8") as fh:
        data = json.load(fh)
    root = data["root"]
    hits = {f: set(v) for f, v in data["hits"].items()}

    reports: list[FileReport] = []
    for dirpath, _dirs, files in os.walk(root.rstrip(os.sep)):
        if "__pycache__" in dirpath:
            continue
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            exe = executable_lines(path)
            if not exe:
                continue
            try:
                with open(path, encoding="utf-8") as fh:
                    srclines = fh.readlines()
            except OSError:
                srclines = []
            exe = {
                n
                for n in exe
                if 1 <= n <= len(srclines)
                and not _is_noise_line(srclines[n - 1])
            }
            got = hits.get(path, set()) & exe
            miss = sorted(exe - got)
            reports.append(FileReport(path, len(exe), len(got), miss))
    reports.sort(key=lambda r: (r.pct, -(r.total - r.hit)))
    return reports


def _ranges(nums: list[int]) -> str:
    if not nums:
        return ""
    out, start, prev = [], nums[0], nums[0]
    for n in nums[1:]:
        if n == prev + 1:
            prev = n
            continue
        out.append(f"{start}-{prev}" if prev > start else str(start))
        start = prev = n
    out.append(f"{start}-{prev}" if prev > start else str(start))
    return ",".join(out)


def format_report(
    reports: list[FileReport], *, show_missing_for: str | None = None
) -> str:
    buf = io.StringIO()
    tot = hit = 0
    for r in reports:
        tot += r.total
        hit += r.hit
        rel = os.path.relpath(r.path)
        buf.write(f"{r.pct:6.1f}%  {r.hit:5d}/{r.total:<5d} {rel}\n")
        if show_missing_for and show_missing_for in rel:
            buf.write(f"         missing: {_ranges(r.missing)}\n")
    pct = 100.0 * hit / tot if tot else 100.0
    buf.write(f"{pct:6.1f}%  {hit:5d}/{tot:<5d} TOTAL\n")
    return buf.getvalue()


def main(argv: list[str]) -> int:
    if len(argv) >= 2 and argv[0] == "report":
        show = None
        if "-m" in argv:
            show = argv[argv.index("-m") + 1]
        reports = build_report(argv[1])
        sys.stdout.write(format_report(reports, show_missing_for=show))
        return 0
    sys.stderr.write(__doc__ or "")
    return 2


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
