"""Lock-order deadlock detection — the Python analog of `go test -race`.

The reference runs its whole suite under the Go race detector
(reference: scripts/tests-unit.sh:26-33). CPython's GIL hides data races
but NOT deadlocks: inconsistent lock acquisition order across threads is
the daemon's realistic concurrency hazard. This module instruments lock
creation so a stress run produces:

- the **lock-order graph**: edge A→B when a thread acquired B while
  holding A (with the first acquisition site per edge). A cycle in this
  graph is a potential deadlock even if the run never interleaved badly.
- **self-deadlock** reports: a thread blocking on a non-reentrant lock it
  already holds — a certain deadlock, raised immediately as
  :class:`DeadlockError` instead of hanging the test.

Usage (tests)::

    det = LockOrderDetector()
    with det.installed():          # patches threading.Lock/RLock
        ... exercise the daemon ...
    assert det.cycles() == []

Only locks *created* while installed are tracked; overhead per acquire is
one thread-local list append.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple


class DeadlockError(RuntimeError):
    """A thread blocked on a non-reentrant lock it already holds.

    ``held`` lists the thread's lock stack (oldest first) at the moment
    of the fatal acquire, so the traceback alone answers "holding what?"
    without a debugger attached to a hung test.
    """

    def __init__(self, msg: str, held: Optional[List[str]] = None):
        super().__init__(msg)
        self.held: List[str] = list(held or [])


class _Held(threading.local):
    def __init__(self) -> None:
        self.stack: List["_LockProxy"] = []


class _LockProxy:
    """Wraps a real lock; reports acquire ordering to the detector.

    Delegates everything else (``_is_owned``, ``_release_save``, ...) so
    ``threading.Condition`` keeps working over wrapped (R)Locks.
    """

    __slots__ = ("_lock", "_det", "name", "_reentrant")

    def __init__(self, lock, det: "LockOrderDetector", name: str, reentrant: bool):
        self._lock = lock
        self._det = det
        self.name = name
        self._reentrant = reentrant

    # -- instrumented interface -------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        self._det._before_acquire(self, blocking)
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._det._after_acquire(self)
        return got

    def release(self) -> None:
        self._det._on_release(self)
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()

    # -- threading.Condition protocol -------------------------------------
    # Condition.wait() drops the lock via these instead of release(); the
    # held-stack must mirror that or waits would fabricate order edges.
    def _release_save(self):
        stack = self._det._held.stack
        depth = 0
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                depth += 1
        if hasattr(self._lock, "_release_save"):
            inner = self._lock._release_save()
        else:
            self._lock.release()
            inner = None
        return (inner, depth)

    def _acquire_restore(self, saved):
        inner, depth = saved
        if hasattr(self._lock, "_acquire_restore"):
            self._lock._acquire_restore(inner)
        else:
            self._lock.acquire()
        self._det._held.stack.extend([self] * depth)

    def _is_owned(self):
        if hasattr(self._lock, "_is_owned"):
            return self._lock._is_owned()
        # plain-Lock emulation (what Condition itself does when the lock
        # has no _is_owned)
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True

    def __getattr__(self, item):
        return getattr(self._lock, item)

    def __repr__(self) -> str:
        return f"<tracked {self.name} {self._lock!r}>"


def _creation_site(depth: int = 3) -> str:
    import sys

    try:
        f = sys._getframe(depth)
        return f"{f.f_code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno}"
    except Exception:  # noqa: BLE001
        return "?"


class LockOrderDetector:
    def __init__(self) -> None:
        # edge (held_name, acquired_name) → site string of first sighting
        self.edges: Dict[Tuple[str, str], str] = {}
        self.self_deadlocks: List[str] = []
        self._held = _Held()
        self._elock = threading.Lock()  # guards edges (a plain dict)
        self._installed = False
        self._orig: Optional[tuple] = None
        self._real_lock = threading.Lock
        self._real_rlock = threading.RLock
        self._wrapped_attrs: List[tuple] = []
        # raise immediately on certain deadlock (tests may disable to
        # collect everything first)
        self.raise_on_self_deadlock = True

    # -- proxy callbacks ---------------------------------------------------
    def _before_acquire(self, proxy: _LockProxy, blocking: bool) -> None:
        stack = self._held.stack
        if blocking and not proxy._reentrant and any(p is proxy for p in stack):
            site = _creation_site(depth=4)
            held = [p.name for p in stack]
            msg = (
                f"self-deadlock: {proxy.name} re-acquired at {site} "
                f"(held stack: {' -> '.join(held)})"
            )
            with self._elock:
                self.self_deadlocks.append(msg)
            if self.raise_on_self_deadlock:
                raise DeadlockError(msg, held=held)
        for held in stack:
            if held is proxy:
                continue
            key = (held.name, proxy.name)
            if key not in self.edges:
                with self._elock:
                    self.edges.setdefault(key, _creation_site(depth=4))

    def _after_acquire(self, proxy: _LockProxy) -> None:
        self._held.stack.append(proxy)

    def _on_release(self, proxy: _LockProxy) -> None:
        stack = self._held.stack
        # release in any order: remove the LAST occurrence (RLocks appear
        # once per recursion level)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is proxy:
                del stack[i]
                break

    # -- installation ------------------------------------------------------
    def make_lock(self):
        return _LockProxy(
            self._real_lock(), self, f"Lock@{_creation_site(2)}", reentrant=False
        )

    def make_rlock(self):
        return _LockProxy(
            self._real_rlock(), self, f"RLock@{_creation_site(2)}", reentrant=True
        )

    def wrap_attr(self, obj, attr: str, name: str = "", reentrant: bool = False):
        """Replace an EXISTING lock attribute (e.g. a module-global created
        before install()) with a tracked proxy. Only safe while the lock is
        not concurrently held; returns the proxy."""
        lock = getattr(obj, attr)
        if isinstance(lock, _LockProxy):
            return lock
        proxy = _LockProxy(
            lock, self, name or f"{type(obj).__name__}.{attr}", reentrant
        )
        setattr(obj, attr, proxy)
        self._wrapped_attrs.append((obj, attr, lock))
        return proxy

    def unwrap_all(self) -> None:
        """Restore every wrap_attr replacement (call when done — a proxy
        left on a module global keeps feeding a dead detector)."""
        for obj, attr, lock in reversed(self._wrapped_attrs):
            setattr(obj, attr, lock)
        self._wrapped_attrs.clear()

    def install(self) -> None:
        """Patch threading.Lock/RLock so locks created from now on are
        tracked. Locks that already exist keep their real type."""
        if self._installed:
            return
        # capture current factories (they may already be another
        # detector's proxies in nested-instrument scenarios)
        self._real_lock = threading.Lock
        self._real_rlock = threading.RLock
        self._orig = (threading.Lock, threading.RLock)
        threading.Lock = self.make_lock  # type: ignore[assignment]
        threading.RLock = self.make_rlock  # type: ignore[assignment]
        self._installed = True

    def uninstall(self) -> None:
        if not self._installed:
            return
        threading.Lock, threading.RLock = self._orig  # type: ignore[misc]
        self._installed = False

    def installed(self):
        from contextlib import contextmanager

        @contextmanager
        def cm():
            self.install()
            try:
                yield self
            finally:
                self.uninstall()

        return cm()

    # -- analysis ----------------------------------------------------------
    def cycles(self) -> List[List[str]]:
        """Strongly-connected components of the lock-order graph with more
        than one lock — each is a potential deadlock. Tarjan (iterative,
        linear) — no size cap, so the acyclicity guarantee is total.
        Smallest first."""
        graph: Dict[str, Set[str]] = {}
        nodes: Set[str] = set()
        for a, b in self.edges:
            graph.setdefault(a, set()).add(b)
            nodes.add(a)
            nodes.add(b)

        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        counter = [0]
        out: List[List[str]] = []

        def strongconnect(root: str) -> None:
            work: List[tuple] = [(root, iter(sorted(graph.get(root, ()))))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(sorted(graph.get(w, ())))))
                        advanced = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    comp: List[str] = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1:
                        out.append(sorted(comp))

        for v in sorted(nodes):
            if v not in index:
                strongconnect(v)
        out.sort(key=len)
        return out

    def report(self, edges: bool = True) -> str:
        """Human-readable run summary: every observed order edge with the
        ``file:line`` where it was first acquired, cycles (if any) with
        their member edges, and self-deadlock sightings with held stacks.
        Pass ``edges=False`` to print only the problems."""
        lines = [f"{len(self.edges)} lock-order edges observed"]
        if edges:
            for (a, b), site in sorted(self.edges.items()):
                lines.append(f"  {a} -> {b} (first acquired at {site})")
        for cyc in self.cycles():
            # an SCC is a set, not a path — listing it with arrows would
            # imply acquisition edges that may not exist
            lines.append("CYCLE among locks: {" + ", ".join(cyc) + "}")
            members = set(cyc)
            for (a, b), site in sorted(self.edges.items()):
                if a in members and b in members:
                    lines.append(f"  edge {a} -> {b} (first seen at {site})")
        lines.extend(self.self_deadlocks)
        return "\n".join(lines)
