"""Schema lint: the wire and journal formats are frozen in a golden.

Every byte format two processes (or two *builds*, across a rolling
fleet upgrade) must agree on is pinned in
``gpud_tpu/tools/goldens/wire_schema.json``:

- the rev-3 codec prefixes and :func:`wire.decode_payload` behavior,
  proved against frozen hex wire samples (a ``j``/``z``/``m``/``M``
  payload captured when the format shipped must decode to the same
  object forever);
- :class:`wire.DeltaEncoder` output for a fixed record sequence — the
  len-6 keyframe and len-7 delta positional arrays, the
  ``kind:component`` stream keys, the non-dict payload case — plus the
  decoder round-trip;
- the ``outbox_batch`` frame shape (``BATCH_KEY``/``BATCH_VERSION``/
  ``first_seq``/``last_seq``/``count``/``records``);
- the v2 Frame revisions: ``MAX_REVISION``, the rev-2 bare-JSON
  ``Result.payload_json`` bytes, the rev-3 prefix-framed round-trip,
  and the :func:`typed.negotiate_revision` table;
- the journal / session-outbox / fleet-replica SQLite row schemas
  (table name + ordered column list, parsed from the ``CREATE TABLE``
  source so no database is touched);
- the versioned predict payloads: ``PREDICT_SCHEMA`` /
  ``PREDICT_SCHEMA_MAX`` and the key sets of every payload dict in
  ``predict/engine.py`` that stamps ``"schema": PREDICT_SCHEMA``.

Any drift — a renamed column, a reordered record field, a new key in a
versioned payload, a changed negotiation result — fails lint until the
golden is regenerated with ``python -m gpud_tpu.tools.lint_all
--update-goldens``, which bumps ``golden_version``. The bump is the
point: it forces the diff (and the compatibility story for agents one
build behind) into review instead of letting the format drift under a
green suite whose encoder and decoder drifted together.

msgpack-framed probes are checked only when msgpack is importable (the
container bakes it in; slim installs degrade to JSON framing) — the
golden carries them unconditionally so a full build always checks the
full surface.

Run: ``python -m gpud_tpu.tools.schema_lint``; registered in
``tools/lint_all.py`` so tier-1 enforces it.
"""

from __future__ import annotations

import ast
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

from gpud_tpu.tools.guard_lint import _repo_root

GOLDEN_REL = "gpud_tpu/tools/goldens/wire_schema.json"

# -- frozen probe inputs -----------------------------------------------------
# These hex strings are *inputs* captured when the format shipped; they
# are never regenerated. decode_payload must understand them forever
# (zlib.decompress and msgpack decoding are stable; only our framing
# could break them).
DECODE_PROBES: Dict[str, str] = {
    "json": "6a7b2261223a312c2262223a5b312c322c335d2c22636f6d706f6e656e74223a"
            "2274707530227d",
    "zlib_json": "7a7801ab564acecf2dc8cf4bcd2b51b2522a29283550d2512a49ad00f1"
                 "1293925346320686449e9295792d00927c6d99",
    "msgpack": "6d83a16101a16293010203a9636f6d706f6e656e74a474707530",
    "zlib_msgpack": "4d78016b5e999c9f5b909f979a57b2a4a4a0d46049496a45c92d46"
                    "86c4a4e494918c17e6b10300b89a6e07",
}
_MSGPACK_ONLY = ("msgpack", "zlib_msgpack")

# fixed record sequence for the delta codec: with keyframe_interval=3
# it exercises keyframe, field-change delta, key-removal delta, the
# interval rollover back to a keyframe, a second interleaved stream,
# and the non-dict payload shape
DELTA_INPUT: List[Tuple[int, float, str, str, object]] = [
    (1, 10.5, "health", "h:tpu0:1", {"component": "tpu0", "health": "ok",
                                     "reason": "boot"}),
    (2, 11.5, "health", "h:tpu0:2", {"component": "tpu0", "health": "bad",
                                     "reason": "boot"}),
    (3, 12.5, "health", "h:tpu0:3", {"component": "tpu0", "health": "bad"}),
    (4, 13.5, "metric", "m:tpu1:1", {"component": "tpu1", "v": 1}),
    (5, 14.5, "health", "h:tpu0:4", {"component": "tpu0", "health": "ok"}),
    (6, 15.5, "event", "e:1", "raw-string-payload"),
]
DELTA_KEYFRAME_INTERVAL = 3

NEGOTIATE_ACKS = (0, 1, 2, 3, 4, 9)

# (view key, repo-relative module, table-name constant in that module)
TABLES = (
    ("journal", "gpud_tpu/manager/rollup.py", "TABLE"),
    ("outbox", "gpud_tpu/session/outbox.py", "TABLE"),
    ("replica", "gpud_tpu/manager/federation.py", "REPLICA_TABLE"),
)

_CONSTRAINT_WORDS = frozenset({
    "UNIQUE", "PRIMARY", "FOREIGN", "CHECK", "CONSTRAINT",
})


# -- source extraction (no imports of heavy modules) -------------------------

def _read(root: str, rel: str) -> str:
    with open(os.path.join(root, rel), encoding="utf-8") as f:
        return f.read()


def _table_schema(text: str, const: str,
                  rel: str) -> Tuple[Optional[str], List[str], List[str]]:
    """(table name, ordered columns, problems) parsed from source."""
    problems: List[str] = []
    m = re.search(rf'^{const}\s*=\s*"([^"]+)"', text, re.M)
    if m is None:
        return None, [], [f"{rel}: no `{const} = \"...\"` constant found"]
    name = m.group(1)
    marker = f"CREATE TABLE IF NOT EXISTS {{{const}}}"
    idx = text.find(marker)
    if idx < 0:
        return name, [], [f"{rel}: no CREATE TABLE statement for {const}"]
    open_idx = text.find("(", idx)
    depth, end = 0, -1
    for i in range(open_idx, len(text)):
        if text[i] == "(":
            depth += 1
        elif text[i] == ")":
            depth -= 1
            if depth == 0:
                end = i
                break
    if end < 0:
        return name, [], [f"{rel}: unbalanced CREATE TABLE for {const}"]
    cols: List[str] = []
    for line in text[open_idx + 1:end].splitlines():
        tok = line.strip().split(" ", 1)[0].rstrip(",")
        if tok and tok.upper() not in _CONSTRAINT_WORDS and tok.isidentifier():
            cols.append(tok)
    return name, cols, problems


def _module_int(text: str, const: str, rel: str,
                problems: List[str]) -> Optional[int]:
    m = re.search(rf"^{const}\s*=\s*(\d+)", text, re.M)
    if m is None:
        problems.append(f"{rel}: no `{const} = <int>` constant found")
        return None
    return int(m.group(1))


def _predict_key_sets(text: str, rel: str,
                      problems: List[str]) -> List[List[str]]:
    """Sorted key lists of every dict literal stamping
    ``"schema": PREDICT_SCHEMA`` in predict/engine.py — the versioned
    payload surface."""
    try:
        tree = ast.parse(text, filename=rel)
    except SyntaxError as e:
        problems.append(f"{rel}: unparseable: {e}")
        return []
    out: List[List[str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        stamped = False
        for k, v in zip(node.keys, node.values):
            if (isinstance(k, ast.Constant) and k.value == "schema"
                    and isinstance(v, ast.Name)
                    and v.id == "PREDICT_SCHEMA"):
                stamped = True
        if not stamped:
            continue
        keys = sorted(
            k.value for k in node.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)
        )
        out.append(keys)
    if not out:
        problems.append(
            f"{rel}: no payload dict stamps \"schema\": PREDICT_SCHEMA — "
            "the versioned predict surface is gone"
        )
    return sorted(out)


# -- the current view --------------------------------------------------------

def current_view(root: str) -> Tuple[Dict, List[str]]:
    """(diffable schema view computed from the current tree, problems).

    Everything in the view is JSON-canonical; behavioral checks are
    folded in as booleans so a behavior regression shows up as a diff
    against the golden's ``true``.
    """
    problems: List[str] = []
    from gpud_tpu.session import wire

    view: Dict = {}
    view["prefixes"] = {
        "json": wire.PREFIX_JSON.decode("ascii"),
        "zlib": wire.PREFIX_ZLIB.decode("ascii"),
        "msgpack": wire.PREFIX_MSGPACK.decode("ascii"),
        "zlib_msgpack": wire.PREFIX_ZLIB_MSGPACK.decode("ascii"),
    }

    # frozen wire samples → whatever the current decoder says they mean
    probes: Dict[str, object] = {}
    for name, hexstr in DECODE_PROBES.items():
        if name in _MSGPACK_ONLY and wire._msgpack is None:
            continue  # slim install: golden-only paths are skipped too
        try:
            probes[name] = wire.decode_payload(bytes.fromhex(hexstr))
        except Exception as e:  # noqa: BLE001 - any failure IS the finding
            probes[name] = f"DECODE FAILED: {e}"
    view["decode_probes"] = probes

    # encode → decode must round-trip regardless of codec availability
    rt_obj = {"component": "tpu0", "n": [1, 2, 3], "s": "x" * 600}
    try:
        small = wire.encode_payload({"a": 1}, min_bytes=1 << 30)
        big = wire.encode_payload(rt_obj, min_bytes=0)
        view["encode_round_trip"] = (
            wire.decode_payload(small) == {"a": 1}
            and wire.decode_payload(big) == rt_obj
            and small[:1] in (wire.PREFIX_JSON, wire.PREFIX_MSGPACK)
            and big[:1] in (wire.PREFIX_ZLIB, wire.PREFIX_ZLIB_MSGPACK)
        )
    except Exception as e:  # noqa: BLE001
        view["encode_round_trip"] = f"FAILED: {e}"

    # delta codec over the fixed sequence
    enc = wire.DeltaEncoder(keyframe_interval=DELTA_KEYFRAME_INTERVAL)
    encoded = [
        enc.encode_record(seq, ts, kind, key,
                          dict(p) if isinstance(p, dict) else p)
        for seq, ts, kind, key, p in DELTA_INPUT
    ]
    view["delta"] = {
        "keyframe_interval": DELTA_KEYFRAME_INTERVAL,
        "encoded": encoded,
        "record_lengths": [len(r) for r in encoded],
    }
    dec = wire.DeltaDecoder()
    try:
        decoded = [dec.decode_record(r) for r in encoded]
        view["delta"]["round_trip"] = all(
            (seq, ts, kind, key) == tuple(d[:4]) and p == d[4]
            for (seq, ts, kind, key, p), d in zip(DELTA_INPUT, decoded)
        )
    except wire.DeltaDecodeError as e:
        view["delta"]["round_trip"] = f"FAILED: {e}"

    view["batch"] = {
        "batch_key": wire.BATCH_KEY,
        "batch_version": wire.BATCH_VERSION,
        "frame": wire.build_batch(encoded),
        "parse_inverse": wire.parse_batch(wire.build_batch(encoded))
        == wire.build_batch(encoded)[wire.BATCH_KEY],
    }

    # v2 Frame revisions
    cp_text = _read(root, "gpud_tpu/manager/control_plane.py")
    max_rev = _module_int(cp_text, "MAX_REVISION",
                          "gpud_tpu/manager/control_plane.py", problems)
    rev: Dict = {"max_revision": max_rev}
    try:
        from gpud_tpu.session.v2 import typed

        rev["negotiate"] = {
            str(ack): typed.negotiate_revision(ack, max_rev or 0)
            for ack in NEGOTIATE_ACKS
        }
        pkt = typed.make_result("r1", {"a": 1}, compress=False)
        rev["rev2_payload_hex"] = pkt.result.payload_json.hex()
        pkt3 = typed.make_result("r1", rt_obj, compress=True)
        rev["rev3_round_trip"] = (
            wire.decode_payload(pkt3.result.payload_json) == rt_obj
        )
    except ImportError as e:  # pragma: no cover - protobuf always baked in
        problems.append(
            f"gpud_tpu/session/v2/typed.py: cannot import to probe Frame "
            f"revisions: {e}"
        )
    view["frame_revisions"] = rev

    # row schemas, parsed from source
    tables: Dict = {}
    for key, rel, const in TABLES:
        name, cols, p = _table_schema(_read(root, rel), const, rel)
        problems.extend(p)
        tables[key] = {"name": name, "columns": cols}
    view["tables"] = tables

    # versioned predict payloads
    cal_rel = "gpud_tpu/predict/calibrate.py"
    roll_rel = "gpud_tpu/manager/rollup.py"
    eng_rel = "gpud_tpu/predict/engine.py"
    view["predict"] = {
        "schema": _module_int(_read(root, cal_rel), "PREDICT_SCHEMA",
                              cal_rel, problems),
        "schema_max": _module_int(_read(root, roll_rel), "PREDICT_SCHEMA_MAX",
                                  roll_rel, problems),
        "payload_key_sets": _predict_key_sets(_read(root, eng_rel), eng_rel,
                                              problems),
    }
    # canonicalize (tuples → lists, key order) so diffs are type-stable
    return json.loads(json.dumps(view, sort_keys=True)), problems


# -- diff --------------------------------------------------------------------

def _flatten(obj, prefix: str, out: Dict[str, object]) -> None:
    if isinstance(obj, dict):
        for k in sorted(obj):
            _flatten(obj[k], f"{prefix}.{k}" if prefix else str(k), out)
    elif isinstance(obj, list):
        out[f"{prefix}#len"] = len(obj)
        for i, v in enumerate(obj):
            _flatten(v, f"{prefix}[{i}]", out)
    else:
        out[prefix] = obj


def _skip_for_env(path: str) -> bool:
    """Golden paths a slim install (no msgpack) cannot check."""
    from gpud_tpu.session import wire

    if wire._msgpack is not None:
        return False
    return any(path.startswith(f"decode_probes.{n}") for n in _MSGPACK_ONLY)


def run_full(root: str = "",
             golden_rel: str = GOLDEN_REL) -> Tuple[List[str], List[str]]:
    """(problems, notes); ([], _) = the wire surface matches the golden."""
    root = root or _repo_root()
    golden_path = os.path.join(root, golden_rel)
    if not os.path.isfile(golden_path):
        return ([
            f"{golden_rel}: golden missing — generate it with "
            "`python -m gpud_tpu.tools.lint_all --update-goldens`"
        ], [])
    try:
        with open(golden_path, encoding="utf-8") as f:
            golden = json.load(f)
    except ValueError as e:
        return [f"{golden_rel}: golden is not valid JSON: {e}"], []
    version = golden.get("golden_version")
    if not (isinstance(version, int) and version >= 1):
        return [f"{golden_rel}: golden_version must be an int >= 1"], []

    view, problems = current_view(root)
    want: Dict[str, object] = {}
    got: Dict[str, object] = {}
    _flatten(golden.get("view", {}), "", want)
    _flatten(view, "", got)
    for path in sorted(set(want) | set(got)):
        if _skip_for_env(path):
            continue
        if path not in got:
            problems.append(
                f"{golden_rel}: schema drift at {path}: frozen as "
                f"{want[path]!r} but the current tree no longer produces it"
            )
        elif path not in want:
            problems.append(
                f"{golden_rel}: schema drift at {path}: current tree "
                f"produces {got[path]!r} which the golden does not pin"
            )
        elif want[path] != got[path]:
            problems.append(
                f"{golden_rel}: schema drift at {path}: golden pins "
                f"{want[path]!r}, current tree produces {got[path]!r}"
            )
    if problems:
        problems.append(
            f"{golden_rel}: wire-schema drift is a compatibility event: "
            "if intentional, regenerate with `python -m gpud_tpu.tools."
            "lint_all --update-goldens` (bumps golden_version to "
            f"{version + 1}) and describe the rollout story in the PR"
        )
    notes = [f"golden_version {version}"]
    return problems, notes


def run_lint(root: str = "") -> List[str]:
    return run_full(root)[0]


def update_golden(root: str = "",
                  golden_rel: str = GOLDEN_REL) -> Tuple[str, bool]:
    """Regenerate the golden from the current tree. Returns (path,
    changed). Idempotent: an unchanged view does not bump the version."""
    root = root or _repo_root()
    view, problems = current_view(root)
    if problems:
        raise RuntimeError(
            "cannot freeze a broken schema surface: " + "; ".join(problems)
        )
    path = os.path.join(root, golden_rel)
    version = 1
    if os.path.isfile(path):
        try:
            with open(path, encoding="utf-8") as f:
                old = json.load(f)
            if old.get("view") == view:
                return path, False
            version = int(old.get("golden_version", 0)) + 1
        except (ValueError, TypeError):
            version = 1
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"golden_version": version, "view": view}, f,
                  indent=1, sort_keys=True)
        f.write("\n")
    return path, True


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--update-goldens" in argv:
        path, changed = update_golden()
        print(f"schema-lint: {'updated' if changed else 'unchanged'} {path}")
        return 0
    problems, notes = run_full()
    for n in notes:
        print(f"schema-lint: {n}")
    for p in problems:
        print(f"schema-lint: {p}", file=sys.stderr)
    if problems:
        print(f"schema-lint: {len(problems)} problem(s)", file=sys.stderr)
        return 1
    print("schema-lint: wire surface matches the golden")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
