"""Generate docs/CATALOG.md from the live error catalog.

The reference ships its XID catalog as generated code
(catalog_generated.go); here the catalog is source and the operator doc
is generated — a test asserts the committed doc matches a fresh render so
the two can never drift.

Run: ``python -m gpud_tpu.tools.gen_catalog_doc [--check]``
"""

from __future__ import annotations

import sys

from gpud_tpu.components.tpu.catalog import CATALOG

HEADER = """# TPU error catalog

Generated from `gpud_tpu/components/tpu/catalog.py` — do not edit by
hand (`python -m gpud_tpu.tools.gen_catalog_doc` regenerates; a test
keeps this file in sync). Matching is first-hit-wins over kmsg lines;
`tpud inject-fault --name <name>` writes each entry's canonical
injection line.

| Code | Name | Severity | Critical | Reboot threshold | Suggested actions | Description |
|---|---|---|---|---|---|---|
"""


def render() -> str:
    rows = []
    for e in sorted(CATALOG, key=lambda e: e.code):
        actions = ", ".join(e.repair_actions) or "—"
        thr = str(e.reboot_threshold) if e.reboot_threshold else "never escalates"
        rows.append(
            f"| {e.code} | `{e.name}` | {e.event_type} | "
            f"{'yes' if e.critical else 'no'} | {thr} | {actions} | "
            f"{e.description} |"
        )
    return HEADER + "\n".join(rows) + "\n"


def main() -> int:
    out = render()
    path = "docs/CATALOG.md"
    if "--check" in sys.argv:
        try:
            current = open(path, "r", encoding="utf-8").read()
        except OSError:
            current = ""
        if current != out:
            print(f"{path} is out of date; regenerate with "
                  f"python -m gpud_tpu.tools.gen_catalog_doc", file=sys.stderr)
            return 1
        print(f"{path} in sync ({len(CATALOG)} entries)")
        return 0
    with open(path, "w", encoding="utf-8") as f:
        f.write(out)
    print(f"wrote {path} ({len(CATALOG)} entries)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
