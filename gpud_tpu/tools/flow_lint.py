"""Flow lint: interprocedural hot-path blocking analysis.

guard_lint proves *lock discipline* lexically; nothing proved the other
standing invariant of the manager tier — that the threads with latency
contracts never *block*. PR 12 shipped exactly that regression once
(outbox ingest ran inline on the session reader thread, so one slow
BatchWriter flush stalled every agent frame behind it), and PR 14's fix
("the reader only enqueues") lived purely in review discipline. ROADMAP
item 2 (multiprocess shard executors) is blocked on these guarantees
being machine-checked, so this lint walks the call graph:

- **Entrypoints** are classified by thread role (``ENTRYPOINTS`` plus
  two discovered families: every ``scheduler.add_job(...)`` target is a
  *scheduler worker*, every ``router.add_get/add_post(...)`` handler an
  *http handler*).
- Each role declares **forbidden sink categories** (``ROLES``): blocking
  SQLite calls, ``BatchWriter.flush``/``drain`` barriers, ``time.sleep``,
  socket I/O, unbounded waits.
- The lint builds an AST-derived call graph over ``gpud_tpu/`` — methods
  via ``self``, in-module bases, ``self.attr = ClassName(...)`` type
  inference, cross-module ``from gpud_tpu.x import y`` resolution — and
  walks **reachability** from every entrypoint, proving no hot
  entrypoint reaches a forbidden sink.
- **Role transitions** happen where closures are handed to another
  thread: ``ingest_executor.submit(id, lambda: ...)`` re-roots the
  closure under the *shard executor* role, ``run_in_executor(pool, fn)``
  and ``ThreadPoolExecutor.submit`` under the *op worker* role,
  ``Thread(target=...)`` under *thread worker* — so "the reader only
  enqueues" is checked on both sides of the handoff.
- Injected callbacks the AST cannot see are pinned declaratively:
  ``ATTR_BINDINGS`` types ``AgentHandle.ingest_executor``;
  ``DYNAMIC_CALLS`` lists what ``AgentHandle.on_records`` is bound to
  (``ControlPlane._register``: the rollup ingest, or the federation
  replica sink). If the wiring moves, the binding goes stale and the
  missing-entrypoint/stale-waiver errors surface it.

The analysis is deliberately *under*-approximate: a call it cannot
resolve (duck-typed parameter, ``srv.*`` through a closure) is not
walked, so a clean report means "no blocking sink on any *resolvable*
path", not a soundness proof. The resolvable set covers the paths the
invariants are about — the manager ingest spine is typed end-to-end.

Waivers follow the guard_lint convention: ``WAIVERS`` maps
``(role, function, category)`` — category ``"*"`` waives the whole
function under that role — to a non-empty justification; a waiver that
is never consulted during the walk is itself an error (stale), as is an
empty reason or an expired ``until: PR-N`` stamp (guard_lint expiry).

Run: ``python -m gpud_tpu.tools.flow_lint`` (exit 1 on any problem);
registered in ``tools/lint_all.py`` so tier-1 enforces it.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Optional, Set, Tuple

from gpud_tpu.tools.guard_lint import _repo_root, waiver_reason_problems

SCAN_ROOT = "gpud_tpu"

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

# role -> forbidden sink categories. A role absent here cannot be used.
ROLES: Dict[str, frozenset] = {
    # manager threads that read agent session frames: one slow frame
    # stalls every agent multiplexed behind it (PR 14: only enqueues)
    "session_reader": frozenset({"sql", "flush", "sleep", "socket", "wait"}),
    # per-shard ingest workers: may take shard locks and buffer writes
    # (bounded backpressure), must never commit, barrier, or leave process
    "shard_executor": frozenset({"sql", "flush", "sleep", "socket"}),
    # scheduler pool workers: blocking SQL/flush is their job; sleeping
    # steals a shared worker — cadence belongs to the scheduler heap
    "scheduler_worker": frozenset({"sleep"}),
    # asyncio handlers: anything blocking wedges the event loop; real
    # work must cross a run_in_executor transition first
    "http_handler": frozenset({"sql", "flush", "sleep", "socket", "wait"}),
    # replication shipper tick: reads the journal (sql) and does socket
    # I/O by design; must not sleep or barrier-flush on its cadence
    "federation_shipper": frozenset({"sleep", "flush"}),
    # offloaded blocking work: blocking is the point
    "op_worker": frozenset(),
    "thread_worker": frozenset(),
}

# (role, "rel/path.py::Qual.name", why this is an entrypoint)
ENTRYPOINTS: Tuple[Tuple[str, str, str], ...] = (
    ("session_reader",
     "gpud_tpu/manager/control_plane.py::AgentHandle.resolve",
     "v1 write-stream loop and v2 drain_responses call resolve() for "
     "every frame an agent sends"),
    ("session_reader",
     "gpud_tpu/manager/federation.py::JournalShipper._dispatch",
     "peer replication stream reader (outboxAck handling)"),
    ("session_reader",
     "gpud_tpu/manager/federation.py::JournalShipper._on_connected",
     "runs on the peer session's reader thread at (re)connect"),
    ("federation_shipper",
     "gpud_tpu/manager/federation.py::JournalShipper.tick",
     "replication tick: ships journal rows to the ring successor"),
    ("shard_executor",
     "gpud_tpu/manager/shard.py::ShardIngestExecutor._worker",
     "per-shard worker loop (the submitted closures are additionally "
     "re-rooted here by the submit() transition)"),
)

# resolvable calls that ARE the contract boundaries, by category —
# walked-into bodies would report their internals; naming them keeps
# findings anchored where the contract lives. "append" is forbidden by
# no role: submit/submit_many are the sanctioned write-behind appends
# (bounded 50ms backpressure, sync fallback only on a *stopped* writer,
# i.e. daemon shutdown / CLI tools) — the walk stops at them instead of
# reporting their internal fallback SQL as if callers could reach it hot
PRIMITIVE_SINKS: Dict[str, str] = {
    "gpud_tpu/storage/writer.py::BatchWriter.flush": "flush",
    "gpud_tpu/storage/writer.py::BatchWriter.drain": "flush",
    "gpud_tpu/storage/writer.py::BatchWriter.submit": "append",
    "gpud_tpu/storage/writer.py::BatchWriter.submit_many": "append",
}

# method attr names that mark an unresolvable call as a sink
_SQL_ATTRS = frozenset({"execute", "executemany", "query", "query_one",
                        "run_batch"})
_SOCKET_ATTRS = frozenset({"urlopen", "create_connection", "getaddrinfo",
                           "recv", "sendall", "sendto"})
_WAIT_ATTRS = frozenset({"wait", "wait_for", "result"})
_FLUSH_ATTRS = frozenset({"flush", "drain"})

# attribute-name → type, for injected objects every store shares
GLOBAL_ATTR_TYPES: Dict[str, Tuple[str, str]] = {
    "writer": ("gpud_tpu/storage/writer.py", "BatchWriter"),
}

# (rel, class, attr) -> (rel, class): dependency-injected attributes the
# AST can't type from an assignment in the owning module
ATTR_BINDINGS: Dict[Tuple[str, str, str], Tuple[str, str]] = {
    ("gpud_tpu/manager/control_plane.py", "AgentHandle", "ingest_executor"):
        ("gpud_tpu/manager/shard.py", "ShardIngestExecutor"),
}

# (rel, class, attr) -> callee quals: dynamically-bound callbacks
# (``ControlPlane._register`` wires AgentHandle.on_records)
DYNAMIC_CALLS: Dict[Tuple[str, str, str], Tuple[str, ...]] = {
    ("gpud_tpu/manager/control_plane.py", "AgentHandle", "on_records"): (
        "gpud_tpu/manager/rollup.py::FleetRollupStore.ingest",
        "gpud_tpu/manager/federation.py::ReplicaStore.replica_ingest",
    ),
}

# (role, qual, category) -> justification. category "*" = skip the whole
# function under that role. Conventions match guard_lint._LOCK_FREE:
# non-empty reason, stale waivers are errors, `until: PR-N` expires.
WAIVERS: Dict[Tuple[str, str, str], str] = {
    ("session_reader",
     "gpud_tpu/manager/control_plane.py::AgentHandle._ingest_outbox", "*"):
        "inline fallback taken only when no ShardIngestExecutor is wired "
        "(standalone handles in unit tests and chaos harnesses); "
        "ControlPlane._register always wires one, so the enqueue-only "
        "path is the only reader path in a running manager — "
        "test_flow_lint pins the regression fixture that would make this "
        "edge unconditional",
    ("shard_executor",
     "gpud_tpu/manager/rollup.py::FleetRollupStore.ingest", "sql"):
        "db.executemany branch runs only when constructed without a "
        "BatchWriter (unit tests, CLI tools over a cold state file); the "
        "manager wires a writer and takes the buffered submit_many path "
        "pinned by storage_lint HOT_WRITE_METHODS",
    ("shard_executor",
     "gpud_tpu/manager/federation.py::ReplicaStore.replica_ingest", "sql"):
        "same writer-less fallback as FleetRollupStore.ingest: "
        "db.executemany only without a BatchWriter; the federation plane "
        "always passes the shared writer",
    ("http_handler",
     "gpud_tpu/chaos/fake_plane.py::FakeControlPlane._session", "*"):
        "chaos-harness fake manager: the sleeps and inline ingest on "
        "this route ARE the fault injection (latency/disconnect "
        "scenarios exercising agent reconnect paths); test-only "
        "process, never part of the daemon",
}


# -- module index ------------------------------------------------------------

class _Func:
    __slots__ = ("qual", "rel", "cls", "name", "node")

    def __init__(self, qual: str, rel: str, cls: Optional[str], name: str,
                 node) -> None:
        self.qual = qual
        self.rel = rel
        self.cls = cls
        self.name = name
        self.node = node


class _Module:
    __slots__ = ("rel", "tree", "classes", "bases", "attr_types",
                 "mod_aliases", "name_aliases", "funcs")

    def __init__(self, rel: str, tree: ast.Module) -> None:
        self.rel = rel
        self.tree = tree
        self.classes: Dict[str, ast.ClassDef] = {}
        self.bases: Dict[str, List[str]] = {}
        # (class, attr) -> (rel, class) from `self.attr = ClassName(...)`
        self.attr_types: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self.mod_aliases: Dict[str, str] = {}    # alias -> rel of module
        self.name_aliases: Dict[str, Tuple[str, str]] = {}  # alias->(rel,nm)
        self.funcs: Dict[str, _Func] = {}        # qual-suffix -> _Func


class Index:
    """Every function in the scanned tree plus just enough typing to
    resolve the repo's call idioms."""

    def __init__(self, root: str) -> None:
        self.root = root
        self.modules: Dict[str, _Module] = {}
        self.funcs: Dict[str, _Func] = {}  # full qual -> _Func
        self._load()
        self._link()

    # -- loading -----------------------------------------------------------
    def _load(self) -> None:
        scan = os.path.join(self.root, SCAN_ROOT)
        for dirpath, _dirs, files in os.walk(scan):
            for fname in sorted(files):
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                rel = os.path.relpath(path, self.root).replace(os.sep, "/")
                try:
                    with open(path, encoding="utf-8") as f:
                        tree = ast.parse(f.read(), filename=rel)
                except (SyntaxError, OSError):
                    continue
                self.modules[rel] = self._index_module(rel, tree)

    def _index_module(self, rel: str, tree: ast.Module) -> _Module:
        mod = _Module(rel, tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                if not node.module.startswith("gpud_tpu"):
                    continue
                target = self._module_rel(node.module)
                for alias in node.names:
                    name = alias.asname or alias.name
                    sub = self._module_rel(f"{node.module}.{alias.name}")
                    if sub is not None:
                        mod.mod_aliases[name] = sub
                    elif target is not None:
                        mod.name_aliases[name] = (target, alias.name)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.startswith("gpud_tpu"):
                        target = self._module_rel(alias.name)
                        if target is not None:
                            name = alias.asname or alias.name.split(".")[-1]
                            mod.mod_aliases[name] = target
        for stmt in tree.body:
            if isinstance(stmt, _FUNC_NODES):
                self._add_func(mod, None, stmt.name, stmt)
                for inner in stmt.body:
                    if isinstance(inner, _FUNC_NODES):
                        self._add_func(
                            mod, None, f"{stmt.name}.{inner.name}", inner
                        )
            elif isinstance(stmt, ast.ClassDef):
                mod.classes[stmt.name] = stmt
                mod.bases[stmt.name] = [
                    b.id for b in stmt.bases if isinstance(b, ast.Name)
                ]
                for item in stmt.body:
                    if isinstance(item, _FUNC_NODES):
                        self._add_func(
                            mod, stmt.name, f"{stmt.name}.{item.name}", item
                        )
        return mod

    def _add_func(self, mod: _Module, cls: Optional[str], suffix: str,
                  node) -> None:
        qual = f"{mod.rel}::{suffix}"
        fn = _Func(qual, mod.rel, cls, suffix.rsplit(".", 1)[-1], node)
        mod.funcs[suffix] = fn
        self.funcs[qual] = fn

    def _module_rel(self, dotted: str) -> Optional[str]:
        rel = dotted.replace(".", "/") + ".py"
        if os.path.isfile(os.path.join(self.root, rel)):
            return rel
        pkg = dotted.replace(".", "/") + "/__init__.py"
        if os.path.isfile(os.path.join(self.root, pkg)):
            return pkg
        return None

    # -- typing pass -------------------------------------------------------
    def _link(self) -> None:
        for mod in self.modules.values():
            for cls_name, cls in mod.classes.items():
                for node in ast.walk(cls):
                    if not (isinstance(node, ast.Assign)
                            and isinstance(node.value, ast.Call)):
                        continue
                    typ = self.resolve_class(mod, node.value.func)
                    if typ is None:
                        continue
                    for tgt in node.targets:
                        if (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            mod.attr_types[(cls_name, tgt.attr)] = typ

    # -- resolution --------------------------------------------------------
    def resolve_class(self, mod: _Module,
                      func: ast.expr) -> Optional[Tuple[str, str]]:
        """``ClassName`` / ``alias.ClassName`` expression -> (rel, class)."""
        if isinstance(func, ast.Name):
            if func.id in mod.classes:
                return (mod.rel, func.id)
            tgt = mod.name_aliases.get(func.id)
            if tgt is not None:
                other = self.modules.get(tgt[0])
                if other is not None and tgt[1] in other.classes:
                    return (tgt[0], tgt[1])
        elif isinstance(func, ast.Attribute) and isinstance(func.value,
                                                            ast.Name):
            tgt_rel = mod.mod_aliases.get(func.value.id)
            if tgt_rel is not None:
                other = self.modules.get(tgt_rel)
                if other is not None and func.attr in other.classes:
                    return (tgt_rel, func.attr)
        return None

    def method(self, rel: str, cls: str, name: str) -> Optional[_Func]:
        """Method lookup walking in-module base classes."""
        mod = self.modules.get(rel)
        seen: Set[str] = set()
        while mod is not None and cls not in seen:
            seen.add(cls)
            fn = mod.funcs.get(f"{cls}.{name}")
            if fn is not None:
                return fn
            nxt = next((b for b in mod.bases.get(cls, ())
                        if b in mod.classes), None)
            if nxt is None:
                return None
            cls = nxt
        return None

    def attr_type(self, rel: str, cls: Optional[str],
                  attr: str) -> Optional[Tuple[str, str]]:
        if cls is not None:
            bound = ATTR_BINDINGS.get((rel, cls, attr))
            if bound is not None:
                return bound
            mod = self.modules.get(rel)
            if mod is not None:
                typ = mod.attr_types.get((cls, attr))
                if typ is not None:
                    return typ
        return GLOBAL_ATTR_TYPES.get(attr)


# -- per-function effects ----------------------------------------------------

class _Effects:
    """What one function body does: resolvable call edges, lexical
    sinks, and role-transition handoffs."""

    __slots__ = ("edges", "sinks", "transitions")

    def __init__(self) -> None:
        self.edges: List[Tuple[str, int]] = []          # (qual, line)
        self.sinks: List[Tuple[str, int, str]] = []     # (cat, line, what)
        self.transitions: List[Tuple[str, object, int]] = []  # (role, fn, ln)


def _callable_args(call: ast.Call) -> List[ast.expr]:
    out: List[ast.expr] = []
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        if isinstance(arg, (ast.Lambda, ast.Name, ast.Attribute)):
            out.append(arg)
    return out


class _Scanner:
    def __init__(self, index: Index, fn: _Func) -> None:
        self.index = index
        self.fn = fn
        self.mod = index.modules[fn.rel]
        self.eff = _Effects()
        # local name -> ("type", rel, cls) | ("dyn", key) aliases
        self.locals: Dict[str, tuple] = {}

    def scan(self) -> _Effects:
        node = self.fn.node
        body = node.body if not isinstance(node, ast.Lambda) else [
            ast.Expr(value=node.body)
        ]
        self._stmts(body)
        return self.eff

    # -- statements --------------------------------------------------------
    def _stmts(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, node: ast.stmt) -> None:
        if isinstance(node, _FUNC_NODES) or isinstance(node, ast.ClassDef):
            return  # nested defs are separate functions, reached if called
        if isinstance(node, ast.Assign):
            self._track_assign(node)
            self._expr(node.value)
            return
        for _field, value in ast.iter_fields(node):
            if isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.stmt):
                        self._stmt(v)
                    elif isinstance(v, ast.expr):
                        self._expr(v)
                    elif isinstance(v, ast.excepthandler):
                        self._stmts(v.body)
                    elif isinstance(v, getattr(ast, "match_case", ())):
                        self._stmts(v.body)
                    elif isinstance(v, (ast.withitem,)):
                        self._expr(v.context_expr)
            elif isinstance(value, ast.stmt):
                self._stmt(value)
            elif isinstance(value, ast.expr):
                self._expr(value)

    def _track_assign(self, node: ast.Assign) -> None:
        if len(node.targets) != 1 or not isinstance(node.targets[0], ast.Name):
            return
        name = node.targets[0].id
        val = node.value
        self.locals.pop(name, None)
        if (isinstance(val, ast.Attribute) and isinstance(val.value, ast.Name)
                and val.value.id == "self" and self.fn.cls is not None):
            key = (self.fn.rel, self.fn.cls, val.attr)
            if key in DYNAMIC_CALLS:
                self.locals[name] = ("dyn", key)
                return
            typ = self.index.attr_type(self.fn.rel, self.fn.cls, val.attr)
            if typ is not None:
                self.locals[name] = ("type",) + typ
        elif isinstance(val, ast.Call):
            typ = self.index.resolve_class(self.mod, val.func)
            if typ is not None:
                self.locals[name] = ("type",) + typ

    # -- expressions -------------------------------------------------------
    def _expr(self, node: Optional[ast.expr]) -> None:
        if node is None:
            return
        stack: List[ast.AST] = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Lambda):
                # inline lambda (sort keys, predicates): same thread
                stack.append(n.body)
                continue
            if isinstance(n, ast.Call):
                if self._call(n):
                    # transition consumed the callable args; still scan
                    # the non-callable ones
                    for arg in list(n.args) + [kw.value for kw in n.keywords]:
                        if not isinstance(arg, (ast.Lambda,)):
                            stack.append(arg)
                    stack.append(n.func)
                    continue
            stack.extend(ast.iter_child_nodes(n))

    def _call(self, call: ast.Call) -> bool:
        """Handle one call; returns True when it was a role transition
        (caller must not descend into its callable args)."""
        func = call.func
        line = call.lineno
        # -- role transitions ---------------------------------------------
        if isinstance(func, ast.Attribute):
            if func.attr == "run_in_executor":
                args = call.args
                if len(args) >= 2:
                    self._transition("op_worker", args[1], line)
                return True
            if func.attr == "submit":
                role = "op_worker"
                typ = self._receiver_type(func.value)
                if typ is not None and typ[1] == "ShardIngestExecutor":
                    role = "shard_executor"
                elif typ is not None and typ[1] == "BatchWriter":
                    return False  # buffered append, not a handoff
                for arg in _callable_args(call):
                    self._transition(role, arg, line)
                return True
        if (isinstance(func, ast.Name) and func.id == "Thread") or (
                isinstance(func, ast.Attribute) and func.attr == "Thread"):
            for kw in call.keywords:
                if kw.arg == "target":
                    self._transition("thread_worker", kw.value, line)
            return True
        if isinstance(func, ast.Attribute) and func.attr == "add_job":
            if len(call.args) >= 2:
                self._transition("scheduler_worker", call.args[1], line)
            return True
        # -- resolvable edges ----------------------------------------------
        target = self._resolve_call(func)
        if target is not None:
            if isinstance(target, list):
                for qual in target:
                    self.eff.edges.append((qual, line))
            else:
                self.eff.edges.append((target, line))
            return False
        # -- lexical sinks on unresolved calls -----------------------------
        if isinstance(func, ast.Attribute):
            attr = func.attr
            if attr in _SQL_ATTRS:
                self.eff.sinks.append(("sql", line, f".{attr}()"))
            elif attr == "sleep":
                self.eff.sinks.append(("sleep", line, "time.sleep()"))
            elif attr in _SOCKET_ATTRS:
                self.eff.sinks.append(("socket", line, f".{attr}()"))
            elif attr in _WAIT_ATTRS:
                self.eff.sinks.append(("wait", line, f".{attr}()"))
            elif attr in _FLUSH_ATTRS:
                self.eff.sinks.append(("flush", line, f".{attr}()"))
        elif isinstance(func, ast.Name) and func.id == "urlopen":
            self.eff.sinks.append(("socket", line, "urlopen()"))
        return False

    def _transition(self, role: str, fn_expr: ast.expr, line: int) -> None:
        self.eff.transitions.append((role, fn_expr, line))

    def _receiver_type(self, expr: ast.expr) -> Optional[Tuple[str, str]]:
        if isinstance(expr, ast.Name):
            ent = self.locals.get(expr.id)
            if ent is not None and ent[0] == "type":
                return (ent[1], ent[2])
            return None
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            return self.index.attr_type(self.fn.rel, self.fn.cls, expr.attr)
        return None

    def _resolve_call(self, func: ast.expr):
        """Call target -> qual, list of quals (dynamic), or None."""
        index, mod, fn = self.index, self.mod, self.fn
        if isinstance(func, ast.Name):
            ent = self.locals.get(func.id)
            if ent is not None and ent[0] == "dyn":
                return list(DYNAMIC_CALLS[ent[1]])
            # nested child (handlers defined inside this very function,
            # e.g. build_app registering its own nested async defs) …
            suffix = fn.qual.split("::", 1)[1]
            child = mod.funcs.get(f"{suffix}.{func.id}")
            if child is not None:
                return child.qual
            # … or nested sibling (one handler calling another)
            if "." in suffix:
                outer = suffix.split(".")[0]
                sib = mod.funcs.get(f"{outer}.{func.id}")
                if sib is not None:
                    return sib.qual
            target = mod.funcs.get(func.id)
            if target is not None and target.cls is None:
                return target.qual
            alias = mod.name_aliases.get(func.id)
            if alias is not None:
                other = index.modules.get(alias[0])
                if other is not None:
                    f2 = other.funcs.get(alias[1])
                    if f2 is not None:
                        return f2.qual
                    if alias[1] in other.classes:
                        init = index.method(alias[0], alias[1], "__init__")
                        return init.qual if init else None
            if func.id in mod.classes:
                init = index.method(mod.rel, func.id, "__init__")
                return init.qual if init else None
            return None
        if not isinstance(func, ast.Attribute):
            return None
        recv = func.value
        if isinstance(recv, ast.Name) and recv.id == "self":
            if fn.cls is None:
                return None
            key = (fn.rel, fn.cls, func.attr)
            if key in DYNAMIC_CALLS:
                return list(DYNAMIC_CALLS[key])
            target = index.method(fn.rel, fn.cls, func.attr)
            return target.qual if target else None
        if isinstance(recv, ast.Name):
            tgt_rel = mod.mod_aliases.get(recv.id)
            if tgt_rel is not None:
                other = index.modules.get(tgt_rel)
                if other is not None:
                    f2 = other.funcs.get(func.attr)
                    if f2 is not None:
                        return f2.qual
                    if func.attr in other.classes:
                        init = index.method(tgt_rel, func.attr, "__init__")
                        return init.qual if init else None
                return None
        typ = self._receiver_type(recv)
        if typ is not None:
            target = index.method(typ[0], typ[1], func.attr)
            return target.qual if target else None
        if (isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self"):
            inner = self.index.attr_type(fn.rel, fn.cls, recv.attr)
            if inner is not None:
                target = index.method(inner[0], inner[1], func.attr)
                return target.qual if target else None
        return None


# -- reachability walk -------------------------------------------------------

class _Walker:
    def __init__(self, index: Index, waivers: Dict) -> None:
        self.index = index
        self.waivers = waivers
        self.used_waivers: Set[Tuple[str, str, str]] = set()
        self.problems: List[str] = []
        self._effects: Dict[str, _Effects] = {}
        self._lambda_n = 0

    def effects_of(self, fn: _Func) -> _Effects:
        eff = self._effects.get(fn.qual)
        if eff is None:
            eff = _Scanner(self.index, fn).scan()
            self._effects[fn.qual] = eff
        return eff

    def _waived(self, role: str, qual: str, cat: str) -> bool:
        for key in ((role, qual, cat), (role, qual, "*")):
            if key in self.waivers:
                self.used_waivers.add(key)
                return True
        return False

    def walk(self, role: str, fn: _Func, why: str) -> None:
        forbidden = ROLES[role]
        if not forbidden:
            return
        if self._waived(role, fn.qual, "*"):
            return
        visited: Set[str] = set()
        # (func, call chain up to and including it)
        stack: List[Tuple[_Func, Tuple[str, ...]]] = [(fn, (fn.qual,))]
        while stack:
            cur, chain = stack.pop()
            if cur.qual in visited:
                continue
            visited.add(cur.qual)
            eff = self.effects_of(cur)
            for cat, line, what in eff.sinks:
                if cat not in forbidden:
                    continue
                if self._waived(role, cur.qual, cat):
                    continue
                self.problems.append(
                    f"{cur.rel}:{line}: [{role}] {chain[0]} reaches "
                    f"forbidden {cat} sink {what} "
                    f"via {' -> '.join(chain)} ({why})"
                )
            for qual, line in eff.edges:
                prim = PRIMITIVE_SINKS.get(qual)
                if prim is not None:
                    if prim in forbidden and not self._waived(
                            role, cur.qual, prim):
                        self.problems.append(
                            f"{cur.rel}:{line}: [{role}] {chain[0]} reaches "
                            f"forbidden {prim} barrier {qual.split('::')[1]} "
                            f"via {' -> '.join(chain)} ({why})"
                        )
                    continue
                nxt = self.index.funcs.get(qual)
                if nxt is None or nxt.qual in visited:
                    continue
                if self._waived(role, nxt.qual, "*"):
                    continue
                if len(chain) < 24:
                    stack.append((nxt, chain + (nxt.qual,)))
            for t_role, fn_expr, line in eff.transitions:
                target = self._transition_target(cur, fn_expr)
                if target is None:
                    continue
                t_forbidden = ROLES.get(t_role, frozenset())
                if not t_forbidden:
                    continue
                if not self._waived(t_role, target.qual, "*"):
                    self.walk(
                        t_role, target,
                        f"handed off at {cur.rel}:{line}",
                    )

    def _transition_target(self, cur: _Func,
                           fn_expr: ast.expr) -> Optional[_Func]:
        if isinstance(fn_expr, ast.Lambda):
            self._lambda_n += 1
            qual = f"{cur.qual}.<lambda:{fn_expr.lineno}>"
            fn = _Func(qual, cur.rel, cur.cls, "<lambda>", fn_expr)
            if qual not in self.index.funcs:
                self.index.funcs[qual] = fn
            return self.index.funcs[qual]
        scanner = _Scanner(self.index, cur)
        target = scanner._resolve_call(fn_expr)
        if isinstance(target, list):
            target = target[0] if target else None
        if target is None:
            return None
        return self.index.funcs.get(target)


# -- discovered entrypoint families ------------------------------------------

_HTTP_ADDERS = frozenset({"add_get", "add_post", "add_put", "add_delete"})


def _discovered_entrypoints(index: Index) -> List[Tuple[str, _Func, str]]:
    """Scheduler job targets and HTTP handlers, found at their
    registration sites so new jobs/routes are classified automatically."""
    out: List[Tuple[str, _Func, str]] = []
    seen: Set[str] = set()
    for mod in index.modules.values():
        for fn in list(mod.funcs.values()):
            scanner = _Scanner(index, fn)
            for node in ast.walk(fn.node):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                attr = node.func.attr
                if attr == "add_job" and len(node.args) >= 2:
                    target = scanner._resolve_call(node.args[1])
                    job = ""
                    if node.args and isinstance(node.args[0], ast.Constant):
                        job = str(node.args[0].value)
                    role, why = "scheduler_worker", f"scheduler job {job!r}"
                elif attr in _HTTP_ADDERS and len(node.args) >= 2:
                    target = scanner._resolve_call(node.args[1])
                    path = ""
                    if isinstance(node.args[0], ast.Constant):
                        path = str(node.args[0].value)
                    role, why = "http_handler", f"route {path}"
                else:
                    continue
                if isinstance(target, list):
                    target = target[0] if target else None
                if target is None or target in seen:
                    continue
                f2 = index.funcs.get(target)
                if f2 is None:
                    continue
                seen.add(target)
                out.append((role, f2, why))
    return out


# -- entry points ------------------------------------------------------------

def run_full(root: str = "", waivers: Optional[Dict] = None,
             entrypoints=None) -> Tuple[List[str], List[str]]:
    """(problems, waiver notes) over the tree at ``root``; ([], _) = clean."""
    root = root or _repo_root()
    waivers = WAIVERS if waivers is None else waivers
    entrypoints = ENTRYPOINTS if entrypoints is None else entrypoints
    index = Index(root)
    walker = _Walker(index, waivers)

    problems: List[str] = []
    for role, qual, why in entrypoints:
        fn = index.funcs.get(qual)
        if fn is None:
            problems.append(
                f"{qual.split('::')[0]}: entrypoint {qual} is gone — "
                "renamed or moved; update flow_lint.ENTRYPOINTS"
            )
            continue
        walker.walk(role, fn, why)
    for role, fn, why in _discovered_entrypoints(index):
        walker.walk(role, fn, why)
    problems.extend(walker.problems)

    notes: List[str] = []
    for key, reason in sorted(waivers.items()):
        role, qual, cat = key
        rel = qual.split("::")[0]
        problems.extend(
            f"{rel}: flow waiver {key}: {p}"
            for p in waiver_reason_problems(reason, root=root)
        )
        if key not in walker.used_waivers:
            problems.append(
                f"{rel}: flow waiver {key} was never reached from any "
                f"{role} entrypoint (stale waiver — remove it)"
            )
        else:
            notes.append(f"[{role}] {qual} ({cat}) — {reason}")
    return problems, notes


def run_lint(root: str = "") -> List[str]:
    return run_full(root)[0]


def main() -> int:
    problems, notes = run_full()
    for n in notes:
        print(f"flow-lint: waived {n}")
    for p in problems:
        print(f"flow-lint: {p}", file=sys.stderr)
    if problems:
        print(f"flow-lint: {len(problems)} problem(s)", file=sys.stderr)
        return 1
    print(
        f"flow-lint: {len(ENTRYPOINTS)} pinned entrypoint(s) + discovered "
        f"scheduler/http families clean, {len(notes)} justified waiver(s)"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
