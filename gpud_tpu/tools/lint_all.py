"""One entry point for every registration lint.

Each lint guards a registry that silently drifts: a metric module left
out of ``metrics_lint._METRIC_MODULES`` never gets linted, a store left
out of ``storage_lint.STORE_MODULES`` can regress to per-row commits,
and an HTTP route without a docstring ships an OpenAPI operation with
no summary. Running them as one suite — and wiring that suite into
tier-1 (tests/test_lint_all.py) — turns "forgot to register it" from a
bench-only discovery into a failing unit test.

Checks:

- **metrics**: import every metric-defining module, lint the default
  registry (prefix, help text, unit suffixes, reserved labels).
- **storage**: AST-scan every SQLite-backed store's declared
  ``HOT_WRITE_METHODS`` for writer routing.
- **openapi**: build the node HTTP app against a throwaway unstarted
  Server, render /openapi.json straight from the route table, and
  check both parity directions plus a non-empty summary per operation.

Run: ``python -m gpud_tpu.tools.lint_all`` (exit 1 on any problem).
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import tempfile
from typing import List


def openapi_parity_problems() -> List[str]:
    """Route-table vs document parity without sockets: the openapi
    handler ignores its request argument and reads only the router, so
    it can run against an app that was built but never served."""
    from gpud_tpu.config import default_config
    from gpud_tpu.server.app import build_app
    from gpud_tpu.server.server import Server

    problems: List[str] = []
    with tempfile.TemporaryDirectory(prefix="tpud-lint-") as tmp:
        kmsg = os.path.join(tmp, "kmsg.fixture")
        with open(kmsg, "w", encoding="utf-8"):
            pass
        cfg = default_config(
            data_dir=os.path.join(tmp, "data"), port=0, tls=False,
            kmsg_path=kmsg,
        )
        cfg.components_disabled = ["network-latency"]  # egress-free
        srv = Server(config=cfg)
        try:
            app = build_app(srv)
            handler = None
            served = set()
            for route in app.router.routes():
                info = route.resource.get_info() if route.resource else {}
                path = info.get("path") or info.get("formatter") or ""
                method = route.method.lower()
                if path == "/openapi.json" and method == "get":
                    handler = route.handler
                if not path or path == "/openapi.json" or method == "head":
                    continue
                served.add((path, method))
            if handler is None:
                return ["/openapi.json route is not registered"]
            resp = asyncio.run(handler(None))
            doc = json.loads(resp.body.decode())
            documented = {
                (path, method)
                for path, methods in doc["paths"].items()
                for method in methods
            }
            for path, method in sorted(served - documented):
                problems.append(
                    f"served but undocumented: {method.upper()} {path}"
                )
            for path, method in sorted(documented - served):
                problems.append(
                    f"documented but not served: {method.upper()} {path}"
                )
            for path, methods in sorted(doc["paths"].items()):
                for method, op in methods.items():
                    if not op.get("summary"):
                        problems.append(
                            f"{method.upper()} {path}: operation has no "
                            "summary (handler docstring missing)"
                        )
        finally:
            srv.stop()
    return problems


def run_all() -> List[str]:
    """Every lint, one problem list; [] = clean. Problems are prefixed
    with their lint's name so a CI log line is self-locating."""
    from gpud_tpu.metrics.registry import DEFAULT_REGISTRY
    from gpud_tpu.tools import metrics_lint, storage_lint

    problems: List[str] = []
    metrics_lint.populate_default_registry()
    problems.extend(
        f"metrics: {p}" for p in metrics_lint.lint_registry(DEFAULT_REGISTRY)
    )
    problems.extend(f"storage: {p}" for p in storage_lint.run_lint())
    problems.extend(f"openapi: {p}" for p in openapi_parity_problems())
    return problems


def main() -> int:
    problems = run_all()
    for p in problems:
        print(f"lint-all: {p}", file=sys.stderr)
    if problems:
        print(f"lint-all: {len(problems)} problem(s)", file=sys.stderr)
        return 1
    print("lint-all: metrics + storage + openapi clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
