"""One entry point for every registration lint.

Each lint guards a registry that silently drifts: a metric module left
out of ``metrics_lint._METRIC_MODULES`` never gets linted, a store left
out of ``storage_lint.STORE_MODULES`` can regress to per-row commits,
and an HTTP route without a docstring ships an OpenAPI operation with
no summary. Running them as one suite — and wiring that suite into
tier-1 (tests/test_lint_all.py) — turns "forgot to register it" from a
bench-only discovery into a failing unit test.

Checks:

- **metrics**: import every metric-defining module, lint the default
  registry (prefix, help text, unit suffixes, reserved labels).
- **storage**: AST-scan every SQLite-backed store's declared
  ``HOT_WRITE_METHODS`` for writer routing.
- **openapi**: build the node HTTP app against a throwaway unstarted
  Server, render /openapi.json straight from the route table, and
  check both parity directions plus a non-empty summary per operation.
- **guard**: AST-verify every GUARDED_BY-annotated attribute access in
  the threaded modules sits under its declared lock (guard_lint).
- **parity**: config knobs referenced/documented/validated, dispatcher
  matrix + SDK coverage, /v1 route matrix coverage (parity_lint).
- **race**: the ``bench.py --race`` harness stays wired — flag, dispatch,
  GIL amplifier, and exit gates all present (the harness itself is a
  bench, only its registration is linted here).
- **flow**: interprocedural hot-path reachability — no session-reader /
  shard-executor / scheduler / HTTP entrypoint reaches a blocking sink
  without a justified waiver (flow_lint).
- **boundary**: payloads crossing the outbox / Frame / ingest-executor
  serialization seams stay msgpack-safe and journal-derivable
  (boundary_lint).
- **schema**: the wire surface (codec prefixes, delta records,
  ``outbox_batch``, Frame revisions, journal rows, predict payloads)
  matches the frozen golden (schema_lint).

Run: ``python -m gpud_tpu.tools.lint_all`` (exit 1 on any problem);
``--json`` emits a machine-readable problem list instead of text;
``--update-goldens`` regenerates the schema golden from the current
tree (bumping its version) instead of linting.
"""

from __future__ import annotations

import asyncio
import json
import os
import re
import sys
import tempfile
from typing import Dict, List


def openapi_parity_problems() -> List[str]:
    """Route-table vs document parity without sockets: the openapi
    handler ignores its request argument and reads only the router, so
    it can run against an app that was built but never served."""
    from gpud_tpu.config import default_config
    from gpud_tpu.server.app import build_app
    from gpud_tpu.server.server import Server

    problems: List[str] = []
    with tempfile.TemporaryDirectory(prefix="tpud-lint-") as tmp:
        kmsg = os.path.join(tmp, "kmsg.fixture")
        with open(kmsg, "w", encoding="utf-8"):
            pass
        cfg = default_config(
            data_dir=os.path.join(tmp, "data"), port=0, tls=False,
            kmsg_path=kmsg,
        )
        cfg.components_disabled = ["network-latency"]  # egress-free
        srv = Server(config=cfg)
        try:
            app = build_app(srv)
            handler = None
            served = set()
            for route in app.router.routes():
                info = route.resource.get_info() if route.resource else {}
                path = info.get("path") or info.get("formatter") or ""
                method = route.method.lower()
                if path == "/openapi.json" and method == "get":
                    handler = route.handler
                if not path or path == "/openapi.json" or method == "head":
                    continue
                served.add((path, method))
            if handler is None:
                return ["/openapi.json route is not registered"]
            resp = asyncio.run(handler(None))
            doc = json.loads(resp.body.decode())
            documented = {
                (path, method)
                for path, methods in doc["paths"].items()
                for method in methods
            }
            for path, method in sorted(served - documented):
                problems.append(
                    f"served but undocumented: {method.upper()} {path}"
                )
            for path, method in sorted(documented - served):
                problems.append(
                    f"documented but not served: {method.upper()} {path}"
                )
            for path, methods in sorted(doc["paths"].items()):
                for method, op in methods.items():
                    if not op.get("summary"):
                        problems.append(
                            f"{method.upper()} {path}: operation has no "
                            "summary (handler docstring missing)"
                        )
        finally:
            srv.stop()
    return problems


def race_harness_problems() -> List[str]:
    """The --race harness itself is a bench (~90s of chaos), far too slow
    for tier-1 — but its *wiring* is lintable: the flag must stay
    registered, dispatch to bench_race, and bench_race must keep its
    GIL-preemption amplifier, detector, and exit gates. This pins the
    harness against silent removal the same way the other registries are
    pinned."""
    import ast

    from gpud_tpu.tools.guard_lint import _repo_root

    path = os.path.join(_repo_root(), "bench.py")
    if not os.path.isfile(path):
        return ["bench.py: missing (race harness unregistered)"]
    with open(path, encoding="utf-8") as f:
        src = f.read()
    problems: List[str] = []
    tree = ast.parse(src, filename="bench.py")
    fn = next(
        (n for n in tree.body
         if isinstance(n, ast.FunctionDef) and n.name == "bench_race"),
        None,
    )
    if fn is None:
        return ["bench.py: bench_race() is gone — the race harness must "
                "stay registered"]
    seg = ast.get_source_segment(src, fn) or ""
    for needle, why in (
        ("sys.setswitchinterval(1e-5)", "GIL-preemption amplifier"),
        ("LockOrderDetector", "lock-order instrumentation"),
        ("det.cycles()", "acyclicity gate"),
        ("self_deadlocks", "self-deadlock gate"),
        ("_nondaemon_threads", "thread-leak audit"),
    ):
        if needle not in seg:
            problems.append(
                f"bench.py:{fn.lineno}: bench_race() lost its "
                f"{why} ({needle!r} not found)"
            )
    if '"--race"' not in src or "args.race" not in src:
        problems.append(
            "bench.py: the --race flag is no longer wired to bench_race()"
        )
    return problems


def run_all() -> List[str]:
    """Every lint, one problem list; [] = clean. Problems are prefixed
    with their lint's name so a CI log line is self-locating."""
    from gpud_tpu.metrics.registry import DEFAULT_REGISTRY
    from gpud_tpu.tools import (
        boundary_lint,
        flow_lint,
        guard_lint,
        metrics_lint,
        parity_lint,
        schema_lint,
        storage_lint,
    )

    problems: List[str] = []
    metrics_lint.populate_default_registry()
    problems.extend(
        f"metrics: {p}" for p in metrics_lint.lint_registry(DEFAULT_REGISTRY)
    )
    problems.extend(f"storage: {p}" for p in storage_lint.run_lint())
    problems.extend(f"openapi: {p}" for p in openapi_parity_problems())
    problems.extend(f"guard: {p}" for p in guard_lint.run_lint())
    problems.extend(f"parity: {p}" for p in parity_lint.run_lint())
    problems.extend(f"race: {p}" for p in race_harness_problems())
    problems.extend(f"flow: {p}" for p in flow_lint.run_lint())
    problems.extend(f"boundary: {p}" for p in boundary_lint.run_lint())
    problems.extend(f"schema: {p}" for p in schema_lint.run_lint())
    return problems


# problems carry a "<lint>: <file>:<line>: <message>" shape when they
# anchor to a source line; lints that check cross-file invariants (e.g.
# openapi parity) omit the location
_PROBLEM_RE = re.compile(r"^(?P<lint>[a-z]+): (?:(?P<file>[^\s:]+\.(?:py|md|json))"
                         r"(?::(?P<line>\d+))?: )?(?P<message>.*)$", re.S)


def problems_as_json(problems: List[str]) -> List[Dict]:
    """Machine-readable problem list: lint name, file, line, message."""
    out: List[Dict] = []
    for p in problems:
        m = _PROBLEM_RE.match(p)
        if m is None:
            out.append({"lint": "", "file": None, "line": None, "message": p})
            continue
        out.append({
            "lint": m.group("lint"),
            "file": m.group("file"),
            "line": int(m.group("line")) if m.group("line") else None,
            "message": m.group("message"),
        })
    return out


def main(argv: List[str] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if "--update-goldens" in argv:
        from gpud_tpu.tools import schema_lint

        path, changed = schema_lint.update_golden()
        print(f"lint-all: {'updated' if changed else 'unchanged'} {path}")
        return 0
    as_json = "--json" in argv
    problems = run_all()
    if as_json:
        print(json.dumps(problems_as_json(problems), indent=2))
        return 1 if problems else 0
    for p in problems:
        print(f"lint-all: {p}", file=sys.stderr)
    if problems:
        print(f"lint-all: {len(problems)} problem(s)", file=sys.stderr)
        return 1
    print("lint-all: metrics + storage + openapi + guard + parity + "
          "race-wiring + flow + boundary + schema clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
