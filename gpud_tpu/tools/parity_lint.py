"""Parity lint: the standing review rules, mechanized.

Three parity contracts have until now lived only in review discipline
(docs/PARITY.md, the round-2/3 verdicts). Each is cheap to check
syntactically and expensive to discover broken at runtime, so this lint
pins them:

1. **Config knobs** — every ``Config`` dataclass field must be
   *referenced* outside config.py (a knob nothing reads is a dead knob:
   operators set it and nothing changes), *documented* (its name appears
   in docs/ or the README), and — for numeric knobs — *validated* (a
   range check in ``Config.validate()``; a typo'd negative interval must
   die at startup, not wedge a scheduler job).
2. **Session dispatcher** — every ``_m_*`` method must have at least one
   row in the dispatcher error matrix (tests/test_dispatch_error_matrix
   .py) and a declared SDK disposition in ``DISPATCH_TO_SDK`` below:
   either the ``client/v1.py`` method that fronts it, or ``None`` with a
   reason (control-plane-only verbs have no SDK surface by design). The
   mapping must cover the method set exactly — a new dispatch method
   fails the lint until its SDK story is stated.
3. **HTTP routes** — every registered ``/v1/*`` path in server/app.py
   AND in the manager's control_plane.py must appear in the HTTP route
   matrix (tests/test_http_route_matrix.py), so a new route ships with
   at least one method/shape row.

Run: ``python -m gpud_tpu.tools.parity_lint`` (exit 1 on any problem);
registered in ``tools/lint_all.py`` so tier-1 enforces it.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Optional, Set, Tuple

CONFIG_MODULE = "gpud_tpu/config.py"
DISPATCH_MODULE = "gpud_tpu/session/dispatch.py"
SDK_MODULE = "gpud_tpu/client/v1.py"
APP_MODULE = "gpud_tpu/server/app.py"
MANAGER_MODULE = "gpud_tpu/manager/control_plane.py"
DISPATCH_MATRIX_TEST = "tests/test_dispatch_error_matrix.py"
ROUTE_MATRIX_TEST = "tests/test_http_route_matrix.py"

# Dispatcher method -> SDK method in client/v1.py, or None + reason.
# This table IS the parity statement: every dispatch verb either has a
# public SDK front door or an explicit "control-plane only" rationale.
DISPATCH_TO_SDK: Dict[str, Tuple[Optional[str], str]] = {
    "states": ("get_health_states", ""),
    "events": ("get_events", ""),
    "stateHistory": ("get_state_history", ""),
    "predictStatus": ("get_predict_scores", ""),
    "predictCalibration": ("get_predict_calibration", ""),
    "fabricStatus": ("get_fabric", ""),
    "remediationStatus": ("get_remediation_audit", ""),
    "remediationPolicy": ("get_remediation_policy", ""),
    "metrics": ("get_metrics", ""),
    "traces": (None, "node debug ring is /v1/debug/traces; the SDK "
                     "fronts the correlated manager view (get_fleet_traces)"),
    "gossip": (None, "session keep-alive frame; never operator-initiated"),
    "diagnostic": (None, "control-plane remote diagnostics channel"),
    "reboot": (None, "control-plane remediation verb; deliberately no "
                     "local SDK front door"),
    "setHealthy": ("set_healthy", ""),
    "triggerComponent": ("trigger_check", ""),
    "deregisterComponent": ("deregister_component", ""),
    "injectFault": ("inject_fault", ""),
    "chaosRun": ("run_chaos", ""),
    "chaosStatus": ("get_chaos_campaigns", ""),
    "outboxAck": (None, "manager->agent delivery ack; internal to the "
                        "at-least-once session protocol"),
    "outboxStatus": ("get_session_status", ""),
    "peerStatus": (None, "agent-side failover introspection over the "
                         "session channel; the operator pane is the "
                         "manager's GET /v1/fleet/peers (SDK "
                         "get_fleet_peers)"),
    "bootstrap": (None, "control-plane provisioning script channel"),
    "updateConfig": (None, "control-plane config push"),
    "updateToken": (None, "enrollment rotation; control-plane only"),
    "getToken": (None, "enrollment introspection; control-plane only"),
    "logout": (None, "machine lifecycle verb; control-plane only"),
    "delete": (None, "machine lifecycle verb; control-plane only"),
    "packageStatus": (None, "package manager status; served locally via "
                            "/admin/packages, no typed SDK call"),
    "update": (None, "self-update trigger; control-plane only"),
    "kapMTLSStatus": (None, "credential-plane status; control-plane only"),
    "kapMTLSUpdateCredentials": (None, "credential rotation; control-plane "
                                       "only"),
    "kapMTLSActivate": (None, "credential activation; control-plane only"),
    "getPluginSpecs": (None, "plugin spec sync; local read is /v1/plugins"),
    "setPluginSpecs": (None, "plugin spec push; control-plane only"),
}

# Non-numeric knobs (bool/str/list/dict) carry no range to validate;
# numeric knobs get no such pass.
_NUMERIC_ANNOTATIONS = {"int", "float"}


def _repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _read(root: str, rel: str) -> str:
    with open(os.path.join(root, rel), encoding="utf-8") as f:
        return f.read()


def _class_methods(tree: ast.Module, prefix: str = "") -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if (isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and item.name.startswith(prefix)):
                    out.add(item.name)
    return out


# -- 1. config knobs ---------------------------------------------------------

def config_problems(root: str) -> List[str]:
    src = _read(root, CONFIG_MODULE)
    tree = ast.parse(src, filename=CONFIG_MODULE)
    cls = next(
        (n for n in tree.body
         if isinstance(n, ast.ClassDef) and n.name == "Config"),
        None,
    )
    if cls is None:
        return [f"{CONFIG_MODULE}: no Config dataclass found"]
    fields: List[Tuple[str, int, str]] = []  # (name, line, annotation)
    validate_fn = None
    for stmt in cls.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            ann = stmt.annotation
            ann_name = ann.id if isinstance(ann, ast.Name) else ""
            fields.append((stmt.target.id, stmt.lineno, ann_name))
        elif isinstance(stmt, ast.FunctionDef) and stmt.name == "validate":
            validate_fn = stmt
    problems: List[str] = []
    if validate_fn is None:
        return [f"{CONFIG_MODULE}: Config has no validate() method"]
    validated: Set[str] = {
        n.attr for n in ast.walk(validate_fn)
        if isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name)
        and n.value.id == "self"
    }

    # one pass over the rest of the tree for reference detection
    code_blob: List[str] = []
    for sub, _dirs, files in os.walk(os.path.join(root, "gpud_tpu")):
        for fn in files:
            if fn.endswith(".py"):
                path = os.path.join(sub, fn)
                if os.path.relpath(path, root) == CONFIG_MODULE:
                    continue
                with open(path, encoding="utf-8") as f:
                    code_blob.append(f.read())
    for extra in ("bench.py",):
        p = os.path.join(root, extra)
        if os.path.isfile(p):
            with open(p, encoding="utf-8") as f:
                code_blob.append(f.read())
    for sub, _dirs, files in os.walk(os.path.join(root, "tests")):
        for fn in files:
            if fn.endswith(".py"):
                with open(os.path.join(sub, fn), encoding="utf-8") as f:
                    code_blob.append(f.read())
    code = "\n".join(code_blob)

    docs_blob: List[str] = []
    docs_dir = os.path.join(root, "docs")
    if os.path.isdir(docs_dir):
        for fn in os.listdir(docs_dir):
            if fn.endswith(".md"):
                with open(os.path.join(docs_dir, fn), encoding="utf-8") as f:
                    docs_blob.append(f.read())
    readme = os.path.join(root, "README.md")
    if os.path.isfile(readme):
        with open(readme, encoding="utf-8") as f:
            docs_blob.append(f.read())
    docs = "\n".join(docs_blob)

    for name, line, ann in fields:
        if not re.search(rf"\b{re.escape(name)}\b", code):
            problems.append(
                f"{CONFIG_MODULE}:{line}: Config.{name} is a dead knob — "
                "nothing outside config.py references it"
            )
        if not re.search(rf"\b{re.escape(name)}\b", docs):
            problems.append(
                f"{CONFIG_MODULE}:{line}: Config.{name} is undocumented — "
                "name it in docs/*.md or README.md (docs/config.md is the "
                "knob reference)"
            )
        if ann in _NUMERIC_ANNOTATIONS and name not in validated:
            problems.append(
                f"{CONFIG_MODULE}:{line}: Config.{name} is numeric but "
                "validate() never range-checks it — a typo'd value must "
                "die at startup"
            )
    return problems


# -- 2. dispatcher matrix + SDK parity ---------------------------------------

def dispatch_problems(root: str) -> List[str]:
    tree = ast.parse(_read(root, DISPATCH_MODULE), filename=DISPATCH_MODULE)
    methods = {
        name[len("_m_"):] for name in _class_methods(tree, prefix="_m_")
    }
    if not methods:
        return [f"{DISPATCH_MODULE}: no _m_* dispatch methods found"]
    problems: List[str] = []

    # matrix coverage
    mtree = ast.parse(
        _read(root, DISPATCH_MATRIX_TEST), filename=DISPATCH_MATRIX_TEST
    )
    covered: Set[str] = set()
    matrix_line = 0
    for node in mtree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "MATRIX" for t in node.targets
        ):
            matrix_line = node.lineno
            # rows hold non-literal params (float("nan")) — read only the
            # leading method-name constant of each tuple
            if isinstance(node.value, (ast.List, ast.Tuple)):
                for row in node.value.elts:
                    if (isinstance(row, ast.Tuple) and row.elts
                            and isinstance(row.elts[0], ast.Constant)
                            and isinstance(row.elts[0].value, str)):
                        covered.add(row.elts[0].value)
    if not covered:
        problems.append(
            f"{DISPATCH_MATRIX_TEST}: MATRIX literal missing or unparsable"
        )
    for m in sorted(methods - covered):
        problems.append(
            f"{DISPATCH_MATRIX_TEST}:{matrix_line}: dispatch method "
            f"{m!r} has no error-matrix row"
        )
    for m in sorted(covered - methods):
        problems.append(
            f"{DISPATCH_MATRIX_TEST}:{matrix_line}: matrix row for "
            f"{m!r} names no existing dispatch method (stale row)"
        )

    # SDK disposition
    sdk_tree = ast.parse(_read(root, SDK_MODULE), filename=SDK_MODULE)
    sdk_methods = _class_methods(sdk_tree)
    for m in sorted(methods - set(DISPATCH_TO_SDK)):
        problems.append(
            f"{DISPATCH_MODULE}: dispatch method {m!r} has no entry in "
            "parity_lint.DISPATCH_TO_SDK — state its SDK front door or "
            "waive it with a reason"
        )
    for m in sorted(set(DISPATCH_TO_SDK) - methods):
        problems.append(
            f"DISPATCH_TO_SDK names {m!r} but dispatch.py defines no "
            f"_m_{m} (stale mapping)"
        )
    for m, (sdk, reason) in sorted(DISPATCH_TO_SDK.items()):
        if sdk is None:
            if not reason.strip():
                problems.append(
                    f"DISPATCH_TO_SDK[{m!r}] waives the SDK counterpart "
                    "without a reason"
                )
        elif sdk not in sdk_methods:
            problems.append(
                f"DISPATCH_TO_SDK[{m!r}] names client method {sdk!r} "
                f"but {SDK_MODULE} defines no such method"
            )
    return problems


# -- 3. /v1 route matrix ------------------------------------------------------

def _module_routes(root: str, module: str) -> List[Tuple[str, str, int]]:
    tree = ast.parse(_read(root, module), filename=module)
    routes: List[Tuple[str, str, int]] = []  # (method, path, line)
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("add_get", "add_post", "add_delete",
                                       "add_put", "add_patch")
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            path = node.args[0].value
            if path.startswith("/v1/"):
                routes.append(
                    (node.func.attr[len("add_"):].upper(), path, node.lineno)
                )
    return routes


def route_problems(root: str) -> List[str]:
    matrix_src = _read(root, ROUTE_MATRIX_TEST)
    problems: List[str] = []
    for module in (APP_MODULE, MANAGER_MODULE):
        # the agent app is the lint's anchor and must exist; the manager
        # module is optional so the synthetic fixture trees the lint's
        # own tests build (agent app only) stay valid inputs
        if (module is not APP_MODULE
                and not os.path.isfile(os.path.join(root, module))):
            continue
        routes = _module_routes(root, module)
        if not routes:
            problems.append(
                f"{module}: no /v1/* routes found (parser drift?)"
            )
            continue
        for method, path, line in sorted(routes):
            if path not in matrix_src:
                problems.append(
                    f"{module}:{line}: {method} {path} has no row in "
                    f"{ROUTE_MATRIX_TEST}"
                )
    return problems


def run_lint(root: str = "") -> List[str]:
    """One problem string per violation; [] = clean."""
    root = root or _repo_root()
    problems: List[str] = []
    problems.extend(config_problems(root))
    problems.extend(dispatch_problems(root))
    problems.extend(route_problems(root))
    return problems


def main() -> int:
    problems = run_lint()
    for p in problems:
        print(f"parity-lint: {p}", file=sys.stderr)
    if problems:
        print(f"parity-lint: {len(problems)} problem(s)", file=sys.stderr)
        return 1
    print("parity-lint: config knobs + dispatcher matrix/SDK + /v1 routes clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
