"""Boundary lint: payloads crossing process-shaped seams must be
serialization-safe.

Three seams in this codebase are *process boundaries in waiting*:

- ``outbox.publish(kind, payload, ...)`` — the payload is journaled,
  wire-encoded (msgpack/JSON via ``session.wire``), delta-framed, and
  replayed on the manager from the journal alone;
- ``Frame(data=...)`` — frame data goes straight onto a socket;
- ``ingest_executor.submit(id, closure)`` — today the closure hops to a
  shard worker *thread*; ROADMAP item 2 moves shard executors out of
  process, at which point anything the closure drags along must pickle.

Today the GIL and shared address space make violations invisible: a
``threading.Lock`` smuggled inside a payload dict round-trips fine
through a thread handoff and only explodes when the boundary becomes a
real socket or a real ``fork``. This lint makes the seam's contract
lexical, so the multiprocess cut-over is a mechanical change rather
than an archaeology project:

- payload/data expressions must not *be* or *contain* unserializable
  AST shapes — ``lambda``, ``set`` literals/comprehensions, generator
  expressions (msgpack has no set type; generators and lambdas don't
  pickle);
- identifiers inside a payload expression (and inside a submitted
  closure's body) must not match the deny list of runtime-resource
  names — locks (``_mu``/``_lock``/``_cv``/``_cond*``), threads,
  sqlite handles (``db``/``conn*``), sockets, the BatchWriter — the
  things that are meaningful only in the sending process. Method
  *calls* through ``self`` are fine (they become dispatch on the far
  side); it is carrying the raw resource that is flagged.

Like every lint here the check is lexical and under-approximate: a
variable whose *value* is a set sails through. The seams it guards are
written in a literal style (dict literals of scalars, ``wire.*`` calls,
one lambda in ``AgentHandle.resolve``), so the lexical contract is the
real contract.

Waivers: ``WAIVERS[(rel, line-qualifier, pattern)] = reason`` with the
guard_lint conventions (non-empty reason, stale = error, ``until:
PR-N`` expiry).

Run: ``python -m gpud_tpu.tools.boundary_lint``; registered in
``tools/lint_all.py`` so tier-1 enforces it.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

from gpud_tpu.tools.guard_lint import _repo_root, waiver_reason_problems

# modules containing boundary call sites — keep in sync when a new
# publisher/shipper appears (a listed module with zero sites is an error
# so the list cannot silently rot)
BOUNDARY_MODULES = (
    "gpud_tpu/manager/control_plane.py",
    "gpud_tpu/manager/federation.py",
    "gpud_tpu/server/server.py",
    "gpud_tpu/session/dispatch.py",
    "gpud_tpu/session/outbox.py",
    "gpud_tpu/session/session.py",
    "gpud_tpu/session/v2/client.py",
)

# identifiers that name in-process runtime resources; carrying one
# across a serialization seam is the bug this lint exists for
_DENY_RE = re.compile(
    r"(?:^|_)(?:mu|lock|locks|cv|cond|conds|thread|threads|db|conn|"
    r"connection|cursor|sock|socket|writer|pool|executor|session)\d*$"
)

# AST node kinds msgpack/pickle cannot carry
_UNSAFE_NODES = (ast.Lambda, ast.Set, ast.SetComp, ast.GeneratorExp)

# (rel, f"{site}@{name}", offender) -> reason; offender "*" waives the
# whole site. `site` is "publish" | "frame" | "submit-closure"; `name`
# is the enclosing function name.
WAIVERS: Dict[Tuple[str, str, str], str] = {
    # the current tree is clean — the seams pass dict literals of
    # scalars, pre-encoded bytes, and one enqueue-only lambda
}


class _SiteScanner(ast.NodeVisitor):
    """Finds boundary call sites in one module and checks their payload
    expressions."""

    def __init__(self, rel: str) -> None:
        self.rel = rel
        self.sites: List[Tuple[str, str, int, ast.expr]] = []
        # executor-locals: names assigned from an ingest_executor attr
        self._exec_names: set = set()
        self._func: List[str] = ["<module>"]

    # -- helpers -----------------------------------------------------------
    def _enclosing(self) -> str:
        return self._func[-1]

    def visit_FunctionDef(self, node) -> None:
        self._func.append(node.name)
        self.generic_visit(node)
        self._func.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node: ast.Assign) -> None:
        if (len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr == "ingest_executor"):
            self._exec_names.add(node.targets[0].id)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr == "publish" and node.args:
                # publish(kind, payload, **meta): payload + every kwarg
                # value is journaled
                for expr in node.args[1:] + [kw.value for kw in node.keywords]:
                    self.sites.append(
                        ("publish", self._enclosing(), node.lineno, expr)
                    )
            elif func.attr == "submit" and self._is_executor(func.value):
                for expr in node.args[1:]:
                    if isinstance(expr, ast.Lambda):
                        self.sites.append(
                            ("submit-closure", self._enclosing(),
                             node.lineno, expr.body)
                        )
                    else:
                        self.sites.append(
                            ("submit-closure", self._enclosing(),
                             node.lineno, expr)
                        )
        if (isinstance(func, ast.Name) and func.id == "Frame") or (
                isinstance(func, ast.Attribute) and func.attr == "Frame"):
            for expr in list(node.args) + [
                kw.value for kw in node.keywords if kw.arg in (None, "data")
            ]:
                self.sites.append(
                    ("frame", self._enclosing(), node.lineno, expr)
                )
        self.generic_visit(node)

    def _is_executor(self, recv: ast.expr) -> bool:
        if isinstance(recv, ast.Name):
            return recv.id in self._exec_names
        if isinstance(recv, ast.Attribute):
            return recv.attr == "ingest_executor"
        return False


def _offenders(expr: ast.expr) -> List[Tuple[int, str]]:
    """(line, offender) pairs for unserializable content in ``expr``."""
    out: List[Tuple[int, str]] = []
    for n in ast.walk(expr):
        if isinstance(n, _UNSAFE_NODES):
            kind = type(n).__name__
            out.append((
                getattr(n, "lineno", 0),
                {"Lambda": "a lambda", "Set": "a set literal",
                 "SetComp": "a set comprehension",
                 "GeneratorExp": "a generator expression"}[kind],
            ))
        elif isinstance(n, ast.Attribute) and _DENY_RE.search(n.attr):
            # self.method(...) is dispatch, not a carried resource
            if not _is_called(expr, n):
                out.append((n.lineno, n.attr))
        elif isinstance(n, ast.Name) and _DENY_RE.search(n.id):
            if not _is_called(expr, n):
                out.append((n.lineno, n.id))
    return out


def _is_called(root: ast.expr, node: ast.AST) -> bool:
    """True when ``node`` is the func of some Call in ``root`` (method
    dispatch through a deny-named receiver is allowed; carrying the
    receiver itself is not)."""
    for n in ast.walk(root):
        if isinstance(n, ast.Call) and n.func is node:
            return True
    return False


def lint_module(path: str, rel: str) -> Tuple[List[str], List[Tuple], int]:
    """(problems, flagged site keys, total sites) for one module.
    Flagged keys are pre-waiver so the caller can match waivers."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=rel)
    scanner = _SiteScanner(rel)
    scanner.visit(tree)
    problems: List[str] = []
    flagged: List[Tuple] = []
    for site, fname, line, expr in scanner.sites:
        for off_line, offender in _offenders(expr):
            flagged.append((rel, f"{site}@{fname}", offender,
                            off_line or line))
    return problems, flagged, len(scanner.sites)


def run_full(root: str = "",
             waivers: Optional[Dict] = None) -> Tuple[List[str], List[str]]:
    """(problems, waiver notes) across BOUNDARY_MODULES; ([], _) = clean."""
    root = root or _repo_root()
    waivers = WAIVERS if waivers is None else waivers
    problems: List[str] = []
    notes: List[str] = []
    used: set = set()
    for rel in BOUNDARY_MODULES:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            problems.append(f"{rel}: boundary module missing")
            continue
        p, flagged, n_sites = lint_module(path, rel)
        problems.extend(p)
        if n_sites == 0:
            problems.append(
                f"{rel}: listed in BOUNDARY_MODULES but has no publish/"
                "Frame/ingest-submit site — remove it or the seam moved"
            )
        for rel_, key, offender, line in flagged:
            wkey = None
            for cand in ((rel_, key, offender), (rel_, key, "*")):
                if cand in waivers:
                    wkey = cand
                    break
            if wkey is not None:
                used.add(wkey)
                continue
            site = key.split("@")[0]
            problems.append(
                f"{rel_}:{line}: {key} payload carries {offender!r} across "
                f"the {site} serialization boundary — not msgpack-safe / "
                "journal-derivable"
            )
    for wkey, reason in sorted(waivers.items()):
        rel_ = wkey[0]
        problems.extend(
            f"{rel_}: boundary waiver {wkey}: {p}"
            for p in waiver_reason_problems(reason, root=root)
        )
        if wkey not in used:
            problems.append(
                f"{rel_}: boundary waiver {wkey} matches no flagged site "
                "(stale waiver — remove it)"
            )
        else:
            notes.append(f"{wkey[1]} ({wkey[2]}) in {rel_} — {reason}")
    return problems, notes


def run_lint(root: str = "") -> List[str]:
    return run_full(root)[0]


def main() -> int:
    problems, notes = run_full()
    for n in notes:
        print(f"boundary-lint: waived {n}")
    for p in problems:
        print(f"boundary-lint: {p}", file=sys.stderr)
    if problems:
        print(f"boundary-lint: {len(problems)} problem(s)", file=sys.stderr)
        return 1
    print(
        f"boundary-lint: {len(BOUNDARY_MODULES)} module(s) clean, "
        f"{len(notes)} justified waiver(s)"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
