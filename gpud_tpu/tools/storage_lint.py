"""Storage-path lint: hot-path writes must ride the write-behind layer.

Every store that migrated onto the batching ``BatchWriter``
(gpud_tpu/storage/writer.py) declares its ingest entry points in a
module-level ``HOT_WRITE_METHODS`` tuple. This lint parses those modules
and enforces, per declared method:

  - the method actually exists on some class in the module (a stale
    marker is a lint error, not dead metadata), and
  - it submits through the writer (``*.submit``/``submit_many``), and
  - every direct ``db.execute()``/``db.executemany()`` inside it sits
    under an ``if`` whose test mentions ``writer`` — i.e. it is the
    explicit synchronous fallback for writer-less construction (tests,
    tools), never an unconditional hot-path commit.

The rule is deliberately syntactic: a per-row ``db.execute()`` on the
ingest path costs one implicit transaction + fsync per observation and
is exactly the pattern the write-behind layer exists to remove. Read
paths, purges, and schema setup are untouched — only the declared hot
write methods are scanned.

The store modules are pinned in ``STORE_MODULES``: a store that
drops its ``HOT_WRITE_METHODS`` declaration (or a new store added to the
list without one) fails the lint, so "all stores write through the
shared layer" stays true by construction. Runs in CI via
``tests/test_storage_writer.py`` and standalone:

    python -m gpud_tpu.tools.storage_lint
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Tuple

# repo-relative paths of every module that owns a SQLite-backed store's
# ingest path — keep in sync when a new store appears
STORE_MODULES = (
    "gpud_tpu/eventstore.py",
    "gpud_tpu/health_history.py",
    "gpud_tpu/manager/federation.py",
    "gpud_tpu/manager/rollup.py",
    "gpud_tpu/metrics/store.py",
    "gpud_tpu/remediation/audit.py",
    "gpud_tpu/session/outbox.py",
)

_EXEC_ATTRS = ("execute", "executemany")


def _repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _hot_methods(tree: ast.Module) -> Tuple[str, ...]:
    """The module-level HOT_WRITE_METHODS tuple, or () when absent."""
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name) and tgt.id == "HOT_WRITE_METHODS":
                try:
                    val = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    return ()
                if isinstance(val, (tuple, list)):
                    return tuple(str(v) for v in val)
    return ()


def _is_db_execute(call: ast.Call) -> bool:
    """True for ``<something>.db.execute*`` / ``db.execute*`` calls."""
    fn = call.func
    if not (isinstance(fn, ast.Attribute) and fn.attr in _EXEC_ATTRS):
        return False
    base = fn.value
    if isinstance(base, ast.Name):
        return base.id in ("db", "_db")
    if isinstance(base, ast.Attribute):
        return base.attr in ("db", "_db")
    return False


def _mentions_writer(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and "writer" in sub.id:
            return True
        if isinstance(sub, ast.Attribute) and "writer" in sub.attr:
            return True
    return False


def _scan_method(path: str, cls: str, fn: ast.FunctionDef) -> List[str]:
    problems: List[str] = []
    submits = False
    # (node, guarded) work stack: guarded flips True once we descend into
    # any If whose test involves the writer — that branch IS the declared
    # synchronous fallback
    stack: List[Tuple[ast.AST, bool]] = [(s, False) for s in fn.body]
    while stack:
        node, guarded = stack.pop()
        if isinstance(node, ast.Call):
            fname = node.func
            if (isinstance(fname, ast.Attribute)
                    and fname.attr in ("submit", "submit_many")):
                submits = True
            if _is_db_execute(node) and not guarded:
                problems.append(
                    f"{path}:{node.lineno}: {cls}.{fn.name}() commits "
                    "per-row via db.execute* outside a writer-presence "
                    "branch — hot-path writes go through the batch writer"
                )
        child_guard = guarded
        if isinstance(node, ast.If) and _mentions_writer(node.test):
            child_guard = True
        for child in ast.iter_child_nodes(node):
            stack.append((child, child_guard))
    if not submits:
        problems.append(
            f"{path}: {cls}.{fn.name}() is declared in HOT_WRITE_METHODS "
            "but never submits to the batch writer"
        )
    return problems


def lint_module(path: str, rel: str) -> List[str]:
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=rel)
    hot = _hot_methods(tree)
    if not hot:
        return [
            f"{rel}: store module declares no HOT_WRITE_METHODS — every "
            "SQLite-backed store must mark its ingest entry points"
        ]
    problems: List[str] = []
    found: Dict[str, bool] = {name: False for name in hot}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for item in node.body:
            if isinstance(item, ast.FunctionDef) and item.name in found:
                found[item.name] = True
                problems.extend(_scan_method(rel, node.name, item))
    for name, ok in found.items():
        if not ok:
            problems.append(
                f"{rel}: HOT_WRITE_METHODS names {name!r} but no class "
                "defines it (stale marker)"
            )
    return problems


def run_lint(root: str = "") -> List[str]:
    """One problem string per violation across STORE_MODULES; [] = clean."""
    root = root or _repo_root()
    problems: List[str] = []
    for rel in STORE_MODULES:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            problems.append(f"{rel}: store module missing")
            continue
        problems.extend(lint_module(path, rel))
    return problems


def main() -> int:
    problems = run_lint()
    for p in problems:
        print(f"storage-lint: {p}", file=sys.stderr)
    if problems:
        print(f"storage-lint: {len(problems)} problem(s)", file=sys.stderr)
        return 1
    print(f"storage-lint: {len(STORE_MODULES)} store module(s) clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
