"""Pure-Python renderer for tpud's helm chart (no helm binary needed).

Closes the "helm chart unverified" gap (round-2 verdict, Weak #4): the
sandbox/CI image has no helm, so this renders the chart's Go-template
subset well enough to YAML-parse the result and assert the shape — and
doubles as an operator sanity tool:

    python -m gpud_tpu.tools.helm_render deployments/helm/tpud \\
        --set controlPlane.endpoint=https://cp --name myrelease

Supported template subset (the chart is deliberately kept within it; the
sync test fails loudly on anything else):
- ``{{ .Values.a.b }}`` / ``{{ .Release.Name }}`` / ``{{ . }}`` lookups
- ``{{- if PIPELINE }} ... {{- end }}`` (Go truthiness)
- ``{{- with PIPELINE }} ... {{- end }}`` (rebinds dot)
- ``{{- range PIPELINE }} ... {{- end }}`` (rebinds dot per element)
- ``{{ include "name" . }}`` of ``{{- define "name" -}}`` helpers
- pipe functions: default, quote, toYaml, nindent, indent, trunc,
  trimSuffix, printf (%s only)
- ``{{-``/``-}}`` whitespace trimming
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

import yaml

_ACTION = re.compile(r"\{\{-?\s*(.*?)\s*-?\}\}", re.DOTALL)


class TemplateError(Exception):
    pass


def _tokenize(src: str) -> List[Tuple[str, str]]:
    """[(kind, payload)] where kind is 'text' or 'action'; whitespace
    trimming for {{- and -}} is applied to the adjacent text tokens."""
    out: List[Tuple[str, str]] = []
    pos = 0
    for m in _ACTION.finditer(src):
        text = src[pos : m.start()]
        if m.group(0).startswith("{{-"):
            text = text.rstrip(" \t")
            if text.endswith("\n"):
                text = text[:-1]
        out.append(("text", text))
        out.append(("action", m.group(1).strip()))
        pos = m.end()
        if m.group(0).endswith("-}}"):
            rest = src[pos:]
            stripped = rest.lstrip(" \t")
            if stripped.startswith("\n"):
                stripped = stripped[1:]
            pos = len(src) - len(stripped)
    out.append(("text", src[pos:]))
    return out


# -- pipeline evaluation ----------------------------------------------------

def _truthy(v: Any) -> bool:
    return bool(v)


def _lookup(path: str, ctx: Dict[str, Any], dot: Any) -> Any:
    if path == ".":
        return dot
    cur: Any = ctx if path.startswith(".Values") or path.startswith(".Release") or path.startswith(".Chart") else dot
    for part in path.lstrip(".").split("."):
        if part == "":
            continue
        if isinstance(cur, dict):
            cur = cur.get(part)
        else:
            cur = getattr(cur, part, None)
        if cur is None:
            return None
    return cur


def _to_yaml(v: Any) -> str:
    return yaml.safe_dump(v, default_flow_style=False, sort_keys=False).rstrip("\n")


def _split_args(s: str) -> List[str]:
    """Split on spaces, respecting double-quoted strings."""
    return re.findall(r'"[^"]*"|\S+', s)


def _eval_term(term: str, ctx: Dict[str, Any], dot: Any, defines: Dict[str, str]) -> Any:
    args = _split_args(term)
    head = args[0]
    if head.startswith('"') and head.endswith('"'):
        return head[1:-1]
    if head == "include":
        name = args[1].strip('"')
        body = defines.get(name)
        if body is None:
            raise TemplateError(f"include of undefined template {name!r}")
        sub_dot = _lookup(args[2], ctx, dot) if len(args) > 2 and args[2] != "." else dot
        return _render(body, ctx, sub_dot, defines)
    if head == "toYaml":
        return _to_yaml(_eval_term(args[1], ctx, dot, defines))
    if head == "printf":
        fmt = args[1].strip('"')
        vals = [_eval_term(a, ctx, dot, defines) for a in args[2:]]
        return fmt.replace("%s", "{}").format(*vals)
    if head.startswith("."):
        return _lookup(head, ctx, dot)
    raise TemplateError(f"unsupported term {term!r}")


def _eval_pipeline(expr: str, ctx: Dict[str, Any], dot: Any, defines: Dict[str, str]) -> Any:
    stages = [s.strip() for s in expr.split("|")]
    val = _eval_term(stages[0], ctx, dot, defines)
    for stage in stages[1:]:
        args = _split_args(stage)
        fn = args[0]
        if fn == "default":
            dflt = args[1].strip('"')
            val = val if _truthy(val) else dflt
        elif fn == "quote":
            val = f'"{val}"'
        elif fn == "toYaml":
            val = _to_yaml(val)
        elif fn in ("nindent", "indent"):
            n = int(args[1])
            pad = " " * n
            val = "\n".join(pad + ln for ln in str(val).splitlines())
            if fn == "nindent":
                val = "\n" + val
        elif fn == "trunc":
            val = str(val)[: int(args[1])]
        elif fn == "trimSuffix":
            sfx = args[1].strip('"')
            val = str(val)
            if val.endswith(sfx):
                val = val[: -len(sfx)]
        else:
            raise TemplateError(f"unsupported pipe function {fn!r}")
    return val


# -- block-structured rendering ---------------------------------------------

def _find_block_end(tokens: List[Tuple[str, str]], start: int) -> int:
    """Index of the matching `end` for the block opened at tokens[start]."""
    depth = 1
    i = start + 1
    while i < len(tokens):
        kind, payload = tokens[i]
        if kind == "action":
            word = payload.split(None, 1)[0] if payload else ""
            if word in ("if", "with", "range", "define"):
                depth += 1
            elif word == "end":
                depth -= 1
                if depth == 0:
                    return i
        i += 1
    raise TemplateError("unbalanced block: missing {{ end }}")


def _render_tokens(
    tokens: List[Tuple[str, str]],
    ctx: Dict[str, Any],
    dot: Any,
    defines: Dict[str, str],
) -> str:
    out: List[str] = []
    i = 0
    while i < len(tokens):
        kind, payload = tokens[i]
        if kind == "text":
            out.append(payload)
            i += 1
            continue
        word = payload.split(None, 1)[0] if payload else ""
        if word in ("if", "with", "range"):
            expr = payload[len(word) :].strip()
            end = _find_block_end(tokens, i)
            body = tokens[i + 1 : end]
            val = _eval_pipeline(expr, ctx, dot, defines)
            if word == "if":
                if _truthy(val):
                    out.append(_render_tokens(body, ctx, dot, defines))
            elif word == "with":
                if _truthy(val):
                    out.append(_render_tokens(body, ctx, val, defines))
            else:  # range
                for item in val or []:
                    out.append(_render_tokens(body, ctx, item, defines))
            i = end + 1
        elif word == "define":
            # handled during preprocessing; skip the whole block here
            i = _find_block_end(tokens, i) + 1
        elif word == "end":
            raise TemplateError("unexpected {{ end }}")
        else:
            val = _eval_pipeline(payload, ctx, dot, defines)
            out.append("" if val is None else str(val))
            i += 1
    return "".join(out)


def _render(src: str, ctx: Dict[str, Any], dot: Any, defines: Dict[str, str]) -> str:
    return _render_tokens(_tokenize(src), ctx, dot, defines)


def _collect_defines(src: str, defines: Dict[str, str]) -> None:
    tokens = _tokenize(src)
    i = 0
    while i < len(tokens):
        kind, payload = tokens[i]
        if kind == "action" and payload.startswith("define"):
            name = payload.split(None, 1)[1].strip().strip('"')
            end = _find_block_end(tokens, i)
            # re-serialize the body tokens back to template source
            body: List[str] = []
            for k, p in tokens[i + 1 : end]:
                body.append(p if k == "text" else "{{ " + p + " }}")
            defines[name] = "".join(body)
            i = end + 1
        else:
            i += 1


def render_chart(
    chart_dir: str,
    release_name: str = "tpud",
    overrides: Optional[Dict[str, str]] = None,
) -> Dict[str, str]:
    """Render every template in the chart → {filename: rendered YAML}."""
    with open(os.path.join(chart_dir, "values.yaml"), "r", encoding="utf-8") as f:
        values = yaml.safe_load(f) or {}
    with open(os.path.join(chart_dir, "Chart.yaml"), "r", encoding="utf-8") as f:
        chart = yaml.safe_load(f) or {}
    for key, val in (overrides or {}).items():
        cur = values
        parts = key.split(".")
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = yaml.safe_load(val)

    ctx = {
        "Values": values,
        "Release": {"Name": release_name, "Namespace": "default"},
        "Chart": chart,
    }
    tmpl_dir = os.path.join(chart_dir, "templates")
    defines: Dict[str, str] = {}
    sources: Dict[str, str] = {}
    for name in sorted(os.listdir(tmpl_dir)):
        with open(os.path.join(tmpl_dir, name), "r", encoding="utf-8") as f:
            src = f.read()
        _collect_defines(src, defines)
        if not name.endswith(".tpl"):
            sources[name] = src
    return {
        name: _render(src, ctx, ctx, defines) for name, src in sources.items()
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("chart_dir")
    ap.add_argument("--name", default="tpud", help="release name")
    ap.add_argument(
        "--set", action="append", default=[], metavar="KEY=VALUE",
        help="override a values key (dotted path)",
    )
    args = ap.parse_args(argv)
    overrides = dict(s.split("=", 1) for s in args.set)
    try:
        rendered = render_chart(args.chart_dir, args.name, overrides)
        # validate BEFORE printing so a template typo yields the clean
        # failure message, not partial output plus a traceback
        for name, body in rendered.items():
            list(yaml.safe_load_all(body))  # multi-document templates ok
    except (TemplateError, OSError, yaml.YAMLError) as e:
        print(f"render failed: {e}", file=sys.stderr)
        return 1
    for name, body in rendered.items():
        print(f"---\n# Source: {name}")
        print(body)
    return 0


if __name__ == "__main__":
    sys.exit(main())
