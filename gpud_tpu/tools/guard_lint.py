"""Guarded-by lint: threaded state must be touched under its lock.

The reference daemon gets this check for free from ``go test -race``;
CPython's GIL hides the same bugs until a preemption lands between a
check and an act. This lint is the static half of the port's answer
(the dynamic half is ``bench.py --race``): every threaded class in the
modules pinned below declares a clang-thread-safety-style mapping

    GUARDED_BY = {"_agents": "_lock", "_pending": "_cv", ...}

from attribute name to the ``self.<lock>`` that guards it, and the lint
verifies every read or mutation of a guarded attribute occurs lexically
inside a ``with self.<lock>:`` block. Three escape hatches, all
deliberate and all visible in the report:

  - ``__init__`` is always exempt — the object is pre-publication and
    no other thread can hold a reference yet.
  - methods named ``*_locked`` are exempt — the suffix is the repo's
    standing caller-holds-the-lock convention, and the lint checks the
    *callers* instead.
  - a class may declare ``_LOCK_FREE = {"method": "reason"}``; waived
    methods are skipped but every waiver must carry a non-empty reason
    string, must still be *needed* (a waiver over a clean method is a
    stale-marker error), and is printed in the lint report so review
    sees the full waiver surface on every run. A reason may carry an
    expiry stamp ``until: PR-N``: the waiver fails once PR N is being
    built (``current_pr_number`` = max CHANGES.md entry + 1), so
    temporary waivers cannot quietly become permanent.

The analysis is lexical, not interprocedural, with two affordances the
codebase's idiom requires:

  - **lock aliases**: ``cond = self._conds[i]`` (or a ``for`` target
    iterating ``self._conds``) marks ``cond`` as holding ``_conds``
    when used in ``with cond:`` — the lock-striped executor and every
    Condition-per-shard pattern binds locks to locals first.
  - **closure reset**: a nested ``def``/``lambda`` body is scanned with
    an *empty* held-lock set, because closures outlive the enclosing
    ``with`` block and run on other threads (the chaos runner's
    scenario thunks are the canonical case).
  - **base merge**: ``GUARDED_BY`` merges down from in-module base
    classes, so ``Gauge``/``Counter`` inherit ``_Metric``'s map.

Run: ``python -m gpud_tpu.tools.guard_lint`` (exit 1 on any problem);
registered in ``tools/lint_all.py`` so tier-1 enforces it.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, FrozenSet, List, Optional, Tuple

# repo-relative paths of every module that owns cross-thread mutable
# state — keep in sync when a new threaded subsystem appears
GUARD_MODULES = (
    "gpud_tpu/chaos/runner.py",
    "gpud_tpu/fabric/plane.py",
    "gpud_tpu/health_history.py",
    "gpud_tpu/manager/federation.py",
    "gpud_tpu/manager/peers.py",
    "gpud_tpu/manager/rollup.py",
    "gpud_tpu/manager/shard.py",
    "gpud_tpu/metrics/registry.py",
    "gpud_tpu/predict/engine.py",
    "gpud_tpu/scheduler/core.py",
    "gpud_tpu/session/outbox.py",
    "gpud_tpu/storage/writer.py",
)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


_UNTIL_RE = re.compile(r"until:\s*PR-(\d+)\b")
_PR_LINE_RE = re.compile(r"^PR (\d+)\b", re.M)
_pr_cache: Dict[str, int] = {}


def current_pr_number(root: str = "") -> int:
    """The PR being built right now: max ``PR N`` entry in CHANGES.md
    plus one (each session appends its line only at the end)."""
    root = root or _repo_root()
    cached = _pr_cache.get(root)
    if cached is not None:
        return cached
    seen = 0
    path = os.path.join(root, "CHANGES.md")
    try:
        with open(path, encoding="utf-8") as f:
            for m in _PR_LINE_RE.finditer(f.read()):
                seen = max(seen, int(m.group(1)))
    except OSError:
        pass
    _pr_cache[root] = seen + 1
    return seen + 1


def waiver_reason_problems(reason: object, root: str = "") -> List[str]:
    """Shared waiver-reason checks (guard_lint, flow_lint, boundary_lint):
    a reason must be a non-empty string, and may carry an expiry stamp
    ``until: PR-N`` — the waiver is good for PRs *before* N and fails
    once PR N is being built, forcing the owner to resolve or re-justify
    it in that PR."""
    if not (isinstance(reason, str) and reason.strip()):
        return ["has no justification — every waiver carries a reason"]
    m = _UNTIL_RE.search(reason)
    if m is not None:
        deadline = int(m.group(1))
        current = current_pr_number(root)
        if current >= deadline:
            return [
                f"expired: stamped `until: PR-{deadline}` and this is "
                f"PR {current} — resolve the waiver or restamp it with a "
                "new deadline and justification"
            ]
    return []


def _class_dict(cls: ast.ClassDef, name: str) -> Tuple[Optional[Dict], int]:
    """A class-level ``name = {...}`` literal, or (None, 0) when absent."""
    for stmt in cls.body:
        if not isinstance(stmt, ast.Assign):
            continue
        for tgt in stmt.targets:
            if isinstance(tgt, ast.Name) and tgt.id == name:
                try:
                    val = ast.literal_eval(stmt.value)
                except (ValueError, SyntaxError):
                    return None, stmt.lineno
                if isinstance(val, dict):
                    return val, stmt.lineno
                return None, stmt.lineno
    return None, 0


class _MethodScanner:
    """Lexical walk of one method body tracking which locks are held."""

    def __init__(self, guarded: Dict[str, str]) -> None:
        self.guarded = guarded
        self.locks = set(guarded.values())
        self.violations: List[Tuple[int, str, str]] = []  # (line, attr, lock)

    def scan(self, fn: ast.FunctionDef) -> None:
        self._stmts(fn.body, frozenset(), {})

    # -- helpers -------------------------------------------------------------
    def _lock_mentioned(self, expr: ast.AST) -> Optional[str]:
        """First ``self.<lock>`` attribute reachable in ``expr``."""
        for n in ast.walk(expr):
            if (isinstance(n, ast.Attribute)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self" and n.attr in self.locks):
                return n.attr
        return None

    def _lock_of(self, expr: ast.expr, aliases: Dict[str, str]) -> Optional[str]:
        """The lock a ``with <expr>:`` acquires, if we can tell."""
        if isinstance(expr, ast.Name):
            return aliases.get(expr.id)
        return self._lock_mentioned(expr)

    # -- expression scan -----------------------------------------------------
    def _expr(self, node: Optional[ast.AST], held: FrozenSet[str],
              aliases: Optional[Dict[str, str]] = None) -> None:
        aliases = aliases or {}
        if node is None:
            return
        stack: List[ast.AST] = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Lambda):
                # closure: runs later, possibly on another thread, with
                # no lock held — scan its body from a cold start
                self._expr(n.body, frozenset(), aliases)
                continue
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "wait_for"):
                # Condition.wait_for(predicate): the predicate runs with
                # the condition's lock re-acquired — its lambda body is
                # locked, not a cold closure
                lock = self._lock_of(n.func.value, aliases)
                if lock:
                    self._expr(n.func.value, held, aliases)
                    for arg in list(n.args) + [kw.value for kw in n.keywords]:
                        if isinstance(arg, ast.Lambda):
                            self._expr(arg.body, held | {lock}, aliases)
                        else:
                            self._expr(arg, held, aliases)
                    continue
            if (isinstance(n, ast.Attribute)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self" and n.attr in self.guarded):
                lock = self.guarded[n.attr]
                if lock not in held:
                    self.violations.append((n.lineno, n.attr, lock))
            stack.extend(ast.iter_child_nodes(n))

    # -- statement scan ------------------------------------------------------
    def _stmts(self, body: List[ast.stmt], held: FrozenSet[str],
               aliases: Dict[str, str]) -> None:
        for stmt in body:
            self._stmt(stmt, held, aliases)

    def _stmt(self, node: ast.stmt, held: FrozenSet[str],
              aliases: Dict[str, str]) -> None:
        if isinstance(node, _FUNC_NODES) or isinstance(node, ast.ClassDef):
            # nested scope = closure: scanned lock-free (see module doc)
            self._stmts(node.body, frozenset(), {})
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = set()
            for item in node.items:
                self._expr(item.context_expr, held, aliases)
                lock = self._lock_of(item.context_expr, aliases)
                if lock:
                    acquired.add(lock)
                    if isinstance(item.optional_vars, ast.Name):
                        aliases[item.optional_vars.id] = lock
            self._stmts(node.body, held | acquired, aliases)
            return
        if isinstance(node, ast.Assign):
            self._expr(node.value, held, aliases)
            for tgt in node.targets:
                self._expr(tgt, held, aliases)
            if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                lock = self._lock_mentioned(node.value)
                if lock:
                    aliases[name] = lock
                else:
                    aliases.pop(name, None)  # rebound to something else
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self._expr(node.iter, held, aliases)
            self._expr(node.target, held, aliases)
            lock = self._lock_mentioned(node.iter)
            if lock:
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        aliases[n.id] = lock
            self._stmts(node.body, held, aliases)
            self._stmts(node.orelse, held, aliases)
            return
        # generic statement: check contained expressions, recurse into
        # contained statement lists (If/While/Try/Match bodies)
        for _field, value in ast.iter_fields(node):
            if isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.stmt):
                        self._stmt(v, held, aliases)
                    elif isinstance(v, ast.expr):
                        self._expr(v, held, aliases)
                    elif isinstance(v, ast.excepthandler):
                        self._expr(v.type, held, aliases)
                        self._stmts(v.body, held, aliases)
                    elif isinstance(v, getattr(ast, "match_case", ())):
                        self._expr(v.guard, held, aliases)
                        self._stmts(v.body, held, aliases)
            elif isinstance(value, ast.stmt):
                self._stmt(value, held, aliases)
            elif isinstance(value, ast.expr):
                self._expr(value, held, aliases)


def _lock_defined(classes: List[ast.ClassDef], lock: str) -> bool:
    """The lock attribute is assigned somewhere in the class chain."""
    for cls in classes:
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self" and tgt.attr == lock):
                        return True
                    if isinstance(tgt, ast.Name) and tgt.id == lock:
                        return True
    return False


def lint_module(path: str, rel: str,
                root: str = "") -> Tuple[List[str], List[str]]:
    """Returns (problems, waivers) for one module."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=rel)
    problems: List[str] = []
    waivers: List[str] = []

    by_name: Dict[str, ast.ClassDef] = {
        n.name: n for n in tree.body if isinstance(n, ast.ClassDef)
    }
    annotated = 0
    for cls in by_name.values():
        own, gb_line = _class_dict(cls, "GUARDED_BY")
        if gb_line and own is None:
            problems.append(
                f"{rel}:{gb_line}: {cls.name}.GUARDED_BY is not a literal "
                "dict of str -> str"
            )
            continue
        # merge GUARDED_BY down from in-module bases (subclass wins)
        chain: List[ast.ClassDef] = [cls]
        guarded: Dict[str, str] = {}
        for base in cls.bases:
            if isinstance(base, ast.Name) and base.id in by_name:
                base_cls = by_name[base.id]
                base_gb, _ = _class_dict(base_cls, "GUARDED_BY")
                if base_gb:
                    guarded.update(base_gb)
                    chain.append(base_cls)
        if own:
            guarded.update(own)
        if not guarded:
            continue
        annotated += 1

        for attr, lock in guarded.items():
            if not (isinstance(attr, str) and isinstance(lock, str)):
                problems.append(
                    f"{rel}:{gb_line}: {cls.name}.GUARDED_BY entries must "
                    "map attribute name -> lock attribute name (strings)"
                )
                continue
            if not _lock_defined(chain, lock):
                problems.append(
                    f"{rel}:{gb_line or cls.lineno}: {cls.name}.GUARDED_BY "
                    f"names lock {lock!r} for {attr!r} but the class never "
                    "assigns it (stale annotation)"
                )

        lock_free, lf_line = _class_dict(cls, "_LOCK_FREE")
        lock_free = lock_free or {}
        methods = {
            item.name: item for item in cls.body
            if isinstance(item, _FUNC_NODES)
        }
        for name, reason in lock_free.items():
            if name not in methods:
                problems.append(
                    f"{rel}:{lf_line}: {cls.name}._LOCK_FREE waives "
                    f"{name!r} but no such method exists (stale waiver)"
                )
            for why in waiver_reason_problems(reason, root=root):
                problems.append(
                    f"{rel}:{lf_line}: {cls.name}._LOCK_FREE[{name!r}] {why}"
                )

        for name, fn in methods.items():
            if name == "__init__" or name.endswith("_locked"):
                continue  # pre-publication / caller-holds-lock convention
            scanner = _MethodScanner(guarded)
            scanner.scan(fn)
            if name in lock_free:
                reason = lock_free[name]
                if not scanner.violations:
                    problems.append(
                        f"{rel}:{fn.lineno}: {cls.name}.{name}() is waived "
                        "in _LOCK_FREE but touches no guarded attribute "
                        "outside a lock (stale waiver — remove it)"
                    )
                else:
                    waivers.append(
                        f"{rel}:{fn.lineno}: {cls.name}.{name}() — {reason}"
                    )
                continue
            for line, attr, lock in scanner.violations:
                problems.append(
                    f"{rel}:{line}: {cls.name}.{name}() touches "
                    f"self.{attr} outside `with self.{lock}` "
                    "(GUARDED_BY violation)"
                )
    if not annotated:
        problems.append(
            f"{rel}: threaded module declares no GUARDED_BY class — every "
            "module in GUARD_MODULES must annotate its shared state"
        )
    return problems, waivers


def run_full(root: str = "") -> Tuple[List[str], List[str]]:
    """(problems, waivers) across GUARD_MODULES; ([], _) = clean."""
    root = root or _repo_root()
    problems: List[str] = []
    waivers: List[str] = []
    for rel in GUARD_MODULES:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            problems.append(f"{rel}: guarded module missing")
            continue
        p, w = lint_module(path, rel, root=root)
        problems.extend(p)
        waivers.extend(w)
    return problems, waivers


def run_lint(root: str = "") -> List[str]:
    """One problem string per violation across GUARD_MODULES; [] = clean."""
    return run_full(root)[0]


def main() -> int:
    problems, waivers = run_full()
    for w in waivers:
        print(f"guard-lint: waived {w}")
    for p in problems:
        print(f"guard-lint: {p}", file=sys.stderr)
    if problems:
        print(f"guard-lint: {len(problems)} problem(s)", file=sys.stderr)
        return 1
    print(
        f"guard-lint: {len(GUARD_MODULES)} module(s) clean, "
        f"{len(waivers)} justified waiver(s)"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
