"""Metric-registry lint: naming, unit, label, and help-text discipline.

Every metric the daemon registers must (a) carry the ``tpud_`` namespace
prefix — fleet Prometheus setups scrape many exporters into one TSDB, and
an unprefixed name collides or becomes unattributable — and (b) carry
non-empty help text, because `/metrics` is the operator's first (often
only) documentation of what a series means. On top of that, Prometheus
unit conventions are enforced: counters end ``_total``; time-valued
histograms and gauges use base seconds (no ``_ms``/``_us``/... suffixes);
histogram names carry a base unit (``_seconds``/``_bytes``); and no
metric may mint a label the exposition format reserves (``le``,
``quantile``, ``__*``). The lint runs in CI via
``tests/test_metrics_lint.py`` so new instrumentation cannot silently ship
unnamed or undocumented metrics, and is runnable standalone:

    python -m gpud_tpu.tools.metrics_lint
"""

from __future__ import annotations

import sys
from typing import List

METRIC_NAME_PREFIX = "tpud_"

# non-base time units: Prometheus wants base seconds so dashboards never
# have to guess the scale of a duration series
_BAD_UNIT_SUFFIXES = (
    "_ms", "_milliseconds", "_us", "_microseconds",
    "_ns", "_nanoseconds", "_minutes", "_hours",
)

# base units a histogram may be denominated in
_HISTOGRAM_UNIT_SUFFIXES = ("_seconds", "_bytes")

# label names the exposition format itself mints (histogram buckets,
# summary quantiles) or reserves (double-underscore internals)
_RESERVED_LABELS = ("le", "quantile")

# modules whose import (or cheap construction) registers every metric the
# daemon can expose — keep in sync with new instrumentation sites
_METRIC_MODULES = (
    "gpud_tpu.chaos.runner",
    "gpud_tpu.components.all",
    "gpud_tpu.components.base",
    "gpud_tpu.eventstore",
    "gpud_tpu.fabric.plane",
    "gpud_tpu.health_history",
    "gpud_tpu.manager.exposition",
    "gpud_tpu.manager.rollup",
    "gpud_tpu.manager.shard",
    "gpud_tpu.predict.engine",
    "gpud_tpu.scheduler.core",
    "gpud_tpu.server.app",
    "gpud_tpu.session.dispatch",
    "gpud_tpu.session.outbox",
    "gpud_tpu.session.session",
    "gpud_tpu.session.wire",
    "gpud_tpu.sqlite",
    "gpud_tpu.storage.writer",
)


def _counter_base_name(name: str) -> str:
    """Counter unit checks apply to the name minus the ``_total`` suffix."""
    return name[: -len("_total")] if name.endswith("_total") else name


def lint_registry(registry) -> List[str]:
    """Return one problem string per violation; empty list = clean."""
    problems: List[str] = []
    for m in registry.all_metrics():
        if not m.name.startswith(METRIC_NAME_PREFIX):
            problems.append(
                f"{m.name}: missing {METRIC_NAME_PREFIX!r} name prefix"
            )
        if not m.help_text.strip():
            problems.append(f"{m.name}: empty help text")
        kind = getattr(m, "TYPE", "")
        if kind == "counter" and not m.name.endswith("_total"):
            problems.append(f"{m.name}: counter must end in '_total'")
        if kind == "histogram" and not m.name.endswith(_HISTOGRAM_UNIT_SUFFIXES):
            problems.append(
                f"{m.name}: histogram must carry a base unit suffix "
                f"({'|'.join(_HISTOGRAM_UNIT_SUFFIXES)})"
            )
        unit_name = _counter_base_name(m.name) if kind == "counter" else m.name
        for suffix in _BAD_UNIT_SUFFIXES:
            if unit_name.endswith(suffix):
                problems.append(
                    f"{m.name}: non-base time unit {suffix!r} "
                    "(use base seconds: '_seconds')"
                )
                break
        # labels_values() is the scalar view: for histograms it excludes
        # the self-minted per-bucket 'le', so anything reserved here was
        # supplied by instrumentation code
        seen: set = set()
        for key, _value in m.labels_values():
            for lname, _lval in key:
                if lname in seen:
                    continue
                seen.add(lname)
                if lname in _RESERVED_LABELS or lname.startswith("__"):
                    problems.append(
                        f"{m.name}: label {lname!r} collides with a "
                        "reserved Prometheus label name"
                    )
    return problems


def populate_default_registry() -> None:
    """Import every metric-defining module so module-level registrations
    land in the default registry, then construct the recorder (its metrics
    register at construction, not import)."""
    import importlib

    for mod in _METRIC_MODULES:
        importlib.import_module(mod)

    from gpud_tpu.metrics.registry import DEFAULT_REGISTRY
    from gpud_tpu.metrics.store import SelfMetricsRecorder
    from gpud_tpu.sqlite import DB

    db = DB(":memory:")
    try:
        SelfMetricsRecorder(DEFAULT_REGISTRY, db)
    finally:
        db.close()


def main() -> int:
    populate_default_registry()
    from gpud_tpu.metrics.registry import DEFAULT_REGISTRY

    problems = lint_registry(DEFAULT_REGISTRY)
    for p in problems:
        print(f"metrics-lint: {p}", file=sys.stderr)
    n = len(DEFAULT_REGISTRY.all_metrics())
    if problems:
        print(f"metrics-lint: {len(problems)} problem(s) in {n} metrics",
              file=sys.stderr)
        return 1
    print(f"metrics-lint: {n} metrics clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
