"""Metric-registry lint: naming and help-text discipline.

Every metric the daemon registers must (a) carry the ``tpud_`` namespace
prefix — fleet Prometheus setups scrape many exporters into one TSDB, and
an unprefixed name collides or becomes unattributable — and (b) carry
non-empty help text, because `/metrics` is the operator's first (often
only) documentation of what a series means. The lint runs in CI via
``tests/test_metrics_lint.py`` so new instrumentation cannot silently ship
unnamed or undocumented metrics, and is runnable standalone:

    python -m gpud_tpu.tools.metrics_lint
"""

from __future__ import annotations

import sys
from typing import List

METRIC_NAME_PREFIX = "tpud_"

# modules whose import (or cheap construction) registers every metric the
# daemon can expose — keep in sync with new instrumentation sites
_METRIC_MODULES = (
    "gpud_tpu.components.all",
    "gpud_tpu.components.base",
    "gpud_tpu.server.app",
    "gpud_tpu.session.dispatch",
    "gpud_tpu.sqlite",
)


def lint_registry(registry) -> List[str]:
    """Return one problem string per violation; empty list = clean."""
    problems: List[str] = []
    for m in registry.all_metrics():
        if not m.name.startswith(METRIC_NAME_PREFIX):
            problems.append(
                f"{m.name}: missing {METRIC_NAME_PREFIX!r} name prefix"
            )
        if not m.help_text.strip():
            problems.append(f"{m.name}: empty help text")
    return problems


def populate_default_registry() -> None:
    """Import every metric-defining module so module-level registrations
    land in the default registry, then construct the recorder (its metrics
    register at construction, not import)."""
    import importlib

    for mod in _METRIC_MODULES:
        importlib.import_module(mod)

    from gpud_tpu.metrics.registry import DEFAULT_REGISTRY
    from gpud_tpu.metrics.store import SelfMetricsRecorder
    from gpud_tpu.sqlite import DB

    db = DB(":memory:")
    try:
        SelfMetricsRecorder(DEFAULT_REGISTRY, db)
    finally:
        db.close()


def main() -> int:
    populate_default_registry()
    from gpud_tpu.metrics.registry import DEFAULT_REGISTRY

    problems = lint_registry(DEFAULT_REGISTRY)
    for p in problems:
        print(f"metrics-lint: {p}", file=sys.stderr)
    n = len(DEFAULT_REGISTRY.all_metrics())
    if problems:
        print(f"metrics-lint: {len(problems)} problem(s) in {n} metrics",
              file=sys.stderr)
        return 1
    print(f"metrics-lint: {n} metrics clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
