"""Metadata key-value table.

Reference: pkg/metadata/metadata.go:33-53 — persists machine_id, token,
machine_proof, endpoint, public/private IP, node labels, login timestamp in
the state DB so the daemon can resume its control-plane identity across
restarts and reboots.
"""

from __future__ import annotations

from typing import Dict, Optional

from gpud_tpu.sqlite import DB

TABLE = "tpud_metadata_v0_1"

# canonical keys (reference: pkg/metadata/metadata.go:33-53)
KEY_MACHINE_ID = "machine_id"
KEY_TOKEN = "token"
KEY_MACHINE_PROOF = "machine_proof"
KEY_ENDPOINT = "endpoint"
KEY_PUBLIC_IP = "public_ip"
KEY_PRIVATE_IP = "private_ip"
KEY_NODE_LABELS = "node_labels"
KEY_LOGIN_SUCCESS_TS = "login_success_ts"
KEY_EXPECTED_CHIP_COUNT = "expected_chip_count"
KEY_ACCELERATOR_TYPE = "accelerator_type"
KEY_ICI_THRESHOLDS = "ici_thresholds"  # legacy name, unused
KEY_CONFIG_OVERRIDES = "config_overrides"
# persisted auth-failure record (reference: session auth-failure
# persistence, session_v2.go:359): "<unix_ts>|<reason>"
KEY_LAST_AUTH_FAILURE = "last_auth_failure"
# ICI expected-link baseline: most links ever observed on this host, so a
# link that vanished across a daemon restart still alarms
KEY_ICI_MAX_LINKS_SEEN = "ici_max_links_seen"


def normalize_endpoint(value) -> str:
    """Canonical control-plane endpoint form (no trailing slash).

    Applied at every WRITE site (login, FIFO rotation, updateToken) so
    readers can compare persisted values without re-normalizing."""
    return (value or "").rstrip("/")


class Metadata:
    def __init__(self, db: DB) -> None:
        self.db = db
        db.execute(
            f"CREATE TABLE IF NOT EXISTS {TABLE} (key TEXT PRIMARY KEY, value TEXT)"
        )

    def get(self, key: str, default: str = "") -> str:
        row = self.db.query_one(f"SELECT value FROM {TABLE} WHERE key=?", (key,))
        return row[0] if row else default

    def set(self, key: str, value: str) -> None:
        self.db.execute(
            f"INSERT INTO {TABLE} (key, value) VALUES (?, ?) "
            "ON CONFLICT(key) DO UPDATE SET value=excluded.value",
            (key, value),
        )

    def set_many(self, items: Dict[str, str]) -> None:
        """All-or-nothing upsert. Credential pairs (endpoint+token) must
        never be torn by a crash between two writes — a half-written pair
        would be trusted over fresh boot flags on the next start."""
        self.db.executemany(
            f"INSERT INTO {TABLE} (key, value) VALUES (?, ?) "
            "ON CONFLICT(key) DO UPDATE SET value=excluded.value",
            list(items.items()),
        )

    def set_credential_pair(self, endpoint: str, token: str) -> None:
        self.set_many(
            {KEY_ENDPOINT: normalize_endpoint(endpoint), KEY_TOKEN: token}
        )

    def delete(self, key: str) -> None:
        self.db.execute(f"DELETE FROM {TABLE} WHERE key=?", (key,))

    def all(self) -> Dict[str, str]:
        return {r[0]: r[1] for r in self.db.query(f"SELECT key, value FROM {TABLE}")}

    def machine_id(self) -> Optional[str]:
        v = self.get(KEY_MACHINE_ID)
        return v or None
