"""Fleet-wide ICI history scan — the control-plane-side analytics tool.

Per-host daemons keep 14 days of per-link snapshots in their state DBs
(components/tpu/ici_store.py, the reference's IB-store analog). At pod
scale an operator wants one sweep over every host's history — v5p-256 ⇒
128 chips × 6 links × 1440 samples/day — which is exactly the shape the
accelerated scan kernels were built for (ops/window_scan.py): the whole
fleet's history packs into [L, T] arrays, the scan shards along L over a
device mesh (parallel/fleet.py), and XLA fuses the pass into a few
kernels.

Entry point: ``tpud fleet-scan host1.db host2.db ... [--window S]``.
Each DB is opened read-only; link names are prefixed with the DB's stem
(disambiguated when two DBs share a filename) and set-healthy tombstones
are honored exactly like the per-host scan.

Histories are *packed*: each link's snapshots sit left-aligned in ts
order with suffix padding (a prefix validity mask) — every consecutive
snapshot pair is compared exactly like ICIStore.scan walks them, so the
fleet classes match the per-host scan snapshot-for-snapshot. Packing is
also the layout the Pallas kernel wants (ops/pallas_scan.py), which runs
the whole scan in one VPU pass per tile when a TPU is visible. Per-link
sample counts are bounded by window/step (and a hard 14-days-of-minutes
cap), keeping the dense array from OOMing the compiler.
"""

from __future__ import annotations

import os
import sqlite3
import time
from typing import Dict, List, Optional, Tuple

from gpud_tpu.log import get_logger

logger = get_logger(__name__)

TABLE = "tpud_ici_snapshots_v0_1"  # components/tpu/ici_store.py schema
TOMBSTONE_TABLE = "tpud_ici_tombstones_v0_1"

DEFAULT_WINDOW_SECONDS = 3600.0
DEFAULT_STEP_SECONDS = 60.0
# dense-array bound: 14 days of minutes. A window/step pair exceeding this
# is coarsened (larger effective step) instead of materializing a huge
# [L, T] array that can OOM the compiler.
MAX_STEPS = 20160


def load_fleet_history(
    db_paths: List[str],
    window_seconds: float = DEFAULT_WINDOW_SECONDS,
    now: Optional[float] = None,
    max_samples: int = MAX_STEPS,
):
    """Read every host DB's snapshots in the window into packed arrays.

    Returns (names, states, counters, valid, truncated) where names[i]
    labels row i as ``<host>/<link>``; arrays are [L, T] with each link's
    samples left-aligned in ts order (``valid`` is a prefix mask). A link
    exceeding ``max_samples`` (the dense-array memory bound, 14 days of
    minutes by default) keeps its LATEST samples and is reported in
    ``truncated`` — never silently.
    """
    import numpy as np

    t_now = now if now is not None else time.time()
    start = t_now - window_seconds

    from urllib.parse import quote

    seqs: Dict[str, List[Tuple[int, int]]] = {}  # name → [(state, crc), ...]
    names: List[str] = []
    used_hosts: Dict[str, int] = {}
    for path in db_paths:
        host = os.path.splitext(os.path.basename(path))[0]
        # two DBs named host1.db in different dirs must not merge
        n_seen = used_hosts.get(host, 0)
        used_hosts[host] = n_seen + 1
        if n_seen:
            host = f"{host}-{n_seen + 1}"
        # immutable=1 would reject WAL files; ro mode is enough. Escape the
        # path: '?', '#' or '%' would otherwise be URI-parsed.
        uri = f"file:{quote(os.path.abspath(path))}?mode=ro"
        conn = sqlite3.connect(uri, uri=True)
        try:
            tombstones = {}
            try:
                tombstones = dict(
                    conn.execute(f"SELECT link, ts FROM {TOMBSTONE_TABLE}")
                )
            except sqlite3.OperationalError:
                pass  # older DB without the table
            global_ts = tombstones.get("*", 0.0)
            cur = conn.execute(
                f"SELECT link, ts, state, crc_errors FROM {TABLE} "
                "WHERE ts>=? ORDER BY link, ts ASC",
                (start,),
            )
            for link, ts, state, crc in cur:
                # honor set-healthy exactly like ICIStore.scan
                if ts < max(global_ts, tombstones.get(link, 0.0)):
                    continue
                name = f"{host}/{link}"
                if name not in seqs:
                    seqs[name] = []
                    names.append(name)
                seqs[name].append((int(state), int(crc)))
        finally:
            conn.close()

    if not names:
        z = np.zeros((0, 1), dtype=np.int8)
        return [], z, z.astype(np.int32), z.astype(bool), []

    truncated: List[str] = []
    for name, seq in seqs.items():
        if len(seq) > max_samples:
            seqs[name] = seq[-max_samples:]  # keep the latest
            truncated.append(name)
    if truncated:
        logger.warning(
            "fleet-scan truncated %d link(s) to the latest %d samples "
            "(history denser than the array bound): %s",
            len(truncated), max_samples, ", ".join(sorted(truncated)[:5]),
        )
    t_max = max(len(seq) for seq in seqs.values())
    L = len(names)
    states = np.zeros((L, t_max), dtype=np.int8)
    counters = np.zeros((L, t_max), dtype=np.int32)
    valid = np.zeros((L, t_max), dtype=bool)
    for i, name in enumerate(names):
        seq = seqs[name]
        n = len(seq)
        states[i, :n] = [s for s, _c in seq]
        # rebase counters on the first sample: deltas are invariant and
        # small magnitudes keep the float32 Pallas path exact
        base = seq[0][1] if n else 0
        counters[i, :n] = np.clip(
            [c - base for _s, c in seq], -(2**31), 2**31 - 1
        )
        valid[i, :n] = True
    return names, states, counters, valid, truncated


def _scan_links_numpy(
    states, counters, valid, flap_threshold: int = 3, crc_threshold: int = 100
):
    """Pure-numpy twin of ops/window_scan.scan_links + classify_links
    (forward-fill across gaps, positive counter steps, same class rules);
    parity-tested against the JAX kernels."""
    import numpy as np

    states = states.astype(np.int8)
    valid = valid.astype(bool)
    L, T = states.shape

    # forward-fill last valid state/counter along time
    idx = np.where(valid, np.arange(T)[None, :], -1)
    ff_idx = np.maximum.accumulate(idx, axis=1)
    has_ff = ff_idx >= 0
    safe_idx = np.maximum(ff_idx, 0)
    state_ff = np.take_along_axis(states, safe_idx, axis=1)
    counter_ff = np.take_along_axis(counters, safe_idx, axis=1)

    prev, prev_has = state_ff[:, :-1], has_ff[:, :-1]
    nxt = states[:, 1:]
    v_pair = valid[:, 1:] & prev_has
    drops = np.sum((prev == 1) & (nxt == 0) & v_pair, axis=1)
    flaps = np.sum((prev == 0) & (nxt == 1) & v_pair, axis=1)

    last_idx = T - 1 - np.argmax(valid[:, ::-1], axis=1)
    has_any = valid.any(axis=1)
    last_state = np.take_along_axis(states, last_idx[:, None], axis=1)[:, 0]
    currently_down = has_any & (last_state == 0)

    diffs = counters[:, 1:] - counter_ff[:, :-1]
    counter_delta = np.sum(np.where(v_pair, np.maximum(diffs, 0), 0), axis=1)

    heavy = (drops >= flap_threshold) | (flaps >= flap_threshold)
    unhealthy = currently_down | heavy
    degraded = (drops > 0) | (flaps > 0) | (counter_delta >= crc_threshold)
    return np.where(unhealthy, 2, np.where(degraded, 1, 0)).astype(np.int32)


def fleet_scan(
    db_paths: List[str],
    window_seconds: float = DEFAULT_WINDOW_SECONDS,
    flap_threshold: int = 3,
    crc_threshold: int = 100,
    now: Optional[float] = None,
) -> dict:
    """Scan the fleet's link history on the accelerator (sharded over the
    device mesh when more than one device is visible).

    Returns {"links": {name: "healthy|degraded|unhealthy"},
             "summary": {...}, "devices": n, "window_seconds": S,
             "truncated_links": [...]}.
    """
    import numpy as np

    names, states, counters, valid, truncated = load_fleet_history(
        db_paths, window_seconds, now=now
    )
    out = {
        "window_seconds": window_seconds,
        "links": {},
        "summary": {"healthy": 0, "degraded": 0, "unhealthy": 0},
        "devices": 0,
        "truncated_links": truncated,
    }
    if not names:
        return out

    import jax

    from gpud_tpu.ops.window_scan import WindowScan, classify_links, scan_links
    from gpud_tpu.parallel.fleet import make_mesh, sharded_link_scan

    def classify_packed(scan) -> "np.ndarray":
        # one rule set: adapt the packed (float32) result to
        # classify_links' integer/bool shapes
        drops = np.asarray(scan.drops).astype(np.int32)
        ws = WindowScan(
            drops=drops,
            flaps=np.asarray(scan.flaps).astype(np.int32),
            currently_down=np.asarray(scan.currently_down) > 0.5,
            down_time_frac=np.zeros_like(drops, dtype=np.float32),
            counter_delta=np.asarray(scan.counter_delta).astype(np.int64),
        )
        return np.asarray(
            classify_links(
                ws, flap_threshold=flap_threshold, crc_threshold=crc_threshold
            )
        )

    def run_scan():
        n_devices = len(jax.devices())
        out["devices"] = n_devices
        if n_devices > 1:
            # pad L to a multiple of the mesh so the shard is even; padded
            # rows are all-invalid → class 0, dropped after
            pad = (-len(names)) % n_devices
            st, ct, vl = states, counters, valid
            if pad:
                st = np.pad(st, ((0, pad), (0, 0)))
                ct = np.pad(ct, ((0, pad), (0, 0)))
                vl = np.pad(vl, ((0, pad), (0, 0)))
            mesh = make_mesh(n_devices)
            _scan, cls = sharded_link_scan(
                mesh, st, ct, vl,
                flap_threshold=flap_threshold, crc_threshold=crc_threshold,
            )
            return np.asarray(cls)[: len(names)]
        if any("tpu" in d.device_kind.lower() for d in jax.devices()):
            # packed histories are exactly the Pallas kernel's contract:
            # one VPU pass per tile instead of the multi-scan jnp graph
            from gpud_tpu.ops.pallas_scan import scan_links_packed

            try:
                return classify_packed(scan_links_packed(states, counters, valid))
            except Exception as e:  # noqa: BLE001 — lowering varies by runtime
                logger.info("pallas scan unavailable (%s); using jnp", e)
        scan = scan_links(states, counters, valid)
        return np.asarray(
            classify_links(
                scan, flap_threshold=flap_threshold, crc_threshold=crc_threshold
            )
        )

    try:
        classes = run_scan()
    except Exception as e:  # noqa: BLE001 — a broken accelerator runtime
        # must not take the diagnostic tool down with it: a pure-numpy
        # twin of the scan runs anywhere (switching jax backends after
        # initialization is not reliable)
        logger.warning("fleet scan on the accelerator failed (%s); "
                       "falling back to the numpy scan", e)
        out["devices"] = 0
        classes = _scan_links_numpy(
            states, counters, valid,
            flap_threshold=flap_threshold, crc_threshold=crc_threshold,
        )

    class_names = {0: "healthy", 1: "degraded", 2: "unhealthy"}
    summary = {"healthy": 0, "degraded": 0, "unhealthy": 0}
    for name, c in zip(names, classes):
        label = class_names[int(c)]
        out["links"][name] = label
        summary[label] += 1
    out["summary"] = summary
    return out
