"""Logical mesh discovery + link enumeration for the fabric sweep.

Discovery degrades through three sources (MT4G's lesson in PAPERS.md —
topology auto-discovery is itself the observability product, so never
require the operator to declare the mesh):

1. ``jax`` — when the operator opted into the exclusive libtpu client
   (``TPUD_TPU_USE_JAX``) and ``jax.devices()`` yields real TPU devices,
   the mesh is the near-square factorization of the device count, the
   same shape SNIPPETS.md [2]/[3] build with
   ``Mesh(np.array(jax.devices()).reshape(r, c), axis_names=...)``.
2. ``sysfs`` — the ICI link inventory (sysfs layout or mock backend)
   gives the local chip set; the mesh is its near-square factorization.
3. ``degraded`` — no inventory at all (tier-1 under ``JAX_PLATFORMS=cpu``
   with no fixture tree): a 1×1 mesh with zero links, so every consumer
   sees a trivially complete, trivially healthy sweep instead of an
   error path.

Axis/port convention (2D torus): each chip exposes
``ici_links_per_chip`` ports; port ``2k`` faces the negative direction
of axis ``k`` and port ``2k+1`` the positive direction, with axis 0 =
``"x"`` (fast, column index) and axis 1 = ``"y"`` (row index). A logical
mesh link ``src→dst`` along axis ``k`` is therefore down when src's
port ``2k+1`` or dst's port ``2k`` reports down.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from gpud_tpu.log import get_logger

logger = get_logger(__name__)

# axis order mirrors the port layout: ports (0,1) walk "x", (2,3) "y",
# (4,5) "z" on 3D generations
AXIS_NAMES = ("x", "y", "z")

SOURCE_JAX = "jax"
SOURCE_SYSFS = "sysfs"
SOURCE_DEGRADED = "degraded"

ENV_USE_JAX = "TPUD_TPU_USE_JAX"


@dataclass(frozen=True)
class MeshLink:
    """One logical mesh edge (directed src→dst along one axis)."""

    src_chip: int
    dst_chip: int
    axis: str

    @property
    def name(self) -> str:
        return f"c{self.src_chip}-c{self.dst_chip}/{self.axis}"

    def to_dict(self) -> Dict:
        return {
            "link": self.name,
            "src_chip": self.src_chip,
            "dst_chip": self.dst_chip,
            "axis": self.axis,
        }


@dataclass(frozen=True)
class MeshSpec:
    """A discovered logical mesh: row-major chip grid + provenance."""

    shape: Tuple[int, ...]          # (rows, cols) — rows walk "y", cols "x"
    chips: Tuple[int, ...] = field(default=())  # chip ids, row-major
    source: str = SOURCE_DEGRADED

    @property
    def rows(self) -> int:
        return self.shape[0] if self.shape else 1

    @property
    def cols(self) -> int:
        return self.shape[1] if len(self.shape) > 1 else 1

    def coords(self, index: int) -> Tuple[int, int]:
        return index // self.cols, index % self.cols

    def to_dict(self) -> Dict:
        return {
            "shape": list(self.shape),
            "chips": len(self.chips),
            "source": self.source,
        }


def near_square_factor(n: int) -> Tuple[int, int]:
    """``(rows, cols)`` with ``rows*cols == n``, rows the largest divisor
    ≤ √n — 8 → 2×4, 16 → 4×4, a prime p → 1×p (a ring)."""
    if n <= 1:
        return (1, max(1, n))
    rows = 1
    r = 1
    while r * r <= n:
        if n % r == 0:
            rows = r
        r += 1
    return (rows, n // rows)


def _jax_chip_count() -> int:
    """Device count from the exclusive libtpu client, 0 when unavailable
    or not actually TPU (``JAX_PLATFORMS=cpu`` lands here → 0)."""
    if os.environ.get(ENV_USE_JAX, "") not in ("1", "true", "yes"):
        return 0
    try:
        import jax

        devices = [d for d in jax.devices() if d.platform == "tpu"]
        return len(devices)
    except Exception as exc:  # noqa: BLE001 — no jax / no TPU / init race
        logger.debug("jax mesh discovery unavailable: %s", exc)
        return 0


def discover_mesh(tpu=None) -> MeshSpec:
    """Derive the logical mesh (module docstring for the source ladder)."""
    n = _jax_chip_count()
    if n >= 2:
        return MeshSpec(
            shape=near_square_factor(n),
            chips=tuple(range(n)),
            source=SOURCE_JAX,
        )
    chips: List[int] = []
    if tpu is not None:
        try:
            chips = sorted({snap.chip_id for snap in tpu.ici_links()})
        except Exception as exc:  # noqa: BLE001 — backend probe failed
            logger.debug("ici inventory unavailable for mesh discovery: %s", exc)
            chips = []
    if len(chips) >= 2:
        return MeshSpec(
            shape=near_square_factor(len(chips)),
            chips=tuple(chips),
            source=SOURCE_SYSFS,
        )
    return MeshSpec(shape=(1, 1), chips=tuple(chips[:1]), source=SOURCE_DEGRADED)


def mesh_links(mesh: MeshSpec) -> List[MeshLink]:
    """Enumerate every logical link, per axis: nearest-neighbor edges
    along each row ("x") and column ("y"), plus the torus wrap edge when
    the axis is longer than 2 (at size 2 the wrap would duplicate the
    neighbor edge). A 1×1 mesh has no links; 2×4 has 12 (4+wrap per row
    × 2 rows along x, 4 columns × 1 along y)."""
    rows, cols = mesh.rows, mesh.cols
    chips = mesh.chips
    if len(chips) < rows * cols or rows * cols < 2:
        return []

    def chip(r: int, c: int) -> int:
        return chips[r * cols + c]

    links: List[MeshLink] = []
    for r in range(rows):
        for c in range(cols - 1):
            links.append(MeshLink(chip(r, c), chip(r, c + 1), "x"))
        if cols > 2:
            links.append(MeshLink(chip(r, cols - 1), chip(r, 0), "x"))
    for c in range(cols):
        for r in range(rows - 1):
            links.append(MeshLink(chip(r, c), chip(r + 1, c), "y"))
        if rows > 2:
            links.append(MeshLink(chip(rows - 1, c), chip(0, c), "y"))
    return links


def link_ports(link: MeshLink) -> Tuple[Tuple[int, int], Tuple[int, int]]:
    """The two physical ports a logical link rides: ``((src_chip,
    src_port), (dst_chip, dst_port))`` under the port convention in the
    module docstring."""
    axis_idx = AXIS_NAMES.index(link.axis)
    return (
        (link.src_chip, 2 * axis_idx + 1),
        (link.dst_chip, 2 * axis_idx),
    )


def link_port_state(
    link: MeshLink, port_up: Dict[Tuple[int, int], bool]
) -> Optional[bool]:
    """Fold the two endpoint ports into one link verdict: ``False`` when
    either reports down, ``True`` when at least one reports up and none
    down, ``None`` when neither port is in the inventory (derived
    topology without per-port state — callers treat that as up)."""
    (src, sp), (dst, dp) = link_ports(link)
    a = port_up.get((src, sp))
    b = port_up.get((dst, dp))
    if a is False or b is False:
        return False
    if a is None and b is None:
        return None
    return True
