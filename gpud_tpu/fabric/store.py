"""Durable per-link fabric matrix history (SQLite via the PR-7 writer).

One row per (sweep, link): the ``(src_chip, dst_chip, axis, latency,
state)`` tuple ISSUE 16 asks for, plus the EWMA deviation the sweep
computed against that link's baseline. The latest sweep is served from
the plane's in-memory matrix; this table answers history questions
("when did c1-c2/x last degrade") and survives restarts. Retention is
time-based via ``purge`` wired into the server's retention job.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

TABLE = "tpud_fabric_matrix_v0_1"

_INSERT_SQL = (
    f"INSERT INTO {TABLE} "
    "(ts, link, src_chip, dst_chip, axis, state, latency_seconds, deviation) "
    "VALUES (?, ?, ?, ?, ?, ?, ?, ?)"
)


class FabricMatrixStore:
    """Append-only sweep matrix rows + time-retained history reads.

    Writes route through the shared ``BatchWriter`` (group commit with
    the event/health stores) when one is wired; the sync ``executemany``
    fallback keeps the store usable standalone (tests, tools). SQLite
    serializes access, so no lock is held here.
    """

    def __init__(self, db, writer=None, time_now_fn=None) -> None:
        self.db = db
        self.writer = writer
        self.time_now_fn = time_now_fn or time.time
        self.db.execute(
            f"""CREATE TABLE IF NOT EXISTS {TABLE} (
                ts              REAL NOT NULL,
                link            TEXT NOT NULL,
                src_chip        INTEGER NOT NULL,
                dst_chip        INTEGER NOT NULL,
                axis            TEXT NOT NULL,
                state           TEXT NOT NULL,
                latency_seconds REAL NOT NULL DEFAULT 0,
                deviation       REAL NOT NULL DEFAULT 0
            )"""
        )
        self.db.execute(
            f"CREATE INDEX IF NOT EXISTS idx_fabric_link_ts "
            f"ON {TABLE} (link, ts)"
        )
        self.db.execute(
            f"CREATE INDEX IF NOT EXISTS idx_fabric_ts ON {TABLE} (ts)"
        )

    def insert_sweep(self, rows: List[Dict], ts: Optional[float] = None) -> int:
        """Record one sweep's matrix rows (dicts in the matrix() shape)."""
        if not rows:
            return 0
        when = self.time_now_fn() if ts is None else ts
        params = [
            (
                float(r.get("ts", when) or when),
                str(r["link"]),
                int(r.get("src_chip", -1)),
                int(r.get("dst_chip", -1)),
                str(r.get("axis", "")),
                str(r.get("state", "")),
                float(r.get("latency_seconds", 0.0) or 0.0),
                float(r.get("deviation", 0.0) or 0.0),
            )
            for r in rows
        ]
        if self.writer is not None:
            self.writer.submit_many("fabric", _INSERT_SQL, params)
        else:
            self.db.executemany(_INSERT_SQL, params)
        return len(params)

    def _barrier(self) -> None:
        """Read-after-write: a history question right after a sweep must
        see that sweep's rows (no-pending fast path is one lock)."""
        if self.writer is not None:
            self.writer.flush()

    def history(
        self, link: str = "", since: float = 0.0, limit: int = 256
    ) -> List[Dict]:
        """Matrix rows newest-first, optionally one link / since a ts."""
        self._barrier()
        limit = max(1, min(10_000, int(limit)))
        where = ["ts >= ?"]
        args: list = [float(since)]
        if link:
            where.append("link = ?")
            args.append(str(link))
        args.append(limit)
        rows = self.db.query(
            f"SELECT ts, link, src_chip, dst_chip, axis, state, "
            f"latency_seconds, deviation FROM {TABLE} "
            f"WHERE {' AND '.join(where)} ORDER BY ts DESC LIMIT ?",
            tuple(args),
        )
        return [
            {
                "ts": ts,
                "link": lnk,
                "src_chip": src,
                "dst_chip": dst,
                "axis": axis,
                "state": state,
                "latency_seconds": lat,
                "deviation": dev,
            }
            for ts, lnk, src, dst, axis, state, lat, dev in rows
        ]

    def row_count(self) -> int:
        self._barrier()
        row = self.db.query_one(f"SELECT COUNT(*) FROM {TABLE}")
        return int(row[0]) if row else 0

    def purge(self, before: Optional[float] = None) -> int:
        """Drop rows older than ``before`` (retention job hook)."""
        self._barrier()
        cutoff = self.time_now_fn() if before is None else float(before)
        return self.db.execute(
            f"DELETE FROM {TABLE} WHERE ts < ?", (cutoff,)
        ).rowcount
