"""Mesh-wide all-links sweep with per-link EWMA latency baselines.

The plane runs one scheduler job (``fabric-sweep``) that, each tick:

1. resolves the logical mesh (``fabric/mesh.py`` ladder — JAX devices,
   sysfs/mock ICI inventory, or a degraded 1×1 mesh);
2. folds the physical port states into per-logical-link up/down;
3. probes each link's latency — on hardware the operator can point
   ``telemetry_fn`` at a per-axis collective timing; off-hardware a
   deterministic synthetic probe keeps the EWMA machinery exercised
   (the chaos/bench planes override ``telemetry_fn`` to inject ramps);
4. updates each link's EWMA baseline and flags Degraded on deviation
   (z past ``latency_threshold_z``), not just down — the "quiet
   degradation" failure mode PAPERS.md's "When GPUs Fail Quietly"
   documents for NVLink applies verbatim to ICI;
5. records the matrix row set into ``FabricMatrixStore`` and publishes
   ``ici_link`` outbox records for every not-up link and every state
   change (including recovery), which the manager journals into the
   fleet pane (``GET /v1/fleet/fabric``).

Per-link gauges are cardinality-bounded: at most ``metric_links_max``
links are exported (sorted by name for stable series), the rest are
counted in ``tpud_fabric_metric_links_truncated`` — same accounting
contract as the fleet exposition.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from gpud_tpu.fabric import mesh as meshmod
from gpud_tpu.fabric.mesh import MeshLink, MeshSpec, link_port_state, mesh_links
from gpud_tpu.fabric.store import FabricMatrixStore
from gpud_tpu.log import get_logger
from gpud_tpu.metrics.registry import counter, gauge, histogram
from gpud_tpu.predict.features import Ewma, clamp01, neighbor_cooccurrence
from gpud_tpu.tpu.instance import LinkState

logger = get_logger(__name__)

JOB_NAME = "fabric-sweep"

STATE_UP = "up"
STATE_DEGRADED = "degraded"
STATE_DOWN = "down"

_STATE_RANK = {STATE_UP: 0, STATE_DEGRADED: 1, STATE_DOWN: 2}

# deterministic off-hardware probe baseline (seconds) — constant, so an
# un-faulted link's EWMA variance collapses and any injected ramp is an
# unambiguous deviation (predict/features.Ewma.z has a relative floor)
SYNTHETIC_LATENCY_SECONDS = 1e-4

DEFAULT_METRIC_LINKS_MAX = 64

_g_link_health = gauge(
    "tpud_ici_link_health",
    "per logical mesh link: 2=up, 1=degraded (EWMA latency deviation), "
    "0=down (cardinality bounded; see tpud_fabric_metric_links_truncated)",
)
_g_link_deviation = gauge(
    "tpud_ici_link_deviation",
    "per logical mesh link: latency deviation from the link's EWMA "
    "baseline, in z-score units (cardinality bounded)",
)
_h_link_latency = histogram(
    "tpud_ici_link_latency_seconds",
    "per-axis sweep probe latency across all links of that mesh axis",
    buckets=(1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2, 0.1, 0.5),
)
_c_sweeps = counter(
    "tpud_fabric_sweeps_total",
    "completed all-links fabric sweeps",
)
_h_sweep = histogram(
    "tpud_fabric_sweep_duration_seconds",
    "wall time of one all-links fabric sweep",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
             0.1, 0.25, 0.5, 1.0, 2.5),
)
_g_links = gauge(
    "tpud_fabric_links",
    "logical mesh links the sweep observes (0 on a degraded 1x1 mesh)",
)
_g_links_degraded = gauge(
    "tpud_fabric_links_degraded",
    "links currently flagged degraded by EWMA latency deviation",
)
_g_links_down = gauge(
    "tpud_fabric_links_down",
    "links currently hard-down (either endpoint port down)",
)
_g_truncated = gauge(
    "tpud_fabric_metric_links_truncated",
    "links beyond the per-link gauge cardinality cap this sweep "
    "(still swept, stored, and shipped — only the gauges are capped)",
)


class _LinkTrack:
    """Per-link sweep state: EWMA baseline + last published verdict."""

    __slots__ = ("ewma", "state", "deviation", "latency", "last_ts",
                 "last_degraded_ts", "samples")

    def __init__(self, alpha: float) -> None:
        self.ewma = Ewma(alpha)
        self.state = ""
        self.deviation = 0.0
        self.latency = 0.0
        self.last_ts = 0.0
        self.last_degraded_ts = 0.0
        self.samples = 0


class FabricPlane:
    """Owns the mesh, the baselines, the matrix, and the sweep job.

    Thread-safe: the sweep runs on a scheduler worker while reads come
    from the HTTP executor, the session serve loop, and the predict
    scan. All mutable sweep state lives under ``_mu``; probing and
    storage run outside it.
    """

    GUARDED_BY = {
        "_mesh": "_mu",
        "_links": "_mu",
        "_tracks": "_mu",
        "_adjacency": "_mu",
        "_sweeps": "_mu",
        "_last_sweep_ts": "_mu",
        "_last_duration": "_mu",
        "_published": "_mu",
    }

    # the ICI component whose predict feature set we feed (satellite e:
    # neighbor co-occurrence signal)
    component_name = "accelerator-tpu-ici"

    def __init__(
        self,
        db,
        tpu=None,
        writer=None,
        interval_seconds: float = 60.0,
        latency_threshold_z: float = 4.0,
        ewma_alpha: float = 0.3,
        warmup_sweeps: int = 3,
        retention_seconds: float = 7 * 86400.0,
        metric_links_max: int = DEFAULT_METRIC_LINKS_MAX,
        time_now_fn: Optional[Callable[[], float]] = None,
    ) -> None:
        self.store = FabricMatrixStore(db, writer=writer)
        self.tpu = tpu
        self.interval_seconds = float(interval_seconds)
        self.latency_threshold_z = float(latency_threshold_z)
        self.ewma_alpha = float(ewma_alpha)
        self.warmup_sweeps = int(warmup_sweeps)
        self.retention_seconds = float(retention_seconds)
        self.metric_links_max = int(metric_links_max)
        self.time_now_fn = time_now_fn or time.time
        self.store.time_now_fn = self.time_now_fn
        # injectables (chaos/bench/hardware override; None = defaults)
        self.telemetry_fn: Optional[Callable[[MeshLink], float]] = None
        self.links_fn: Optional[Callable[[], list]] = None
        self.on_publish: Optional[Callable[[dict], None]] = None
        self._mu = threading.Lock()
        self._mesh: Optional[MeshSpec] = None
        self._links: List[MeshLink] = []
        self._tracks: Dict[str, _LinkTrack] = {}
        self._adjacency: Dict[str, List[str]] = {}
        self._sweeps = 0
        self._last_sweep_ts = 0.0
        self._last_duration = 0.0
        self._published = 0
        self._job = None

    # -- defaults ----------------------------------------------------------
    def synthetic_latency(self, link: MeshLink) -> float:  # noqa: ARG002
        """Deterministic off-hardware probe (module docstring)."""
        return SYNTHETIC_LATENCY_SECONDS

    def default_links(self) -> list:
        """Physical port snapshots from the TPU backend (sysfs or mock)."""
        if self.tpu is None:
            return []
        try:
            return self.tpu.ici_links()
        except Exception as exc:  # noqa: BLE001 — backend probe failed
            logger.debug("fabric port walk failed: %s", exc)
            return []

    # -- mesh --------------------------------------------------------------
    def _discover_locked(self) -> None:
        mesh = meshmod.discover_mesh(self.tpu)
        links = mesh_links(mesh)
        self._mesh = mesh
        self._links = links
        self._adjacency = _build_adjacency(links)
        stale = set(self._tracks) - {ln.name for ln in links}
        for name in stale:
            del self._tracks[name]
        logger.info(
            "fabric mesh discovered: shape=%s source=%s links=%d",
            "x".join(str(d) for d in mesh.shape), mesh.source, len(links),
        )

    def rediscover(self) -> None:
        """Force re-discovery on the next sweep (topology change)."""
        with self._mu:
            self._mesh = None

    # -- sweep -------------------------------------------------------------
    def sweep_once(self) -> Dict:
        """One all-links sweep; returns the recorded matrix row list."""
        t0 = time.monotonic()
        now = self.time_now_fn()
        with self._mu:
            if self._mesh is None:
                self._discover_locked()
            mesh = self._mesh
            links = list(self._links)
        # probe outside the lock: port walk + latency hook may block
        snaps = (self.links_fn or self.default_links)()
        port_up = {
            (s.chip_id, s.link_id): s.state == LinkState.UP for s in snaps
        }
        probe = self.telemetry_fn or self.synthetic_latency
        probed: List[tuple] = []
        for link in links:
            up = link_port_state(link, port_up)
            try:
                latency = float(probe(link))
            except Exception as exc:  # noqa: BLE001 — operator hook failed
                logger.debug("fabric probe failed for %s: %s", link.name, exc)
                latency = 0.0
            probed.append((link, up, latency))
        with self._mu:
            rows, publishes = self._apply_sweep_locked(probed, now)
            self._sweeps += 1
            self._last_sweep_ts = now
            self._last_duration = time.monotonic() - t0
            duration = self._last_duration
            self._published += len(publishes)
        self.store.insert_sweep(rows, ts=now)
        sink = self.on_publish
        if sink is not None:
            for body in publishes:
                try:
                    sink(body)
                except Exception:  # noqa: BLE001 — outbox must not kill sweep
                    logger.exception("fabric publish hook failed")
        self._export_metrics(mesh, rows, duration)
        return {"ts": now, "links": len(rows), "published": len(publishes)}

    def _apply_sweep_locked(
        self, probed: List[tuple], now: float
    ) -> tuple:
        rows: List[Dict] = []
        publishes: List[Dict] = []
        threshold = self.latency_threshold_z
        for link, up, latency in probed:
            tr = self._tracks.get(link.name)
            if tr is None:
                tr = self._tracks[link.name] = _LinkTrack(self.ewma_alpha)
            prev_state = tr.state
            deviation = 0.0
            if up is False:
                state = STATE_DOWN
            else:
                if tr.samples >= self.warmup_sweeps:
                    deviation = tr.ewma.z(latency)
                if deviation >= threshold:
                    # deviating sample: flag, and keep it OUT of the
                    # baseline so a persistent latency shift stays
                    # flagged instead of being absorbed
                    state = STATE_DEGRADED
                else:
                    state = STATE_UP
                    tr.ewma.update(latency)
                    tr.samples += 1
            tr.state = state
            tr.deviation = deviation
            tr.latency = latency
            tr.last_ts = now
            if state == STATE_DEGRADED:
                tr.last_degraded_ts = now
            row = dict(link.to_dict())
            row.update({
                "ts": now,
                "state": state,
                "latency_seconds": latency,
                "deviation": deviation,
            })
            rows.append(row)
            if state != STATE_UP or (prev_state and prev_state != state):
                publishes.append(dict(row))
        return rows, publishes

    def _export_metrics(self, mesh, rows: List[Dict], duration: float) -> None:
        _c_sweeps.inc()
        _h_sweep.observe(duration)
        _g_links.set(len(rows))
        degraded = sum(1 for r in rows if r["state"] == STATE_DEGRADED)
        down = sum(1 for r in rows if r["state"] == STATE_DOWN)
        _g_links_degraded.set(degraded)
        _g_links_down.set(down)
        exported = sorted(rows, key=lambda r: r["link"])[: self.metric_links_max]
        _g_truncated.set(max(0, len(rows) - len(exported)))
        for r in exported:
            labels = {"link": r["link"]}
            _g_link_health.set(
                float(2 - _STATE_RANK[r["state"]]), labels=labels
            )
            _g_link_deviation.set(float(r["deviation"]), labels=labels)
        for r in rows:
            _h_link_latency.observe(
                r["latency_seconds"], labels={"axis": r["axis"]}
            )

    # -- reads -------------------------------------------------------------
    def status(self) -> Dict:
        """Sweep/mesh summary (``GET /v1/fabric``, ``fabricStatus``)."""
        with self._mu:
            mesh = self._mesh
            degraded = sorted(
                name for name, tr in self._tracks.items()
                if tr.state == STATE_DEGRADED
            )
            down = sorted(
                name for name, tr in self._tracks.items()
                if tr.state == STATE_DOWN
            )
            return {
                "mesh": mesh.to_dict() if mesh else None,
                "links": len(self._links),
                "sweeps": self._sweeps,
                "last_sweep_ts": self._last_sweep_ts,
                "last_sweep_seconds": self._last_duration,
                "interval_seconds": self.interval_seconds,
                "latency_threshold_z": self.latency_threshold_z,
                "warmup_sweeps": self.warmup_sweeps,
                "degraded": degraded[:32],
                "down": down[:32],
                "published": self._published,
            }

    def matrix(self) -> List[Dict]:
        """Current per-link matrix, one row per logical link, sorted."""
        with self._mu:
            links = list(self._links)
            out = []
            for link in sorted(links, key=lambda ln: ln.name):
                tr = self._tracks.get(link.name)
                row = link.to_dict()
                row.update({
                    "state": tr.state if tr and tr.state else "",
                    "latency_seconds": tr.latency if tr else 0.0,
                    "deviation": tr.deviation if tr else 0.0,
                    "ts": tr.last_ts if tr else 0.0,
                    "last_degraded_ts": tr.last_degraded_ts if tr else 0.0,
                })
                out.append(row)
            return out

    def history(
        self, link: str = "", since: float = 0.0, limit: int = 256
    ) -> List[Dict]:
        return self.store.history(link=link, since=since, limit=limit)

    def deviation_scores(self) -> Dict[str, float]:
        """Per-link deviation normalized to [0,1] for the predict plane:
        0.5 at the degrade threshold, 1.0 at twice it or hard-down."""
        scale = 2.0 * max(1e-9, self.latency_threshold_z)
        with self._mu:
            out: Dict[str, float] = {}
            for name, tr in self._tracks.items():
                if tr.state == STATE_DOWN:
                    out[name] = 1.0
                else:
                    out[name] = clamp01(tr.deviation / scale)
            return out

    def cooccurrence_score(self) -> float:
        """Neighbor co-occurrence over the mesh adjacency — correlated
        deviations on links sharing a chip score together (ROADMAP item
        4's cross-component co-occurrence, first leg)."""
        with self._mu:
            adjacency = self._adjacency
        return neighbor_cooccurrence(self.deviation_scores(), adjacency)

    # -- lifecycle ---------------------------------------------------------
    def start(self, scheduler) -> None:
        self._job = scheduler.add_job(
            JOB_NAME,
            self.sweep_once,
            interval=self.interval_seconds,
            initial_delay=self.interval_seconds,
        )

    def poke(self) -> None:
        """Run a sweep now (chaos expectations, trigger paths)."""
        job = self._job
        if job is not None and hasattr(job, "poke"):
            job.poke()
        else:
            self.sweep_once()

    def purge_once(self) -> int:
        """Retention hook: drop matrix rows past the window."""
        return self.store.purge(
            before=self.time_now_fn() - self.retention_seconds
        )

    def close(self) -> None:
        job, self._job = self._job, None
        if job is not None and hasattr(job, "cancel"):
            job.cancel()


def _build_adjacency(links: List[MeshLink]) -> Dict[str, List[str]]:
    """link name -> names of links sharing a chip endpoint."""
    by_chip: Dict[int, List[str]] = {}
    for ln in links:
        by_chip.setdefault(ln.src_chip, []).append(ln.name)
        by_chip.setdefault(ln.dst_chip, []).append(ln.name)
    adj: Dict[str, set] = {ln.name: set() for ln in links}
    for names in by_chip.values():
        for name in names:
            adj[name].update(n for n in names if n != name)
    return {name: sorted(peers) for name, peers in adj.items()}
