"""Fabric observability plane: mesh discovery, all-links sweep, matrix.

The per-host ICI component (components/tpu/ici.py) answers "is any port
on this host down". This package answers the fabric-level question the
ROADMAP's north star asks — "which ICI links in the pod degraded this
week" — by discovering the logical device mesh, sweeping every logical
link on a scheduler cadence, keeping per-link EWMA latency baselines,
and shipping deviations to the manager as ``ici_link`` outbox records
(see docs/fabric.md).
"""

from gpud_tpu.fabric.mesh import MeshLink, MeshSpec, discover_mesh, mesh_links
from gpud_tpu.fabric.plane import FabricPlane
from gpud_tpu.fabric.store import FabricMatrixStore

__all__ = [
    "FabricMatrixStore",
    "FabricPlane",
    "MeshLink",
    "MeshSpec",
    "discover_mesh",
    "mesh_links",
]
