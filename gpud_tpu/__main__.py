import sys

from gpud_tpu.cli import main

sys.exit(main())
