"""Fault injector.

Reference: pkg/fault-injector/fault_injector.go:12-69 — an ``Injector``
wrapping the KmsgWriter; requests carry either a catalogued error name
(the XID-id analog) or a raw kernel message. Injected lines flow through
the real watcher→syncer→eventstore detection path, making injection both a
product feature and the e2e test harness (SURVEY §4.7).

Beyond the reference's one-shot write, a request may carry a burst/flap
pattern (``repeat`` writes spaced ``interval_seconds`` apart) so chaos
campaigns (gpud_tpu/chaos/) can model link flaps and error storms with a
single request, and ``inject`` returns a structured :class:`InjectResult`
(line written, catalog entry, timestamp, write count) instead of a bare
error-or-None.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

from gpud_tpu.components.tpu import catalog
from gpud_tpu.kmsg.writer import KmsgWriter
from gpud_tpu.log import audit, get_logger

logger = get_logger(__name__)

DEFAULT_PRIORITY = 2  # crit

# burst-pattern guardrails: injection is a product feature reachable from
# the control plane, so a single request must never be able to spin a
# worker for minutes or flood kmsg unbounded
MAX_REPEAT = 100
MAX_INTERVAL_SECONDS = 5.0
MAX_BURST_SECONDS = 30.0


@dataclass
class Request:
    """Either ``tpu_error_name`` (catalogued) or ``kernel_message``
    (reference: Request{XID|KernelMessage}). ``repeat``/``interval_seconds``
    turn the one-shot into a burst (flap storms, cascading link loss)."""

    tpu_error_name: str = ""
    chip_id: int = 0
    detail: str = ""
    kernel_message: str = ""
    priority: int = DEFAULT_PRIORITY
    repeat: int = 1
    interval_seconds: float = 0.0

    def validate(self) -> Optional[str]:
        if not self.tpu_error_name and not self.kernel_message:
            return "one of tpu_error_name or kernel_message is required"
        if self.tpu_error_name and catalog.lookup(self.tpu_error_name) is None:
            known = ", ".join(sorted(e.name for e in catalog.CATALOG))
            return f"unknown tpu_error_name {self.tpu_error_name!r}; known: {known}"
        if not (1 <= self.repeat <= MAX_REPEAT):
            return f"repeat must be in [1, {MAX_REPEAT}]"
        if not (0.0 <= self.interval_seconds <= MAX_INTERVAL_SECONDS):
            return f"interval_seconds must be in [0, {MAX_INTERVAL_SECONDS:g}]"
        if (self.repeat - 1) * self.interval_seconds > MAX_BURST_SECONDS:
            return (
                f"burst too long: {(self.repeat - 1) * self.interval_seconds:g}s "
                f"(max {MAX_BURST_SECONDS:g}s)"
            )
        return None

    @classmethod
    def from_dict(cls, d: dict) -> "Request":
        return cls(
            tpu_error_name=d.get("tpu_error_name", "") or d.get("name", ""),
            chip_id=int(d.get("chip_id", 0)),
            detail=d.get("detail", ""),
            kernel_message=d.get("kernel_message", ""),
            priority=int(d.get("priority", DEFAULT_PRIORITY)),
            repeat=int(d.get("repeat", 1)),
            interval_seconds=float(d.get("interval_seconds", 0.0)),
        )


@dataclass
class InjectResult:
    """What one ``inject`` call actually did: the kmsg line written, the
    catalog entry it maps to (empty for raw kernel messages), when, and
    how many burst writes landed. ``ok`` is False with ``error`` set on
    validation or writer failure."""

    ok: bool
    error: str = ""
    line: str = ""
    entry: str = ""
    code: int = 0
    timestamp: float = field(default=0.0)
    writes: int = 0

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "error": self.error,
            "line": self.line,
            "entry": self.entry,
            "code": self.code,
            "timestamp": self.timestamp,
            "writes": self.writes,
        }


class Injector:
    def __init__(self, writer: Optional[KmsgWriter] = None, kmsg_path: str = "") -> None:
        self.writer = writer or KmsgWriter(path=kmsg_path)
        # injectable for burst tests: no real sleeping under a fake clock
        self.sleep_fn = time.sleep
        self.time_now_fn = time.time

    def inject(self, req: Request) -> InjectResult:
        """Write the fault line (``repeat`` times, ``interval_seconds``
        apart) and return a structured :class:`InjectResult`."""
        err = req.validate()
        if err:
            return InjectResult(ok=False, error=err)
        entry_name, code = "", 0
        if req.tpu_error_name:
            line = catalog.injection_line(req.tpu_error_name, req.chip_id, req.detail)
            entry = catalog.lookup(req.tpu_error_name)
            if entry is not None:
                entry_name, code = entry.name, entry.code
        else:
            line = req.kernel_message
        audit("inject_fault", line=line, repeat=req.repeat)
        logger.info("injecting fault (x%d): %s", req.repeat, line)
        writes = 0
        ts = self.time_now_fn()
        for i in range(req.repeat):
            if i > 0 and req.interval_seconds > 0:
                self.sleep_fn(req.interval_seconds)
            werr = self.writer.write(line, priority=req.priority)
            if werr:
                return InjectResult(
                    ok=False,
                    error=werr,
                    line=line,
                    entry=entry_name,
                    code=code,
                    timestamp=ts,
                    writes=writes,
                )
            writes += 1
        return InjectResult(
            ok=True,
            line=line,
            entry=entry_name,
            code=code,
            timestamp=ts,
            writes=writes,
        )
