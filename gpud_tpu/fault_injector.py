"""Fault injector.

Reference: pkg/fault-injector/fault_injector.go:12-69 — an ``Injector``
wrapping the KmsgWriter; requests carry either a catalogued error name
(the XID-id analog) or a raw kernel message. Injected lines flow through
the real watcher→syncer→eventstore detection path, making injection both a
product feature and the e2e test harness (SURVEY §4.7).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from gpud_tpu.components.tpu import catalog
from gpud_tpu.kmsg.writer import KmsgWriter
from gpud_tpu.log import audit, get_logger

logger = get_logger(__name__)

DEFAULT_PRIORITY = 2  # crit


@dataclass
class Request:
    """Either ``tpu_error_name`` (catalogued) or ``kernel_message``
    (reference: Request{XID|KernelMessage})."""

    tpu_error_name: str = ""
    chip_id: int = 0
    detail: str = ""
    kernel_message: str = ""
    priority: int = DEFAULT_PRIORITY

    def validate(self) -> Optional[str]:
        if not self.tpu_error_name and not self.kernel_message:
            return "one of tpu_error_name or kernel_message is required"
        if self.tpu_error_name and catalog.lookup(self.tpu_error_name) is None:
            known = ", ".join(sorted(e.name for e in catalog.CATALOG))
            return f"unknown tpu_error_name {self.tpu_error_name!r}; known: {known}"
        return None

    @classmethod
    def from_dict(cls, d: dict) -> "Request":
        return cls(
            tpu_error_name=d.get("tpu_error_name", "") or d.get("name", ""),
            chip_id=int(d.get("chip_id", 0)),
            detail=d.get("detail", ""),
            kernel_message=d.get("kernel_message", ""),
            priority=int(d.get("priority", DEFAULT_PRIORITY)),
        )


class Injector:
    def __init__(self, writer: Optional[KmsgWriter] = None, kmsg_path: str = "") -> None:
        self.writer = writer or KmsgWriter(path=kmsg_path)

    def inject(self, req: Request) -> Optional[str]:
        """Returns an error string or None."""
        err = req.validate()
        if err:
            return err
        if req.tpu_error_name:
            line = catalog.injection_line(req.tpu_error_name, req.chip_id, req.detail)
        else:
            line = req.kernel_message
        audit("inject_fault", line=line)
        logger.info("injecting fault: %s", line)
        return self.writer.write(line, priority=req.priority)
