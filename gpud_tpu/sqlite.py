"""SQLite helpers.

Reference: pkg/sqlite/sqlite.go:70-130 — read-write/read-only connection
pair, WAL-ish pragmas, Compact (VACUUM), DB-size reader. The reference uses
cgo go-sqlite3; here we use CPython's built-in ``sqlite3`` (the same C
SQLite library underneath — the equivalent native component, per SURVEY §2.7).

Connections are per-thread via a small pool keyed on thread id, since the
daemon checks run on many poller threads.
"""

from __future__ import annotations

import os
import sqlite3
import threading
import time
from typing import Any, Iterable, Optional, Tuple

from gpud_tpu.log import get_logger
from gpud_tpu.metrics.registry import histogram
from gpud_tpu.tracing import DEFAULT_TRACER

logger = get_logger(__name__)


class _NullLock:
    """No-op context manager for the file-backed (per-thread conn) path."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_LOCK = _NullLock()

# self-observability counters (reference: pkg/metrics/recorder/gpud_metrics.go:14-60)
_stats_mu = threading.Lock()
_stats = {
    "select_total": 0,
    "select_seconds": 0.0,
    "insert_update_delete_total": 0,
    "insert_update_delete_seconds": 0.0,
    "vacuum_total": 0,
    "vacuum_seconds": 0.0,
}

# per-query latency distribution — the totals above say how much time sqlite
# ate overall; the histogram says whether it was many fast queries or a few
# stalls (WAL contention, checkpointing, a cold VACUUM)
_h_query = histogram(
    "tpud_sqlite_query_duration_seconds",
    "SQLite query latency by operation kind (select|insert_update_delete|vacuum)",
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
             0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0),
)


def stats() -> dict:
    with _stats_mu:
        return dict(_stats)


def _record(kind: str, seconds: float) -> None:
    with _stats_mu:
        _stats[f"{kind}_total"] += 1
        _stats[f"{kind}_seconds"] += seconds
    _h_query.observe(seconds, {"op": kind})
    # trace only as a child: standalone queries at scrape cadence would
    # flood the ring, but inside a slow check/dispatch span the sqlite leaf
    # is exactly the breakdown the debugger wants
    DEFAULT_TRACER.record(
        f"sqlite.{kind}",
        seconds,
        component="sqlite",
        parent_required=True,
    )


class DB:
    """Thread-safe SQLite handle with per-thread connections.

    ``read_only=True`` opens with mode=ro the way the reference keeps an RO
    connection alongside the RW one (reference: pkg/server/server.go:132-154).
    """

    def __init__(self, path: str, read_only: bool = False) -> None:
        self.path = path
        self.read_only = read_only
        self._local = threading.local()
        self._in_memory = path == ":memory:"
        self._mem_conn: Optional[sqlite3.Connection] = None
        self._mem_lock = threading.Lock()
        if not self._in_memory:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)

    def _connect(self) -> sqlite3.Connection:
        if self._in_memory:
            # a single shared in-memory connection (with a lock) so all
            # threads see the same data (--db-in-memory mode,
            # reference: server.go:132-154)
            with self._mem_lock:
                if self._mem_conn is None:
                    self._mem_conn = sqlite3.connect(
                        ":memory:", check_same_thread=False
                    )
                    self._apply_pragmas(self._mem_conn)
                return self._mem_conn
        conn = getattr(self._local, "conn", None)
        if conn is None:
            if self.read_only:
                uri = f"file:{self.path}?mode=ro"
                conn = sqlite3.connect(uri, uri=True, timeout=10.0)
            else:
                conn = sqlite3.connect(self.path, timeout=10.0)
                self._apply_pragmas(conn)
            self._local.conn = conn
        return conn

    @staticmethod
    def _apply_pragmas(conn: sqlite3.Connection) -> None:
        # WAL + normal sync: the low-footprint write path
        # (reference: pkg/sqlite/sqlite.go:70 connection-string options)
        try:
            conn.execute("PRAGMA journal_mode=WAL")
        except sqlite3.OperationalError:
            pass
        conn.execute("PRAGMA synchronous=NORMAL")
        conn.execute("PRAGMA busy_timeout=10000")

    # -- query API ---------------------------------------------------------
    def execute(self, sql: str, params: Iterable[Any] = ()) -> sqlite3.Cursor:
        conn = self._connect()
        t0 = time.monotonic()
        if self._in_memory:
            with self._mem_lock:
                cur = conn.execute(sql, tuple(params))
                conn.commit()
        else:
            cur = conn.execute(sql, tuple(params))
            conn.commit()
        _record("insert_update_delete", time.monotonic() - t0)
        return cur

    def executemany(self, sql: str, seq) -> None:
        conn = self._connect()
        t0 = time.monotonic()
        if self._in_memory:
            with self._mem_lock:
                conn.executemany(sql, seq)
                conn.commit()
        else:
            conn.executemany(sql, seq)
            conn.commit()
        _record("insert_update_delete", time.monotonic() - t0)

    def run_batch(
        self,
        groups: Iterable[Tuple[str, list]],
        fsync: bool = False,
    ) -> int:
        """Group commit: every (sql, params_list) group in ONE transaction.

        This is the write-behind layer's drain path — the whole flush
        window becomes a single WAL append instead of one commit per row.
        ``fsync=True`` upgrades just this commit to ``synchronous=FULL``
        (one fsync per batch: group-commit durability without paying a
        per-row fsync anywhere else). Atomic: on error the transaction
        rolls back and no group is applied. Returns rows written.
        """
        conn = self._connect()
        t0 = time.monotonic()
        n = 0
        lock = self._mem_lock if self._in_memory else _NULL_LOCK
        with lock:
            if fsync and not self._in_memory:
                conn.execute("PRAGMA synchronous=FULL")
            try:
                for sql, params_list in groups:
                    if not params_list:
                        continue
                    conn.executemany(sql, params_list)
                    n += len(params_list)
                conn.commit()
            except Exception:
                conn.rollback()
                raise
            finally:
                if fsync and not self._in_memory:
                    conn.execute("PRAGMA synchronous=NORMAL")
        _record("insert_update_delete", time.monotonic() - t0)
        return n

    def query(self, sql: str, params: Iterable[Any] = ()) -> list:
        conn = self._connect()
        t0 = time.monotonic()
        if self._in_memory:
            with self._mem_lock:
                rows = conn.execute(sql, tuple(params)).fetchall()
        else:
            rows = conn.execute(sql, tuple(params)).fetchall()
        _record("select", time.monotonic() - t0)
        return rows

    def query_one(self, sql: str, params: Iterable[Any] = ()) -> Optional[Tuple]:
        rows = self.query(sql, params)
        return rows[0] if rows else None

    # -- maintenance -------------------------------------------------------
    def compact(self) -> float:
        """VACUUM (reference: pkg/sqlite/sqlite.go:100 Compact). Returns seconds."""
        conn = self._connect()
        t0 = time.monotonic()
        if self._in_memory:
            with self._mem_lock:
                conn.execute("VACUUM")
        else:
            conn.execute("VACUUM")
        dt = time.monotonic() - t0
        _record("vacuum", dt)
        return dt

    def size_bytes(self) -> int:
        """Reference: pkg/sqlite/sqlite.go:123 DB-size reader."""
        row = self.query_one(
            "SELECT page_count * page_size FROM pragma_page_count(), pragma_page_size()"
        )
        return int(row[0]) if row else 0

    def wal_size_bytes(self) -> int:
        """Size of the sidecar ``-wal`` file (0 when absent / in-memory)."""
        if self._in_memory:
            return 0
        try:
            return os.stat(self.path + "-wal").st_size
        except OSError:
            return 0

    def wal_checkpoint(self, mode: str = "TRUNCATE") -> Tuple[int, int, int]:
        """Run ``PRAGMA wal_checkpoint(mode)``; returns (busy, log_pages,
        checkpointed_pages) — SQLite's own result row. No-op (0, -1, -1)
        for in-memory databases, which have no WAL."""
        if mode not in ("PASSIVE", "FULL", "RESTART", "TRUNCATE"):
            raise ValueError(f"bad wal_checkpoint mode: {mode!r}")
        if self._in_memory:
            return (0, -1, -1)
        conn = self._connect()
        t0 = time.monotonic()
        row = conn.execute(f"PRAGMA wal_checkpoint({mode})").fetchone()
        _record("vacuum", time.monotonic() - t0)
        return (int(row[0]), int(row[1]), int(row[2])) if row else (0, -1, -1)

    def close(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None
        if self._mem_conn is not None and self._in_memory:
            # keep in-memory conn alive until explicit close of the DB object
            with self._mem_lock:
                self._mem_conn.close()
                self._mem_conn = None


def open_rw_ro(path: str) -> Tuple[DB, DB]:
    """Open the RW+RO pair (reference: pkg/server/server.go:132-154).
    For in-memory mode both handles are the same shared connection."""
    rw = DB(path, read_only=False)
    if path == ":memory:":
        return rw, rw
    # make sure the file exists before an RO open
    rw.execute("SELECT 1")
    ro = DB(path, read_only=True)
    return rw, ro
