"""Wire types for the tpud API (v1).

These are the core data types exchanged between components, the local HTTP
API, the client SDK, and the control-plane session. They mirror the semantic
surface of the reference daemon's API types (reference: api/v1/types.go:17-259)
re-designed for TPU fleets: ``TPUInfo`` replaces ``GPUInfo``
(reference: api/v1/types.go:363-391), ICI topology replaces NVLink/IB.

Everything is a plain dataclass with explicit ``to_dict``/``from_dict`` so
the JSON wire format is stable and dependency-free.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


# ---------------------------------------------------------------------------
# Health states (reference: api/v1/types.go:18-25)
# ---------------------------------------------------------------------------

class HealthStateType:
    HEALTHY = "Healthy"
    UNHEALTHY = "Unhealthy"
    DEGRADED = "Degraded"
    INITIALIZING = "Initializing"


class ComponentType:
    CUSTOM_PLUGIN = "custom-plugin"


class RunModeType:
    AUTO = "auto"
    MANUAL = "manual"


# ---------------------------------------------------------------------------
# Suggested actions (reference: api/v1/types.go:183-221)
# ---------------------------------------------------------------------------

class RepairActionType:
    IGNORE_NO_ACTION_REQUIRED = "IGNORE_NO_ACTION_REQUIRED"
    REBOOT_SYSTEM = "REBOOT_SYSTEM"
    HARDWARE_INSPECTION = "HARDWARE_INSPECTION"
    CHECK_USER_APP_AND_TPU = "CHECK_USER_APP_AND_TPU"
    # minted by the predict engine (gpud_tpu/predict/) ahead of a hard
    # fault; advisory only — map_suggested_action never resolves it to an
    # executable action, so it can never leave dry-run
    PREDICTED_DEGRADATION = "PREDICTED_DEGRADATION"


@dataclass
class SuggestedActions:
    description: str = ""
    repair_actions: List[str] = field(default_factory=list)

    def describe_actions(self) -> str:
        return ", ".join(self.repair_actions)

    def to_dict(self) -> Dict[str, Any]:
        return {"description": self.description, "repair_actions": list(self.repair_actions)}

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> Optional["SuggestedActions"]:
        if not d:
            return None
        return cls(
            description=d.get("description", ""),
            repair_actions=list(d.get("repair_actions", []) or []),
        )


# ---------------------------------------------------------------------------
# Event types (reference: api/v1/types.go:222-259)
# ---------------------------------------------------------------------------

class EventType:
    UNKNOWN = "Unknown"
    INFO = "Info"          # informative, no action needed
    WARNING = "Warning"    # may impact workloads, automatic recovery expected
    CRITICAL = "Critical"  # impacting workloads, action required, not hardware
    FATAL = "Fatal"        # hardware/system-wide, may require reboot/repair

    _ALL = ("Info", "Warning", "Critical", "Fatal")

    @staticmethod
    def from_string(s: str) -> str:
        return s if s in EventType._ALL else EventType.UNKNOWN


# ---------------------------------------------------------------------------
# HealthState (reference: api/v1/types.go:46-100)
# ---------------------------------------------------------------------------

@dataclass
class HealthState:
    time: float = 0.0  # unix seconds
    component: str = ""
    component_type: str = ""
    name: str = ""
    run_mode: str = ""
    health: str = HealthStateType.HEALTHY
    reason: str = ""
    error: str = ""
    suggested_actions: Optional[SuggestedActions] = None
    extra_info: Dict[str, str] = field(default_factory=dict)
    raw_output: str = ""

    MAX_RAW_OUTPUT = 4096

    def __post_init__(self) -> None:
        if not self.time:
            self.time = _time.time()
        if len(self.raw_output) > self.MAX_RAW_OUTPUT:
            self.raw_output = self.raw_output[: self.MAX_RAW_OUTPUT]

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"time": self.time, "health": self.health}
        for k in ("component", "component_type", "name", "run_mode", "reason", "error", "raw_output"):
            v = getattr(self, k)
            if v:
                d[k] = v
        if self.suggested_actions is not None:
            d["suggested_actions"] = self.suggested_actions.to_dict()
        if self.extra_info:
            d["extra_info"] = dict(self.extra_info)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "HealthState":
        return cls(
            time=float(d.get("time", 0.0)),
            component=d.get("component", ""),
            component_type=d.get("component_type", ""),
            name=d.get("name", ""),
            run_mode=d.get("run_mode", ""),
            health=d.get("health", HealthStateType.HEALTHY),
            reason=d.get("reason", ""),
            error=d.get("error", ""),
            suggested_actions=SuggestedActions.from_dict(d.get("suggested_actions")),
            extra_info=dict(d.get("extra_info", {}) or {}),
            raw_output=d.get("raw_output", ""),
        )


# ---------------------------------------------------------------------------
# Event (reference: api/v1/types.go:102-136)
# ---------------------------------------------------------------------------

@dataclass
class Event:
    component: str = ""
    time: float = 0.0
    name: str = ""
    type: str = EventType.INFO
    message: str = ""
    # structured payload carried alongside the event, e.g. the raw TPU error
    # detail the way xid events carry their payload in ExtraInfo
    # (reference: xid/component.go:545-570)
    extra_info: Dict[str, str] = field(default_factory=dict)

    def __post_init__(self) -> None:
        if not self.time:
            self.time = _time.time()

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "component": self.component,
            "time": self.time,
            "name": self.name,
            "type": self.type,
            "message": self.message,
        }
        if self.extra_info:
            d["extra_info"] = dict(self.extra_info)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Event":
        return cls(
            component=d.get("component", ""),
            time=float(d.get("time", 0.0)),
            name=d.get("name", ""),
            type=d.get("type", EventType.INFO),
            message=d.get("message", ""),
            extra_info=dict(d.get("extra_info", {}) or {}),
        )


# ---------------------------------------------------------------------------
# Metric (reference: api/v1/types.go:138-150)
# ---------------------------------------------------------------------------

@dataclass
class Metric:
    unix_seconds: int = 0
    name: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    value: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "unix_seconds": self.unix_seconds,
            "name": self.name,
            "value": self.value,
        }
        if self.labels:
            d["labels"] = dict(self.labels)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Metric":
        return cls(
            unix_seconds=int(d.get("unix_seconds", 0)),
            name=d.get("name", ""),
            labels=dict(d.get("labels", {}) or {}),
            value=float(d.get("value", 0.0)),
        )


# ---------------------------------------------------------------------------
# Aggregate wire envelopes (reference: api/v1/types.go:97-176)
# ---------------------------------------------------------------------------

@dataclass
class ComponentHealthStates:
    component: str = ""
    states: List[HealthState] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {"component": self.component, "states": [s.to_dict() for s in self.states]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ComponentHealthStates":
        return cls(
            component=d.get("component", ""),
            states=[HealthState.from_dict(x) for x in d.get("states", []) or []],
        )


@dataclass
class ComponentEvents:
    component: str = ""
    start_time: float = 0.0
    end_time: float = 0.0
    events: List[Event] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "component": self.component,
            "startTime": self.start_time,
            "endTime": self.end_time,
            "events": [e.to_dict() for e in self.events],
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ComponentEvents":
        return cls(
            component=d.get("component", ""),
            start_time=float(d.get("startTime", 0.0)),
            end_time=float(d.get("endTime", 0.0)),
            events=[Event.from_dict(x) for x in d.get("events", []) or []],
        )


@dataclass
class ComponentMetrics:
    component: str = ""
    metrics: List[Metric] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {"component": self.component, "metrics": [m.to_dict() for m in self.metrics]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ComponentMetrics":
        return cls(
            component=d.get("component", ""),
            metrics=[Metric.from_dict(x) for x in d.get("metrics", []) or []],
        )


@dataclass
class ComponentInfo:
    component: str = ""
    start_time: float = 0.0
    end_time: float = 0.0
    states: List[HealthState] = field(default_factory=list)
    events: List[Event] = field(default_factory=list)
    metrics: List[Metric] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "component": self.component,
            "startTime": self.start_time,
            "endTime": self.end_time,
            "info": {
                "states": [s.to_dict() for s in self.states],
                "events": [e.to_dict() for e in self.events],
                "metrics": [m.to_dict() for m in self.metrics],
            },
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ComponentInfo":
        info = d.get("info", {}) or {}
        return cls(
            component=d.get("component", ""),
            start_time=float(d.get("startTime", 0.0)),
            end_time=float(d.get("endTime", 0.0)),
            states=[HealthState.from_dict(x) for x in info.get("states", []) or []],
            events=[Event.from_dict(x) for x in info.get("events", []) or []],
            metrics=[Metric.from_dict(x) for x in info.get("metrics", []) or []],
        )


# ---------------------------------------------------------------------------
# Package status (reference: api/v1/types.go:167-181)
# ---------------------------------------------------------------------------

class PackagePhase:
    INSTALLED = "Installed"
    INSTALLING = "Installing"
    UNKNOWN = "Unknown"
    SKIPPED = "Skipped"


@dataclass
class PackageStatus:
    name: str = ""
    phase: str = PackagePhase.UNKNOWN
    status: str = ""
    current_version: str = ""
    target_version: str = ""
    progress: int = 0
    is_installed: bool = False
    installing: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "phase": self.phase,
            "status": self.status,
            "current_version": self.current_version,
            "target_version": self.target_version,
            "progress": self.progress,
            "is_installed": self.is_installed,
            "installing": self.installing,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PackageStatus":
        return cls(
            name=d.get("name", ""),
            phase=d.get("phase", PackagePhase.UNKNOWN),
            status=d.get("status", ""),
            current_version=d.get("current_version", ""),
            target_version=d.get("target_version", ""),
            progress=int(d.get("progress", 0)),
            is_installed=bool(d.get("is_installed", False)),
            installing=bool(d.get("installing", False)),
        )


# ---------------------------------------------------------------------------
# Machine info tree (reference: api/v1/types.go:261-499) — TPU edition
# ---------------------------------------------------------------------------

@dataclass
class TPUChipInfo:
    """Per-chip info; the TPU analog of GPUInfo (reference: api/v1/types.go:363-391)."""

    chip_id: int = 0
    device_path: str = ""        # /dev/accel0, /dev/vfio/…
    pci_address: str = ""
    serial: str = ""
    hbm_total_bytes: int = 0
    cores_per_chip: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "chip_id": self.chip_id,
            "device_path": self.device_path,
            "pci_address": self.pci_address,
            "serial": self.serial,
            "hbm_total_bytes": self.hbm_total_bytes,
            "cores_per_chip": self.cores_per_chip,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TPUChipInfo":
        return cls(
            chip_id=int(d.get("chip_id", 0)),
            device_path=d.get("device_path", ""),
            pci_address=d.get("pci_address", ""),
            serial=d.get("serial", ""),
            hbm_total_bytes=int(d.get("hbm_total_bytes", 0)),
            cores_per_chip=int(d.get("cores_per_chip", 0)),
        )


@dataclass
class TPUInfo:
    """Slice/topology description, reported in MachineInfo the way GPUInfo
    reports UUID/BusID (reference: api/v1/types.go:363-391, SURVEY §5.8)."""

    product: str = ""            # e.g. "v5p"
    accelerator_type: str = ""   # e.g. "v5p-256"
    topology: str = ""           # e.g. "4x4x8"
    generation: str = ""         # e.g. "v5p"
    chip_count: int = 0
    hosts_per_slice: int = 1
    worker_id: int = 0
    runtime_version: str = ""    # tpu-vm runtime / libtpu version
    driver_version: str = ""
    chips: List[TPUChipInfo] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "product": self.product,
            "accelerator_type": self.accelerator_type,
            "topology": self.topology,
            "generation": self.generation,
            "chip_count": self.chip_count,
            "hosts_per_slice": self.hosts_per_slice,
            "worker_id": self.worker_id,
            "runtime_version": self.runtime_version,
            "driver_version": self.driver_version,
            "chips": [c.to_dict() for c in self.chips],
        }

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> Optional["TPUInfo"]:
        if not d:
            return None
        return cls(
            product=d.get("product", ""),
            accelerator_type=d.get("accelerator_type", ""),
            topology=d.get("topology", ""),
            generation=d.get("generation", ""),
            chip_count=int(d.get("chip_count", 0)),
            hosts_per_slice=int(d.get("hosts_per_slice", 1)),
            worker_id=int(d.get("worker_id", 0)),
            runtime_version=d.get("runtime_version", ""),
            driver_version=d.get("driver_version", ""),
            chips=[TPUChipInfo.from_dict(c) for c in d.get("chips", []) or []],
        )


@dataclass
class DiskInfo:
    device: str = ""
    mount_point: str = ""
    fstype: str = ""
    total_bytes: int = 0
    used_bytes: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "device": self.device,
            "mount_point": self.mount_point,
            "fstype": self.fstype,
            "total_bytes": self.total_bytes,
            "used_bytes": self.used_bytes,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DiskInfo":
        return cls(
            device=d.get("device", ""),
            mount_point=d.get("mount_point", ""),
            fstype=d.get("fstype", ""),
            total_bytes=int(d.get("total_bytes", 0)),
            used_bytes=int(d.get("used_bytes", 0)),
        )


@dataclass
class NICInfo:
    name: str = ""
    mac: str = ""
    addresses: List[str] = field(default_factory=list)
    mtu: int = 0
    speed_mbps: int = 0
    driver: str = ""       # kernel driver bound to the device (gve, virtio_net, ...)
    virtual: bool = False  # no backing device in /sys/class/net/<nic>/device

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "mac": self.mac,
            "addresses": list(self.addresses),
            "mtu": self.mtu,
            "speed_mbps": self.speed_mbps,
            "driver": self.driver,
            "virtual": self.virtual,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "NICInfo":
        return cls(
            name=d.get("name", ""),
            mac=d.get("mac", ""),
            addresses=list(d.get("addresses", []) or []),
            mtu=int(d.get("mtu", 0)),
            speed_mbps=int(d.get("speed_mbps", 0)),
            driver=d.get("driver", ""),
            virtual=bool(d.get("virtual", False)),
        )


@dataclass
class BlockDeviceInfo:
    """One node of the block-device tree (reference:
    pkg/machine-info/machine_info.go:45-434 builds the lsblk-style
    disk tree; here it is read from /sys/block directly)."""

    name: str = ""
    type: str = "disk"          # disk | part
    size_bytes: int = 0
    model: str = ""
    rotational: bool = False
    removable: bool = False
    mount_point: str = ""
    fstype: str = ""
    used_bytes: int = 0
    children: List["BlockDeviceInfo"] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "type": self.type,
            "size_bytes": self.size_bytes,
            "model": self.model,
            "rotational": self.rotational,
            "removable": self.removable,
            "mount_point": self.mount_point,
            "fstype": self.fstype,
            "used_bytes": self.used_bytes,
        }
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "BlockDeviceInfo":
        return cls(
            name=d.get("name", ""),
            type=d.get("type", "disk"),
            size_bytes=int(d.get("size_bytes", 0)),
            model=d.get("model", ""),
            rotational=bool(d.get("rotational", False)),
            removable=bool(d.get("removable", False)),
            mount_point=d.get("mount_point", ""),
            fstype=d.get("fstype", ""),
            used_bytes=int(d.get("used_bytes", 0)),
            children=[
                cls.from_dict(c) for c in d.get("children", []) or []
            ],
        )


@dataclass
class MachineInfo:
    """Host description sent in the login/gossip requests
    (reference: api/v1/types.go:261-361)."""

    machine_id: str = ""
    hostname: str = ""
    os: str = ""
    kernel_version: str = ""
    boot_id: str = ""
    uptime_seconds: int = 0
    cpu_model: str = ""
    cpu_logical_cores: int = 0
    memory_total_bytes: int = 0
    provider: str = ""
    region: str = ""
    instance_type: str = ""
    public_ip: str = ""
    private_ip: str = ""
    tpud_version: str = ""
    containerized: bool = False
    tpu_info: Optional[TPUInfo] = None
    disks: List[DiskInfo] = field(default_factory=list)
    nics: List[NICInfo] = field(default_factory=list)
    block_devices: List[BlockDeviceInfo] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "machine_id": self.machine_id,
            "hostname": self.hostname,
            "os": self.os,
            "kernel_version": self.kernel_version,
            "boot_id": self.boot_id,
            "uptime_seconds": self.uptime_seconds,
            "cpu_model": self.cpu_model,
            "cpu_logical_cores": self.cpu_logical_cores,
            "memory_total_bytes": self.memory_total_bytes,
            "provider": self.provider,
            "region": self.region,
            "instance_type": self.instance_type,
            "public_ip": self.public_ip,
            "private_ip": self.private_ip,
            "tpud_version": self.tpud_version,
            "containerized": self.containerized,
            "disks": [x.to_dict() for x in self.disks],
            "nics": [x.to_dict() for x in self.nics],
            "block_devices": [x.to_dict() for x in self.block_devices],
        }
        if self.tpu_info is not None:
            d["tpu_info"] = self.tpu_info.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "MachineInfo":
        return cls(
            machine_id=d.get("machine_id", ""),
            hostname=d.get("hostname", ""),
            os=d.get("os", ""),
            kernel_version=d.get("kernel_version", ""),
            boot_id=d.get("boot_id", ""),
            uptime_seconds=int(d.get("uptime_seconds", 0)),
            cpu_model=d.get("cpu_model", ""),
            cpu_logical_cores=int(d.get("cpu_logical_cores", 0)),
            memory_total_bytes=int(d.get("memory_total_bytes", 0)),
            provider=d.get("provider", ""),
            region=d.get("region", ""),
            instance_type=d.get("instance_type", ""),
            public_ip=d.get("public_ip", ""),
            private_ip=d.get("private_ip", ""),
            tpud_version=d.get("tpud_version", ""),
            containerized=bool(d.get("containerized", False)),
            tpu_info=TPUInfo.from_dict(d.get("tpu_info")),
            disks=[DiskInfo.from_dict(x) for x in d.get("disks", []) or []],
            nics=[NICInfo.from_dict(x) for x in d.get("nics", []) or []],
            block_devices=[
                BlockDeviceInfo.from_dict(x)
                for x in d.get("block_devices", []) or []
            ],
        )


# ---------------------------------------------------------------------------
# Login / gossip (reference: api/v1/login.go:6-80, api/v1/gossip.go:3-13)
# ---------------------------------------------------------------------------

@dataclass
class LoginRequest:
    token: str = ""
    machine_id: str = ""
    network: Dict[str, str] = field(default_factory=dict)
    machine_info: Optional[MachineInfo] = None
    node_labels: Dict[str, str] = field(default_factory=dict)
    provider: str = ""
    region: str = ""

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "token": self.token,
            "machine_id": self.machine_id,
            "network": dict(self.network),
            "node_labels": dict(self.node_labels),
            "provider": self.provider,
            "region": self.region,
        }
        if self.machine_info is not None:
            d["machine_info"] = self.machine_info.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "LoginRequest":
        # the manager side decodes what the agent encodes (the reference
        # only ships the agent; our runnable control plane needs both
        # directions of the login wire type)
        return cls(
            token=d.get("token", ""),
            machine_id=d.get("machine_id", ""),
            network=dict(d.get("network", {}) or {}),
            machine_info=(
                MachineInfo.from_dict(d["machine_info"])
                if d.get("machine_info")
                else None
            ),
            node_labels=dict(d.get("node_labels", {}) or {}),
            provider=d.get("provider", ""),
            region=d.get("region", ""),
        )


@dataclass
class LoginResponse:
    machine_id: str = ""
    token: str = ""
    machine_proof: str = ""
    error: str = ""
    status: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {
            "machine_id": self.machine_id,
            "token": self.token,
            "machine_proof": self.machine_proof,
            "error": self.error,
            "status": self.status,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "LoginResponse":
        return cls(
            machine_id=d.get("machine_id", ""),
            token=d.get("token", ""),
            machine_proof=d.get("machine_proof", ""),
            error=d.get("error", ""),
            status=d.get("status", ""),
        )


@dataclass
class GossipRequest:
    machine_id: str = ""
    machine_info: Optional[MachineInfo] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"machine_id": self.machine_id}
        if self.machine_info is not None:
            d["machine_info"] = self.machine_info.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "GossipRequest":
        return cls(
            machine_id=d.get("machine_id", ""),
            machine_info=(
                MachineInfo.from_dict(d["machine_info"])
                if d.get("machine_info")
                else None
            ),
        )
