"""OS component (reference: components/os — uname, /proc fd counts
(file_descriptors.go), reboot events, kernel panic detection via pstore,
too-many-open-files thresholds)."""

from __future__ import annotations

import os
import re
from typing import Optional

from gpud_tpu import host as pkghost
from gpud_tpu.api.v1.types import EventType, HealthStateType
from gpud_tpu.components.base import CheckResult, PollingComponent, TpudInstance
from gpud_tpu.metrics.registry import gauge

NAME = "os"

_g_fds_alloc = gauge("tpud_os_file_descriptors_allocated", "system-wide allocated fds")
_g_fds_limit = gauge("tpud_os_file_descriptors_limit", "system-wide fd limit")
_g_uptime = gauge("tpud_os_uptime_seconds", "seconds since boot")

LABELS = {"component": NAME}

DEFAULT_FD_USAGE_DEGRADED = 0.90

PANIC_RE = re.compile(
    r"(Kernel panic|kernel BUG at|Oops:|general protection fault|unable to handle kernel)",
    re.IGNORECASE,
)


def match_kernel_panic(line: str) -> Optional[tuple]:
    if PANIC_RE.search(line):
        return ("kernel_panic", EventType.FATAL, line.strip())
    return None


def _read_file_nr() -> tuple:
    """(allocated, limit) from /proc/sys/fs/file-nr."""
    try:
        with open("/proc/sys/fs/file-nr", "r", encoding="ascii") as f:
            parts = f.read().split()
        return int(parts[0]), int(parts[2])
    except (OSError, IndexError, ValueError):
        return 0, 0


class OSComponent(PollingComponent):
    NAME = NAME
    TAGS = ["host", "os"]

    def __init__(self, instance: TpudInstance) -> None:
        super().__init__(instance)
        self.get_file_nr_fn = _read_file_nr
        self.get_uptime_fn = pkghost.uptime_seconds
        self._event_bucket = (
            instance.event_store.bucket(NAME) if instance.event_store else None
        )
        # pstore crash attribution: a dump appearing after a reboot means
        # the reboot was a kernel panic (reference: components/os + pkg/pstore)
        self._pstore_history = None
        if instance.db_rw is not None:
            from gpud_tpu.pstore import PstoreHistory

            self._pstore_history = PstoreHistory(instance.db_rw)

    def _check_pstore(self) -> None:
        if self._pstore_history is None or self._event_bucket is None:
            return
        from gpud_tpu.api.v1.types import Event, EventType
        from gpud_tpu.pstore import read_crash_files

        fresh = self._pstore_history.record_new(read_crash_files())
        for rec in fresh:
            self._event_bucket.insert(
                Event(
                    component=NAME,
                    time=rec.mtime,
                    name="kernel_crash_dump",
                    type=EventType.FATAL,
                    message=f"pstore {rec.kind} dump {rec.path}: {rec.excerpt[:300]}",
                )
            )

    def check_once(self) -> CheckResult:
        try:
            self._check_pstore()
        except Exception:  # noqa: BLE001 — crash attribution is a side
            # feature; it must never take down fd/uptime monitoring
            import logging

            logging.getLogger("tpud.components.os").exception("pstore check failed")
        alloc, limit = self.get_file_nr_fn()
        up = self.get_uptime_fn()
        _g_fds_alloc.set(alloc, LABELS)
        _g_fds_limit.set(limit, LABELS)
        _g_uptime.set(up, LABELS)

        health = HealthStateType.HEALTHY
        reason = (
            f"kernel {pkghost.kernel_version()}, up {up / 3600:.1f}h, "
            f"fds {alloc}/{limit or '?'}"
        )
        if limit and alloc / limit >= DEFAULT_FD_USAGE_DEGRADED:
            health = HealthStateType.DEGRADED
            reason = f"too many open files: {alloc}/{limit}"
        return CheckResult(
            self.NAME,
            health=health,
            reason=reason,
            extra_info={
                "kernel_version": pkghost.kernel_version(),
                "os_name": pkghost.os_name(),
                "boot_id": pkghost.boot_id(),
                "machine_id": pkghost.machine_id(),
                "uptime_seconds": f"{up:.0f}",
                "fds_allocated": str(alloc),
                "fds_limit": str(limit),
            },
        )

    def events(self, since: float):
        # reboot events live in the os bucket (reference: pkg/host/event.go)
        if self._event_bucket is None:
            return []
        return self._event_bucket.get(since)
