"""Disk component (reference: components/disk — lsblk/findmnt/statfs usage
with configurable mount points; we use psutil + statvfs which reads the
same kernel sources without exec'ing external tools)."""

from __future__ import annotations

import os
from typing import Dict, List

import psutil

from gpud_tpu.api.v1.types import HealthStateType
from gpud_tpu.components.base import CheckResult, PollingComponent, TpudInstance
from gpud_tpu.metrics.registry import gauge

NAME = "disk"

_g_total = gauge("tpud_disk_total_bytes", "filesystem size")
_g_used = gauge("tpud_disk_used_bytes", "filesystem used")
_g_used_pct = gauge("tpud_disk_used_percent", "filesystem used percent")

DEFAULT_USED_PCT_DEGRADED = 95.0

_EPHEMERAL_FS = {"tmpfs", "devtmpfs", "overlay", "squashfs", "proc", "sysfs", "ramfs"}


class DiskComponent(PollingComponent):
    NAME = NAME
    TAGS = ["host", "disk"]

    def __init__(self, instance: TpudInstance) -> None:
        super().__init__(instance)
        self.mount_points: List[str] = list(instance.mount_points)
        self.mount_targets: List[str] = list(instance.mount_targets)
        self.get_partitions_fn = psutil.disk_partitions
        self.get_usage_fn = psutil.disk_usage

    def _watched_mounts(self) -> Dict[str, str]:
        """mount point → device; always includes '/', plus configured ones."""
        mounts: Dict[str, str] = {}
        try:
            for p in self.get_partitions_fn(all=False):
                if p.fstype in _EPHEMERAL_FS:
                    continue
                mounts[p.mountpoint] = p.device
        except OSError:
            pass
        if "/" not in mounts:
            mounts["/"] = "rootfs"
        return mounts

    def check_once(self) -> CheckResult:
        missing = [p for p in self.mount_points if not os.path.isdir(p)]
        missing += [p for p in self.mount_targets if not os.path.isdir(p)]

        worst_pct = 0.0
        extra: Dict[str, str] = {}
        for mp in sorted(self._watched_mounts()):
            try:
                u = self.get_usage_fn(mp)
            except OSError:
                continue
            labels = {"component": NAME, "mount_point": mp}
            _g_total.set(u.total, labels)
            _g_used.set(u.used, labels)
            _g_used_pct.set(u.percent, labels)
            extra[f"used_percent:{mp}"] = f"{u.percent:.1f}"
            worst_pct = max(worst_pct, u.percent)

        if missing:
            return CheckResult(
                self.NAME,
                health=HealthStateType.UNHEALTHY,
                reason=f"mount point(s) missing: {', '.join(missing)}",
                extra_info=extra,
            )
        health = HealthStateType.HEALTHY
        reason = f"max filesystem usage {worst_pct:.1f}%"
        if worst_pct >= DEFAULT_USED_PCT_DEGRADED:
            health = HealthStateType.DEGRADED
            reason = f"filesystem nearly full: {worst_pct:.1f}% used"
        return CheckResult(self.NAME, health=health, reason=reason, extra_info=extra)
