"""Disk component: usage, mount liveness, block-device tree, and kernel
I/O-error detection.

Reference: components/disk (1306 LoC — lsblk/findmnt device tree, mount
tracking, usage) plus the reference's kmsg-matcher discipline from the
cpu/memory components. Enumeration reads the kernel surfaces directly
(gpud_tpu/blockdev.py — /sys/block + /proc/mounts, no lsblk exec). The
failure path the reference lacks per-line but a dying boot disk needs
(VERDICT r3 #2): blk_update_request / Buffer I/O / EXT4-XFS error /
device-offline kmsg lines flip this component unhealthy, sticky until
set-healthy. Note the TPU kmsg catalog deliberately *excludes* nvme/ahci
lines (components/tpu/catalog.py _NON_TPU_DRIVERS) so storage faults are
never classified as accelerator faults — they are classified here
instead.
"""

from __future__ import annotations

import os
import re
import time
from typing import Dict, List, Optional

import psutil

from gpud_tpu.api.v1.types import (
    Event,
    EventType,
    HealthStateType,
    RepairActionType,
    SuggestedActions,
)
from gpud_tpu.components.base import CheckResult, PollingComponent, TpudInstance
from gpud_tpu.metrics.registry import gauge

NAME = "disk"

_g_total = gauge("tpud_disk_total_bytes", "filesystem size")
_g_used = gauge("tpud_disk_used_bytes", "filesystem used")
_g_used_pct = gauge("tpud_disk_used_percent", "filesystem used percent")
_g_io_errors = gauge(
    "tpud_disk_io_error_events_total", "disk I/O error events in lookback window"
)

DEFAULT_USED_PCT_DEGRADED = 95.0
# Deliberate 3h window (NOT derived from event-store retention, which is
# 14d): long enough that a flapping disk can't look healthy between
# bursts, short enough that one transient I/O error doesn't degrade the
# node for days. Fatal conditions stay sticky until set-healthy anyway
# via recurrence — the window only ages out *isolated* events.
DEFAULT_EVENT_LOOKBACK_SECONDS = 3.0 * 3600

_EPHEMERAL_FS = {"tmpfs", "devtmpfs", "overlay", "squashfs", "proc", "sysfs", "ramfs"}

# --- kernel storage-error lines (kernel printk formats, most-specific
# first; each cites the emitting kernel site) ------------------------------

# block/blk-core.c blk_update_request / older print_req_error: the
# definitive "the device returned an error for a bio" line
_IO_ERROR_RE = re.compile(
    r"(blk_update_request: (?:critical )?(?:medium|target|I/O) error"
    r"|print_req_error: I/O error"
    r"|Buffer I/O error on dev)",
    re.IGNORECASE,
)
# fs/ext4/super.c ext4_handle_error + fs/xfs/xfs_fsops.c shutdown paths
_FS_ERROR_RE = re.compile(
    r"(EXT4-fs error \(device"
    r"|EXT4-fs \([^)]+\): .*(aborted journal|journal has aborted)"
    r"|XFS \([^)]+\): .*(Corruption|shutting down|Internal error)"
    r"|JBD2: .*(detected IO errors|aborting))",
    re.IGNORECASE,
)
# ext4/xfs remount-ro on error (errors=remount-ro) — the boot disk is now
# read-only; the node will limp until writes matter
_REMOUNT_RO_RE = re.compile(
    r"(Remounting filesystem read-only|EXT4-fs \([^)]+\): re-mounted.*read-only)",
    re.IGNORECASE,
)
# scsi/sd.c offline rejection + nvme/host/core.c controller death
_OFFLINE_RE = re.compile(
    r"(rejecting I/O to offline device"
    r"|nvme\s?\S*: (controller is down|Disabling device|Removing after probe failure)"
    r"|nvme\s?\S*: I/O \d+ QID \d+ timeout)",
    re.IGNORECASE,
)

# "(device sda1)" / "on dev sda1" / "nvme0n1: I/O error" — best-effort
# device extraction for the event message
_DEV_RE = re.compile(
    r"(?:device |dev )((?:sd[a-z]+|nvme\d+n\d+|vd[a-z]+|xvd[a-z]+|hd[a-z]+|mmcblk\d+)p?\d*)",
    re.IGNORECASE,
)


def match_disk_error(line: str) -> Optional[tuple]:
    """Kmsg matcher (wired in server._wire_kmsg_syncers, same seam as
    cpu-lockup/OOM): storage I/O, filesystem and device-offline errors
    → disk events. Returns (name, type, message[, extra])."""
    if _REMOUNT_RO_RE.search(line):
        return ("disk_remount_ro", EventType.FATAL, line.strip(), _dev_extra(line))
    if _FS_ERROR_RE.search(line):
        return ("disk_fs_error", EventType.FATAL, line.strip(), _dev_extra(line))
    if _OFFLINE_RE.search(line):
        return ("disk_device_offline", EventType.FATAL, line.strip(), _dev_extra(line))
    if _IO_ERROR_RE.search(line):
        return ("disk_io_error", EventType.CRITICAL, line.strip(), _dev_extra(line))
    return None


def _dev_extra(line: str) -> Dict[str, str]:
    m = _DEV_RE.search(line)
    return {"device": m.group(1)} if m else {}


_FATAL_DISK_EVENTS = {"disk_remount_ro", "disk_fs_error", "disk_device_offline"}


class DiskComponent(PollingComponent):
    NAME = NAME
    TAGS = ["host", "disk"]

    def __init__(self, instance: TpudInstance) -> None:
        super().__init__(instance)
        self.mount_points: List[str] = list(instance.mount_points)
        self.mount_targets: List[str] = list(instance.mount_targets)
        self.get_partitions_fn = psutil.disk_partitions
        self.get_usage_fn = psutil.disk_usage
        self.event_lookback_seconds = DEFAULT_EVENT_LOOKBACK_SECONDS
        self.time_now_fn = time.time
        self.proc_mounts_path = ""   # fixture override
        self._event_bucket = (
            instance.event_store.bucket(NAME) if instance.event_store else None
        )

    def _watched_mounts(self) -> Dict[str, str]:
        """mount point → device; always includes '/', plus configured ones."""
        mounts: Dict[str, str] = {}
        try:
            for p in self.get_partitions_fn(all=False):
                if p.fstype in _EPHEMERAL_FS:
                    continue
                mounts[p.mountpoint] = p.device
        except OSError:
            pass
        if "/" not in mounts:
            mounts["/"] = "rootfs"
        return mounts

    def _read_only_mounts(self) -> List[str]:
        """Filesystems that *tripped* to read-only — the steady-state
        signature of an errors=remount-ro trip (catches remounts from
        before the daemon started, which kmsg can't). Requires BOTH
        ``ro`` and ``errors=remount-ro`` in the options: a deliberately
        ro-mounted volume shows plain ``ro,relatime`` (no errors= policy
        — it is meaningless on a ro mount), while a tripped ext4 keeps
        its fstab error policy alongside the new ro. Scans the whole
        /dev/*-backed table (via blockdev.read_mount_table, which honors
        TPUD_HOST_ROOT): in a container the psutil watched-set sees the
        overlay namespace and would hide a tripped host boot disk."""
        from gpud_tpu.blockdev import read_mount_table

        return sorted(
            e.mount_point
            for e in read_mount_table(proc_mounts=self.proc_mounts_path)
            if "ro" in e.options and "errors=remount-ro" in e.options
        )

    def _recent_disk_events(self) -> List[Event]:
        """Disk events in the lookback window, cut at the latest
        SetHealthy marker (operator clear starts a fresh slate)."""
        if self._event_bucket is None:
            return []
        recent = self._event_bucket.get(
            self.time_now_fn() - self.event_lookback_seconds
        )
        out: List[Event] = []
        for e in recent:  # newest first
            if e.name == "SetHealthy":
                break
            out.append(e)
        return out

    def _block_tree_extra(self, extra: Dict[str, str]) -> None:
        """Disk→partition inventory from /sys/block (the lsblk analog)."""
        from gpud_tpu.blockdev import read_block_tree

        try:
            tree = read_block_tree()
        except Exception:  # noqa: BLE001 — inventory is best-effort
            return
        for d in tree:
            parts = ",".join(p.name for p in d.children) or "-"
            extra[f"blockdev:{d.name}"] = (
                f"{d.size_bytes >> 30}GiB parts={parts}"
                + (f" mount={d.mount_point}" if d.mount_point else "")
            )

    def check_once(self) -> CheckResult:
        missing = [p for p in self.mount_points if not os.path.isdir(p)]
        missing += [p for p in self.mount_targets if not os.path.isdir(p)]

        worst_pct = 0.0
        extra: Dict[str, str] = {}
        for mp in sorted(self._watched_mounts()):
            try:
                u = self.get_usage_fn(mp)
            except OSError:
                continue
            labels = {"component": NAME, "mount_point": mp}
            _g_total.set(u.total, labels)
            _g_used.set(u.used, labels)
            _g_used_pct.set(u.percent, labels)
            extra[f"used_percent:{mp}"] = f"{u.percent:.1f}"
            worst_pct = max(worst_pct, u.percent)
        self._block_tree_extra(extra)

        events = self._recent_disk_events()
        _g_io_errors.set(float(len(events)), {"component": NAME})

        if missing:
            return CheckResult(
                self.NAME,
                health=HealthStateType.UNHEALTHY,
                reason=f"mount point(s) missing: {', '.join(missing)}",
                extra_info=extra,
            )

        ro = self._read_only_mounts()
        fatal = [e for e in events if e.name in _FATAL_DISK_EVENTS]
        if ro or fatal:
            bits = []
            if ro:
                bits.append(f"read-only filesystem(s): {', '.join(ro)}")
            if fatal:
                devs = sorted(
                    {e.extra_info.get("device", "?") for e in fatal if e.extra_info}
                ) or ["?"]
                bits.append(
                    f"{len(fatal)} fatal storage event(s) on {', '.join(devs)} "
                    f"(latest: {fatal[0].name})"
                )
            return CheckResult(
                self.NAME,
                health=HealthStateType.UNHEALTHY,
                reason="; ".join(bits),
                suggested_actions=SuggestedActions(
                    description=(
                        "storage failure — check the disk; fsck/replace, "
                        "then set-healthy to clear"
                    ),
                    repair_actions=[
                        RepairActionType.REBOOT_SYSTEM,
                        RepairActionType.HARDWARE_INSPECTION,
                    ],
                ),
                extra_info=extra,
            )

        if events:  # CRITICAL-but-not-fatal I/O errors: degraded
            devs = sorted(
                {e.extra_info.get("device", "?") for e in events if e.extra_info}
            ) or ["?"]
            return CheckResult(
                self.NAME,
                health=HealthStateType.DEGRADED,
                reason=(
                    f"{len(events)} disk I/O error event(s) on {', '.join(devs)} "
                    f"in last {int(self.event_lookback_seconds / 3600)}h"
                ),
                suggested_actions=SuggestedActions(
                    description="disk I/O errors — SMART/media suspect",
                    repair_actions=[RepairActionType.HARDWARE_INSPECTION],
                ),
                extra_info=extra,
            )

        health = HealthStateType.HEALTHY
        reason = f"max filesystem usage {worst_pct:.1f}%"
        if worst_pct >= DEFAULT_USED_PCT_DEGRADED:
            health = HealthStateType.DEGRADED
            reason = f"filesystem nearly full: {worst_pct:.1f}% used"
        return CheckResult(self.NAME, health=health, reason=reason, extra_info=extra)

    def events(self, since: float):
        if self._event_bucket is None:
            return []
        return self._event_bucket.get(since)

    def set_healthy(self) -> None:
        """Operator clear after disk replacement/fsck (reference pattern:
        components/memory/set_healthy.go)."""
        if self._event_bucket is not None:
            self._event_bucket.insert(
                Event(component=NAME, name="SetHealthy", type=EventType.INFO,
                      message="operator set-healthy")
            )
