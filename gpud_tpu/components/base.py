"""Component model and registry.

The component model is the heart of the daemon: every health check is a
``Component`` that the registry owns and the server/scan paths drive
(reference: components/types.go:20-107, components/registry.go:24-226).

Design notes (TPU edition):
- ``TpudInstance`` is the dependency-injection container handed to every
  component constructor (reference: components/registry.go:24-104 GPUdInstance).
- ``PollingComponent`` implements the shared 1-minute self-ticker pattern
  (reference: components/accelerator/nvidia/temperature/component.go:81-97) so
  concrete components only implement ``check_once``.
- A component's externals are function-valued attributes so tests can swap
  them without mocking frameworks (reference test strategy, SURVEY §4.1).
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Callable, Dict, List, Optional, TYPE_CHECKING

from gpud_tpu.api.v1.types import (
    Event,
    EventType,
    HealthState,
    HealthStateType,
    SuggestedActions,
)
from gpud_tpu.log import get_logger
from gpud_tpu.metrics.registry import counter, gauge, histogram
from gpud_tpu import tracing
from gpud_tpu.tracing import DEFAULT_TRACER

if TYPE_CHECKING:  # avoid import cycles at runtime
    from gpud_tpu.eventstore import EventStore
    from gpud_tpu.health_history import HealthLedger
    from gpud_tpu.host import RebootEventStore
    from gpud_tpu.tpu.instance import TPUInstance

logger = get_logger(__name__)

DEFAULT_POLL_INTERVAL = 60.0  # seconds (reference: temperature/component.go:83)

# self-observability: every component check is measured (tentpole of the
# observability layer; reference direction: pkg/metrics/recorder)
_h_check_duration = histogram(
    "tpud_component_check_duration_seconds",
    "wall time of one component check, by component and outcome",
)
_c_checks = counter(
    "tpud_component_check_total",
    "component checks by component and status (success|failure)",
)
_g_last_check = gauge(
    "tpud_component_last_check_unix_seconds",
    "unix time the component last completed a check (staleness signal)",
)


class AlreadyRegisteredError(Exception):
    pass


class FailureInjector:
    """Test-only failure injection knobs threaded through TpudInstance
    (reference: components/registry.go:77-104)."""

    def __init__(
        self,
        chip_ids_lost: Optional[List[int]] = None,
        chip_ids_requires_reset: Optional[List[int]] = None,
        chip_ids_hbm_ecc_pending: Optional[List[int]] = None,
        chip_ids_thermal_slowdown: Optional[List[int]] = None,
        ici_links_down: Optional[List[str]] = None,
        tpu_enumeration_error: bool = False,
        product_name_override: str = "",
    ) -> None:
        self.chip_ids_lost = chip_ids_lost or []
        self.chip_ids_requires_reset = chip_ids_requires_reset or []
        self.chip_ids_hbm_ecc_pending = chip_ids_hbm_ecc_pending or []
        self.chip_ids_thermal_slowdown = chip_ids_thermal_slowdown or []
        self.ici_links_down = ici_links_down or []
        self.tpu_enumeration_error = tpu_enumeration_error
        self.product_name_override = product_name_override

    def empty(self) -> bool:
        return not (
            self.chip_ids_lost
            or self.chip_ids_requires_reset
            or self.chip_ids_hbm_ecc_pending
            or self.chip_ids_thermal_slowdown
            or self.ici_links_down
            or self.tpu_enumeration_error
            or self.product_name_override
        )


class TpudInstance:
    """DI container for component constructors
    (reference: components/registry.go:24-104)."""

    def __init__(
        self,
        machine_id: str = "",
        tpu_instance: Optional["TPUInstance"] = None,
        db_rw=None,
        db_ro=None,
        event_store: Optional["EventStore"] = None,
        reboot_event_store: Optional["RebootEventStore"] = None,
        mount_points: Optional[List[str]] = None,
        mount_targets: Optional[List[str]] = None,
        kernel_modules_to_check: Optional[List[str]] = None,
        kmsg_path: str = "",
        failure_injector: Optional[FailureInjector] = None,
        config=None,
        health_ledger: Optional["HealthLedger"] = None,
        scheduler=None,
    ) -> None:
        self.machine_id = machine_id
        self.tpu_instance = tpu_instance
        self.db_rw = db_rw
        self.db_ro = db_ro
        self.event_store = event_store
        self.reboot_event_store = reboot_event_store
        self.mount_points = mount_points or []
        self.mount_targets = mount_targets or []
        self.kernel_modules_to_check = kernel_modules_to_check or []
        self.kmsg_path = kmsg_path
        self.failure_injector = failure_injector
        self.config = config
        # health-transition ledger (None in scan mode — like event_store,
        # one-shot scans record no persistent timeline)
        self.health_ledger = health_ledger
        # unified check scheduler (gpud_tpu/scheduler): when present,
        # PollingComponent.start() registers a heap job instead of
        # spawning a dedicated poller thread. None (standalone/test/scan
        # use) keeps the legacy thread-per-poller path.
        self.scheduler = scheduler
        # cross-component fast path: the kmsg pipeline (inotify, ~ms) calls
        # these on fabric-class catalog matches so pollers can open an
        # adaptive fast-poll window instead of waiting out their cadence
        # (listeners take the catalog error name; see ici.py)
        self.fabric_suspicion_listeners: List[Callable[[str], None]] = []


class CheckResult:
    """Result of one component check (reference: components/types.go:85-101).

    Concrete components may subclass to attach structured payloads; the base
    carries the health state list which is all the server needs.
    """

    def __init__(
        self,
        component_name: str,
        health: str = HealthStateType.HEALTHY,
        reason: str = "",
        error: str = "",
        suggested_actions: Optional[SuggestedActions] = None,
        extra_info: Optional[Dict[str, str]] = None,
        component_type: str = "",
        run_mode: str = "",
        raw_output: str = "",
        states: Optional[List[HealthState]] = None,
    ) -> None:
        self._component_name = component_name
        self.health = health
        self.reason = reason
        self.error = error
        self.suggested_actions = suggested_actions
        self.extra_info = extra_info or {}
        self.component_type = component_type
        self.run_mode = run_mode
        self.raw_output = raw_output
        self.time = time.time()
        self._states = states

    def component_name(self) -> str:
        return self._component_name

    def summary(self) -> str:
        return self.reason or ("ok" if self.health == HealthStateType.HEALTHY else self.health)

    def health_state_type(self) -> str:
        return self.health

    def health_states(self) -> List[HealthState]:
        if self._states is not None:
            return list(self._states)
        return [
            HealthState(
                time=self.time,
                component=self._component_name,
                component_type=self.component_type,
                name=self._component_name,
                run_mode=self.run_mode,
                health=self.health,
                reason=self.reason,
                error=self.error,
                suggested_actions=self.suggested_actions,
                extra_info=dict(self.extra_info),
                raw_output=self.raw_output,
            )
        ]

    def __str__(self) -> str:
        return self.summary()


class Component:
    """Base component (reference: components/types.go:20-67).

    Subclasses must set ``NAME`` and implement ``check_once() -> CheckResult``.
    Optional capabilities mirror the reference's optional interfaces:
    ``can_deregister()`` (Deregisterable), ``set_healthy()`` (HealthSettable).
    """

    NAME = ""
    TAGS: List[str] = []

    def __init__(self, instance: TpudInstance) -> None:
        self.instance = instance
        self._last_mu = threading.Lock()
        self._last_check_result: Optional[CheckResult] = None
        self._last_check_duration = 0.0

    # -- identity ----------------------------------------------------------
    def name(self) -> str:
        return self.NAME

    def tags(self) -> List[str]:
        return list(self.TAGS)

    def is_supported(self) -> bool:
        return True

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Called at server start; spawn pollers here."""

    def close(self) -> None:
        """Called at server shutdown."""

    # -- checking ----------------------------------------------------------
    def check_once(self) -> CheckResult:
        raise NotImplementedError

    def check(self) -> CheckResult:
        """Run the check, trapping exceptions into an Unhealthy result so a
        crashing data source never takes the poller loop down. Every check
        is measured: duration histogram + success/failure counter + a trace
        span in the ring (sqlite leaves nest under it)."""
        t0 = time.monotonic()
        raised = False
        # one correlation id per check run: stamped on the root span AND
        # held in the tracing thread-local across the ledger observe()
        # below (which fires transition hooks after the span closes) —
        # the outbox producers read it so the manager can stitch a fleet
        # event back to this exact trace
        cid = tracing.new_correlation_id()
        tracing.set_correlation_id(cid)
        try:
            with DEFAULT_TRACER.span("component.check", component=self.NAME) as sp:
                sp.set_attr("correlation_id", cid)
                try:
                    cr = self.check_once()
                except Exception as e:  # noqa: BLE001 — health checks must not raise
                    raised = True
                    logger.exception("component %s check failed", self.NAME)
                    cr = CheckResult(
                        component_name=self.NAME,
                        health=HealthStateType.UNHEALTHY,
                        reason=f"check failed: {e}",
                        error=traceback.format_exc(limit=5),
                    )
                sp.set_attr("health", cr.health)
                if cr.reason:
                    sp.set_attr("reason", cr.reason[:200])
                if raised:
                    sp.status = "error"
                    sp.error = cr.reason[:500]
            duration = time.monotonic() - t0
            ok = not raised and cr.health == HealthStateType.HEALTHY
            _h_check_duration.observe(duration, {"component": self.NAME})
            _c_checks.inc(
                labels={
                    "component": self.NAME,
                    "status": "success" if ok else "failure",
                }
            )
            _g_last_check.set(time.time(), {"component": self.NAME})
            ledger = getattr(self.instance, "health_ledger", None)
            if ledger is not None:
                try:
                    annotations = ledger.observe(self.NAME, cr.health, cr.reason)
                    if annotations:
                        cr.extra_info.update(annotations)
                except Exception:  # noqa: BLE001 — accounting must not fail checks
                    logger.exception("health ledger observe failed for %s", self.NAME)
        finally:
            tracing.clear_correlation_id()
        self._last_check_duration = duration
        with self._last_mu:
            self._last_check_result = cr
        return cr

    def last_health_states(self) -> List[HealthState]:
        """Latest cached health states; Healthy-by-default before first check
        (reference: components/types.go:54-58)."""
        with self._last_mu:
            cr = self._last_check_result
        if cr is None:
            return [
                HealthState(
                    component=self.NAME,
                    name=self.NAME,
                    health=HealthStateType.INITIALIZING,
                    reason="no check performed yet",
                )
            ]
        return cr.health_states()

    def events(self, since: float) -> List[Event]:
        return []

    # -- optional capabilities --------------------------------------------
    def can_deregister(self) -> bool:
        return False


class PollingComponent(Component):
    """Component with the shared periodic-check pattern
    (reference: components/accelerator/nvidia/temperature/component.go:81-97).

    With a scheduler on the instance (the daemon path), ``start()``
    registers a deadline-heap job on the shared bounded pool — no thread
    is spawned, the first check runs on the pool off the startup path,
    and a hung check is watchdogged into a Degraded-stale cached result
    while the pool keeps draining. Without one (standalone components in
    tests/benches, scan mode), the legacy dedicated ``tpud-poll-<name>``
    thread is kept.

    ``time_now_fn`` / ``sleep interval`` are injectable for tests.
    """

    POLL_INTERVAL = DEFAULT_POLL_INTERVAL
    # a check slower than SLOW_CHECK_FACTOR × poll_interval() can't keep its
    # cadence; emit a Warning event so the control plane sees WHICH check is
    # dragging (rate-limited: one event per cooldown window, not per cycle)
    SLOW_CHECK_FACTOR = 1.0
    SLOW_CHECK_EVENT_COOLDOWN = 300.0

    def __init__(self, instance: TpudInstance) -> None:
        super().__init__(instance)
        self._stop_event = threading.Event()
        self._poke_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._job = None  # scheduler Job when scheduler-driven
        self._last_slow_event_at = 0.0
        self.time_now_fn: Callable[[], float] = time.time

    def start(self) -> None:
        scheduler = getattr(self.instance, "scheduler", None)
        if scheduler is not None:
            if self._job is not None:
                return
            self._job = scheduler.add_job(
                f"component:{self.NAME}",
                self._scheduled_run,
                interval_fn=self.poll_interval,
                on_hang=self._mark_check_stale,
            )
            return
        if self._thread is not None:
            return
        self._stop_event.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"tpud-poll-{self.NAME}", daemon=True
        )
        self._thread.start()

    def poll_interval(self) -> float:
        """Next sleep; override for adaptive cadences (e.g. the ICI
        component's fast-poll-on-suspicion window). Re-read by the
        scheduler after every run."""
        return self.POLL_INTERVAL

    def poke(self) -> None:
        """Wake the poller now (event-triggered check instead of waiting
        out the cadence)."""
        if self._job is not None:
            self._job.poke()
            return
        self._poke_event.set()

    def _scheduled_run(self) -> None:
        """One scheduler-dispatched cycle: the body of one loop turn."""
        self.check()
        self._report_if_slow()

    def _mark_check_stale(self, elapsed: float) -> None:
        """Watchdog callback: the in-flight check blew its hang budget.
        Publish a Degraded-stale cached state (the staleness is the
        finding — the data source is wedged) without waiting for the
        stuck call; when the real check eventually returns, its result
        overwrites this marker."""
        cr = CheckResult(
            component_name=self.NAME,
            health=HealthStateType.DEGRADED,
            reason=(
                f"check stale: still running after {elapsed:.0f}s "
                "(watchdog fired; data source presumed wedged)"
            ),
        )
        with self._last_mu:
            self._last_check_result = cr

    def _loop(self) -> None:
        # first check runs inside the poller thread so a hung data source
        # can never wedge daemon startup (reference runs the initial Check in
        # the spawned goroutine, temperature/component.go:81-97)
        self.check()
        self._report_if_slow()
        while not self._stop_event.is_set():
            self._poke_event.wait(self.poll_interval())
            self._poke_event.clear()
            if self._stop_event.is_set():
                return
            self.check()
            self._report_if_slow()

    def _report_if_slow(self) -> None:
        """After-the-fact answer to 'why was this check slow': a check that
        outran its own cadence becomes a Warning event in the eventstore,
        carrying the measured duration (which /v1/debug/traces can then
        break down span-by-span)."""
        duration = self._last_check_duration
        threshold = self.SLOW_CHECK_FACTOR * self.poll_interval()
        es = getattr(self.instance, "event_store", None)
        if es is None or threshold <= 0 or duration <= threshold:
            return
        now = self.time_now_fn()
        if now - self._last_slow_event_at < self.SLOW_CHECK_EVENT_COOLDOWN:
            return
        self._last_slow_event_at = now
        try:
            es.bucket(self.NAME).insert(
                Event(
                    component=self.NAME,
                    time=now,
                    name="slow_check",
                    type=EventType.WARNING,
                    message=(
                        f"check took {duration:.3f}s, over "
                        f"{self.SLOW_CHECK_FACTOR:g}x the {self.poll_interval():g}s "
                        "poll interval"
                    ),
                    extra_info={
                        "duration_seconds": f"{duration:.6f}",
                        "poll_interval_seconds": f"{self.poll_interval():g}",
                    },
                )
            )
        except Exception:  # noqa: BLE001 — observability must not kill the poller
            logger.exception("slow-check event emit failed for %s", self.NAME)

    def close(self) -> None:
        if self._job is not None:
            self._job.cancel()
            self._job = None
        self._stop_event.set()
        self._poke_event.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


InitFunc = Callable[[TpudInstance], Component]


class Registry:
    """Thread-safe name→Component registry
    (reference: components/registry.go:106-226)."""

    def __init__(self, instance: TpudInstance) -> None:
        self._mu = threading.RLock()
        self._instance = instance
        self._components: Dict[str, Component] = {}

    def must_register(self, init_func: InitFunc) -> Component:
        c, err = self.register(init_func)
        if err is not None:
            raise err
        assert c is not None
        return c

    def register(self, init_func: InitFunc):
        try:
            c = init_func(self._instance)
        except Exception as e:  # noqa: BLE001
            return None, e
        with self._mu:
            if c.name() in self._components:
                return None, AlreadyRegisteredError(c.name())
            self._components[c.name()] = c
        return c, None

    def all(self) -> List[Component]:
        with self._mu:
            return [self._components[k] for k in sorted(self._components)]

    def get(self, name: str) -> Optional[Component]:
        with self._mu:
            return self._components.get(name)

    def deregister(self, name: str) -> Optional[Component]:
        with self._mu:
            return self._components.pop(name, None)

    def names(self) -> List[str]:
        with self._mu:
            return sorted(self._components)
