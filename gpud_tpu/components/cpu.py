"""CPU component (reference: components/cpu — gopsutil times/load, kmsg
CPU-lockup matcher at component.go:50-83)."""

from __future__ import annotations

import os
import re
from typing import Optional

import psutil

from gpud_tpu.api.v1.types import EventType, HealthStateType
from gpud_tpu.components.base import CheckResult, PollingComponent, TpudInstance
from gpud_tpu.metrics.registry import gauge

NAME = "cpu"

# kernel soft/hard lockup lines (reference: components/cpu kmsg matcher)
LOCKUP_RE = re.compile(
    r"(soft lockup|hard LOCKUP|watchdog: BUG: soft lockup|hung_task|blocked for more than \d+ seconds)",
    re.IGNORECASE,
)

_g_usage = gauge("tpud_cpu_usage_percent", "total CPU usage percent")
_g_load1 = gauge("tpud_cpu_load_avg_1m", "1-minute load average")
_g_load5 = gauge("tpud_cpu_load_avg_5m", "5-minute load average")
_g_load15 = gauge("tpud_cpu_load_avg_15m", "15-minute load average")

LABELS = {"component": NAME}


def match_cpu_lockup(line: str) -> Optional[tuple]:
    if LOCKUP_RE.search(line):
        return ("cpu_lockup", EventType.CRITICAL, line.strip())
    return None


class CPUComponent(PollingComponent):
    NAME = NAME
    TAGS = ["host", "cpu"]

    def __init__(self, instance: TpudInstance) -> None:
        super().__init__(instance)
        psutil.cpu_percent(interval=0.0)  # prime: first call has no baseline
        self.get_usage_fn = lambda: psutil.cpu_percent(interval=0.0)
        self.get_load_fn = os.getloadavg
        self.get_core_count_fn = lambda: psutil.cpu_count(logical=True) or 1
        self._event_bucket = (
            instance.event_store.bucket(NAME) if instance.event_store else None
        )

    def check_once(self) -> CheckResult:
        usage = self.get_usage_fn()
        load1, load5, load15 = self.get_load_fn()
        cores = self.get_core_count_fn()
        _g_usage.set(usage, LABELS)
        _g_load1.set(load1, LABELS)
        _g_load5.set(load5, LABELS)
        _g_load15.set(load15, LABELS)

        health = HealthStateType.HEALTHY
        reason = f"usage {usage:.1f}%, load1 {load1:.2f} ({cores} cores)"
        if load5 > cores * 4:
            health = HealthStateType.DEGRADED
            reason = f"sustained high load: load5 {load5:.2f} on {cores} cores"
        return CheckResult(
            self.NAME,
            health=health,
            reason=reason,
            extra_info={
                "usage_percent": f"{usage:.1f}",
                "load_1m": f"{load1:.2f}",
                "load_5m": f"{load5:.2f}",
                "load_15m": f"{load15:.2f}",
                "logical_cores": str(cores),
            },
        )

    def events(self, since: float):
        if self._event_bucket is None:
            return []
        return self._event_bucket.get(since)
