"""TPU power + utilization component.

Reference: components/accelerator/nvidia/power (493) + utilization (403) +
gpm (733) — draw/limit gauges and duty-cycle/tensorcore utilization,
collapsed into one TPU component since all values come from the same
telemetry sample.
"""

from __future__ import annotations

import collections
import threading
import time

from gpud_tpu.api.v1.types import HealthStateType
from gpud_tpu.components.base import CheckResult, PollingComponent, TpudInstance
from gpud_tpu.components.tpu.shared import sampler_for, telemetry_source
from gpud_tpu.metrics.registry import gauge

NAME = "accelerator-tpu-power"

_g_power = gauge("tpud_tpu_power_watts", "TPU chip power draw")
_g_duty = gauge("tpud_tpu_duty_cycle_percent", "TensorCore duty cycle")
_g_util = gauge("tpud_tpu_tensorcore_util_percent", "TensorCore utilization")
_g_clock = gauge("tpud_tpu_clock_mhz", "TPU core clock")
# sampled-over-interval analog of the reference's GPM metrics (SM occupancy
# sampled over a GPM window, gpm/component.go:34): a point-in-time duty
# cycle aliases badly against bursty training steps, so a windowed mean
# over recent samples is exported alongside the instantaneous value. The
# window is time-based (not poll-count) so on-demand triggered checks
# can't evict real history with duplicate cached samples.
_g_duty_avg = gauge(
    "tpud_tpu_duty_cycle_avg_percent",
    "TensorCore duty cycle averaged over the sampling window",
)

SAMPLING_WINDOW_SECONDS = 300.0  # ≈5 polls at the default cadence


class TPUPowerComponent(PollingComponent):
    NAME = NAME
    TAGS = ["accelerator", "tpu", "power"]

    def __init__(self, instance: TpudInstance) -> None:
        super().__init__(instance)
        self.tpu = instance.tpu_instance
        self.sampler = sampler_for(self.tpu)
        self.sampling_window_seconds = SAMPLING_WINDOW_SECONDS
        self.time_now_fn = time.time
        self._hist_mu = threading.Lock()  # triggered checks race the poller
        self._duty_hist: dict = {}  # chip_id → deque of (ts, duty) samples

    def is_supported(self) -> bool:
        return (
            self.tpu is not None
            and self.tpu.tpu_lib_exists()
            and self.tpu.telemetry_supported()
        )

    def check_once(self) -> CheckResult:
        if not self.is_supported():
            return CheckResult(
                self.NAME,
                health=HealthStateType.HEALTHY,
                reason="no TPU telemetry on this host",
            )
        tel = self.sampler.telemetry()
        now = self.time_now_fn()
        total_w = 0.0
        extra = {"telemetry_source": telemetry_source(self.tpu)}
        with self._hist_mu:
            # prune chips gone from telemetry: hours-old samples from a
            # reset chip must not blend into its average when it returns
            for gone in set(self._duty_hist) - set(tel):
                del self._duty_hist[gone]
        for cid, t in sorted(tel.items()):
            labels = {"component": NAME, "chip": str(cid)}
            _g_power.set(t.power_w, labels)
            _g_duty.set(t.duty_cycle_pct, labels)
            _g_util.set(t.tensorcore_util_pct, labels)
            _g_clock.set(t.clock_mhz, labels)
            with self._hist_mu:
                hist = self._duty_hist.setdefault(cid, collections.deque())
                # one sample per sampler refresh: a triggered check inside
                # the sampler TTL re-reads the same cached value
                if not hist or now - hist[-1][0] >= self.sampler.ttl:
                    hist.append((now, t.duty_cycle_pct))
                cutoff = now - self.sampling_window_seconds
                while hist and hist[0][0] < cutoff:
                    hist.popleft()
                avg = sum(v for _ts, v in hist) / len(hist)
            _g_duty_avg.set(avg, labels)
            total_w += t.power_w
            extra[f"chip{cid}_power_w"] = f"{t.power_w:.1f}"
            extra[f"chip{cid}_duty_pct"] = f"{t.duty_cycle_pct:.1f}"
        return CheckResult(
            self.NAME,
            reason=f"total draw {total_w:.0f}W across {len(tel)} chips",
            extra_info=extra,
        )
