"""TPU kernel-error component — the XID-component analog.

Reference: components/accelerator/nvidia/xid (5137 LoC) — kmsg regex +
catalog; event-sourced health merging reboot events with error events and
escalating suggested actions via per-error reboot thresholds
(component.go:400-650); SetHealthy trims history (636-650); daemon mode
consumes the follow watcher, scan mode reads the whole ring buffer
(component.go:214-265).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from gpud_tpu.api.v1.types import Event, EventType, HealthStateType
from gpud_tpu.components.base import CheckResult, Component, TpudInstance
from gpud_tpu.components.tpu import catalog
from gpud_tpu.components.tpu.health_state import (
    EVENT_NAME_SET_HEALTHY,
    evolve_health,
)
from gpud_tpu.kmsg.syncer import Syncer
from gpud_tpu.kmsg.watcher import read_all
from gpud_tpu.log import get_logger
from gpud_tpu.metrics.registry import counter

NAME = "accelerator-tpu-error-kmsg"

logger = get_logger(__name__)

_c_errors = counter("tpud_tpu_kmsg_errors_total", "matched TPU kernel errors")

DEFAULT_LOOKBACK_SECONDS = 14 * 86400  # events retention window
UPDATE_INTERVAL = 30.0  # state re-evaluation ticker (reference: component.go 30s)


def kmsg_match(line: str) -> Optional[tuple]:
    """MatchFunc for the shared kmsg watcher; forwards the chip attribution
    the matcher extracted so evolve_health's per-chip tracks read it from
    extra_info instead of re-parsing the line every evaluation."""
    m = catalog.match(line)
    if m is None:
        return None
    extra = {"chip": str(m.chip_id)} if m.chip_id is not None else None
    return (m.entry.name, m.entry.event_type, line.strip(), extra)


class TPUErrorKmsgComponent(Component):
    NAME = NAME
    TAGS = ["accelerator", "tpu", "kmsg"]

    def __init__(self, instance: TpudInstance) -> None:
        super().__init__(instance)
        self._event_bucket = (
            instance.event_store.bucket(NAME) if instance.event_store else None
        )
        self.reboot_event_store = instance.reboot_event_store
        self.lookback_seconds = DEFAULT_LOOKBACK_SECONDS
        # per-error-name reboot-threshold overrides pushed via updateConfig
        self.reboot_threshold_overrides: dict = {}
        self.time_now_fn = time.time
        self._stop = threading.Event()
        self._ticker: Optional[threading.Thread] = None
        self._job = None  # scheduler Job when scheduler-driven
        self.syncer: Optional[Syncer] = None
        if self._event_bucket is not None:
            self.syncer = Syncer(
                kmsg_match, self._event_bucket, on_event=self._on_event
            )

    def is_supported(self) -> bool:
        # supported wherever kmsg is readable; on non-TPU hosts it simply
        # never matches (cheap regex on the shared watcher). In scan mode
        # (no event store) check_once reads the whole ring buffer instead
        # (reference: xid/component.go:214-265).
        return True

    # -- event path --------------------------------------------------------
    def _on_event(self, ev: Event) -> None:
        _c_errors.inc(labels={"component": NAME, "error": ev.name})
        # fabric-class matches open the ICI component's fast-poll window —
        # the inotify kmsg path is ~ms, so sysfs confirmation starts now
        # instead of at the next 60s tick (see ici.py raise_suspicion)
        for listener in self.instance.fabric_suspicion_listeners:
            try:
                listener(ev.name)
            except Exception:  # noqa: BLE001 — a listener bug must not
                pass           # break error recording
        self._reevaluate()

    def start(self) -> None:
        # the SharedWatcher (server-owned) feeds self.syncer; here we only
        # run the periodic re-evaluation ticker (reference: component.go
        # updateCurrentState every 30s) — a scheduler job in the daemon,
        # a dedicated thread only in scheduler-less standalone use
        scheduler = getattr(self.instance, "scheduler", None)
        if scheduler is not None:
            if self._job is None:
                self._job = scheduler.add_job(
                    f"component:{NAME}", self.check, interval=UPDATE_INTERVAL
                )
            return
        if self._ticker is not None:
            return
        self._stop.clear()
        self._ticker = threading.Thread(
            target=self._tick_loop, name=f"tpud-{NAME}-ticker", daemon=True
        )
        self._ticker.start()

    def _tick_loop(self) -> None:
        self.check()
        while not self._stop.wait(UPDATE_INTERVAL):
            self.check()

    def close(self) -> None:
        if self._job is not None:
            self._job.cancel()
            self._job = None
        self._stop.set()
        if self._ticker is not None:
            self._ticker.join(timeout=2.0)
            self._ticker = None

    # -- health evaluation -------------------------------------------------
    def _merged_events(self) -> List[Event]:
        since = self.time_now_fn() - self.lookback_seconds
        evs: List[Event] = []
        if self._event_bucket is not None:
            evs.extend(self._event_bucket.get(since))
        if self.reboot_event_store is not None:
            evs.extend(self.reboot_event_store.get_reboot_events(since))
        return evs

    def _reevaluate(self) -> CheckResult:
        return self.check()

    def check_once(self) -> CheckResult:
        if self._event_bucket is None:
            # scan mode (no event store): read the whole ring buffer now
            # (reference: xid/component.go:214-265 scan path)
            found = []
            for msg in read_all():
                m = catalog.match(msg.message)
                if m is not None:
                    found.append(
                        Event(
                            component=NAME,
                            time=msg.time,
                            name=m.entry.name,
                            type=m.entry.event_type,
                            message=msg.message,
                            extra_info=(
                                {"chip": str(m.chip_id)}
                                if m.chip_id is not None
                                else {}
                            ),
                        )
                    )
            ev = evolve_health(found, self.reboot_threshold_overrides)
            return CheckResult(
                self.NAME,
                health=ev.health,
                reason=ev.reason or "no TPU errors in kmsg ring buffer",
                suggested_actions=ev.suggested_actions,
            )
        ev = evolve_health(self._merged_events(), self.reboot_threshold_overrides)
        extra = {name: str(n) for name, n in ev.active_errors.items()}
        return CheckResult(
            self.NAME,
            health=ev.health,
            reason=ev.reason,
            suggested_actions=ev.suggested_actions,
            extra_info=extra,
        )

    def events(self, since: float):
        if self._event_bucket is None:
            return []
        return self._event_bucket.get(since)

    # -- operator actions --------------------------------------------------
    def set_healthy(self) -> None:
        """Insert a SetHealthy marker: evolve_health clears everything
        before it (reference: xid/set_healthy.go + component.go:636-650)."""
        if self._event_bucket is not None:
            self._event_bucket.insert(
                Event(
                    component=NAME,
                    time=self.time_now_fn(),
                    name=EVENT_NAME_SET_HEALTHY,
                    type=EventType.INFO,
                    message="operator set-healthy",
                )
            )
        self.check()
