"""TPU driver/runtime kernel-error catalog.

This is the TPU analog of the NVIDIA XID catalog (reference:
components/accelerator/nvidia/xid/catalog_generated.go:1-30 — 94 codes with
per-code severities, suggested actions and reboot thresholds, plus the
NVSwitch SXid catalog). The reference's catalog is NVIDIA-documented; TPU
driver error strings are not publicly catalogued the same way, so this
catalog covers the observable classes of TPU-VM kernel/driver failures:

- the Google accel/TPU driver (``accel``/``google_tpu``/gasket kmsg lines),
- HBM ECC machine-check lines,
- ICI (inter-chip interconnect) link state transitions,
- PCIe AER errors on the TPU's root ports,
- libtpu/runtime fatal lines forwarded to kmsg by the fault injector,
- tpud's own canonical injection format ``TPU-ERR: <name> ...``
  (pkg/fault-injector analog) so injected and organic faults share one
  detection path.

Each entry carries the per-error reboot threshold driving the
reboot→HW-inspection escalation state machine
(reference: xid/threshold.go + health_state.go:56-80).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List, Optional, Pattern

from gpud_tpu.api.v1.types import EventType, RepairActionType


@dataclass(frozen=True)
class CatalogEntry:
    code: int
    name: str
    pattern: Pattern
    event_type: str
    description: str
    repair_actions: tuple = ()
    # after this many reboots with the error recurring, escalate to
    # HARDWARE_INSPECTION (reference: xid/threshold.go). 0 = never escalate.
    reboot_threshold: int = 1
    # does this error impact workloads (drives Unhealthy vs informational)?
    critical: bool = True
    # lines matching this are NOT this error (e.g. AER lines from known
    # non-TPU drivers); keeps host-wide kernel formats device-scoped
    exclude: Optional[Pattern] = None


def _e(
    code: int,
    name: str,
    regex: str,
    event_type: str,
    description: str,
    repair: tuple,
    reboot_threshold: int = 1,
    critical: bool = True,
    exclude: str = "",
) -> CatalogEntry:
    return CatalogEntry(
        code=code,
        name=name,
        pattern=re.compile(regex, re.IGNORECASE),
        event_type=event_type,
        description=description,
        repair_actions=repair,
        reboot_threshold=reboot_threshold,
        critical=critical,
        exclude=re.compile(exclude, re.IGNORECASE) if exclude else None,
    )


# AER/PCIe kernel formats are host-wide; lines clearly attributed to common
# non-TPU device drivers must not be classified as TPU errors
_NON_TPU_DRIVERS = r"\b(nvme|ahci|e1000\w*|mlx\d\w*|ixgbe|igb|r8169|virtio|xhci|usb)\b"


_REBOOT = (RepairActionType.REBOOT_SYSTEM,)
_HW = (RepairActionType.HARDWARE_INSPECTION,)
_REBOOT_HW = (RepairActionType.REBOOT_SYSTEM, RepairActionType.HARDWARE_INSPECTION)
_NONE = (RepairActionType.IGNORE_NO_ACTION_REQUIRED,)
_APP = (RepairActionType.CHECK_USER_APP_AND_TPU,)

# NOTE: match() is first-hit-wins, so within each section entries are
# ordered most-specific-first (e.g. "uncorrectable" before "correctable",
# which it contains as a substring; "retrain limit" before the generic
# retrain/flap entry).
CATALOG: List[CatalogEntry] = [
    # --- driver-level chip failures (accel / gasket / apex driver) --------
    _e(1, "tpu_chip_lost",
       r"(accel\d+.*(device lost|not responding|fell off the bus)|TPU-ERR: tpu_chip_lost)",
       EventType.FATAL,
       "TPU chip stopped responding to the driver",
       _REBOOT_HW, reboot_threshold=2),
    _e(3, "tpu_driver_crash",
       r"(accel\d*.*(firmware (crash|fault)|fatal error)|google_tpu.*(oops|panic|BUG)|TPU-ERR: tpu_driver_crash)",
       EventType.FATAL,
       "TPU driver/firmware crashed",
       _REBOOT_HW, reboot_threshold=2),
    _e(7, "tpu_reset_failed",
       r"((accel|gasket|apex).*reset.*(fail|timed? ?out)|TPU-ERR: tpu_reset_failed)",
       EventType.FATAL,
       "TPU chip reset attempt failed",
       _REBOOT_HW, reboot_threshold=1),
    _e(4, "tpu_chip_reset_required",
       r"(accel\d+.*reset required|TPU-ERR: tpu_chip_reset_required)",
       EventType.CRITICAL,
       "TPU chip requires reset",
       _REBOOT, reboot_threshold=3),
    _e(15, "tpu_sram_parity",
       r"((accel|TPU).*(SRAM|scratchpad).*parity|SRAM parity error|TPU-ERR: tpu_sram_parity)",
       EventType.FATAL,
       "on-chip SRAM parity error",
       _REBOOT_HW, reboot_threshold=1),
    _e(6, "tpu_core_wedged",
       r"((accel\d*|TPU|tensor ?core).*wedge|TPU-ERR: tpu_core_wedged)",
       EventType.FATAL,
       "TensorCore wedged — compute pipeline stuck",
       _REBOOT_HW, reboot_threshold=2),
    _e(16, "tpu_scalar_core_fault",
       r"(scalar core.*(fault|halt|hang|exception)|TPU-ERR: tpu_scalar_core_fault)",
       EventType.CRITICAL,
       "scalar core fault/halt",
       _REBOOT, reboot_threshold=2),
    _e(5, "tpu_page_fault",
       r"((accel|gasket|apex).*((page|mmu) ?fault|page table error)|TPU-ERR: tpu_page_fault)",
       EventType.CRITICAL,
       "TPU MMU/page fault — often a bad workload access pattern",
       _APP, reboot_threshold=2),
    _e(9, "tpu_interrupt_timeout",
       r"((accel|gasket|apex).*(interrupt|IRQ|MSI-?X?).*(timeout|lost|storm|not received)|TPU-ERR: tpu_interrupt_timeout)",
       EventType.CRITICAL,
       "TPU interrupt delivery timeout/lost",
       _REBOOT, reboot_threshold=2),
    _e(13, "tpu_dma_error",
       r"((accel|gasket|apex).*DMA.*(error|fault|timeout|abort)|TPU-ERR: tpu_dma_error)",
       EventType.CRITICAL,
       "TPU DMA engine error",
       _REBOOT_HW, reboot_threshold=2),
    _e(14, "tpu_firmware_load_failed",
       r"((accel|gasket|apex).*firmware.*(load|download|image).*fail|TPU-ERR: tpu_firmware_load_failed)",
       EventType.CRITICAL,
       "TPU firmware load failed",
       _REBOOT_HW, reboot_threshold=1),
    # driver resource setup (gasket/accel class patterns; the production
    # TPU driver is out-of-tree, so these anchor on the class vocabulary
    # rather than verbatim strings). Before the generic probe/init entry:
    # "interrupt vector init failed" must hit the specific class.
    _e(61, "tpu_msix_init_failed",
       r"((gasket|accel|apex).*(MSI-?X|interrupt vector).*(alloc|init|enable)\w*.*fail|TPU-ERR: tpu_msix_init_failed)",
       EventType.CRITICAL,
       "TPU interrupt vector allocation/initialization failed",
       _REBOOT, reboot_threshold=2),
    _e(62, "tpu_bar_map_failed",
       r"((gasket|accel|apex).*(BAR ?\d?|register space).*(map|request|reserve)\w*.*fail|TPU-ERR: tpu_bar_map_failed)",
       EventType.CRITICAL,
       "TPU BAR/register-space mapping failed",
       _REBOOT, reboot_threshold=1),
    _e(8, "tpu_driver_init_failed",
       r"((gasket|apex|accel).*(probe|init\w*).*fail|TPU-ERR: tpu_driver_init_failed)",
       EventType.CRITICAL,
       "TPU driver probe/initialization failed",
       _REBOOT, reboot_threshold=2),
    _e(2, "tpu_driver_timeout",
       r"(accel\d*.*(command |request |ioctl )?timeout|google_tpu.*timeout|TPU-ERR: tpu_driver_timeout)",
       EventType.CRITICAL,
       "TPU driver command timeout",
       _REBOOT, reboot_threshold=2),
    # --- HBM / memory -----------------------------------------------------
    _e(10, "tpu_hbm_ecc_uncorrectable",
       r"((uncorrectable|double[- ]bit).*(HBM|ECC|memory error)|HBM.*uncorrectable|TPU-ERR: tpu_hbm_ecc_uncorrectable)",
       EventType.FATAL,
       "uncorrectable HBM ECC error",
       _REBOOT_HW, reboot_threshold=1),
    _e(18, "tpu_edac_uncorrectable",
       r"(EDAC.*(\bUE\b|[Uu]ncorrect)|TPU-ERR: tpu_edac_uncorrectable)",
       EventType.FATAL,
       "EDAC uncorrectable memory error",
       _REBOOT_HW, reboot_threshold=1),
    _e(24, "tpu_hbm_row_remap_pending",
       r"(HBM.*row.*(remap|retire)|row remap.*pending|TPU-ERR: tpu_hbm_row_remap_pending)",
       EventType.CRITICAL,
       "HBM row remap/retirement pending — reboot to apply",
       _REBOOT, reboot_threshold=1),
    _e(11, "tpu_hbm_ecc_correctable",
       r"((correctable|single[- ]bit).*(HBM|ECC)|HBM.*correctable|TPU-ERR: tpu_hbm_ecc_correctable)",
       EventType.WARNING,
       "correctable HBM ECC error (no action; tracked for trends)",
       _NONE, reboot_threshold=0, critical=False),
    _e(19, "tpu_edac_correctable",
       r"(EDAC.*(\bCE\b|correct)|TPU-ERR: tpu_edac_correctable)",
       EventType.WARNING,
       "EDAC correctable memory error (tracked for trends)",
       _NONE, reboot_threshold=0, critical=False),
    # memory-anchored only: "mce: [Hardware Error]: Machine check events
    # logged" replays at every boot on any host with MCE history and must
    # not alarm
    _e(17, "tpu_hbm_mce",
       r"(Machine [Cc]heck.*(memory|HBM)|mce:.*memory (read|write|scrub)\w* error|TPU-ERR: tpu_hbm_mce)",
       EventType.FATAL,
       "machine-check memory error (HBM path)",
       _REBOOT_HW, reboot_threshold=1),
    _e(12, "tpu_hbm_oom",
       r"(HBM (allocation failure|out of memory)|RESOURCE_EXHAUSTED.*HBM|TPU-ERR: tpu_hbm_oom)",
       EventType.WARNING,
       "HBM allocation failure — likely workload oversubscription",
       _APP, reboot_threshold=0, critical=False),
    # --- ICI fabric -------------------------------------------------------
    _e(23, "tpu_ici_cable_fault",
       r"(ICI.*cable (fault|error|unplugged)|TPU-ERR: tpu_ici_cable_fault)",
       EventType.FATAL,
       "ICI cable fault",
       _HW, reboot_threshold=0),
    _e(20, "tpu_ici_link_down",
       r"(ICI (link|port).*(down|inactive|lost)|interchip interconnect.*down|TPU-ERR: tpu_ici_link_down)",
       EventType.CRITICAL,
       "ICI link down — slice fabric degraded",
       _REBOOT_HW, reboot_threshold=2),
    _e(28, "tpu_ici_retrain_limit",
       r"(ICI.*retrain.*(limit|exceeded|storm)|TPU-ERR: tpu_ici_retrain_limit)",
       EventType.CRITICAL,
       "ICI link retrain limit exceeded — link quality failing",
       _HW, reboot_threshold=1),
    _e(25, "tpu_ici_width_degraded",
       r"(ICI.*(width|lanes?).*(degrad|reduc)|TPU-ERR: tpu_ici_width_degraded)",
       EventType.WARNING,
       "ICI link running at reduced width",
       _HW, reboot_threshold=2, critical=False),
    _e(27, "tpu_ici_routing_error",
       r"(ICI.*routing.*(error|corrupt|invalid)|TPU-ERR: tpu_ici_routing_error)",
       EventType.CRITICAL,
       "ICI routing error — fabric table corrupt",
       _REBOOT, reboot_threshold=2),
    _e(22, "tpu_ici_crc_errors",
       r"(ICI.*CRC error|interchip.*checksum|TPU-ERR: tpu_ici_crc_errors)",
       EventType.WARNING,
       "ICI CRC errors — cable/connector suspect",
       _HW, reboot_threshold=2, critical=False),
    _e(26, "tpu_ici_port_error",
       r"(ICI port.*(error|fault)|TPU-ERR: tpu_ici_port_error)",
       EventType.CRITICAL,
       "ICI port error",
       _REBOOT_HW, reboot_threshold=2),
    _e(21, "tpu_ici_link_flap",
       r"(ICI (link|port).*(flap|retrain|re-?established)|TPU-ERR: tpu_ici_link_flap)",
       EventType.WARNING,
       "ICI link flapped",
       _NONE, reboot_threshold=3, critical=False),
    # --- thermal / power --------------------------------------------------
    _e(31, "tpu_power_fault",
       r"((TPU|accel).*(power (fault|brownout|supply failure))|TPU-ERR: tpu_power_fault)",
       EventType.FATAL,
       "TPU power delivery fault",
       _HW, reboot_threshold=1),
    _e(34, "tpu_vrm_fault",
       r"((VRM|voltage regulator).*(fault|overcurrent|failure)|TPU-ERR: tpu_vrm_fault)",
       EventType.FATAL,
       "voltage-regulator fault on TPU power path",
       _HW, reboot_threshold=1),
    _e(30, "tpu_thermal_trip",
       r"((TPU|accel).*(thermal (trip|shutdown|throttl)|overtemp)|TPU-ERR: tpu_thermal_trip)",
       EventType.CRITICAL,
       "TPU thermal trip/throttle",
       _HW, reboot_threshold=2),
    _e(33, "tpu_power_throttle",
       r"((TPU|accel).*power.*throttl|power (cap|limit).*(throttl|engaged)|TPU-ERR: tpu_power_throttle)",
       EventType.WARNING,
       "TPU power throttling engaged",
       _NONE, reboot_threshold=0, critical=False),
    # TPU-attributed lines only — generic ACPI thermal_zone trips fire on
    # CPU/board zones of healthy hosts
    _e(32, "tpu_thermal_warning",
       r"((TPU|accel).*temperature.*(above|exceed|warning)|TPU-ERR: tpu_thermal_warning)",
       EventType.WARNING,
       "TPU temperature above warning threshold",
       _NONE, reboot_threshold=0, critical=False),
    # --- PCIe -------------------------------------------------------------
    # On TPU VMs the only vfio-pci-bound functions ARE the TPUs (see
    # tpu/sysfs.py), so a vfio-pci-attributed AER line is chip-scoped by
    # construction — stronger attribution than root-port lines.
    # Ordering within this section: recovery-failed (most severe) before
    # the generic vfio-AER entries; corrected before uncorrected so a
    # benign corrected burst never escalates (\bcorrected\b does not match
    # inside "Uncorrected" — no word boundary after "Un").
    # Kernel format: drivers/pci/pcie/err.c pcie_do_recovery
    # ("device recovery failed")
    _e(46, "tpu_pcie_recovery_failed",
       r"((pcieport|vfio-pci).*(AER: )?device recovery failed|TPU-ERR: tpu_pcie_recovery_failed)",
       EventType.FATAL,
       "PCIe error recovery failed — device needs reset/replacement",
       _REBOOT_HW, reboot_threshold=1, exclude=_NON_TPU_DRIVERS),
    # Kernel format: drivers/pci/pcie/aer.c aer_print_error
    # ("PCIe Bus Error: severity=%s, type=%s, (%s)" / "%s error received")
    _e(63, "tpu_vfio_aer_correctable",
       r"(vfio-pci [0-9a-f:.]+.*(severity=Corrected|Corrected error received)|TPU-ERR: tpu_vfio_aer_correctable)",
       EventType.WARNING,
       "corrected PCIe AER error on a vfio-bound TPU function",
       _NONE, reboot_threshold=0, critical=False),
    _e(45, "tpu_vfio_aer",
       r"(vfio-pci [0-9a-f:.]+.*(AER|PCIe Bus Error)|TPU-ERR: tpu_vfio_aer)",
       EventType.CRITICAL,
       "uncorrected PCIe AER error on a vfio-bound TPU function",
       _REBOOT_HW, reboot_threshold=2,
       exclude=r"\bcorrected\b"),
    _e(40, "tpu_pcie_uncorrectable",
       r"(pcieport.*AER.*(uncorrect|fatal)|TPU-ERR: tpu_pcie_uncorrectable)",
       EventType.CRITICAL,
       "PCIe uncorrectable error on TPU path",
       _REBOOT_HW, reboot_threshold=2),
    # Kernel format: drivers/pci/hotplug/pciehp_ctrl.c ("Slot(%s): Link Down")
    _e(47, "tpu_pcie_slot_link_down",
       r"(pciehp .*Slot\([^)]*\): (Link Down|Card not present)|TPU-ERR: tpu_pcie_slot_link_down)",
       EventType.FATAL,
       "hotplug slot link down — device dropped off the bus",
       _REBOOT_HW, reboot_threshold=1, exclude=_NON_TPU_DRIVERS),
    _e(43, "tpu_pcie_surprise_down",
       r"(pcie\w*.*[Ss]urprise ([Ll]ink )?[Dd]own|TPU-ERR: tpu_pcie_surprise_down)",
       EventType.FATAL,
       "PCIe surprise link down — device dropped off the bus",
       _REBOOT_HW, reboot_threshold=1, exclude=_NON_TPU_DRIVERS),
    _e(44, "tpu_pcie_completion_timeout",
       r"((pcie\w*|AER).*[Cc]ompletion [Tt]imeout|TPU-ERR: tpu_pcie_completion_timeout)",
       EventType.CRITICAL,
       "PCIe completion timeout on TPU path",
       _REBOOT, reboot_threshold=2, exclude=_NON_TPU_DRIVERS),
    # Kernel format: drivers/pci/pcie/dpc.c ("DPC: containment event,
    # status:%#06x source:%#06x"). The line names only the ROOT PORT —
    # never the child device — so the catalog cannot tell a contained TPU
    # from a contained NVMe/NIC. Same posture as the IOMMU entry:
    # informational event trail for correlation; if the contained device
    # WAS the TPU, chip-counts / ICI flip health when it detaches.
    _e(64, "tpu_pcie_dpc_containment",
       r"(pcieport .*DPC: (containment event|unmasked uncorrectable error detected)|TPU-ERR: tpu_pcie_dpc_containment)",
       EventType.WARNING,
       "PCIe downstream port containment (root-port attributed; correlate with chip loss)",
       _NONE, reboot_threshold=0, critical=False),
    # second arm: verbatim bandwidth notification
    # (drivers/pci/pci.c pcie_report_downtraining: "%u.%03u Gb/s available
    # PCIe bandwidth, limited by %s x%d link at %s") — anchored to
    # TPU-bound drivers ONLY: the core prints this line for EVERY
    # downtrained device at enumeration with a bare "pci" prefix (a
    # downtrained NIC would spam a TPU event every boot), so the generic
    # form stays unmatched and only driver-attributed re-prints count
    _e(42, "tpu_pcie_link_downgrade",
       r"(pcie.*(link.*(downgrad|degrad)|speed dropped|downtrain)|(vfio-pci|accel|apex) [0-9a-f:.]+:.*available PCIe bandwidth, limited by|TPU-ERR: tpu_pcie_link_downgrade)",
       EventType.WARNING,
       "PCIe link trained below expected speed/width",
       _HW, reboot_threshold=2, critical=False,
       exclude=_NON_TPU_DRIVERS),
    _e(41, "tpu_pcie_correctable",
       r"(pcieport.*AER.*correct|TPU-ERR: tpu_pcie_correctable)",
       EventType.WARNING,
       "PCIe correctable errors on TPU path",
       _NONE, reboot_threshold=0, critical=False),
    # --- driver binding (vfio runtimes) ----------------------------------
    # Kernel format: drivers/vfio/pci/vfio_pci_core.c vfio_pci_core_request
    # ("Relaying device request to user (#%u)") — an unbind/hot-remove was
    # requested while the runtime holds the TPU
    _e(48, "tpu_dev_unbind_requested",
       r"(vfio-pci [0-9a-f:.]+.*Relaying device request to user|(accel|apex|gasket).*(unbind|unregister)|TPU-ERR: tpu_dev_unbind_requested)",
       EventType.WARNING,
       "device unbind requested while TPU in use",
       _APP, reboot_threshold=0, critical=False),
    # Kernel format: drivers/vfio/pci/vfio_pci_core.c vfio_bar_restore
    # ("%s: reset recovery - restoring BARs") — the device reset behind
    # the runtime's back
    _e(49, "tpu_vfio_reset_recovery",
       r"(vfio-pci [0-9a-f:.]+.*reset recovery - restoring BARs|TPU-ERR: tpu_vfio_reset_recovery)",
       EventType.CRITICAL,
       "TPU function reset behind the runtime (BARs restored)",
       _REBOOT, reboot_threshold=2),
    # --- IOMMU ------------------------------------------------------------
    # device-attributed formats only: the generic "DMAR: DRHD: handling
    # fault status" status line appears on healthy hosts (observed in this
    # sandbox) and must not alarm. Even the attributed formats name a BDF
    # the catalog cannot map to the TPU, so this stays informational —
    # an event trail to correlate, not a health flip.
    # DMAR bracket allows the PASID token newer kernels append
    # ("[DMA Read NO_PASID]" — drivers/iommu/intel/dmar.c dmar_fault_do_one)
    _e(56, "tpu_iommu_fault",
       r"(DMAR: \[DMA (Read|Write)[^\]]*\].*Request device|AMD-Vi.*IO_PAGE_FAULT|iommu.*page fault.*(accel|apex|tpu)|TPU-ERR: tpu_iommu_fault)",
       EventType.WARNING,
       "IOMMU DMA fault (device attribution best-effort; correlate BDF with the TPU)",
       _NONE, reboot_threshold=0, critical=False,
       exclude=_NON_TPU_DRIVERS),
    # --- runtime ----------------------------------------------------------
    _e(50, "tpu_runtime_fatal",
       r"(libtpu.*(fatal|SIGSEGV|check failure)|tpu_runtime.*fatal|TPU-ERR: tpu_runtime_fatal)",
       EventType.CRITICAL,
       "TPU runtime (libtpu) fatal error",
       _APP, reboot_threshold=2),
    _e(53, "tpu_runtime_init_failed",
       r"((libtpu|TPU platform|tpu_runtime).*init\w*.*fail|TPU-ERR: tpu_runtime_init_failed)",
       EventType.CRITICAL,
       "TPU runtime initialization failed",
       _REBOOT, reboot_threshold=2),
    _e(52, "tpu_runtime_hang",
       r"(libtpu.*(hang|stuck|deadline exceeded)|TPU runtime.*(hang|stall)|TPU-ERR: tpu_runtime_hang)",
       EventType.CRITICAL,
       "TPU runtime hang/stall",
       _APP, reboot_threshold=2),
    _e(54, "tpu_barrier_timeout",
       r"(megascale.*barrier.*timeout|TPU-ERR: tpu_barrier_timeout)",
       EventType.WARNING,
       "multi-slice barrier timeout — a peer slice is slow/unreachable",
       _APP, reboot_threshold=0, critical=False),
    _e(51, "tpu_megascale_dcn_error",
       r"(megascale.*(error|unreachable|timeout)|DCN transport.*(error|fail)|TPU-ERR: tpu_megascale_dcn_error)",
       EventType.CRITICAL,
       "multi-slice DCN transport error",
       _APP, reboot_threshold=2, critical=False),
    _e(55, "tpu_slice_degraded",
       r"(slice.*(degraded|missing worker|unhealthy worker)|TPU-ERR: tpu_slice_degraded)",
       EventType.CRITICAL,
       "slice health degraded — worker missing/unhealthy",
       _APP, reboot_threshold=2, critical=False),
    # Kernel format: mm/oom_kill.c ("Out of memory: Killed process %d (%s)
    # total-vm:%lukB, ...") — scoped to TPU-runtime-ish process names; the
    # host-wide OOM signal itself belongs to the memory component
    _e(57, "tpu_runtime_oom_killed",
       r"(Out of memory: Killed process \d+ \((tpu|libtpu|megascale)[^)]*\)|TPU-ERR: tpu_runtime_oom_killed)",
       EventType.WARNING,
       "kernel OOM-killed a TPU runtime process",
       _APP, reboot_threshold=0, critical=False),
    # Kernel format: drivers/acpi/apei/ghes.c / CPER decode
    # ("{%d}[Hardware Error]: section_type: memory error") — host DIMM
    # path (not HBM); event trail for fleet correlation
    _e(58, "tpu_host_mem_ghes",
       r"(\{\d+\}\[Hardware Error\]:.*memory error|ghes.*memory error|TPU-ERR: tpu_host_mem_ghes)",
       EventType.WARNING,
       "APEI/GHES host memory error (DIMM path, not HBM)",
       _NONE, reboot_threshold=0, critical=False),
    # Kernel format: drivers/pci/pci.c pci_dev_wait ("not ready %dms after
    # %s; giving up") — the device never returned after an FLR/bus/resume
    # reset. Printed with the bound driver's prefix, so TPU attribution
    # comes from the vfio/accel/apex prefix; an NVMe failing the same way
    # keeps its own prefix and stays excluded.
    _e(65, "tpu_pcie_not_ready",
       r"((vfio-pci|accel|apex|google_tpu) [0-9a-f:.]+:.*not ready \d+ms after (FLR|bus reset|resume|PM D3hot->D0); giving up|TPU-ERR: tpu_pcie_not_ready)",
       EventType.FATAL,
       "TPU did not come back after reset/resume — device lost until reboot",
       _REBOOT_HW, reboot_threshold=1, exclude=_NON_TPU_DRIVERS),
    # Kernel format: drivers/pci/pci.c pcie_flr ("timed out waiting for
    # pending transaction; performing function level reset anyway") —
    # in-flight DMA did not drain before the runtime's FLR; the reset
    # proceeds but the device may come back wedged (watch for not_ready /
    # reset_recovery next)
    _e(66, "tpu_pcie_flr_timeout",
       r"((vfio-pci|accel|apex|google_tpu) [0-9a-f:.]+:.*timed out waiting for pending transaction|TPU-ERR: tpu_pcie_flr_timeout)",
       EventType.WARNING,
       "pending DMA did not drain before TPU function-level reset",
       _NONE, reboot_threshold=0, critical=False,
       exclude=_NON_TPU_DRIVERS),
    # Kernel format: drivers/thermal/thermal_core.c
    # thermal_zone_device_critical ("%s: critical temperature reached,
    # shutting down") — the host is about to thermally shut down, taking
    # the TPUs with it; host-scope correlation trail like GHES.
    _e(67, "tpu_host_thermal_critical",
       r"(thermal thermal_zone\d+: .*critical temperature reached.*shutting down|critical temperature reached \(\d+ C\), shutting down|TPU-ERR: tpu_host_thermal_critical)",
       EventType.CRITICAL,
       "host thermal-critical shutdown imminent (takes the TPUs down)",
       _HW, reboot_threshold=0, critical=False),
]

_BY_NAME = {c.name: c for c in CATALOG}
_BY_CODE = {c.code: c for c in CATALOG}


def lookup(name: str) -> Optional[CatalogEntry]:
    return _BY_NAME.get(name)


def lookup_code(code: int) -> Optional[CatalogEntry]:
    return _BY_CODE.get(code)


_CHIP_RE = re.compile(r"(?:chip[ =]?|accel)(\d+)", re.IGNORECASE)


def extract_chip(line: str) -> Optional[int]:
    """Best-effort chip attribution from a kmsg line (``accel3``,
    ``chip=3``, ``chip 3``); None when the line names no chip."""
    m = _CHIP_RE.search(line)
    if m:
        try:
            return int(m.group(1))
        except ValueError:
            return None
    return None


@dataclass
class MatchedError:
    entry: CatalogEntry
    chip_id: Optional[int]
    raw: str


# Hot-loop prefilter: the matcher runs on EVERY kernel log line (reference
# hot loop #2, SURVEY §3.1), and a healthy host's lines match nothing — a
# single coarse-token scan rejects them without walking every pattern.
# Every catalog pattern's alternatives are anchored by at least one of
# these tokens; tests assert the invariant over the full organic-line
# corpus. The scan itself runs in the native library when present
# (native/tpud_native.cpp tpud_prefilter_match — a case-folded substring
# sweep, no regex engine per line); the regex below is the fallback and
# the parity oracle.
PREFILTER_TOKENS = [
    "tpu", "accel", "gasket", "apex", "ici", "interchip", "hbm", "ecc",
    "edac", "mce", "machine", "pcie", "aer", "dmar", "amd-vi", "iommu",
    "megascale", "dcn", "slice", "vrm", "voltage", "power", "sram",
    "scalar", "tensor", "correctable", "memory", "row remap", "vfio",
    # anchors thermal_zone_device_critical only — routine trip-point
    # lines carry no "critical temperature" and stay prefilter-rejected
    "critical temperature",
]
_PREFILTER = re.compile(
    "|".join(re.escape(t) for t in PREFILTER_TOKENS), re.IGNORECASE
)

try:  # arm the native fast path (absence is fine)
    from gpud_tpu import native as _native

    _NATIVE_PREFILTER = _native.prefilter_init(PREFILTER_TOKENS)
except Exception:  # noqa: BLE001
    _NATIVE_PREFILTER = False


def _prefilter_hit(line: str) -> bool:
    if _NATIVE_PREFILTER:
        hit = _native.prefilter_match(line)
        if hit is not None:
            return hit
    return _PREFILTER.search(line) is not None


def match(line: str) -> Optional[MatchedError]:
    """Match one kmsg line against the catalog (first hit wins; catalog is
    ordered most-specific-first within each class)."""
    if not _prefilter_hit(line):
        return None
    for entry in CATALOG:
        if entry.pattern.search(line):
            if entry.exclude is not None and entry.exclude.search(line):
                continue
            return MatchedError(entry=entry, chip_id=extract_chip(line), raw=line)
    return None


def injection_line(name: str, chip_id: int = 0, detail: str = "") -> str:
    """Canonical injection format understood by ``match`` — what
    ``tpud inject-fault`` writes (reference: pkg/fault-injector
    xid.GetMessageToInject analog)."""
    entry = _BY_NAME.get(name)
    if entry is None:
        raise KeyError(f"unknown TPU error name: {name!r}")
    suffix = f" {detail}" if detail else ""
    return f"TPU-ERR: {name} chip={chip_id}{suffix}"
