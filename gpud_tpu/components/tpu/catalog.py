"""TPU driver/runtime kernel-error catalog.

This is the TPU analog of the NVIDIA XID catalog (reference:
components/accelerator/nvidia/xid/catalog_generated.go:1-30 — 94 codes with
per-code severities, suggested actions and reboot thresholds, plus the
NVSwitch SXid catalog). The reference's catalog is NVIDIA-documented; TPU
driver error strings are not publicly catalogued the same way, so this
catalog covers the observable classes of TPU-VM kernel/driver failures:

- the Google accel/TPU driver (``accel``/``google_tpu``/gasket kmsg lines),
- HBM ECC machine-check lines,
- ICI (inter-chip interconnect) link state transitions,
- PCIe AER errors on the TPU's root ports,
- libtpu/runtime fatal lines forwarded to kmsg by the fault injector,
- tpud's own canonical injection format ``TPU-ERR: <name> ...``
  (pkg/fault-injector analog) so injected and organic faults share one
  detection path.

Each entry carries the per-error reboot threshold driving the
reboot→HW-inspection escalation state machine
(reference: xid/threshold.go + health_state.go:56-80).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List, Optional, Pattern

from gpud_tpu.api.v1.types import EventType, RepairActionType


@dataclass(frozen=True)
class CatalogEntry:
    code: int
    name: str
    pattern: Pattern
    event_type: str
    description: str
    repair_actions: tuple = ()
    # after this many reboots with the error recurring, escalate to
    # HARDWARE_INSPECTION (reference: xid/threshold.go). 0 = never escalate.
    reboot_threshold: int = 1
    # does this error impact workloads (drives Unhealthy vs informational)?
    critical: bool = True


def _e(
    code: int,
    name: str,
    regex: str,
    event_type: str,
    description: str,
    repair: tuple,
    reboot_threshold: int = 1,
    critical: bool = True,
) -> CatalogEntry:
    return CatalogEntry(
        code=code,
        name=name,
        pattern=re.compile(regex, re.IGNORECASE),
        event_type=event_type,
        description=description,
        repair_actions=repair,
        reboot_threshold=reboot_threshold,
        critical=critical,
    )


_REBOOT = (RepairActionType.REBOOT_SYSTEM,)
_HW = (RepairActionType.HARDWARE_INSPECTION,)
_REBOOT_HW = (RepairActionType.REBOOT_SYSTEM, RepairActionType.HARDWARE_INSPECTION)
_NONE = (RepairActionType.IGNORE_NO_ACTION_REQUIRED,)
_APP = (RepairActionType.CHECK_USER_APP_AND_TPU,)

CATALOG: List[CatalogEntry] = [
    # --- driver-level chip failures --------------------------------------
    _e(1, "tpu_chip_lost",
       r"(accel\d+.*(device lost|not responding|fell off the bus)|TPU-ERR: tpu_chip_lost)",
       EventType.FATAL,
       "TPU chip stopped responding to the driver",
       _REBOOT_HW, reboot_threshold=2),
    _e(2, "tpu_driver_timeout",
       r"(accel\d*.*(command |request |ioctl )?timeout|google_tpu.*timeout|TPU-ERR: tpu_driver_timeout)",
       EventType.CRITICAL,
       "TPU driver command timeout",
       _REBOOT, reboot_threshold=2),
    _e(3, "tpu_driver_crash",
       r"(accel\d*.*(firmware (crash|fault)|fatal error)|google_tpu.*(oops|panic|BUG)|TPU-ERR: tpu_driver_crash)",
       EventType.FATAL,
       "TPU driver/firmware crashed",
       _REBOOT_HW, reboot_threshold=2),
    _e(4, "tpu_chip_reset_required",
       r"(accel\d+.*reset required|TPU-ERR: tpu_chip_reset_required)",
       EventType.CRITICAL,
       "TPU chip requires reset",
       _REBOOT, reboot_threshold=3),
    # --- HBM / memory -----------------------------------------------------
    _e(10, "tpu_hbm_ecc_uncorrectable",
       r"((uncorrectable|double[- ]bit).*(HBM|ECC|memory error)|HBM.*uncorrectable|TPU-ERR: tpu_hbm_ecc_uncorrectable)",
       EventType.FATAL,
       "uncorrectable HBM ECC error",
       _REBOOT_HW, reboot_threshold=1),
    _e(11, "tpu_hbm_ecc_correctable",
       r"((correctable|single[- ]bit).*(HBM|ECC)|HBM.*correctable|TPU-ERR: tpu_hbm_ecc_correctable)",
       EventType.WARNING,
       "correctable HBM ECC error (no action; tracked for trends)",
       _NONE, reboot_threshold=0, critical=False),
    _e(12, "tpu_hbm_oom",
       r"(HBM (allocation failure|out of memory)|RESOURCE_EXHAUSTED.*HBM|TPU-ERR: tpu_hbm_oom)",
       EventType.WARNING,
       "HBM allocation failure — likely workload oversubscription",
       _APP, reboot_threshold=0, critical=False),
    # --- ICI fabric -------------------------------------------------------
    _e(20, "tpu_ici_link_down",
       r"(ICI (link|port).*(down|inactive|lost)|interchip interconnect.*down|TPU-ERR: tpu_ici_link_down)",
       EventType.CRITICAL,
       "ICI link down — slice fabric degraded",
       _REBOOT_HW, reboot_threshold=2),
    _e(21, "tpu_ici_link_flap",
       r"(ICI (link|port).*(flap|retrain|re-?established)|TPU-ERR: tpu_ici_link_flap)",
       EventType.WARNING,
       "ICI link flapped",
       _NONE, reboot_threshold=3, critical=False),
    _e(22, "tpu_ici_crc_errors",
       r"(ICI.*CRC error|interchip.*checksum|TPU-ERR: tpu_ici_crc_errors)",
       EventType.WARNING,
       "ICI CRC errors — cable/connector suspect",
       _HW, reboot_threshold=2, critical=False),
    _e(23, "tpu_ici_cable_fault",
       r"(ICI.*cable (fault|error|unplugged)|TPU-ERR: tpu_ici_cable_fault)",
       EventType.FATAL,
       "ICI cable fault",
       _HW, reboot_threshold=0),
    # --- thermal / power --------------------------------------------------
    _e(30, "tpu_thermal_trip",
       r"((TPU|accel).*(thermal (trip|shutdown|throttl)|overtemp)|TPU-ERR: tpu_thermal_trip)",
       EventType.CRITICAL,
       "TPU thermal trip/throttle",
       _HW, reboot_threshold=2),
    _e(31, "tpu_power_fault",
       r"((TPU|accel).*(power (fault|brownout|supply failure))|TPU-ERR: tpu_power_fault)",
       EventType.FATAL,
       "TPU power delivery fault",
       _HW, reboot_threshold=1),
    # --- PCIe -------------------------------------------------------------
    _e(40, "tpu_pcie_uncorrectable",
       r"(pcieport.*AER.*(uncorrect|fatal)|TPU-ERR: tpu_pcie_uncorrectable)",
       EventType.CRITICAL,
       "PCIe uncorrectable error on TPU path",
       _REBOOT_HW, reboot_threshold=2),
    _e(41, "tpu_pcie_correctable",
       r"(pcieport.*AER.*correct|TPU-ERR: tpu_pcie_correctable)",
       EventType.WARNING,
       "PCIe correctable errors on TPU path",
       _NONE, reboot_threshold=0, critical=False),
    # --- runtime ----------------------------------------------------------
    _e(50, "tpu_runtime_fatal",
       r"(libtpu.*(fatal|SIGSEGV|check failure)|tpu_runtime.*fatal|TPU-ERR: tpu_runtime_fatal)",
       EventType.CRITICAL,
       "TPU runtime (libtpu) fatal error",
       _APP, reboot_threshold=2),
    _e(51, "tpu_megascale_dcn_error",
       r"(megascale.*(error|unreachable|timeout)|DCN transport.*(error|fail)|TPU-ERR: tpu_megascale_dcn_error)",
       EventType.CRITICAL,
       "multi-slice DCN transport error",
       _APP, reboot_threshold=2, critical=False),
]

_BY_NAME = {c.name: c for c in CATALOG}
_BY_CODE = {c.code: c for c in CATALOG}


def lookup(name: str) -> Optional[CatalogEntry]:
    return _BY_NAME.get(name)


def lookup_code(code: int) -> Optional[CatalogEntry]:
    return _BY_CODE.get(code)


_CHIP_RE = re.compile(r"(?:chip[ =]?|accel)(\d+)", re.IGNORECASE)


@dataclass
class MatchedError:
    entry: CatalogEntry
    chip_id: Optional[int]
    raw: str


def match(line: str) -> Optional[MatchedError]:
    """Match one kmsg line against the catalog (first hit wins; catalog is
    ordered most-specific-first within each class)."""
    for entry in CATALOG:
        if entry.pattern.search(line):
            chip = None
            m = _CHIP_RE.search(line)
            if m:
                try:
                    chip = int(m.group(1))
                except ValueError:
                    chip = None
            return MatchedError(entry=entry, chip_id=chip, raw=line)
    return None


def injection_line(name: str, chip_id: int = 0, detail: str = "") -> str:
    """Canonical injection format understood by ``match`` — what
    ``tpud inject-fault`` writes (reference: pkg/fault-injector
    xid.GetMessageToInject analog)."""
    entry = _BY_NAME.get(name)
    if entry is None:
        raise KeyError(f"unknown TPU error name: {name!r}")
    suffix = f" {detail}" if detail else ""
    return f"TPU-ERR: {name} chip={chip_id}{suffix}"
