"""TPU HBM component: usage + ECC health.

Reference blend of components/accelerator/nvidia/memory (usage gauges) and
remapped-rows (587 LoC — pending ⇒ reboot, failed ⇒ HW inspection;
rationale at xid/component.go:276-290). TPU HBM ECC plays the role of GPU
row-remapping: correctable counts are gauges; an uncorrectable/pending
state drives suggested actions.
"""

from __future__ import annotations

from gpud_tpu.api.v1.types import (
    Event,
    EventType,
    HealthStateType,
    RepairActionType,
    SuggestedActions,
)
from gpud_tpu.components.base import CheckResult, PollingComponent, TpudInstance
from gpud_tpu.components.tpu.shared import sampler_for, telemetry_source
from gpud_tpu.metrics.registry import gauge

NAME = "accelerator-tpu-hbm"

_g_used = gauge("tpud_tpu_hbm_used_bytes", "TPU HBM used bytes")
_g_total = gauge("tpud_tpu_hbm_total_bytes", "TPU HBM total bytes")
_g_ecc_corr = gauge("tpud_tpu_hbm_ecc_correctable_total", "correctable HBM ECC errors")
_g_ecc_uncorr = gauge(
    "tpud_tpu_hbm_ecc_uncorrectable_total", "uncorrectable HBM ECC errors"
)


class TPUHbmComponent(PollingComponent):
    NAME = NAME
    TAGS = ["accelerator", "tpu", "hbm"]

    def __init__(self, instance: TpudInstance) -> None:
        super().__init__(instance)
        self.tpu = instance.tpu_instance
        self.sampler = sampler_for(self.tpu)
        # indirection so chaos campaigns can overlay slow-ramp faults on
        # the telemetry read without touching the shared sampler cache;
        # None means "read the live sampler" so late sampler swaps stick
        self.telemetry_fn = None
        self._event_bucket = (
            instance.event_store.bucket(NAME) if instance.event_store else None
        )

    def is_supported(self) -> bool:
        return (
            self.tpu is not None
            and self.tpu.tpu_lib_exists()
            and self.tpu.telemetry_supported()
        )

    def check_once(self) -> CheckResult:
        if not self.is_supported():
            return CheckResult(
                self.NAME,
                health=HealthStateType.HEALTHY,
                reason="no TPU telemetry on this host",
            )
        tel = (self.telemetry_fn or self.sampler.telemetry)()
        ecc_pending = []
        extra = {"telemetry_source": telemetry_source(self.tpu)}
        for cid, t in sorted(tel.items()):
            labels = {"component": NAME, "chip": str(cid)}
            _g_used.set(t.hbm_used_bytes, labels)
            _g_total.set(t.hbm_total_bytes, labels)
            _g_ecc_corr.set(t.hbm_ecc_correctable, labels)
            _g_ecc_uncorr.set(t.hbm_ecc_uncorrectable, labels)
            if t.hbm_total_bytes:
                extra[f"chip{cid}_hbm_used_pct"] = (
                    f"{100.0 * t.hbm_used_bytes / t.hbm_total_bytes:.1f}"
                )
            if t.hbm_ecc_pending or t.hbm_ecc_uncorrectable > 0:
                ecc_pending.append(cid)

        if ecc_pending:
            # record an event so event-sourced health and the control plane
            # see the occurrence even after the condition clears; dedupe on
            # (name, message) against recent history — a still-pending
            # condition must not insert a new event every poll
            if self._event_bucket is not None:
                msg = f"uncorrectable HBM ECC on chip(s) {ecc_pending}"
                recent = self._event_bucket.get(self.time_now_fn() - 86400)
                already = any(
                    e.name == "hbm_ecc_uncorrectable" and e.message == msg
                    for e in recent
                )
                if not already:
                    self._event_bucket.insert(
                        Event(
                            component=NAME,
                            name="hbm_ecc_uncorrectable",
                            type=EventType.FATAL,
                            message=msg,
                        )
                    )
            return CheckResult(
                self.NAME,
                health=HealthStateType.UNHEALTHY,
                reason=f"uncorrectable HBM ECC pending on chip(s) {ecc_pending}",
                suggested_actions=SuggestedActions(
                    description=(
                        "uncorrectable HBM ECC — reboot to re-map; if it "
                        "persists, hardware inspection"
                    ),
                    repair_actions=[
                        RepairActionType.REBOOT_SYSTEM,
                        RepairActionType.HARDWARE_INSPECTION,
                    ],
                ),
                extra_info=extra,
            )
        return CheckResult(
            self.NAME,
            reason=f"HBM healthy on {len(tel)} chips",
            extra_info=extra,
        )

    def events(self, since: float):
        if self._event_bucket is None:
            return []
        return self._event_bucket.get(since)
