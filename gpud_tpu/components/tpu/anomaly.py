"""TPU telemetry anomaly component — the daemon's analytics check.

No direct reference analog (the reference stops at threshold checks); this
is the TPU build's fleet-analytics slot: it feeds recent per-chip telemetry
windows from the metrics store (the 3-stage pipeline of SURVEY §5.5,
reference: pkg/metrics/syncer/syncer.go:22-50) through the robust EWMA/MAD
scorer (gpud_tpu/models/anomaly.py) and surfaces per-chip drift — "chip 3
is running away from its own recent behavior" — as Degraded with events,
before a hard threshold (temperature slowdown, HBM ECC) trips.

Backend selection (``TPUD_ANALYTICS_BACKEND`` = auto|numpy|jax):
- ``numpy`` — the jax-free twin (models/anomaly_np.py); default product
  path, keeps daemon RSS under the footprint target.
- ``jax``  — models/anomaly.robust_scores on the accelerator; for hosts
  that already run jax or fleet-scale batched scoring.
- ``auto`` — jax only if it is already imported (cost already paid).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from gpud_tpu.api.v1.types import Event, EventType, HealthStateType
from gpud_tpu.components.base import CheckResult, PollingComponent, TpudInstance
from gpud_tpu.metrics.registry import gauge
from gpud_tpu.metrics.store import MetricsStore

NAME = "accelerator-tpu-anomaly"

_g_score = gauge("tpud_tpu_anomaly_score", "per-chip telemetry anomaly score")

LABELS = {"component": NAME}

# metric-name → feature column; all are per-chip gauges recorded by the
# temperature/power/hbm components into the shared metrics pipeline
FEATURE_METRICS: List[str] = [
    "tpud_tpu_temperature_celsius",
    "tpud_tpu_hbm_temperature_celsius",
    "tpud_tpu_power_watts",
    "tpud_tpu_duty_cycle_percent",
    "tpud_tpu_tensorcore_util_percent",
    "tpud_tpu_clock_mhz",
    "tpud_tpu_hbm_used_bytes",
]

MIN_SAMPLES = 8          # scrape sweeps needed before scoring (warm-up)
MAX_WINDOW_SAMPLES = 180 # cap at 3h of 1-minute sweeps (metrics retention)
DEFAULT_LOOKBACK = 3 * 3600.0
DEFAULT_SCORE_DEGRADED = 6.0  # well above the ~1-2 nominal band (see tests)


def _jax_backend_initialized() -> bool:
    """True only when a jax device backend is ALREADY live in-process.

    Merely-importable is not enough: the first jit would *initialize* a
    backend — on a TPU VM that opens libtpu, which is exclusive with the
    training workload a side-band daemon must never contend with (same
    rule as the opt-in JaxBackend, tpu/instance.py), and on remote-
    accelerator setups the client init can block for minutes."""
    import sys

    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)  # populated only after init
    except Exception:  # noqa: BLE001 — private API moved → be conservative
        return False


def _score_windows(windows: np.ndarray, backend: str) -> Tuple[np.ndarray, str]:
    """Returns (scores, resolved backend name actually used)."""
    if backend == "auto":
        backend = "jax" if _jax_backend_initialized() else "numpy"
    if backend == "jax":
        from gpud_tpu.models.anomaly import robust_scores

        return np.asarray(robust_scores(windows)), "jax"
    from gpud_tpu.models.anomaly_np import robust_scores_np

    return robust_scores_np(windows), "numpy"


class TPUAnomalyComponent(PollingComponent):
    NAME = NAME
    TAGS = ["accelerator", "tpu", "analytics"]

    def __init__(self, instance: TpudInstance) -> None:
        super().__init__(instance)
        self.tpu = instance.tpu_instance
        self.metrics_store: Optional[MetricsStore] = (
            MetricsStore(instance.db_rw) if instance.db_rw is not None else None
        )
        self._event_bucket = (
            instance.event_store.bucket(NAME) if instance.event_store else None
        )
        self.backend = os.environ.get("TPUD_ANALYTICS_BACKEND", "auto")
        self.lookback_seconds = DEFAULT_LOOKBACK
        self.score_degraded = DEFAULT_SCORE_DEGRADED
        self.min_samples = MIN_SAMPLES
        self.burst_interval_seconds = 0.25  # scan-mode burst sampling cadence

    def is_supported(self) -> bool:
        return (
            self.tpu is not None
            and self.tpu.tpu_lib_exists()
            and self.tpu.telemetry_supported()
        )

    # -- scan-mode burst sampling -----------------------------------------
    def _burst_windows(self) -> Tuple[List[str], np.ndarray]:
        """Scan mode has no metrics history (EventStore/DB are nil there,
        reference: pkg/scan/scan.go:83-100), so take a short burst of live
        telemetry samples instead — the 'read everything now' scan-mode
        path, like xid reading the whole kmsg ring (SURVEY §3.2)."""
        assert self.tpu is not None
        frames: List[Dict[str, List[float]]] = []
        chips: List[str] = []
        for i in range(self.min_samples):
            if i:
                self._stop_event.wait(self.burst_interval_seconds)
            tel = self.tpu.telemetry()
            frame: Dict[str, List[float]] = {}
            for cid, t in sorted(tel.items()):
                frame[str(cid)] = [
                    t.temperature_c,
                    t.hbm_temperature_c,
                    t.power_w,
                    t.duty_cycle_pct,
                    t.tensorcore_util_pct,
                    t.clock_mhz,
                    float(t.hbm_used_bytes),
                ]
            frames.append(frame)
        # keep only frames matching the most complete chip set seen, so the
        # array stays rectangular even if a chip vanishes (or appears late)
        # mid-burst — chip loss alarms via chip-counts, not here
        if not frames:
            return [], np.zeros((0, 0, 0), dtype=np.float32)
        full = max((set(f) for f in frames), key=len)
        chips = sorted(full, key=lambda c: (len(c), c))
        frames = [f for f in frames if set(f) == full]
        if not chips or len(frames) < 2:
            return [], np.zeros((0, 0, 0), dtype=np.float32)
        windows = np.asarray(
            [[f[c] for f in frames] for c in chips], dtype=np.float32
        )
        return chips, windows

    # -- window assembly ---------------------------------------------------
    def _build_windows(self, now: float) -> Tuple[List[str], np.ndarray]:
        """Read recent telemetry from the metrics store into [C, T, F].

        Timeline = the union of observed timestamps; each (chip, feature)
        series is aligned onto it with forward-fill (leading gaps repeat
        the first sample). Intersecting timestamps across all pairs
        instead would let ONE flaky gauge on ONE chip shrink the common
        set below min_samples and silently disable drift scoring
        fleet-wide (round-2 verdict, Weak #5) — the same alignment choice
        as the numpy ICI scan (fleet_scan.py forward-fill). A chip that
        never reported some feature in-window is skipped alone; chip loss
        alarms via chip-counts, not here.
        """
        assert self.metrics_store is not None
        by: Dict[str, Dict[str, Dict[int, float]]] = {}
        # one name-filtered read per feature so the (name, ts) index prunes
        # the scan instead of walking every component's metrics
        for name in FEATURE_METRICS:
            for m in self.metrics_store.read(now - self.lookback_seconds, name=name):
                chip = m.labels.get("chip")
                if chip is None:
                    continue
                by.setdefault(chip, {}).setdefault(name, {})[m.unix_seconds] = m.value
        if not by:
            return [], np.zeros((0, 0, 0), dtype=np.float32)

        union: set = set()
        for feats in by.values():
            for series in feats.values():
                union |= set(series)
        ts_sorted = sorted(union)[-MAX_WINDOW_SAMPLES:]
        if len(ts_sorted) < self.min_samples:
            return [], np.zeros((0, 0, 0), dtype=np.float32)
        timeline = np.asarray(ts_sorted, dtype=np.float64)

        chips: List[str] = []
        rows: List[np.ndarray] = []
        for chip in sorted(by, key=lambda c: (len(c), c)):  # numeric-ish order
            feats = by[chip]
            if any(not feats.get(name) for name in FEATURE_METRICS):
                continue  # no data at all for a feature → skip this chip only
            per_feature = []
            for name in FEATURE_METRICS:
                series = feats[name]
                s_ts = np.asarray(sorted(series), dtype=np.float64)
                s_val = np.asarray(
                    [series[t] for t in sorted(series)], dtype=np.float32
                )
                idx = np.searchsorted(s_ts, timeline, side="right") - 1
                idx = np.clip(idx, 0, len(s_ts) - 1)
                per_feature.append(s_val[idx])
            rows.append(np.stack(per_feature, axis=1))  # [T, F]
            chips.append(chip)
        if not chips:
            return [], np.zeros((0, 0, 0), dtype=np.float32)
        return chips, np.stack(rows, axis=0)

    def _record_event(self, chip: str, score: float, now: float) -> None:
        if self._event_bucket is None:
            return
        name = "tpu_telemetry_anomaly"
        message = f"chip {chip} telemetry drifting (anomaly score {score:.1f})"
        # dedupe: one event per chip per lookback window
        for e in self._event_bucket.get(now - self.lookback_seconds):
            if e.name == name and e.extra_info.get("chip") == chip:
                return
        self._event_bucket.insert(
            Event(
                component=NAME,
                name=name,
                type=EventType.WARNING,
                message=message,
                extra_info={"chip": chip, "score": f"{score:.2f}"},
            )
        )

    def check_once(self) -> CheckResult:
        if not self.is_supported():
            return CheckResult(
                self.NAME,
                health=HealthStateType.HEALTHY,
                reason="no TPU telemetry on this host",
            )
        now = self.time_now_fn()
        if self.metrics_store is not None:
            chips, windows = self._build_windows(now)
        else:
            chips, windows = self._burst_windows()
        if not chips:
            return CheckResult(
                self.NAME,
                health=HealthStateType.HEALTHY,
                reason=f"warming up: <{self.min_samples} telemetry sweeps recorded",
            )

        scores, used_backend = _score_windows(windows, self.backend)
        extra = {"samples": str(windows.shape[1]), "backend": used_backend}
        drifting: List[Tuple[str, float]] = []
        for chip, score in zip(chips, scores):
            s = float(score)
            _g_score.set(s, {"component": NAME, "chip": chip})
            extra[f"chip{chip}_score"] = f"{s:.2f}"
            if s >= self.score_degraded:
                drifting.append((chip, s))

        if drifting:
            for chip, s in drifting:
                self._record_event(chip, s, now)
            names = ", ".join(
                f"chip {c} (score {s:.1f})" for c, s in sorted(drifting)
            )
            return CheckResult(
                self.NAME,
                health=HealthStateType.DEGRADED,
                reason=f"telemetry anomaly: {names}",
                extra_info=extra,
            )
        return CheckResult(
            self.NAME,
            reason=(
                f"telemetry nominal across {len(chips)} chips "
                f"(max score {float(scores.max()):.1f})"
            ),
            extra_info=extra,
        )

    def events(self, since: float):
        if self._event_bucket is None:
            return []
        return self._event_bucket.get(since)
