"""Shared telemetry sampler for TPU components.

The reference's NVIDIA components each call NVML separately but NVML is a
cheap side-band API; TPU telemetry reads can be costlier, so all TPU
components share one cached sample with a short TTL (footprint discipline:
"shared pollers", SURVEY §7 hard parts).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from gpud_tpu.tpu.instance import ICILinkSnapshot, TPUChipTelemetry, TPUInstance

DEFAULT_TTL = 10.0


class TelemetrySampler:
    def __init__(self, instance: TPUInstance, ttl_seconds: float = DEFAULT_TTL) -> None:
        self.instance = instance
        self.ttl = ttl_seconds
        self._mu = threading.Lock()
        self._tel: Dict[int, TPUChipTelemetry] = {}
        self._tel_ts = 0.0
        self._links: List[ICILinkSnapshot] = []
        self._links_ts = 0.0
        self.time_now_fn = time.time

    def telemetry(self) -> Dict[int, TPUChipTelemetry]:
        now = self.time_now_fn()
        with self._mu:
            if now - self._tel_ts >= self.ttl:
                self._tel = self.instance.telemetry()
                self._tel_ts = now
            return dict(self._tel)

    def ici_links(self) -> List[ICILinkSnapshot]:
        now = self.time_now_fn()
        with self._mu:
            if now - self._links_ts >= self.ttl:
                self._links = self.instance.ici_links()
                self._links_ts = now
            return list(self._links)


def telemetry_source(instance: Optional[TPUInstance]) -> str:
    """Measurement-vs-inventory label for check extra_info (VERDICT r3
    #6): operators must be able to tell gRPC-measured telemetry
    ("runtime-metrics") from CLI parses ("cli") or fixtures ("mock")."""
    if instance is None:
        return ""
    src = getattr(instance, "telemetry_source", None)
    return src() if callable(src) else ""


_samplers_mu = threading.Lock()


def sampler_for(instance: Optional[TPUInstance]) -> Optional[TelemetrySampler]:
    """One sampler per TPUInstance, stored on the instance itself so its
    lifetime matches the instance (no process-global cache to leak)."""
    if instance is None:
        return None
    with _samplers_mu:
        s = getattr(instance, "_tpud_sampler", None)
        if s is None:
            s = TelemetrySampler(instance)
            instance._tpud_sampler = s  # type: ignore[attr-defined]
        return s
