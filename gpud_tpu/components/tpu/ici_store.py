"""ICI link time-series store.

The TPU analog of the InfiniBand component's dedicated SQLite store
(reference: components/accelerator/nvidia/infiniband/store/interface.go:9-36):
per-port snapshots over a long horizon, scanned for link drops and flaps,
with tombstones so an admin action (set-healthy) makes the scan ignore
history before a point in time.

Snapshot rows are (ts, link, state, counters...); the scan computes per-link:
- ``currently_down``: latest snapshot has state down,
- ``drops``: up→down transitions inside the window,
- ``flaps``: down→up recoveries inside the window (a drop that recovers),
- counter deltas (CRC errors etc.) across the window.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from gpud_tpu.sqlite import DB
from gpud_tpu.tpu.instance import ICILinkSnapshot, LinkState

TABLE = "tpud_ici_snapshots_v0_1"
TOMBSTONE_TABLE = "tpud_ici_tombstones_v0_1"

DEFAULT_RETENTION = 14 * 86400


@dataclass
class LinkScan:
    link: str
    currently_down: bool = False
    drops: int = 0
    flaps: int = 0
    crc_delta: int = 0
    error_delta: int = 0
    last_state: str = LinkState.UNKNOWN
    last_seen: float = 0.0
    first_seen: float = 0.0
    samples: int = 0


@dataclass
class ScanResult:
    window_start: float
    links: Dict[str, LinkScan] = field(default_factory=dict)

    @property
    def down_links(self) -> List[str]:
        return sorted(k for k, v in self.links.items() if v.currently_down)

    @property
    def flapping_links(self) -> List[str]:
        return sorted(k for k, v in self.links.items() if v.flaps > 0)

    @property
    def dropped_links(self) -> List[str]:
        return sorted(k for k, v in self.links.items() if v.drops > 0)


class ICIStore:
    def __init__(self, db: DB, retention_seconds: int = DEFAULT_RETENTION) -> None:
        self.db = db
        self.retention_seconds = retention_seconds
        self.time_now_fn = time.time
        self.native_enabled = True  # tests flip this off to force parity runs
        db.execute(
            f"""CREATE TABLE IF NOT EXISTS {TABLE} (
                ts REAL NOT NULL,
                link TEXT NOT NULL,
                state INTEGER NOT NULL,
                tx_bytes INTEGER NOT NULL DEFAULT 0,
                rx_bytes INTEGER NOT NULL DEFAULT 0,
                tx_errors INTEGER NOT NULL DEFAULT 0,
                rx_errors INTEGER NOT NULL DEFAULT 0,
                crc_errors INTEGER NOT NULL DEFAULT 0,
                replays INTEGER NOT NULL DEFAULT 0
            )"""
        )
        db.execute(
            f"CREATE INDEX IF NOT EXISTS idx_{TABLE}_link_ts ON {TABLE} (link, ts)"
        )
        # bare-ts index so purge's DELETE ... WHERE ts<? doesn't full-scan
        db.execute(f"CREATE INDEX IF NOT EXISTS idx_{TABLE}_ts ON {TABLE} (ts)")
        db.execute(
            f"CREATE TABLE IF NOT EXISTS {TOMBSTONE_TABLE} "
            "(link TEXT PRIMARY KEY, ts REAL NOT NULL)"
        )

    # -- writes ------------------------------------------------------------
    def insert_snapshot(
        self, links: List[ICILinkSnapshot], ts: Optional[float] = None
    ) -> None:
        t = ts if ts is not None else self.time_now_fn()
        self.db.executemany(
            f"INSERT INTO {TABLE} (ts, link, state, tx_bytes, rx_bytes, "
            "tx_errors, rx_errors, crc_errors, replays) VALUES (?,?,?,?,?,?,?,?,?)",
            [
                (
                    t,
                    ln.name,
                    1 if ln.state == LinkState.UP else 0,
                    ln.tx_bytes,
                    ln.rx_bytes,
                    ln.tx_errors,
                    ln.rx_errors,
                    ln.crc_errors,
                    ln.replays,
                )
                for ln in links
            ],
        )

    def purge(self, before: Optional[float] = None) -> int:
        cutoff = (
            before
            if before is not None
            else self.time_now_fn() - self.retention_seconds
        )
        return self.db.execute(f"DELETE FROM {TABLE} WHERE ts<?", (cutoff,)).rowcount

    # -- tombstones (reference: IB store tombstone on admin action) --------
    def set_tombstone(self, link: str = "*", ts: Optional[float] = None) -> None:
        """``link='*'`` tombstones all links (set-healthy semantics)."""
        t = ts if ts is not None else self.time_now_fn()
        self.db.execute(
            f"INSERT INTO {TOMBSTONE_TABLE} (link, ts) VALUES (?, ?) "
            "ON CONFLICT(link) DO UPDATE SET ts=excluded.ts",
            (link, t),
        )

    def tombstones(self) -> Dict[str, float]:
        """All tombstones as link→ts (one query per scan, not per link)."""
        return {
            r[0]: r[1]
            for r in self.db.query(f"SELECT link, ts FROM {TOMBSTONE_TABLE}")
        }

    def tombstone_for(self, link: str) -> float:
        t = self.tombstones()
        return max(t.get("*", 0.0), t.get(link, 0.0))

    # -- scan --------------------------------------------------------------
    def scan(self, window_seconds: float) -> ScanResult:
        """Walk each link's snapshots in the window (post-tombstone) and
        classify drops/flaps (reference: IB store Scan marks drops/flaps).

        The transition/delta walk runs in the native C++ library when it is
        loaded (native/tpud_native.cpp tpud_scan_links_ragged — one batched
        pass over all links), with the pure-Python walk as the always-there
        fallback; tests assert the two paths agree.
        """
        now = self.time_now_fn()
        start = now - window_seconds
        res = ScanResult(window_start=start)
        rows = self.db.query(
            f"SELECT link, ts, state, tx_errors, rx_errors, crc_errors "
            f"FROM {TABLE} WHERE ts>=? ORDER BY link, ts ASC",
            (start,),
        )
        all_tombstones = self.tombstones()
        global_tombstone = all_tombstones.get("*", 0.0)

        # group per link, dropping tombstone-masked rows up front so both
        # scan backends see identical sequences
        order: List[str] = []
        seqs: Dict[str, list] = {}
        tombstone = 0.0
        cur_link: Optional[str] = None
        for link, ts, state, tx_err, rx_err, crc in rows:
            if link != cur_link:
                cur_link = link
                tombstone = max(global_tombstone, all_tombstones.get(link, 0.0))
                if link not in seqs:
                    order.append(link)
                    seqs[link] = []
            if ts < tombstone:
                continue
            seqs[link].append((ts, state, tx_err + rx_err, crc))
        # links fully masked by a tombstone end up with zero samples — drop
        # them so they don't read as "down since forever"
        order = [l for l in order if seqs[l]]

        classified = self._classify_native(order, seqs)
        if classified is None:
            classified = self._classify_python(order, seqs)

        for link in order:
            seq = seqs[link]
            drops, flaps, currently_down, error_delta, crc_delta = classified[link]
            res.links[link] = LinkScan(
                link=link,
                currently_down=currently_down,
                drops=drops,
                flaps=flaps,
                crc_delta=crc_delta,
                error_delta=error_delta,
                last_state=LinkState.UP if seq[-1][1] == 1 else LinkState.DOWN,
                last_seen=seq[-1][0],
                first_seen=seq[0][0],
                samples=len(seq),
            )
        return res

    def _classify_python(self, order: List[str], seqs: Dict[str, list]) -> Dict[str, tuple]:
        out: Dict[str, tuple] = {}
        for link in order:
            drops = flaps = error_delta = crc_delta = 0
            prev_state: Optional[int] = None
            prev_err: Optional[int] = None
            prev_crc: Optional[int] = None
            state = 1
            for _ts, state, err, crc in seqs[link]:
                if prev_err is not None:
                    # accumulate only positive steps: counters are monotonic
                    # in hardware but may reset on driver reload/reboot
                    error_delta += max(0, err - prev_err)
                    crc_delta += max(0, crc - prev_crc)
                prev_err, prev_crc = err, crc
                if prev_state is not None:
                    if prev_state == 1 and state == 0:
                        drops += 1
                    elif prev_state == 0 and state == 1:
                        flaps += 1
                prev_state = state
            out[link] = (drops, flaps, state == 0, error_delta, crc_delta)
        return out

    def _classify_native(self, order: List[str], seqs: Dict[str, list]) -> Optional[Dict[str, tuple]]:
        """Batched C++ scan; None when the native library is absent."""
        if not self.native_enabled or not order:
            return None if order else {}
        from gpud_tpu import native

        if not native.available():
            return None
        states: List[int] = []
        errs: List[int] = []
        crcs: List[int] = []
        offsets: List[int] = [0]
        for link in order:
            for _ts, state, err, crc in seqs[link]:
                states.append(1 if state == 1 else 0)
                errs.append(err)
                crcs.append(crc)
            offsets.append(len(states))
        both = native.scan_links_ragged2(states, errs, crcs, offsets)
        if both is None:
            return None
        by_err, by_crc = both
        out: Dict[str, tuple] = {}
        for i, link in enumerate(order):
            out[link] = (
                by_err[i]["drops"],
                by_err[i]["flaps"],
                by_err[i]["currently_down"],
                by_err[i]["counter_delta"],
                by_crc[i]["counter_delta"],
            )
        return out

    def link_names(self) -> List[str]:
        return [r[0] for r in self.db.query(f"SELECT DISTINCT link FROM {TABLE}")]
