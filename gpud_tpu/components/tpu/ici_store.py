"""ICI link time-series store.

The TPU analog of the InfiniBand component's dedicated SQLite store
(reference: components/accelerator/nvidia/infiniband/store/interface.go:9-36):
per-port snapshots over a long horizon, scanned for link drops and flaps,
with tombstones so an admin action (set-healthy) makes the scan ignore
history before a point in time.

Snapshot rows are (ts, link, state, counters...); the scan computes per-link:
- ``currently_down``: latest snapshot has state down,
- ``drops``: up→down transitions inside the window,
- ``flaps``: down→up recoveries inside the window (a drop that recovers),
- counter deltas (CRC errors etc.) across the window.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from gpud_tpu.sqlite import DB
from gpud_tpu.tpu.instance import ICILinkSnapshot, LinkState

TABLE = "tpud_ici_snapshots_v0_1"
TOMBSTONE_TABLE = "tpud_ici_tombstones_v0_1"

DEFAULT_RETENTION = 14 * 86400


@dataclass
class LinkScan:
    link: str
    currently_down: bool = False
    drops: int = 0
    flaps: int = 0
    crc_delta: int = 0
    error_delta: int = 0
    last_state: str = LinkState.UNKNOWN
    last_seen: float = 0.0
    first_seen: float = 0.0
    samples: int = 0


@dataclass
class ScanResult:
    window_start: float
    links: Dict[str, LinkScan] = field(default_factory=dict)

    @property
    def down_links(self) -> List[str]:
        return sorted(k for k, v in self.links.items() if v.currently_down)

    @property
    def flapping_links(self) -> List[str]:
        return sorted(k for k, v in self.links.items() if v.flaps > 0)

    @property
    def dropped_links(self) -> List[str]:
        return sorted(k for k, v in self.links.items() if v.drops > 0)


class ICIStore:
    def __init__(self, db: DB, retention_seconds: int = DEFAULT_RETENTION) -> None:
        self.db = db
        self.retention_seconds = retention_seconds
        self.time_now_fn = time.time
        db.execute(
            f"""CREATE TABLE IF NOT EXISTS {TABLE} (
                ts REAL NOT NULL,
                link TEXT NOT NULL,
                state INTEGER NOT NULL,
                tx_bytes INTEGER NOT NULL DEFAULT 0,
                rx_bytes INTEGER NOT NULL DEFAULT 0,
                tx_errors INTEGER NOT NULL DEFAULT 0,
                rx_errors INTEGER NOT NULL DEFAULT 0,
                crc_errors INTEGER NOT NULL DEFAULT 0,
                replays INTEGER NOT NULL DEFAULT 0
            )"""
        )
        db.execute(
            f"CREATE INDEX IF NOT EXISTS idx_{TABLE}_link_ts ON {TABLE} (link, ts)"
        )
        # bare-ts index so purge's DELETE ... WHERE ts<? doesn't full-scan
        db.execute(f"CREATE INDEX IF NOT EXISTS idx_{TABLE}_ts ON {TABLE} (ts)")
        db.execute(
            f"CREATE TABLE IF NOT EXISTS {TOMBSTONE_TABLE} "
            "(link TEXT PRIMARY KEY, ts REAL NOT NULL)"
        )

    # -- writes ------------------------------------------------------------
    def insert_snapshot(
        self, links: List[ICILinkSnapshot], ts: Optional[float] = None
    ) -> None:
        t = ts if ts is not None else self.time_now_fn()
        self.db.executemany(
            f"INSERT INTO {TABLE} (ts, link, state, tx_bytes, rx_bytes, "
            "tx_errors, rx_errors, crc_errors, replays) VALUES (?,?,?,?,?,?,?,?,?)",
            [
                (
                    t,
                    ln.name,
                    1 if ln.state == LinkState.UP else 0,
                    ln.tx_bytes,
                    ln.rx_bytes,
                    ln.tx_errors,
                    ln.rx_errors,
                    ln.crc_errors,
                    ln.replays,
                )
                for ln in links
            ],
        )

    def purge(self, before: Optional[float] = None) -> int:
        cutoff = (
            before
            if before is not None
            else self.time_now_fn() - self.retention_seconds
        )
        return self.db.execute(f"DELETE FROM {TABLE} WHERE ts<?", (cutoff,)).rowcount

    # -- tombstones (reference: IB store tombstone on admin action) --------
    def set_tombstone(self, link: str = "*", ts: Optional[float] = None) -> None:
        """``link='*'`` tombstones all links (set-healthy semantics)."""
        t = ts if ts is not None else self.time_now_fn()
        self.db.execute(
            f"INSERT INTO {TOMBSTONE_TABLE} (link, ts) VALUES (?, ?) "
            "ON CONFLICT(link) DO UPDATE SET ts=excluded.ts",
            (link, t),
        )

    def tombstones(self) -> Dict[str, float]:
        """All tombstones as link→ts (one query per scan, not per link)."""
        return {
            r[0]: r[1]
            for r in self.db.query(f"SELECT link, ts FROM {TOMBSTONE_TABLE}")
        }

    def tombstone_for(self, link: str) -> float:
        t = self.tombstones()
        return max(t.get("*", 0.0), t.get(link, 0.0))

    # -- scan --------------------------------------------------------------
    def scan(self, window_seconds: float) -> ScanResult:
        """Walk each link's snapshots in the window (post-tombstone) and
        classify drops/flaps (reference: IB store Scan marks drops/flaps)."""
        now = self.time_now_fn()
        start = now - window_seconds
        res = ScanResult(window_start=start)
        rows = self.db.query(
            f"SELECT link, ts, state, tx_errors, rx_errors, crc_errors "
            f"FROM {TABLE} WHERE ts>=? ORDER BY link, ts ASC",
            (start,),
        )
        cur: Optional[LinkScan] = None
        prev_state: Optional[int] = None
        prev_counters = None
        tombstone = 0.0
        all_tombstones = self.tombstones()
        global_tombstone = all_tombstones.get("*", 0.0)

        for link, ts, state, tx_err, rx_err, crc in rows:
            if cur is None or link != cur.link:
                cur = LinkScan(link=link, first_seen=ts)
                res.links[link] = cur
                prev_state = None
                prev_counters = None
                tombstone = max(global_tombstone, all_tombstones.get(link, 0.0))
            if ts < tombstone:
                continue
            if cur.samples == 0:
                cur.first_seen = ts
            cur.samples += 1
            cur.last_seen = ts
            if prev_counters is not None:
                # accumulate only positive steps: counters are monotonic in
                # hardware but may reset on driver reload/reboot
                cur.error_delta += max(0, (tx_err + rx_err) - (prev_counters[0] + prev_counters[1]))
                cur.crc_delta += max(0, crc - prev_counters[2])
            prev_counters = (tx_err, rx_err, crc)
            if prev_state is not None:
                if prev_state == 1 and state == 0:
                    cur.drops += 1
                elif prev_state == 0 and state == 1:
                    cur.flaps += 1
            prev_state = state
            cur.last_state = LinkState.UP if state == 1 else LinkState.DOWN
            cur.currently_down = state == 0
        # links fully masked by a tombstone end up with zero samples — drop
        # them so they don't read as "down since forever"
        res.links = {k: v for k, v in res.links.items() if v.samples > 0}
        return res

    def link_names(self) -> List[str]:
        return [r[0] for r in self.db.query(f"SELECT DISTINCT link FROM {TABLE}")]
