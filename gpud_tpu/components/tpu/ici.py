"""ICI fabric component — the NVLink/InfiniBand analog.

Reference: components/accelerator/nvidia/infiniband (SURVEY §2.4, "most
complex check"): its own SQLite time-series of per-port snapshots; Scan
marks drops/flaps; *sticky* unhealthy until ``set-healthy`` or an opt-in
flap auto-clear window (flap_auto_clear_window.go); expected port counts by
product (threshold_default.go); tombstone on admin action.

TPU translation: ports are per-chip ICI links; expected counts come from
the slice topology (v4/v5p: 6 links/chip 3D torus, v5e/v6e: 4 links/chip
2D torus); counters come from the TPU instance backend.
"""

from __future__ import annotations

import time
from typing import List, Optional

from gpud_tpu.api.v1.types import (
    Event,
    EventType,
    HealthStateType,
    RepairActionType,
    SuggestedActions,
)
from gpud_tpu.components.base import CheckResult, PollingComponent, TpudInstance
from gpud_tpu.components.tpu.ici_store import ICIStore, ScanResult
from gpud_tpu.metadata import KEY_ICI_MAX_LINKS_SEEN, Metadata
from gpud_tpu.components.tpu.shared import sampler_for
from gpud_tpu.metrics.registry import gauge

NAME = "accelerator-tpu-ici"

_g_links_up = gauge("tpud_tpu_ici_links_up", "ICI links currently up")
_g_links_expected = gauge("tpud_tpu_ici_links_expected", "expected ICI links")
_g_link_state = gauge("tpud_tpu_ici_link_state", "per-link state (1=up)")
_g_crc = gauge("tpud_tpu_ici_link_crc_errors_total", "per-link CRC errors")

LABELS = {"component": NAME}

DEFAULT_SCAN_WINDOW = 3600.0        # 1h drop/flap window
DEFAULT_FLAP_THRESHOLD = 3          # flaps in window before Degraded
DEFAULT_CRC_DELTA_DEGRADED = 100    # CRC-errors delta in window before Degraded
# opt-in: clear sticky flap state after this much clean uptime; 0 = sticky
# until set-healthy (reference: flap_auto_clear_window.go)
DEFAULT_AUTO_CLEAR_WINDOW = 0.0
# Adaptive fast-poll: on suspicion (a fabric-class kmsg match arriving via
# the ~ms inotify path, or a sample delta — state change / counter step /
# link-set change) the poller drops to FAST_POLL_INTERVAL for
# SUSPICION_WINDOW seconds, then decays back to the 60s cadence. Beats the
# reference's fixed 60s IB poll (SURVEY §6) without raising steady-state
# CPU: a healthy host never enters the window.
DEFAULT_FAST_POLL_INTERVAL = 1.0
DEFAULT_SUSPICION_WINDOW = 60.0
# a counter-step trigger re-arms only after this cooldown — a continuously
# rising CRC counter (Degraded-class, non-urgent) must not hold ~50% fast
# duty by re-opening a window at every steady poll
DEFAULT_COUNTER_RETRIGGER_COOLDOWN = 600.0


class TPUICIComponent(PollingComponent):
    NAME = NAME
    TAGS = ["accelerator", "tpu", "ici", "fabric"]

    def __init__(self, instance: TpudInstance) -> None:
        super().__init__(instance)
        self.tpu = instance.tpu_instance
        self.sampler = sampler_for(self.tpu)
        self.store: Optional[ICIStore] = (
            ICIStore(instance.db_rw) if instance.db_rw is not None else None
        )
        self._event_bucket = (
            instance.event_store.bucket(NAME) if instance.event_store else None
        )
        self.scan_window = DEFAULT_SCAN_WINDOW
        self.flap_threshold = DEFAULT_FLAP_THRESHOLD
        self.crc_delta_degraded = DEFAULT_CRC_DELTA_DEGRADED
        self.auto_clear_window = DEFAULT_AUTO_CLEAR_WINDOW
        self.time_now_fn = time.time
        self._last_purge = 0.0
        # adaptive fast-poll state
        self.fast_poll_interval = DEFAULT_FAST_POLL_INTERVAL
        self.suspicion_window = DEFAULT_SUSPICION_WINDOW
        self.counter_retrigger_cooldown = DEFAULT_COUNTER_RETRIGGER_COOLDOWN
        self._suspicion_until = 0.0
        self._counter_trigger_armed_at = 0.0
        self._prev_sample: dict = {}
        self._last_store_ts = 0.0
        self._cached_scan: Optional[ScanResult] = None
        instance.fabric_suspicion_listeners.append(self._on_fabric_kmsg)
        # explicit expected-link-count override (pushed via updateConfig);
        # 0 = derive from topology / observed high-water mark
        self.expected_links = 0
        # high-water mark persists in metadata: a daemon restart on a host
        # with partial driver exposure must not forget that more links were
        # once visible (a vanished link still alarms after restart)
        self._metadata = None
        self._max_links_seen = 0
        if instance.db_rw is not None:
            self._metadata = Metadata(instance.db_rw)
            try:
                self._max_links_seen = int(
                    self._metadata.get(KEY_ICI_MAX_LINKS_SEEN) or 0
                )
            except ValueError:
                self._max_links_seen = 0

    def is_supported(self) -> bool:
        return (
            self.tpu is not None
            and self.tpu.tpu_lib_exists()
            and self.tpu.ici_supported()
        )

    # -- adaptive fast-poll ------------------------------------------------
    def poll_interval(self) -> float:
        if self.time_now_fn() < self._suspicion_until:
            return self.fast_poll_interval
        return self.POLL_INTERVAL

    def raise_suspicion(self, reason: str = "") -> None:
        """Open (or extend) the fast-poll window and wake the poller."""
        self._suspicion_until = self.time_now_fn() + self.suspicion_window
        self.poke()

    def _on_fabric_kmsg(self, error_name: str) -> None:
        # driver saw a fabric problem; confirm on sysfs immediately
        # instead of waiting out the 60s cadence
        if error_name.startswith("tpu_ici"):
            self.raise_suspicion(error_name)

    def _delta_kind(self, links) -> Optional[str]:
        """Classify the change vs the previous sample: "state" (state or
        link-set change) outranks "counter" (error-counter step)."""
        cur = {
            ln.name: (
                ln.state,
                ln.tx_errors + ln.rx_errors + ln.crc_errors + ln.replays,
            )
            for ln in links
        }
        prev, self._prev_sample = self._prev_sample, cur
        if not prev:
            return None
        if set(prev) != set(cur):
            return "state"
        kind = None
        for name, (state, errs) in cur.items():
            p_state, p_errs = prev[name]
            if state != p_state:
                return "state"
            if errs > p_errs:
                kind = "counter"
        return kind

    def _expected_links(self, reported: int) -> int:
        """Expected link count. Driver sysfs exposure can be partial
        (SURVEY §7: per-link counters are less exposed than IB sysfs), so
        when the backend stably reports fewer links than the topology, the
        baseline is the most links ever observed — a link *vanishing* from
        a previously-larger set still alarms, but a consistently partial
        mapping doesn't page operators forever."""
        if self.expected_links > 0:
            # operator/control-plane pinned the expectation (e.g. after a
            # legitimately smaller re-deployment) — overrides both the
            # topology estimate and the observed high-water mark
            return self.expected_links
        topo = self.tpu.topology() if self.tpu else None
        if topo is None:
            return 0
        topo_expected = len(self.tpu.devices()) * topo.ici_links_per_chip
        source = getattr(self.tpu, "ici_source", lambda: "")()
        if source == "derived-topology":
            # the derived inventory IS the topology count — recording it
            # as an observed high-water mark would poison the baseline
            # for a later partially-mapped per-link layout (which may
            # legitimately expose fewer nodes than the topology)
            return topo_expected
        if reported > self._max_links_seen:
            self._max_links_seen = reported
            if self._metadata is not None:
                self._metadata.set(
                    KEY_ICI_MAX_LINKS_SEEN, str(self._max_links_seen)
                )
        if self._max_links_seen >= topo_expected:
            return topo_expected
        return self._max_links_seen

    def _record_event(self, name: str, ev_type: str, message: str) -> None:
        if self._event_bucket is None:
            return
        ev = Event(component=NAME, name=name, type=ev_type, message=message)
        # dedupe identical message within the last scan window — but only
        # back to the latest SetHealthy marker, so a recurrence after an
        # operator clear is a fresh incident with its own event
        recent = self._event_bucket.get(self.time_now_fn() - self.scan_window)
        for e in recent:  # newest first
            if e.name == "SetHealthy":
                break
            if e.name == name and e.message == message:
                return
        self._event_bucket.insert(ev)

    def check_once(self) -> CheckResult:
        if not self.is_supported():
            return CheckResult(
                self.NAME,
                health=HealthStateType.HEALTHY,
                reason="no ICI fabric on this host",
            )
        links = self.sampler.ici_links()
        now = self.time_now_fn()
        delta = self._delta_kind(links)
        if delta == "state":
            # link state/set moved: hold the fast cadence until the window
            # expires with no further state changes
            self._suspicion_until = now + self.suspicion_window
        elif (
            delta == "counter"
            and now >= self._suspicion_until
            and now >= self._counter_trigger_armed_at
        ):
            # a counter step opens ONE window per cooldown — a steadily-
            # rising CRC counter is a Degraded-class condition that must
            # not pin the poller at (or near) 1 Hz forever
            self._suspicion_until = now + self.suspicion_window
            self._counter_trigger_armed_at = now + self.counter_retrigger_cooldown

        up = 0
        for ln in links:
            labels = {"component": NAME, "link": ln.name}
            _g_link_state.set(1.0 if ln.state == "up" else 0.0, labels)
            _g_crc.set(ln.crc_errors, labels)
            if ln.state == "up":
                up += 1
        expected = self._expected_links(len(links))
        _g_links_up.set(up, LABELS)
        _g_links_expected.set(expected, LABELS)

        scan: Optional[ScanResult] = None
        if self.store is not None:
            # fast polls detect down-links directly from the sample; the
            # history store keeps its steady 60s granularity (plus an
            # immediate row on any delta so the transition is recorded) —
            # a 1 Hz insert + 1h-window scan would be sustained disk/CPU
            # load and ~60x row growth during every suspicion window
            # counter deltas recur on every fast poll of a noisy link —
            # only STATE transitions warrant an off-cadence row
            if delta == "state" or now - self._last_store_ts >= self.POLL_INTERVAL:
                self.store.insert_snapshot(links, ts=now)
                self._last_store_ts = now
                # purge at retention/5 cadence, not per poll (matches the
                # eventstore purger; a per-poll DELETE would walk the table)
                if now - self._last_purge >= self.store.retention_seconds / 5.0:
                    self.store.purge()
                    self._last_purge = now
                self._cached_scan = self.store.scan(self.scan_window)
            scan = self._cached_scan

        # measurement-vs-inventory label (VERDICT r3 #6; reference exposes
        # its port-state source explicitly, infiniband/class/class.go:14-34):
        # "derived-topology" = inventory (links inferred from topology +
        # driver binding, no counters), "mapped-sysfs" = per-link counter
        # files, "runtime-metrics" = libtpu gRPC fabric telemetry.
        source = getattr(self.tpu, "ici_source", lambda: "")()
        extra = {
            "links_up": str(up),
            "links_expected": str(expected),
            "poll_mode": "fast" if now < self._suspicion_until else "steady",
            "ici_source": source,
        }

        # 1. links currently down → Unhealthy (sticky by construction: the
        #    condition persists until the link recovers, and history keeps
        #    the drop visible via events)
        down_now = sorted(ln.name for ln in links if ln.state != "up")
        if down_now or (expected and up < expected):
            missing = down_now or [f"{expected - up} link(s) unreported"]
            for name in down_now:
                self._record_event(
                    "ici_link_down", EventType.CRITICAL, f"ICI link {name} down"
                )
            return CheckResult(
                self.NAME,
                health=HealthStateType.UNHEALTHY,
                reason=f"ICI link(s) down: {', '.join(missing)} ({up}/{expected} up)",
                suggested_actions=SuggestedActions(
                    description="ICI link down — reboot may retrain; persistent loss needs hardware inspection",
                    repair_actions=[
                        RepairActionType.REBOOT_SYSTEM,
                        RepairActionType.HARDWARE_INSPECTION,
                    ],
                ),
                extra_info=extra,
            )

        # 2. sticky history: drops/flaps in the window keep the component
        #    not-healthy even after recovery, until set-healthy tombstones
        #    the history or the auto-clear window elapses
        if scan is not None:
            flapped = [
                s
                for s in scan.links.values()
                if s.drops > 0 or s.flaps > 0
            ]
            if flapped and self.auto_clear_window > 0:
                # opt-in: clear sticky state once every link has been clean
                # for the auto-clear window (reference: flap_auto_clear_window.go)
                if self._all_clean_since(self.auto_clear_window):
                    flapped = []
            if flapped:
                heavy = [
                    s.link
                    for s in flapped
                    if s.flaps >= self.flap_threshold or s.drops >= self.flap_threshold
                ]
                names = sorted(s.link for s in flapped)
                for s in flapped:
                    self._record_event(
                        "ici_link_flap",
                        EventType.WARNING,
                        f"ICI link {s.link} dropped {s.drops}x / recovered {s.flaps}x in window",
                    )
                health = (
                    HealthStateType.UNHEALTHY if heavy else HealthStateType.DEGRADED
                )
                return CheckResult(
                    self.NAME,
                    health=health,
                    reason=(
                        f"ICI link(s) flapped in last {int(self.scan_window / 60)}m: "
                        f"{', '.join(names)} (sticky until set-healthy)"
                    ),
                    suggested_actions=SuggestedActions(
                        description="ICI links unstable — check cabling/seating",
                        repair_actions=[RepairActionType.HARDWARE_INSPECTION],
                    ),
                    extra_info=extra,
                )

            # 3. counter health: CRC deltas in window
            noisy = [
                s.link
                for s in scan.links.values()
                if s.crc_delta >= self.crc_delta_degraded
            ]
            if noisy:
                return CheckResult(
                    self.NAME,
                    health=HealthStateType.DEGRADED,
                    reason=f"ICI CRC errors rising on: {', '.join(sorted(noisy))}",
                    suggested_actions=SuggestedActions(
                        description="ICI CRC errors — cable/connector suspect",
                        repair_actions=[RepairActionType.HARDWARE_INSPECTION],
                    ),
                    extra_info=extra,
                )

        reason = f"all {up}/{expected} ICI links up"
        if source == "derived-topology":
            # an operator must not mistake topology math for telemetry:
            # this "up" means chips are present and driver-bound, not that
            # link counters were read
            reason += " (inventory-derived: chip presence, no link counters)"
        return CheckResult(
            self.NAME,
            reason=reason,
            extra_info=extra,
        )

    def _all_clean_since(self, window: float) -> bool:
        """True when no drop/flap transition occurred within ``window``."""
        if self.store is None:
            return False
        recent = self.store.scan(window)
        return not any(
            s.drops > 0 or s.flaps > 0 or s.currently_down
            for s in recent.links.values()
        )

    def close(self) -> None:
        # a discarded/deregistered component must not keep receiving
        # fabric-suspicion callbacks through the long-lived TpudInstance
        try:
            self.instance.fabric_suspicion_listeners.remove(self._on_fabric_kmsg)
        except ValueError:
            pass
        super().close()

    def events(self, since: float):
        if self._event_bucket is None:
            return []
        return self._event_bucket.get(since)

    def set_healthy(self) -> None:
        """Tombstone all link history so the scan starts fresh
        (reference: IB tombstone on admin action). Deliberately does NOT
        touch the expected-links baseline: clearing a flap alarm must not
        silently accept a vanished link as the new normal — a smaller
        topology is accepted explicitly via the ``expected_links``
        updateConfig override."""
        if self.store is not None:
            self.store.set_tombstone("*", ts=self.time_now_fn())
            # the cached window scan predates the tombstone — drop it and
            # force a fresh insert+scan so the re-check reflects the clear
            self._cached_scan = None
            self._last_store_ts = 0.0
        if self._event_bucket is not None:
            self._event_bucket.insert(
                Event(
                    component=NAME,
                    name="SetHealthy",
                    type=EventType.INFO,
                    message="operator set-healthy; ICI history tombstoned",
                )
            )
        self.check()
