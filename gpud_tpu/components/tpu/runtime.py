"""TPU runtime/fabric service component — the fabric-manager analog.

Reference: components/accelerator/nvidia/fabric-manager (1545 LoC) —
nvidia-fabricmanager.service activeness + arch-dependent strategy
selection (H100-SXM vs GB200 vs PCIe). TPU translation: the per-host
runtime services that keep a slice's fabric usable — the TPU runtime
(tpu-runtime / libtpu grpc server on TPU-VM images) and, for multi-slice,
the megascale DCN transport — health-checked by systemd activeness and
local port probes; single-host generations skip fabric checks the way the
reference skips non-NVSwitch parts.

Also covers components/accelerator/nvidia/processes (661): which
processes hold the TPU device nodes (a training job crash can leave a
zombie holding /dev/accel*, blocking the next job).
"""

from __future__ import annotations

import glob
import os
from typing import Dict, List

from gpud_tpu.api.v1.types import (
    HealthStateType,
    RepairActionType,
    SuggestedActions,
)
from gpud_tpu.components.base import CheckResult, PollingComponent, TpudInstance
from gpud_tpu.metrics.registry import gauge
from gpud_tpu.process import run_command

RUNTIME_NAME = "accelerator-tpu-runtime"
PROCESSES_NAME = "accelerator-tpu-processes"

_g_holders = gauge("tpud_tpu_device_holder_processes", "processes holding TPU devices")

# services probed when present; absence is fine (GKE images differ)
RUNTIME_UNITS = ("tpu-runtime.service", "tpu-device-daemon.service")


class TPURuntimeComponent(PollingComponent):
    NAME = RUNTIME_NAME
    TAGS = ["accelerator", "tpu", "fabric"]

    def __init__(self, instance: TpudInstance) -> None:
        super().__init__(instance)
        self.tpu = instance.tpu_instance
        self.units = list(RUNTIME_UNITS)
        self.is_active_fn = self._systemd_is_active
        # chaos hook: while time_now_fn() < chaos_fail_until the component
        # reports its unit failed, even on mock backends (runtime-crash-
        # mid-remediation campaigns race this against the engine's scan)
        self.chaos_fail_until = 0.0

    def is_supported(self) -> bool:
        return self.tpu is not None and self.tpu.tpu_lib_exists()

    @staticmethod
    def _systemd_is_active(unit: str) -> str:
        """'active' | 'inactive' | 'failed' | 'absent'."""
        r = run_command(["systemctl", "is-active", unit], timeout=10)
        out = r.output.strip()
        if r.exit_code == 0:
            return "active"
        if "could not be found" in out or "not-found" in out or r.error:
            return "absent"
        return out or "inactive"

    def check_once(self) -> CheckResult:
        if self.time_now_fn() < self.chaos_fail_until:
            failed = list(self.units[:1])
            return CheckResult(
                self.NAME,
                health=HealthStateType.UNHEALTHY,
                reason=f"TPU runtime unit(s) failed: {failed} (chaos)",
                suggested_actions=SuggestedActions(
                    description="TPU runtime service failed — restart/reboot",
                    repair_actions=[RepairActionType.REBOOT_SYSTEM],
                ),
                extra_info={u: "failed" for u in failed},
            )
        if self.tpu is not None and self.tpu.is_mock():
            return CheckResult(self.NAME, reason="mock backend; runtime assumed healthy")
        statuses: Dict[str, str] = {u: self.is_active_fn(u) for u in self.units}
        failed = [u for u, s in statuses.items() if s == "failed"]
        present = {u: s for u, s in statuses.items() if s != "absent"}
        if failed:
            return CheckResult(
                self.NAME,
                health=HealthStateType.UNHEALTHY,
                reason=f"TPU runtime unit(s) failed: {failed}",
                suggested_actions=SuggestedActions(
                    description="TPU runtime service failed — restart/reboot",
                    repair_actions=[RepairActionType.REBOOT_SYSTEM],
                ),
                extra_info=statuses,
            )
        if not present:
            return CheckResult(
                self.NAME,
                reason="no TPU runtime services on this image (direct libtpu mode)",
                extra_info=statuses,
            )
        return CheckResult(
            self.NAME,
            reason=f"runtime units healthy: {sorted(present)}",
            extra_info=statuses,
        )


class TPUProcessesComponent(PollingComponent):
    NAME = PROCESSES_NAME
    TAGS = ["accelerator", "tpu"]

    def __init__(self, instance: TpudInstance) -> None:
        super().__init__(instance)
        self.tpu = instance.tpu_instance
        self.proc_root = "/proc"
        self._stuck_last_check: set = set()

    def is_supported(self) -> bool:
        return self.tpu is not None and self.tpu.tpu_lib_exists()

    def _device_holders(self) -> Dict[int, List[str]]:
        """pid → device paths held, from /proc/*/fd symlinks
        (reference: NVML running-processes; TPUs have no side-band process
        API, so fd tables are the source of truth)."""
        holders: Dict[int, List[str]] = {}
        for fd_dir in glob.iglob(os.path.join(self.proc_root, "[0-9]*", "fd")):
            pid_s = fd_dir.split(os.sep)[-2]
            try:
                pid = int(pid_s)
                for fd in os.listdir(fd_dir):
                    try:
                        target = os.readlink(os.path.join(fd_dir, fd))
                    except OSError:
                        continue
                    if target.startswith("/dev/accel") or target.startswith("/dev/vfio"):
                        holders.setdefault(pid, []).append(target)
            except (OSError, ValueError):
                continue
        return holders

    def _proc_state(self, pid: int) -> str:
        try:
            path = os.path.join(self.proc_root, str(pid), "stat")
            # comm may contain ') ' AND non-ASCII (prctl PR_SET_NAME is
            # arbitrary bytes) — read raw and split at the LAST ')' per
            # the stat contract: state is the first field after it
            with open(path, "rb") as f:
                return f.read().rsplit(b")", 1)[1].split()[0].decode("ascii")
        except (OSError, IndexError, UnicodeDecodeError):
            return "?"

    def check_once(self) -> CheckResult:
        if self.tpu is not None and self.tpu.is_mock():
            return CheckResult(self.NAME, reason="mock backend; no device holders")
        holders = self._device_holders()
        _g_holders.set(len(holders), {"component": self.NAME})
        # a defunct process has no open fds (the kernel closes them in
        # do_exit before the Z state), so the stuck-device signal is a
        # holder in uninterruptible sleep ('D') — typically wedged in a
        # driver ioctl; escalate if it stays stuck across checks
        stuck = sorted(p for p in holders if self._proc_state(p) == "D")
        persistent = [p for p in stuck if p in self._stuck_last_check]
        self._stuck_last_check = set(stuck)
        extra = {
            str(pid): ",".join(sorted(set(devs))) for pid, devs in holders.items()
        }
        if persistent:
            return CheckResult(
                self.NAME,
                health=HealthStateType.UNHEALTHY,
                reason=(
                    f"process(es) stuck in uninterruptible sleep holding TPU "
                    f"devices across checks: {persistent}"
                ),
                suggested_actions=SuggestedActions(
                    description="process wedged in TPU driver — check app; reboot frees the device",
                    repair_actions=[RepairActionType.CHECK_USER_APP_AND_TPU,
                                    RepairActionType.REBOOT_SYSTEM],
                ),
                extra_info=extra,
            )
        if stuck:
            return CheckResult(
                self.NAME,
                health=HealthStateType.DEGRADED,
                reason=f"process(es) in uninterruptible sleep holding TPU devices: {stuck}",
                extra_info=extra,
            )
        return CheckResult(
            self.NAME,
            reason=f"{len(holders)} process(es) holding TPU devices",
            extra_info=extra,
        )
