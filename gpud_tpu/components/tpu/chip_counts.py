"""TPU chip-count component: lost-chip detection.

Reference: components/accelerator/nvidia/gpu-counts (502) — device
enumeration vs expected count (settable via flag/session updateConfig).
"""

from __future__ import annotations

from gpud_tpu.api.v1.types import (
    HealthStateType,
    RepairActionType,
    SuggestedActions,
)
from gpud_tpu.components.base import CheckResult, PollingComponent, TpudInstance
from gpud_tpu.metrics.registry import gauge
from gpud_tpu.tpu.topology import expected_local_chips

NAME = "accelerator-tpu-chip-counts"

_g_count = gauge("tpud_tpu_chip_count", "enumerated TPU chips")
_g_expected = gauge("tpud_tpu_chip_count_expected", "expected TPU chips")

LABELS = {"component": NAME}


class TPUChipCountsComponent(PollingComponent):
    NAME = NAME
    TAGS = ["accelerator", "tpu"]

    def __init__(self, instance: TpudInstance) -> None:
        super().__init__(instance)
        self.tpu = instance.tpu_instance
        # runtime-configurable expectation (session updateConfig analog,
        # reference: pkg/session/session.go:222-227)
        cfg = instance.config
        self.expected_count = getattr(cfg, "expected_chip_count", 0) if cfg else 0

    def is_supported(self) -> bool:
        # an enumeration *failure* is supported-but-unhealthy, not
        # unsupported — otherwise a chips-fell-off-the-bus boot would be
        # reported as "not supported" and never checked
        if self.tpu is None:
            return False
        return self.tpu.tpu_lib_exists() or bool(self.tpu.init_error())

    def _expected(self) -> int:
        if self.expected_count:
            return self.expected_count
        if self.tpu is not None:
            return expected_local_chips(self.tpu.accelerator_type())
        return 0

    def check_once(self) -> CheckResult:
        if self.tpu is None or not self.tpu.tpu_lib_exists():
            err = self.tpu.init_error() if self.tpu is not None else "no TPU instance"
            return CheckResult(
                self.NAME,
                health=HealthStateType.UNHEALTHY if err else HealthStateType.HEALTHY,
                reason=err or "no TPUs on this host",
            )
        devs = self.tpu.devices()
        healthy_devs = {cid: d for cid, d in devs.items() if not d.lost}
        lost = sorted(cid for cid, d in devs.items() if d.lost)
        needs_reset = sorted(cid for cid, d in devs.items() if d.requires_reset)
        expected = self._expected()
        _g_count.set(len(healthy_devs), LABELS)
        _g_expected.set(expected, LABELS)

        extra = {
            "found": str(len(healthy_devs)),
            "expected": str(expected),
            "accelerator_type": self.tpu.accelerator_type(),
        }
        if lost or (expected and len(healthy_devs) < expected):
            detail = f"found {len(healthy_devs)}/{expected or '?'} chips"
            if lost:
                detail += f"; lost chip(s) {lost}"
            return CheckResult(
                self.NAME,
                health=HealthStateType.UNHEALTHY,
                reason=f"TPU chip(s) missing: {detail}",
                suggested_actions=SuggestedActions(
                    description="TPU chips fell off the bus — reboot; if it persists, inspect hardware",
                    repair_actions=[
                        RepairActionType.REBOOT_SYSTEM,
                        RepairActionType.HARDWARE_INSPECTION,
                    ],
                ),
                extra_info=extra,
            )
        if needs_reset:
            return CheckResult(
                self.NAME,
                health=HealthStateType.UNHEALTHY,
                reason=f"TPU chip(s) require reset: {needs_reset}",
                suggested_actions=SuggestedActions(
                    description="TPU chips in reset-required state",
                    repair_actions=[RepairActionType.REBOOT_SYSTEM],
                ),
                extra_info=extra,
            )
        return CheckResult(
            self.NAME,
            reason=f"all {len(healthy_devs)} expected chips present",
            extra_info=extra,
        )
