"""TPU temperature component.

Reference: components/accelerator/nvidia/temperature (component.go:119-190,
metrics.go:17-50) — per-chip temps with margin-to-slowdown degraded
threshold and HBM temperature, re-targeted at TPU chip/HBM sensors.
"""

from __future__ import annotations

from gpud_tpu.api.v1.types import (
    HealthStateType,
    RepairActionType,
    SuggestedActions,
)
from gpud_tpu.components.base import CheckResult, PollingComponent, TpudInstance
from gpud_tpu.components.tpu.shared import sampler_for, telemetry_source
from gpud_tpu.metrics.registry import gauge

NAME = "accelerator-tpu-temperature"

_g_temp = gauge("tpud_tpu_temperature_celsius", "TPU chip temperature")
_g_hbm_temp = gauge("tpud_tpu_hbm_temperature_celsius", "TPU HBM temperature")

# thermal design thresholds; slowdown flag from telemetry overrides
DEFAULT_DEGRADED_C = 85.0
DEFAULT_UNHEALTHY_C = 95.0


class TPUTemperatureComponent(PollingComponent):
    NAME = NAME
    TAGS = ["accelerator", "tpu", "temperature"]

    def __init__(self, instance: TpudInstance) -> None:
        super().__init__(instance)
        self.tpu = instance.tpu_instance
        self.sampler = sampler_for(self.tpu)
        # indirection so chaos campaigns can overlay slow-ramp faults on
        # the telemetry read without touching the shared sampler cache;
        # None means "read the live sampler" so late sampler swaps stick
        self.telemetry_fn = None
        self.degraded_c = DEFAULT_DEGRADED_C
        self.unhealthy_c = DEFAULT_UNHEALTHY_C

    def is_supported(self) -> bool:
        return (
            self.tpu is not None
            and self.tpu.tpu_lib_exists()
            and self.tpu.telemetry_supported()
        )

    def check_once(self) -> CheckResult:
        if not self.is_supported():
            return CheckResult(
                self.NAME,
                health=HealthStateType.HEALTHY,
                reason="no TPU telemetry on this host",
            )
        tel = (self.telemetry_fn or self.sampler.telemetry)()
        worst = -1.0
        slowdown_chips = []
        extra = {"telemetry_source": telemetry_source(self.tpu)}
        for cid, t in sorted(tel.items()):
            labels = {"component": NAME, "chip": str(cid)}
            _g_temp.set(t.temperature_c, labels)
            _g_hbm_temp.set(t.hbm_temperature_c, labels)
            extra[f"chip{cid}_temp_c"] = f"{t.temperature_c:.1f}"
            worst = max(worst, t.temperature_c)
            if t.thermal_slowdown:
                slowdown_chips.append(cid)

        if slowdown_chips or worst >= self.unhealthy_c:
            chips = slowdown_chips or [
                cid for cid, t in tel.items() if t.temperature_c >= self.unhealthy_c
            ]
            return CheckResult(
                self.NAME,
                health=HealthStateType.UNHEALTHY,
                reason=f"thermal slowdown on chip(s) {chips}; max temp {worst:.1f}C",
                suggested_actions=SuggestedActions(
                    description="TPU thermal slowdown — check cooling / inspect hardware",
                    repair_actions=[RepairActionType.HARDWARE_INSPECTION],
                ),
                extra_info=extra,
            )
        if worst >= self.degraded_c:
            return CheckResult(
                self.NAME,
                health=HealthStateType.DEGRADED,
                reason=f"high TPU temperature: max {worst:.1f}C",
                extra_info=extra,
            )
        return CheckResult(
            self.NAME,
            reason=f"max temp {worst:.1f}C across {len(tel)} chips",
            extra_info=extra,
        )
