"""Event-sourced health evaluation for TPU errors.

This ports the *shape* of the reference's subtlest logic
(reference: components/accelerator/nvidia/xid/health_state.go:56-80 and
component.go:400-650): walk the merged stream of error events, reboot
events and set-healthy events oldest→newest and evolve the health state:

- a critical error's first occurrence ⇒ Unhealthy, suggest REBOOT_SYSTEM;
- if the same error recurs after ``reboot_threshold`` reboots, escalate the
  suggestion to HARDWARE_INSPECTION (rebooting didn't fix it);
- a SetHealthy event clears the slate (reference: xid/set_healthy.go,
  component.go:636-650 trims history);
- non-critical errors never push past Degraded.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from gpud_tpu.api.v1.types import (
    Event,
    EventType,
    HealthStateType,
    RepairActionType,
    SuggestedActions,
)
from gpud_tpu.components.tpu.catalog import CatalogEntry, extract_chip, lookup

EVENT_NAME_REBOOT = "reboot"
EVENT_NAME_SET_HEALTHY = "SetHealthy"


def _event_chip(ev: Event) -> Optional[int]:
    """Chip attribution for an error event: explicit extra_info first, then
    best-effort parse of the raw kmsg line in the message (the reference
    tracks per-DeviceUUID the same way; xid events carry the device in
    their payload)."""
    raw = ev.extra_info.get("chip") if ev.extra_info else None
    if raw is not None:
        try:
            return int(raw)
        except (TypeError, ValueError):
            pass
    return extract_chip(ev.message or "")


@dataclass
class _ErrorTrack:
    entry: CatalogEntry
    chip_id: Optional[int] = None
    occurrences: int = 0
    reboots_since_first: int = 0
    recurred_after_reboot: bool = False
    last_event: Optional[Event] = None
    pending_reboot_seen: bool = False  # a reboot happened after the last occurrence

    @property
    def display(self) -> str:
        return (
            f"{self.entry.name}(chip {self.chip_id})"
            if self.chip_id is not None
            else self.entry.name
        )


@dataclass
class EvaluatedHealth:
    health: str = HealthStateType.HEALTHY
    reason: str = ""
    suggested_actions: Optional[SuggestedActions] = None
    active_errors: Dict[str, int] = field(default_factory=dict)


def evolve_health(
    merged_events: List[Event],
    threshold_overrides: Optional[Dict[str, int]] = None,
) -> EvaluatedHealth:
    """``merged_events`` may arrive in any order; they are sorted
    oldest→newest here (reference: health_state.go:60+ walks merged reboot
    + xid events the same way). Error events must carry the catalog name in
    ``Event.name``.

    Tracks are keyed by (error name, chip id): a recurring error on chip 3
    and a first occurrence on chip 5 escalate independently, the way the
    reference keys on DeviceUUID (xid events carry the device)."""
    events = sorted(merged_events, key=lambda e: e.time)
    tracks: Dict[Tuple[str, Optional[int]], _ErrorTrack] = {}

    for ev in events:
        if ev.name == EVENT_NAME_SET_HEALTHY:
            # operator cleared the slate: drop all accumulated state
            tracks.clear()
            continue
        if ev.name == EVENT_NAME_REBOOT:
            for tr in tracks.values():
                tr.reboots_since_first += 1
                tr.pending_reboot_seen = True
            continue
        entry = lookup(ev.name)
        if entry is None:
            continue
        key = (ev.name, _event_chip(ev))
        tr = tracks.get(key)
        if tr is None:
            tr = _ErrorTrack(entry=entry, chip_id=key[1])
            tracks[key] = tr
        tr.occurrences += 1
        tr.last_event = ev
        if tr.pending_reboot_seen:
            # the error came back after a reboot — reboot didn't fix it
            tr.recurred_after_reboot = True
            tr.pending_reboot_seen = False

    if not tracks:
        return EvaluatedHealth(reason="no TPU errors observed")

    # Resolution semantics: an error with a reboot after its last occurrence
    # and no recurrence is considered addressed (reference merges reboot
    # events so a clean reboot clears the suggestion path).
    active: Dict[Tuple[str, Optional[int]], _ErrorTrack] = {}
    for key, tr in tracks.items():
        if tr.pending_reboot_seen and not tr.recurred_after_reboot:
            continue  # rebooted, hasn't recurred → resolved
        active[key] = tr

    if not active:
        return EvaluatedHealth(
            reason="previous TPU errors cleared by reboot",
        )

    worst = HealthStateType.DEGRADED
    reasons: List[str] = []
    repair: List[str] = []
    descs: List[str] = []
    counts: Dict[str, int] = {}
    any_escalated = False
    for _key, tr in sorted(
        active.items(), key=lambda kv: (-kv[1].entry.code, kv[0][1] is None, kv[0][1])
    ):
        counts[tr.display] = tr.occurrences
        if tr.entry.critical:
            worst = HealthStateType.UNHEALTHY
        # control-plane-pushed per-error-name thresholds win over the
        # catalog default (reference: XID thresholds via updateConfig,
        # session.go:222-227)
        thr = (threshold_overrides or {}).get(
            tr.entry.name, tr.entry.reboot_threshold
        )
        escalate = (
            thr > 0
            and tr.recurred_after_reboot
            and tr.reboots_since_first >= thr
        )
        if escalate:
            any_escalated = True
            reasons.append(
                f"{tr.display} recurred after {tr.reboots_since_first} reboot(s) "
                f"(x{tr.occurrences})"
            )
            if RepairActionType.HARDWARE_INSPECTION not in repair:
                repair.append(RepairActionType.HARDWARE_INSPECTION)
        else:
            reasons.append(f"{tr.display} (x{tr.occurrences})")
            for act in tr.entry.repair_actions:
                if act not in repair:
                    repair.append(act)
        if tr.entry.description not in descs:
            descs.append(tr.entry.description)

    # once an error escalated, rebooting is known not to help: replace the
    # reboot suggestion with inspection (reference: health_state.go
    # escalation replaces reboot with inspection)
    if any_escalated:
        repair = [a for a in repair if a != RepairActionType.REBOOT_SYSTEM]
        if RepairActionType.HARDWARE_INSPECTION not in repair:
            repair.append(RepairActionType.HARDWARE_INSPECTION)

    sa = None
    if repair and repair != [RepairActionType.IGNORE_NO_ACTION_REQUIRED]:
        sa = SuggestedActions(description="; ".join(descs), repair_actions=repair)
    return EvaluatedHealth(
        health=worst,
        reason="; ".join(reasons),
        suggested_actions=sa,
        active_errors=counts,
    )
