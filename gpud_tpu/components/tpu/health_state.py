"""Event-sourced health evaluation for TPU errors.

This ports the *shape* of the reference's subtlest logic
(reference: components/accelerator/nvidia/xid/health_state.go:56-80 and
component.go:400-650): walk the merged stream of error events, reboot
events and set-healthy events oldest→newest and evolve the health state:

- a critical error's first occurrence ⇒ Unhealthy, suggest REBOOT_SYSTEM;
- if the same error recurs after ``reboot_threshold`` reboots, escalate the
  suggestion to HARDWARE_INSPECTION (rebooting didn't fix it);
- a SetHealthy event clears the slate (reference: xid/set_healthy.go,
  component.go:636-650 trims history);
- non-critical errors never push past Degraded.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from gpud_tpu.api.v1.types import (
    Event,
    EventType,
    HealthStateType,
    RepairActionType,
    SuggestedActions,
)
from gpud_tpu.components.tpu.catalog import CatalogEntry, lookup

EVENT_NAME_REBOOT = "reboot"
EVENT_NAME_SET_HEALTHY = "SetHealthy"


@dataclass
class _ErrorTrack:
    entry: CatalogEntry
    occurrences: int = 0
    reboots_since_first: int = 0
    recurred_after_reboot: bool = False
    last_event: Optional[Event] = None
    pending_reboot_seen: bool = False  # a reboot happened after the last occurrence


@dataclass
class EvaluatedHealth:
    health: str = HealthStateType.HEALTHY
    reason: str = ""
    suggested_actions: Optional[SuggestedActions] = None
    active_errors: Dict[str, int] = field(default_factory=dict)


def evolve_health(merged_events: List[Event]) -> EvaluatedHealth:
    """``merged_events`` may arrive in any order; they are sorted
    oldest→newest here (reference: health_state.go:60+ walks merged reboot
    + xid events the same way). Error events must carry the catalog name in
    ``Event.name``."""
    events = sorted(merged_events, key=lambda e: e.time)
    tracks: Dict[str, _ErrorTrack] = {}

    for ev in events:
        if ev.name == EVENT_NAME_SET_HEALTHY:
            # operator cleared the slate: drop all accumulated state
            tracks.clear()
            continue
        if ev.name == EVENT_NAME_REBOOT:
            for tr in tracks.values():
                tr.reboots_since_first += 1
                tr.pending_reboot_seen = True
            continue
        entry = lookup(ev.name)
        if entry is None:
            continue
        tr = tracks.get(ev.name)
        if tr is None:
            tr = _ErrorTrack(entry=entry)
            tracks[ev.name] = tr
        tr.occurrences += 1
        tr.last_event = ev
        if tr.pending_reboot_seen:
            # the error came back after a reboot — reboot didn't fix it
            tr.recurred_after_reboot = True
            tr.pending_reboot_seen = False

    if not tracks:
        return EvaluatedHealth(reason="no TPU errors observed")

    # Resolution semantics: an error with a reboot after its last occurrence
    # and no recurrence is considered addressed (reference merges reboot
    # events so a clean reboot clears the suggestion path).
    active: Dict[str, _ErrorTrack] = {}
    for name, tr in tracks.items():
        if tr.pending_reboot_seen and not tr.recurred_after_reboot:
            continue  # rebooted, hasn't recurred → resolved
        active[name] = tr

    if not active:
        return EvaluatedHealth(
            reason="previous TPU errors cleared by reboot",
        )

    worst = HealthStateType.DEGRADED
    reasons: List[str] = []
    repair: List[str] = []
    descs: List[str] = []
    counts: Dict[str, int] = {}
    any_escalated = False
    for name, tr in sorted(active.items(), key=lambda kv: -kv[1].entry.code):
        counts[name] = tr.occurrences
        if tr.entry.critical:
            worst = HealthStateType.UNHEALTHY
        escalate = (
            tr.entry.reboot_threshold > 0
            and tr.recurred_after_reboot
            and tr.reboots_since_first >= tr.entry.reboot_threshold
        )
        if escalate:
            any_escalated = True
            reasons.append(
                f"{name} recurred after {tr.reboots_since_first} reboot(s) "
                f"(x{tr.occurrences})"
            )
            if RepairActionType.HARDWARE_INSPECTION not in repair:
                repair.append(RepairActionType.HARDWARE_INSPECTION)
        else:
            reasons.append(f"{name} (x{tr.occurrences})")
            for act in tr.entry.repair_actions:
                if act not in repair:
                    repair.append(act)
        descs.append(tr.entry.description)

    # once an error escalated, rebooting is known not to help: replace the
    # reboot suggestion with inspection (reference: health_state.go
    # escalation replaces reboot with inspection)
    if any_escalated:
        repair = [a for a in repair if a != RepairActionType.REBOOT_SYSTEM]
        if RepairActionType.HARDWARE_INSPECTION not in repair:
            repair.append(RepairActionType.HARDWARE_INSPECTION)

    sa = None
    if repair and repair != [RepairActionType.IGNORE_NO_ACTION_REQUIRED]:
        sa = SuggestedActions(description="; ".join(descs), repair_actions=repair)
    return EvaluatedHealth(
        health=worst,
        reason="; ".join(reasons),
        suggested_actions=sa,
        active_errors=counts,
    )
