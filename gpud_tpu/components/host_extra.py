"""Remaining host components (reference: SURVEY §2.3).

- fuse            — /sys/fs/fuse/connections congestion (reference:
                    components/fuse, pkg/fuse/fuse.go:18)
- kernel-module   — /proc/modules asserts configured modules loaded
                    (reference: components/kernel-module)
- library         — expected shared libraries present (reference:
                    components/library; libtpu instead of libnvidia-ml)
- network-latency — RTT to configured edge targets (reference:
                    components/network/latency; DERP map replaced by
                    configurable TCP-connect targets)
- docker          — docker daemon reachable + container listing
                    (reference: components/docker)
- containerd      — socket presence with consecutive-miss threshold
                    (reference: components/containerd,
                    components/registry.go:99-103)
- kubelet         — read-only port 10255 /pods (reference:
                    components/kubelet; healthy-if-absent)
- pci             — ACS check on baremetal via lspci (reference:
                    components/pci/component.go:156-161 skips VMs)
- nfs             — group NFS checker (reference: components/nfs)
"""

from __future__ import annotations

import glob
import json
import os
import socket
from typing import List, Optional

from gpud_tpu.api.v1.types import HealthStateType
from gpud_tpu.components.base import CheckResult, PollingComponent, TpudInstance
from gpud_tpu.metrics.registry import gauge
from gpud_tpu.nfs_checker import GroupConfig, NFSChecker
from gpud_tpu.process import run_command


# ---------------------------------------------------------------------------
class FuseComponent(PollingComponent):
    NAME = "fuse"
    TAGS = ["host", "fuse"]

    CONGESTED_PCT_DEGRADED = 90.0

    def __init__(self, instance: TpudInstance) -> None:
        super().__init__(instance)
        self.connections_dir = "/sys/fs/fuse/connections"

    def is_supported(self) -> bool:
        return os.path.isdir(self.connections_dir)

    def check_once(self) -> CheckResult:
        congested = []
        n = 0
        for conn in glob.glob(os.path.join(self.connections_dir, "*")):
            n += 1
            try:
                with open(os.path.join(conn, "waiting"), "r") as f:
                    waiting = int(f.read().strip())
                with open(os.path.join(conn, "max_background"), "r") as f:
                    max_bg = int(f.read().strip())
                if max_bg and 100.0 * waiting / max_bg >= self.CONGESTED_PCT_DEGRADED:
                    congested.append(os.path.basename(conn))
            except (OSError, ValueError):
                continue
        if congested:
            return CheckResult(
                self.NAME,
                health=HealthStateType.DEGRADED,
                reason=f"fuse connection(s) congested: {congested}",
            )
        return CheckResult(self.NAME, reason=f"{n} fuse connections ok")


# ---------------------------------------------------------------------------
class KernelModuleComponent(PollingComponent):
    NAME = "kernel-module"
    TAGS = ["host", "kernel"]

    def __init__(self, instance: TpudInstance) -> None:
        super().__init__(instance)
        self.modules_to_check: List[str] = list(instance.kernel_modules_to_check)

    def _loaded_modules(self) -> set:
        out = set()
        try:
            with open("/proc/modules", "r", encoding="ascii") as f:
                for ln in f:
                    out.add(ln.split()[0])
        except OSError:
            pass
        return out

    def check_once(self) -> CheckResult:
        if not self.modules_to_check:
            return CheckResult(self.NAME, reason="no modules configured to check")
        loaded = self._loaded_modules()
        missing = [m for m in self.modules_to_check if m not in loaded]
        if missing:
            return CheckResult(
                self.NAME,
                health=HealthStateType.UNHEALTHY,
                reason=f"kernel module(s) not loaded: {missing}",
            )
        return CheckResult(
            self.NAME, reason=f"all {len(self.modules_to_check)} modules loaded"
        )


# ---------------------------------------------------------------------------
class LibraryComponent(PollingComponent):
    NAME = "library"
    TAGS = ["host", "library"]

    DEFAULT_SEARCH_DIRS = ["/usr/lib", "/usr/lib64", "/usr/local/lib", "/lib"]
    # libtpu replaces libnvidia-ml (reference: components/library/component.go:30-35)
    DEFAULT_LIBRARIES = ["libtpu.so"]

    def __init__(self, instance: TpudInstance) -> None:
        super().__init__(instance)
        self.search_dirs = list(self.DEFAULT_SEARCH_DIRS)
        self.libraries = list(self.DEFAULT_LIBRARIES)
        self.tpu = instance.tpu_instance

    def is_supported(self) -> bool:
        # only meaningful on real TPU machines (reference: per GPU machine);
        # the mock backend has no on-disk libtpu to find
        return (
            self.tpu is not None
            and self.tpu.tpu_lib_exists()
            and not self.tpu.is_mock()
        )

    def _find(self, name: str) -> Optional[str]:
        # iglob short-circuits on the first hit — a full recursive glob of
        # /usr/lib trees would materialize 100k+ entries per poll
        for d in self.search_dirs:
            for hit in glob.iglob(os.path.join(d, "**", name + "*"), recursive=True):
                return hit
        return None

    def check_once(self) -> CheckResult:
        missing = [lib for lib in self.libraries if self._find(lib) is None]
        if missing:
            return CheckResult(
                self.NAME,
                health=HealthStateType.DEGRADED,
                reason=f"expected librar{'y' if len(missing) == 1 else 'ies'} not found: {missing}",
            )
        return CheckResult(self.NAME, reason=f"all {len(self.libraries)} libraries present")


# ---------------------------------------------------------------------------
# base units on the wire (metrics_lint enforces this): seconds, not ms
_g_latency = gauge("tpud_network_latency_seconds", "RTT to edge targets")


class NetworkLatencyComponent(PollingComponent):
    NAME = "network-latency"
    TAGS = ["host", "network"]

    DEGRADED_MS = 250.0

    def __init__(self, instance: TpudInstance) -> None:
        super().__init__(instance)
        from gpud_tpu import netutil

        self.edges = list(netutil.DEFAULT_EDGES)
        self.measure_fn = lambda: netutil.measure_edges(self.edges)

    def check_once(self) -> CheckResult:
        rtts = {}
        for name, rtt in self.measure_fn().items():
            if rtt is not None:
                rtts[name] = rtt
                _g_latency.set(rtt / 1000.0, {"component": self.NAME, "target": name})
        if not rtts:
            return CheckResult(
                self.NAME,
                health=HealthStateType.DEGRADED,
                reason="no edge target reachable (egress blocked or offline)",
            )
        worst = max(rtts.values())
        extra = {k: f"{v:.1f}" for k, v in rtts.items()}
        if worst >= self.DEGRADED_MS:
            return CheckResult(
                self.NAME,
                health=HealthStateType.DEGRADED,
                reason=f"high network latency: {worst:.0f}ms",
                extra_info=extra,
            )
        return CheckResult(
            self.NAME, reason=f"worst RTT {worst:.1f}ms across {len(rtts)} targets",
            extra_info=extra,
        )


# ---------------------------------------------------------------------------
class DockerComponent(PollingComponent):
    NAME = "docker"
    TAGS = ["host", "container"]

    SOCKET = "/var/run/docker.sock"

    def is_supported(self) -> bool:
        return os.path.exists(self.SOCKET) or run_command(
            ["which", "docker"], timeout=5
        ).exit_code == 0

    def check_once(self) -> CheckResult:
        r = run_command(["docker", "ps", "--format", "{{.Names}}"], timeout=20)
        if r.exit_code != 0:
            return CheckResult(
                self.NAME,
                health=HealthStateType.UNHEALTHY,
                reason=f"docker daemon not responding: {(r.error or r.output)[:200]}",
            )
        names = [ln for ln in r.output.strip().splitlines() if ln]
        return CheckResult(self.NAME, reason=f"{len(names)} containers running")


# ---------------------------------------------------------------------------
class ContainerdComponent(PollingComponent):
    NAME = "containerd"
    TAGS = ["host", "container"]

    SOCKET = "/run/containerd/containerd.sock"
    # consecutive-miss threshold before unhealthy
    # (reference: components/registry.go:99-103)
    SOCKET_MISS_THRESHOLD = 3

    def __init__(self, instance: TpudInstance) -> None:
        super().__init__(instance)
        self._consecutive_misses = 0
        self._cri_misses = 0
        self._cri_client = None  # persistent: keeps channel + learned API version
        self.socket_path = self.SOCKET
        self.cri_target = ""  # tests point this at a fake CRI server

    def is_supported(self) -> bool:
        return os.path.exists(self.socket_path) or run_command(
            ["which", "containerd"], timeout=5
        ).exit_code == 0

    def check_once(self) -> CheckResult:
        if os.path.exists(self.socket_path):
            self._consecutive_misses = 0
            return self._check_cri()
        # socket gone: CRI strikes are no longer consecutive — a restarted
        # containerd gets a fresh damping window
        self._cri_misses = 0
        self._drop_cri_client()
        self._consecutive_misses += 1
        if self._consecutive_misses < self.SOCKET_MISS_THRESHOLD:
            return CheckResult(
                self.NAME,
                reason=(
                    f"containerd socket missing "
                    f"({self._consecutive_misses}/{self.SOCKET_MISS_THRESHOLD} strikes)"
                ),
            )
        return CheckResult(
            self.NAME,
            health=HealthStateType.UNHEALTHY,
            reason=f"containerd socket missing {self._consecutive_misses} consecutive checks",
        )

    def _drop_cri_client(self) -> None:
        if self._cri_client is not None:
            try:
                self._cri_client.close()
            except Exception:  # noqa: BLE001
                pass
            self._cri_client = None

    def close(self) -> None:
        self._drop_cri_client()
        super().close()

    def _check_cri(self) -> CheckResult:
        """Socket exists: list pods/containers over CRI gRPC (reference:
        components/containerd CRI ListContainers via k8s.io/cri-api).
        An unresponsive runtime behind a live socket is Degraded — the
        socket file alone proves nothing about the daemon — but only after
        consecutive failures (same damping as the socket-missing path: a
        single slow ListContainers during image GC must not page)."""
        from gpud_tpu import cri

        if not cri.grpc_available():
            # grpcio is an optional extra; without it this check keeps the
            # pre-CRI socket-presence semantics rather than false-alarming
            return CheckResult(
                self.NAME,
                reason="containerd socket present (CRI client unavailable: no grpcio)",
            )
        if self._cri_client is None:
            self._cri_client = cri.CRIClient(
                self.socket_path, target=self.cri_target
            )
        try:
            result = self._cri_client.snapshot()
        except cri.CRIUnservedError:
            # CRI plugin disabled (containerd as Docker's backend etc.) —
            # a configuration, not a failure; keep socket-presence health
            self._cri_misses = 0
            return CheckResult(
                self.NAME,
                reason="containerd socket present (CRI not served)",
            )
        except Exception:  # noqa: BLE001 — any transport failure is a miss
            result = None
            self._drop_cri_client()  # channel may be poisoned
        if result is None:
            self._cri_misses += 1
            if self._cri_misses < self.SOCKET_MISS_THRESHOLD:
                return CheckResult(
                    self.NAME,
                    reason=(
                        f"containerd socket present but CRI unresponsive "
                        f"({self._cri_misses}/{self.SOCKET_MISS_THRESHOLD} strikes)"
                    ),
                )
            return CheckResult(
                self.NAME,
                health=HealthStateType.DEGRADED,
                reason=(
                    f"containerd socket present but CRI unresponsive "
                    f"{self._cri_misses} consecutive checks"
                ),
            )
        self._cri_misses = 0
        containers = result["containers"]
        running = sum(1 for c in containers if c["state"] == "running")
        ver = result["version"].get("runtime_version", "")
        return CheckResult(
            self.NAME,
            reason=(
                f"containerd {ver or 'up'}: {running}/{len(containers)} "
                f"containers running, {len(result['sandboxes'])} pods"
            ),
            extra_info={
                "containers_total": str(len(containers)),
                "containers_running": str(running),
                "pods": str(len(result["sandboxes"])),
                "runtime_version": ver,
            },
        )


# ---------------------------------------------------------------------------
class KubeletComponent(PollingComponent):
    NAME = "kubelet"
    TAGS = ["host", "kubernetes"]

    READONLY_PORT = 10255  # reference: components/kubelet/component.go:37-57

    def is_supported(self) -> bool:
        # healthy-if-absent semantics: only check when the port is open
        try:
            with socket.create_connection(("127.0.0.1", self.READONLY_PORT), timeout=1):
                return True
        except OSError:
            return False

    def check_once(self) -> CheckResult:
        try:
            import urllib.request

            with urllib.request.urlopen(
                f"http://127.0.0.1:{self.READONLY_PORT}/pods", timeout=5
            ) as resp:
                pods = json.loads(resp.read()).get("items", [])
            node = ""
            if pods:
                node = pods[0].get("spec", {}).get("nodeName", "")
            return CheckResult(
                self.NAME,
                reason=f"kubelet ok, {len(pods)} pods",
                extra_info={"node_name": node, "pods": str(len(pods))},
            )
        except Exception as e:  # noqa: BLE001
            return CheckResult(
                self.NAME,
                health=HealthStateType.UNHEALTHY,
                reason=f"kubelet read-only API failed: {e}",
            )


# ---------------------------------------------------------------------------
class PCIComponent(PollingComponent):
    NAME = "pci"
    TAGS = ["host", "pci"]

    def check_once(self) -> CheckResult:
        from gpud_tpu import host as pkghost

        virt = pkghost.virtualization()
        if virt not in ("none", "", "unknown"):
            # ACS only matters on baremetal (reference:
            # components/pci/component.go:156-161 skips KVM)
            return CheckResult(
                self.NAME, reason=f"virtualized ({virt}); ACS check skipped"
            )
        r = run_command(["lspci", "-vvv"], timeout=30)
        if r.exit_code != 0:
            return CheckResult(self.NAME, reason="lspci unavailable; skipped")
        acs_enabled = "ACSCtl:" in r.output and "SrcValid+" in r.output
        if acs_enabled:
            return CheckResult(
                self.NAME,
                health=HealthStateType.DEGRADED,
                reason="PCI ACS enabled on baremetal — disable for P2P performance",
            )
        return CheckResult(self.NAME, reason="ACS disabled or not applicable")


# ---------------------------------------------------------------------------
class NFSComponent(PollingComponent):
    NAME = "nfs"
    TAGS = ["host", "nfs"]

    def __init__(self, instance: TpudInstance) -> None:
        super().__init__(instance)
        self.group_configs: List[GroupConfig] = []
        cfg = instance.config
        for d in getattr(cfg, "nfs_group_dirs", []) if cfg else []:
            self.group_configs.append(GroupConfig(dir=d))
        self.machine_id = instance.machine_id or "unknown"

    def is_supported(self) -> bool:
        return bool(self.group_configs)

    def check_once(self) -> CheckResult:
        checker = NFSChecker(self.machine_id, self.group_configs)
        reports = checker.check_all()
        problems = []
        extra = {}
        for d, rep in reports.items():
            extra[f"{d}:members_fresh"] = str(rep.fresh_members)
            if not rep.write_ok:
                problems.append(f"{d}: write failed ({rep.write_error})")
            cfg = next(c for c in self.group_configs if c.dir == d)
            if cfg.expected_members and rep.fresh_members < cfg.expected_members:
                problems.append(
                    f"{d}: {rep.fresh_members}/{cfg.expected_members} members fresh"
                )
        if problems:
            return CheckResult(
                self.NAME,
                health=HealthStateType.UNHEALTHY,
                reason="; ".join(problems),
                extra_info=extra,
            )
        return CheckResult(
            self.NAME,
            reason=f"{len(reports)} NFS group(s) healthy",
            extra_info=extra,
        )
