"""Memory component (reference: components/memory — gopsutil VM stats, OOM
kmsg matcher ported from cadvisor at kmsg_matcher.go:16-50, SetHealthy
support)."""

from __future__ import annotations

import re
from typing import Optional

import psutil

from gpud_tpu.api.v1.types import Event, EventType, HealthStateType
from gpud_tpu.components.base import CheckResult, PollingComponent, TpudInstance
from gpud_tpu.metrics.registry import gauge

NAME = "memory"

# OOM-killer patterns (reference: components/memory/kmsg_matcher.go:16-50)
OOM_RE = re.compile(
    r"(invoked oom-killer|Out of memory: Kill(?:ed)? process|Memory cgroup out of memory|oom_reaper: reaped process)",
    re.IGNORECASE,
)

_g_total = gauge("tpud_memory_total_bytes", "total physical memory")
_g_used = gauge("tpud_memory_used_bytes", "used physical memory")
_g_avail = gauge("tpud_memory_available_bytes", "available physical memory")
_g_used_pct = gauge("tpud_memory_used_percent", "used memory percent")

LABELS = {"component": NAME}


def match_oom(line: str) -> Optional[tuple]:
    if OOM_RE.search(line):
        return ("oom_kill", EventType.WARNING, line.strip())
    return None


class MemoryComponent(PollingComponent):
    NAME = NAME
    TAGS = ["host", "memory"]

    def __init__(self, instance: TpudInstance) -> None:
        super().__init__(instance)
        self.get_vm_fn = psutil.virtual_memory
        self._event_bucket = (
            instance.event_store.bucket(NAME) if instance.event_store else None
        )

    def check_once(self) -> CheckResult:
        vm = self.get_vm_fn()
        _g_total.set(vm.total, LABELS)
        _g_used.set(vm.used, LABELS)
        _g_avail.set(vm.available, LABELS)
        _g_used_pct.set(vm.percent, LABELS)

        health = HealthStateType.HEALTHY
        reason = f"used {vm.percent:.1f}% of {vm.total // (1 << 30)} GiB"
        if vm.percent >= 95.0:
            health = HealthStateType.DEGRADED
            reason = f"memory pressure: {vm.percent:.1f}% used"
        return CheckResult(
            self.NAME,
            health=health,
            reason=reason,
            extra_info={
                "total_bytes": str(vm.total),
                "used_bytes": str(vm.used),
                "available_bytes": str(vm.available),
                "used_percent": f"{vm.percent:.1f}",
            },
        )

    def events(self, since: float):
        if self._event_bucket is None:
            return []
        return self._event_bucket.get(since)

    def set_healthy(self) -> None:
        """Reference: components/memory/set_healthy.go — drop recorded OOM
        events so state re-evaluates clean."""
        if self._event_bucket is not None:
            self._event_bucket.insert(
                Event(component=NAME, name="SetHealthy", type=EventType.INFO,
                      message="operator set-healthy")
            )
