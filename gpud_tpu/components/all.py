"""Ordered registration list of all built-in components
(reference: components/all/all.go:56-90)."""

from __future__ import annotations

from typing import List

from gpud_tpu.components.base import InitFunc
from gpud_tpu.components.cpu import CPUComponent
from gpud_tpu.components.disk import DiskComponent
from gpud_tpu.components.host_extra import (
    ContainerdComponent,
    DockerComponent,
    FuseComponent,
    KernelModuleComponent,
    KubeletComponent,
    LibraryComponent,
    NetworkLatencyComponent,
    NFSComponent,
    PCIComponent,
)
from gpud_tpu.components.memory import MemoryComponent
from gpud_tpu.components.os_comp import OSComponent
from gpud_tpu.components.tpu.anomaly import TPUAnomalyComponent
from gpud_tpu.components.tpu.chip_counts import TPUChipCountsComponent
from gpud_tpu.components.tpu.error_kmsg import TPUErrorKmsgComponent
from gpud_tpu.components.tpu.hbm import TPUHbmComponent
from gpud_tpu.components.tpu.ici import TPUICIComponent
from gpud_tpu.components.tpu.power import TPUPowerComponent
from gpud_tpu.components.tpu.runtime import (
    TPUProcessesComponent,
    TPURuntimeComponent,
)
from gpud_tpu.components.tpu.temperature import TPUTemperatureComponent


def all_components() -> List[InitFunc]:
    """Registration order mirrors dependency order: host basics first,
    then accelerator components."""
    return [
        OSComponent,
        CPUComponent,
        MemoryComponent,
        DiskComponent,
        FuseComponent,
        KernelModuleComponent,
        LibraryComponent,
        NetworkLatencyComponent,
        NFSComponent,
        PCIComponent,
        ContainerdComponent,
        DockerComponent,
        KubeletComponent,
        TPUChipCountsComponent,
        TPUTemperatureComponent,
        TPUHbmComponent,
        TPUPowerComponent,
        TPUICIComponent,
        TPURuntimeComponent,
        TPUProcessesComponent,
        TPUErrorKmsgComponent,
        TPUAnomalyComponent,
    ]
