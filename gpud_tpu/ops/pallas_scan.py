"""Pallas TPU kernel for the packed ICI link window scan.

The jnp implementation in ``window_scan.py`` handles ragged validity with
gap-spanning forward fills (several associative scans → multiple fused HBM
passes). When histories are *packed* — each link's samples left-aligned and
contiguous, validity only as suffix padding, which is exactly what
``fleet_scan.load_fleet_history`` produces from the SQLite stores — the
transitions are plain adjacent compares and the whole scan collapses into
one VPU pass per tile.
This kernel does that single pass: one [8, T] tile of links per grid step
resident in VMEM, all reductions lane-wise on the VPU, one [8, 128] result
tile out (columns 0..4 carry the per-link scalars).

Layout notes (pallas_guide.md):
- float32 tiles (8, 128): links ride the sublane axis, time rides lanes.
- T is padded to a lane multiple; L to a sublane multiple.
- No MXU work here — this is a bandwidth-bound scan; the win is doing it
  in one pass instead of the multi-scan jnp graph.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

LINK_BLOCK = 8
LANE = 128

# result columns
COL_DROPS = 0
COL_FLAPS = 1
COL_DOWN = 2
COL_VALID = 3
COL_DELTA = 4


class PackedScan(NamedTuple):
    drops: jax.Array
    flaps: jax.Array
    currently_down: jax.Array
    samples: jax.Array
    counter_delta: jax.Array


def _scan_kernel(states_ref, counters_ref, valid_ref, out_ref):
    s = states_ref[:]          # [8, T] float32 (1=up / 0=down)
    c = counters_ref[:]        # [8, T] float32
    v = valid_ref[:]           # [8, T] float32 (prefix mask)

    prev_s = s[:, :-1]
    next_s = s[:, 1:]
    v_pair = v[:, 1:] * v[:, :-1]

    drops = jnp.sum((prev_s > 0.5) * (next_s < 0.5) * v_pair, axis=1)
    flaps = jnp.sum((prev_s < 0.5) * (next_s > 0.5) * v_pair, axis=1)

    n_valid = jnp.sum(v, axis=1)
    # last valid sample via one-hot on the prefix-mask boundary
    # (tpu.iota only produces integer vectors — compare in int32)
    t_idx = jax.lax.broadcasted_iota(jnp.int32, s.shape, dimension=1)
    last_one_hot = (
        t_idx == (n_valid[:, None].astype(jnp.int32) - 1)
    ).astype(jnp.float32) * v
    last_state = jnp.sum(s * last_one_hot, axis=1)
    currently_down = (n_valid > 0.5) * (last_state < 0.5)

    diffs = c[:, 1:] - c[:, :-1]
    delta = jnp.sum(jnp.maximum(diffs, 0.0) * v_pair, axis=1)

    # scatter (.at[].set) has no Mosaic lowering — build the result tile
    # with lane-index masks and selects (pure VPU ops)
    col = jax.lax.broadcasted_iota(jnp.int32, (s.shape[0], LANE), dimension=1)
    out = jnp.zeros((s.shape[0], LANE), dtype=jnp.float32)
    for idx, vals in (
        (COL_DROPS, drops),
        (COL_FLAPS, flaps),
        (COL_DOWN, currently_down.astype(jnp.float32)),
        (COL_VALID, n_valid),
        (COL_DELTA, delta),
    ):
        out = jnp.where(col == idx, vals[:, None], out)
    out_ref[:] = out


def _pad_to(x, mult, axis):
    pad = (-x.shape[axis]) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(jax.jit, static_argnames=("interpret",))
def scan_links_packed(
    states: jax.Array,
    counters: jax.Array,
    valid: jax.Array,
    interpret: bool = False,
) -> PackedScan:
    """Packed-history scan. Inputs [L, T]; ``valid`` must be a prefix mask
    per link (contiguous samples, suffix padding) — the packing contract.
    """
    from jax.experimental import pallas as pl

    L = states.shape[0]
    s = _pad_to(_pad_to(states.astype(jnp.float32), LANE, 1), LINK_BLOCK, 0)
    c = _pad_to(_pad_to(counters.astype(jnp.float32), LANE, 1), LINK_BLOCK, 0)
    v = _pad_to(_pad_to(valid.astype(jnp.float32), LANE, 1), LINK_BLOCK, 0)
    Lp, Tp = s.shape

    grid = (Lp // LINK_BLOCK,)
    block_in = pl.BlockSpec((LINK_BLOCK, Tp), lambda i: (i, 0))
    block_out = pl.BlockSpec((LINK_BLOCK, LANE), lambda i: (i, 0))

    out = pl.pallas_call(
        _scan_kernel,
        out_shape=jax.ShapeDtypeStruct((Lp, LANE), jnp.float32),
        grid=grid,
        in_specs=[block_in, block_in, block_in],
        out_specs=block_out,
        interpret=interpret,
    )(s, c, v)

    out = out[:L]
    return PackedScan(
        drops=out[:, COL_DROPS].astype(jnp.int32),
        flaps=out[:, COL_FLAPS].astype(jnp.int32),
        currently_down=out[:, COL_DOWN] > 0.5,
        samples=out[:, COL_VALID].astype(jnp.int32),
        counter_delta=out[:, COL_DELTA].astype(jnp.int64)
        if jax.config.jax_enable_x64
        else out[:, COL_DELTA].astype(jnp.int32),
    )
