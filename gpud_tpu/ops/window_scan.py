"""Vectorized ICI window-scan ops (JAX).

The SQLite scan in ``ici_store`` is the correctness path for one host
(tens of links). At fleet/pod scale the same scan runs over every link of a
slice — v5p-256 ⇒ 128 chips × 6 links × 1440 samples/day — and the
control-plane side wants it batched. These ops express the scan as pure
array programs so XLA fuses the whole pass into a handful of kernels and it
can be sharded over a device mesh (see gpud_tpu/parallel/fleet.py).

Layout: ``states``  [L, T] int8/bool (1=up), ``counters`` [L, T] int32,
time-major along the last axis (contiguous per link → coalesced loads and
lane-wise reductions on the VPU; keeping L as the sublane axis lets XLA
tile [8,128] natively).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp


class WindowScan(NamedTuple):
    """Per-link scan results over the window (all [L])."""

    drops: jax.Array          # up→down transitions
    flaps: jax.Array          # down→up recoveries
    currently_down: jax.Array # last sample is down
    down_time_frac: jax.Array # fraction of window down
    counter_delta: jax.Array  # sum of positive counter steps (reset-safe)


@jax.jit
def scan_links(states: jax.Array, counters: jax.Array, valid: jax.Array) -> WindowScan:
    """Scan every link's window at once.

    Args:
      states:   [L, T] 1=up / 0=down.
      counters: [L, T] monotonic error counters (may reset to 0).
      valid:    [L, T] bool — sample present (ragged windows are padded).
    """
    states = states.astype(jnp.int8)
    valid = valid.astype(jnp.bool_)

    # Forward-fill: carry the last valid state across gaps so a transition
    # spanning a missed sample still counts — matching ICIStore.scan, which
    # compares consecutive *snapshots* regardless of time gaps.
    def ff_combine(a, b):
        a_has, a_val = a
        b_has, b_val = b
        return a_has | b_has, jnp.where(b_has, b_val, a_val)

    has_ff, state_ff = jax.lax.associative_scan(
        ff_combine, (valid, states), axis=1
    )
    prev = state_ff[:, :-1]
    prev_has = has_ff[:, :-1]
    nxt = states[:, 1:]
    # a transition is counted at each valid sample that differs from the
    # last valid state seen before it
    v_pair = valid[:, 1:] & prev_has
    drops = jnp.sum(((prev == 1) & (nxt == 0) & v_pair), axis=1)
    flaps = jnp.sum(((prev == 0) & (nxt == 1) & v_pair), axis=1)

    # last valid sample per link, without gather loops: index of the last
    # True in `valid` via argmax over reversed cumulative mask
    last_idx = states.shape[1] - 1 - jnp.argmax(valid[:, ::-1], axis=1)
    has_any = jnp.any(valid, axis=1)
    last_state = jnp.take_along_axis(states, last_idx[:, None], axis=1)[:, 0]
    currently_down = has_any & (last_state == 0)

    down_time = jnp.sum((states == 0) & valid, axis=1)
    n_valid = jnp.maximum(1, jnp.sum(valid, axis=1))
    down_time_frac = down_time / n_valid

    _, counter_ff = jax.lax.associative_scan(
        ff_combine, (valid, counters), axis=1
    )
    diffs = counters[:, 1:] - counter_ff[:, :-1]
    counter_delta = jnp.sum(jnp.where(v_pair, jnp.maximum(diffs, 0), 0), axis=1)

    return WindowScan(
        drops=drops,
        flaps=flaps,
        currently_down=currently_down,
        down_time_frac=down_time_frac,
        counter_delta=counter_delta,
    )


@functools.partial(jax.jit, static_argnames=("flap_threshold", "crc_threshold"))
def classify_links(
    scan: WindowScan,
    flap_threshold: int = 3,
    crc_threshold: int = 100,
) -> jax.Array:
    """Health class per link: 0=healthy, 1=degraded (flap/CRC), 2=unhealthy
    (down or heavy flapping) — mirrors the ici component's rules so fleet
    sweeps agree with per-host checks."""
    heavy = (scan.drops >= flap_threshold) | (scan.flaps >= flap_threshold)
    unhealthy = scan.currently_down | heavy
    degraded = (
        (scan.drops > 0)
        | (scan.flaps > 0)
        | (scan.counter_delta >= crc_threshold)
    )
    return jnp.where(unhealthy, 2, jnp.where(degraded, 1, 0)).astype(jnp.int32)


