"""Version info (reference: version/version.go — ldflags-injected there;
here a plain module constant, overridable via env for self-update tests)."""

import os

__version__ = os.environ.get("TPUD_VERSION_OVERRIDE", "0.1.0")
