"""Release distribution signing.

Reference: pkg/release/distsign (603 LoC) — ed25519 root/signing key
generation, signing-key endorsement by root keys, and package
signing/verification, used by the `gpud release` subcommands
(cmd/gpud/command/command.go:446-570). Same chain here:

  root key  ──signs──▶  signing key  ──signs──▶  package tarball

so root keys stay offline while signing keys rotate.
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional, Tuple

# gated: the daemon imports this module transitively (update watcher →
# installer), and a host without the cryptography package must still run —
# only the signing entry points themselves hard-require it
try:
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
except ImportError:  # pragma: no cover - env-dependent
    serialization = None
    Ed25519PrivateKey = None
    Ed25519PublicKey = None

CHUNK = 1 << 20


def _require_crypto() -> None:
    if serialization is None:
        raise RuntimeError(
            "the 'cryptography' package is required for release signing"
        )


# -- key generation ----------------------------------------------------------

def generate_keypair() -> Tuple[bytes, bytes]:
    """Returns (private_pem, public_pem)."""
    _require_crypto()
    priv = Ed25519PrivateKey.generate()
    priv_pem = priv.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )
    pub_pem = priv.public_key().public_bytes(
        serialization.Encoding.PEM,
        serialization.PublicFormat.SubjectPublicKeyInfo,
    )
    return priv_pem, pub_pem


def write_keypair(dir_path: str, name: str) -> Tuple[str, str]:
    os.makedirs(dir_path, exist_ok=True)
    priv_pem, pub_pem = generate_keypair()
    priv_path = os.path.join(dir_path, f"{name}.key")
    pub_path = os.path.join(dir_path, f"{name}.pub")
    with open(priv_path, "wb") as f:
        f.write(priv_pem)
    os.chmod(priv_path, 0o600)
    with open(pub_path, "wb") as f:
        f.write(pub_pem)
    return priv_path, pub_path


def _load_private(path: str) -> Ed25519PrivateKey:
    _require_crypto()
    with open(path, "rb") as f:
        key = serialization.load_pem_private_key(f.read(), password=None)
    if not isinstance(key, Ed25519PrivateKey):
        raise ValueError("not an ed25519 private key")
    return key


def _load_public(path: str) -> Ed25519PublicKey:
    _require_crypto()
    with open(path, "rb") as f:
        key = serialization.load_pem_public_key(f.read())
    if not isinstance(key, Ed25519PublicKey):
        raise ValueError("not an ed25519 public key")
    return key


# -- signing -------------------------------------------------------------------

def _file_digest(path: str) -> bytes:
    h = hashlib.sha512()
    with open(path, "rb") as f:
        while True:
            b = f.read(CHUNK)
            if not b:
                break
            h.update(b)
    return h.digest()


def sign_key(root_key_path: str, signing_pub_path: str, out_path: str = "") -> str:
    """Root key endorses a signing public key (reference: sign-key)."""
    root = _load_private(root_key_path)
    with open(signing_pub_path, "rb") as f:
        payload = f.read()
    sig = root.sign(payload)
    out = out_path or signing_pub_path + ".rootsig"
    with open(out, "wb") as f:
        f.write(sig)
    return out


def verify_key(root_pub_path: str, signing_pub_path: str, sig_path: str) -> bool:
    root_pub = _load_public(root_pub_path)
    with open(signing_pub_path, "rb") as f:
        payload = f.read()
    with open(sig_path, "rb") as f:
        sig = f.read()
    try:
        root_pub.verify(sig, payload)
        return True
    except Exception:  # noqa: BLE001
        return False


def sign_package(signing_key_path: str, package_path: str, out_path: str = "") -> str:
    """Sign a package tarball's sha512 (reference: sign-package)."""
    key = _load_private(signing_key_path)
    sig = key.sign(_file_digest(package_path))
    out = out_path or package_path + ".sig"
    with open(out, "wb") as f:
        f.write(sig)
    return out


def verify_package(
    signing_pub_path: str,
    package_path: str,
    sig_path: str = "",
    root_pub_path: str = "",
    key_sig_path: str = "",
) -> Optional[str]:
    """Verify a package; optionally also verify the signing key's root
    endorsement. Returns error string or None."""
    if root_pub_path:
        if not key_sig_path:
            return "key_sig_path required when verifying the key chain"
        if not verify_key(root_pub_path, signing_pub_path, key_sig_path):
            return "signing key is not endorsed by the root key"
    pub = _load_public(signing_pub_path)
    sig_file = sig_path or package_path + ".sig"
    try:
        with open(sig_file, "rb") as f:
            sig = f.read()
    except OSError as e:
        return f"cannot read signature: {e}"
    try:
        pub.verify(sig, _file_digest(package_path))
        return None
    except Exception:  # noqa: BLE001
        return "signature verification failed"
