"""Health-transition ledger: persistent per-component state timeline.

``/v1/states`` is a point-in-time snapshot — a component that was Unhealthy
for 40 minutes overnight and recovered looks identical to one that never
failed. This module records every health-state *transition* (component,
from, to, reason, unix ts) observed in ``Component.check()`` into SQLite,
surviving daemon restarts, and derives the operator-facing accounting on
top: current-state enum gauge, transition counters, cumulative
seconds-in-state, rolling-window availability, MTTR/MTBF, and flap
detection (the early-warning signal transition patterns carry per arxiv
2509.19575 / 2510.16946).

Two tables, bucket/retention modeled on ``gpud_tpu/eventstore.py``:

- ``tpud_health_transitions_v0_1`` — append-only transition rows, purged
  past retention by a shared ``RetentionPurger``;
- ``tpud_health_last_state_v0_1`` — one row per component: current state,
  episode start, first-seen, last observation. On startup the first fresh
  check reconciles against this row, so a restart into the same state
  continues the episode instead of minting a phantom transition.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional

from gpud_tpu.api.v1.types import Event, EventType, HealthStateType
from gpud_tpu.log import get_logger
from gpud_tpu.metrics.registry import counter, gauge
from gpud_tpu.retention import RetentionPurger
from gpud_tpu.sqlite import DB

logger = get_logger(__name__)

TABLE = "tpud_health_transitions_v0_1"
LAST_TABLE = "tpud_health_last_state_v0_1"

DEFAULT_RETENTION = 14 * 86400  # matches the eventstore window
DEFAULT_FLAP_THRESHOLD = 5      # >= N transitions within the window => flapping
DEFAULT_FLAP_WINDOW = 600.0
DEFAULT_FLAP_EVENT_COOLDOWN = 600.0  # one Warning per component per cooldown
DEFAULT_AVAILABILITY_WINDOW = 3600.0
DEFAULT_CORRELATION_WINDOW = 60.0    # +/- event correlation for timelines

# enum gauge encoding (documented in docs/observability.md; alert on >= 2)
STATE_CODES = {
    HealthStateType.INITIALIZING: 0,
    HealthStateType.HEALTHY: 1,
    HealthStateType.DEGRADED: 2,
    HealthStateType.UNHEALTHY: 3,
}

_g_state = gauge(
    "tpud_component_health_state",
    "current health state as an enum gauge "
    "(0=Initializing 1=Healthy 2=Degraded 3=Unhealthy), by component",
)
_c_transitions = counter(
    "tpud_component_health_transitions_total",
    "health-state transitions by component and from/to state",
)
_c_state_seconds = counter(
    "tpud_component_state_seconds_total",
    "cumulative observed seconds spent in each health state, by component",
)
_g_availability = gauge(
    "tpud_component_availability_ratio",
    "fraction of the rolling availability window spent Healthy, by component",
)
_g_mttr = gauge(
    "tpud_component_mttr_seconds",
    "mean seconds from entering Unhealthy to leaving it, by component",
)
_g_mtbf = gauge(
    "tpud_component_mtbf_seconds",
    "mean seconds between successive entries into Unhealthy, by component",
)
_g_flapping = gauge(
    "tpud_component_flapping",
    "1 while the component is flap-detected "
    "(>= threshold transitions inside the flap window), else 0",
)
_c_purged = counter(
    "tpud_health_transitions_purged_total",
    "transition rows deleted by the retention purger, by component",
)

# write-behind contract (tools/storage_lint.py): these methods must route
# through the BatchWriter, never commit per-row via db.execute directly
HOT_WRITE_METHODS = ("_record_transition", "_persist_last")


class HealthLedger:
    """One ledger per daemon, shared by every component's check wrapper.

    ``observe()`` is the single write path; everything else is read-only
    derivation, so the CLI can open a second ledger over the same state
    file (daemon running or not) and get identical timelines.

    With a ``writer`` (write-behind BatchWriter) the per-observe upsert of
    the last-state row coalesces by component (one committed row per
    component per flush window instead of one per check), transitions
    append into the shared buffer, and public reads run the flush barrier.
    ``observe()`` itself never takes the barrier: flap counting runs
    against an in-memory per-component transition window (seeded from the
    DB at reconcile), and the derived gauges tolerate flush-window
    staleness — otherwise every check would force a commit and defeat the
    batching.
    """

    GUARDED_BY = {
        "_last": "_mu",
        "_last_flap_event": "_mu",
        "_tx_recent": "_mu",
        "_annotations": "_mu",
    }
    # internal helpers reached only from observe()/is_flapping(), which
    # take _mu; not renamed *_locked because HOT_WRITE_METHODS (storage
    # lint) pins two of the names
    _LOCK_FREE = {
        "_reconcile_boot": "caller observe() holds _mu for the whole "
                           "first-observation reconcile",
        "_record_transition": "callers observe()/_reconcile_boot hold _mu",
        "_flap_check": "caller observe() holds _mu around the flap scan",
        "_transitions_in_window": "callers observe() (via _flap_check) and "
                                  "is_flapping() hold _mu",
    }

    def __init__(
        self,
        db: DB,
        event_store=None,
        retention_seconds: int = DEFAULT_RETENTION,
        flap_threshold: int = DEFAULT_FLAP_THRESHOLD,
        flap_window_seconds: float = DEFAULT_FLAP_WINDOW,
        flap_event_cooldown: float = DEFAULT_FLAP_EVENT_COOLDOWN,
        availability_window_seconds: float = DEFAULT_AVAILABILITY_WINDOW,
        correlation_window_seconds: float = DEFAULT_CORRELATION_WINDOW,
        writer=None,
    ) -> None:
        self.db = db
        self.writer = writer
        self.event_store = event_store
        self.retention_seconds = retention_seconds
        self.flap_threshold = flap_threshold
        self.flap_window = flap_window_seconds
        self.flap_event_cooldown = flap_event_cooldown
        self.availability_window = availability_window_seconds
        self.correlation_window = correlation_window_seconds
        self._mu = threading.Lock()
        # optional post-transition observer (the server wires the session
        # outbox here); must never fail the observe path
        self.on_transition = None
        # component -> [state, episode_since, last_seen, first_seen]
        self._last: Dict[str, list] = {}
        self._last_flap_event: Dict[str, float] = {}
        # component -> recent (ts, from, to, reason) tuples (flap-window
        # cache): lets observe() count flaps — and the predict scorer pull
        # cadence features — without a read, and therefore without a flush
        # barrier, on the hot path
        self._tx_recent: Dict[str, deque] = {}
        # component -> externally-owned annotation dict (e.g. the predict
        # engine's {"predicted": "true"}), merged into observe()'s
        # returned annotations alongside the flap marker
        self._annotations: Dict[str, Dict[str, str]] = {}
        import time as _time

        self.time_now_fn = _time.time
        db.execute(
            f"""CREATE TABLE IF NOT EXISTS {TABLE} (
                component TEXT NOT NULL,
                timestamp REAL NOT NULL,
                from_state TEXT NOT NULL,
                to_state TEXT NOT NULL,
                reason TEXT
            )"""
        )
        db.execute(
            f"CREATE INDEX IF NOT EXISTS idx_{TABLE}_comp_ts "
            f"ON {TABLE} (component, timestamp)"
        )
        # /v1/states/history with no component filter is a bare
        # ``timestamp>=? ORDER BY timestamp DESC`` — this index serves
        # both the predicate and the sort, so the endpoint stays flat as
        # the transition table grows toward its 14d retention
        db.execute(
            f"CREATE INDEX IF NOT EXISTS idx_{TABLE}_ts ON {TABLE} (timestamp)"
        )
        db.execute(
            f"""CREATE TABLE IF NOT EXISTS {LAST_TABLE} (
                component TEXT PRIMARY KEY,
                state TEXT NOT NULL,
                since REAL NOT NULL,
                first_seen REAL NOT NULL,
                updated REAL NOT NULL
            )"""
        )
        self._purger = RetentionPurger(
            "tpud-health-ledger-purger",
            retention_seconds / 5.0,
            self._purge_tick,
        )

    # -- write path --------------------------------------------------------
    def observe(
        self, component: str, health: str, reason: str = "",
        now: Optional[float] = None,
    ) -> Dict[str, str]:
        """Record one check outcome; returns state annotations (currently
        the ``flapping`` marker) for the caller to attach to the result."""
        state = health or HealthStateType.HEALTHY
        ts = self.time_now_fn() if now is None else now
        with self._mu:
            ep = self._last.get(component)
            if ep is None:
                ep = self._reconcile_boot(component, state, ts, reason)
            else:
                elapsed = ts - ep[2]
                if elapsed > 0:
                    _c_state_seconds.inc(
                        elapsed, {"component": component, "state": ep[0]}
                    )
                if ep[0] != state:
                    self._record_transition(component, ep[0], state, ts, reason)
                    ep[0] = state
                    ep[1] = ts
                ep[2] = ts
                self._persist_last(component, ep)
            _g_state.set(
                STATE_CODES.get(state, -1.0), {"component": component}
            )
            ann = self._flap_check(component, ts)
            ext = self._annotations.get(component)
            if ext:
                ann = {**ext, **ann}
            self._refresh_derived(component, ts)
        return ann

    def flush(self) -> None:
        """Read-after-write barrier (no-op without a writer)."""
        if self.writer is not None:
            self.writer.flush()

    def _reconcile_boot(
        self, component: str, state: str, ts: float, reason: str
    ) -> list:
        """First observation since process start: continue the persisted
        episode when the state matches, mint exactly one transition when it
        doesn't, and start fresh for a never-seen component."""
        self.flush()  # once per component per process — not a hot path
        row = self.db.query_one(
            f"SELECT state, since, first_seen FROM {LAST_TABLE} WHERE component=?",
            (component,),
        )
        # seed the in-memory flap window from persisted history so a
        # restart mid-flap still detects it (full tuples: the predict
        # scorer reads cadence shape, not just counts)
        self._tx_recent[component] = deque(
            (r[0], r[1], r[2], r[3] or "")
            for r in self.db.query(
                f"SELECT timestamp, from_state, to_state, reason FROM {TABLE} "
                "WHERE component=? AND timestamp>? ORDER BY timestamp ASC",
                (component, ts - self.flap_window),
            )
        )
        if row is None:
            ep = [state, ts, ts, ts]
        else:
            prev_state, prev_since, first_seen = row
            if prev_state == state:
                ep = [state, prev_since, ts, first_seen]
            else:
                self._record_transition(component, prev_state, state, ts, reason)
                ep = [state, ts, ts, first_seen]
        self._last[component] = ep
        self._persist_last(component, ep)
        return ep

    def _persist_last(self, component: str, ep: list) -> None:
        sql = (
            f"""INSERT INTO {LAST_TABLE} (component, state, since, first_seen, updated)
                VALUES (?, ?, ?, ?, ?)
                ON CONFLICT(component) DO UPDATE SET
                    state=excluded.state, since=excluded.since,
                    first_seen=excluded.first_seen, updated=excluded.updated"""
        )
        params = (component, ep[0], ep[1], ep[3], ep[2])
        if self.writer is not None:
            # coalesce by component: only the newest upsert in a flush
            # window commits — the table holds one row per component anyway
            self.writer.submit("ledger", sql, params, key=("hl", component))
        else:
            self.db.execute(sql, params)

    def _record_transition(
        self, component: str, from_state: str, to_state: str,
        ts: float, reason: str,
    ) -> None:
        sql = (
            f"INSERT INTO {TABLE} (component, timestamp, from_state, to_state, reason) "
            "VALUES (?, ?, ?, ?, ?)"
        )
        params = (component, ts, from_state, to_state, reason or "")
        if self.writer is not None:
            self.writer.submit("ledger", sql, params)
        else:
            self.db.execute(sql, params)
        recent = self._tx_recent.setdefault(component, deque())
        recent.append((ts, from_state, to_state, reason or ""))
        _c_transitions.inc(
            labels={"component": component, "from": from_state, "to": to_state}
        )
        hook = self.on_transition
        if hook is not None:
            try:
                hook(component, from_state, to_state, ts, reason or "")
            except Exception:  # noqa: BLE001
                logger.exception("health on_transition hook failed")

    def _flap_check(self, component: str, now: float) -> Dict[str, str]:
        n = self._transitions_in_window(component, now)
        flapping = n >= self.flap_threshold
        _g_flapping.set(1.0 if flapping else 0.0, {"component": component})
        if not flapping:
            return {}
        ann = {"flapping": "true", "transitions_in_window": str(n)}
        es = self.event_store
        # None (never emitted) always fires: seeding with 0.0 would
        # suppress the first warning on clocks near the epoch (tests)
        last = self._last_flap_event.get(component)
        if es is not None and (
            last is None or now - last >= self.flap_event_cooldown
        ):
            self._last_flap_event[component] = now
            try:
                es.bucket(component).insert(
                    Event(
                        component=component,
                        time=now,
                        name="health_flapping",
                        type=EventType.WARNING,
                        message=(
                            f"{n} health transitions in the last "
                            f"{self.flap_window:g}s (threshold "
                            f"{self.flap_threshold})"
                        ),
                        extra_info={
                            "transitions_in_window": str(n),
                            "flap_window_seconds": f"{self.flap_window:g}",
                            "flap_threshold": str(self.flap_threshold),
                        },
                    )
                )
            except Exception:  # noqa: BLE001 — accounting must not kill checks
                logger.exception("flap event emit failed for %s", component)
        return ann

    def _transitions_in_window(self, component: str, now: float) -> int:
        cutoff = now - self.flap_window
        recent = self._tx_recent.get(component)
        if recent is not None:
            # in-memory window (seeded at reconcile, appended on every
            # transition): the observe() hot path never reads the DB, so
            # it never needs the flush barrier
            try:
                while recent and recent[0][0] <= cutoff:
                    recent.popleft()
            except IndexError:  # concurrent prune emptied it under us
                pass
            return len(recent)
        # component never observed by this process (CLI over a shared
        # state file): fall back to the table, behind the barrier
        self.flush()
        row = self.db.query_one(
            f"SELECT COUNT(*) FROM {TABLE} WHERE component=? AND timestamp>?",
            (component, cutoff),
        )
        return int(row[0]) if row else 0

    def recent_transitions(self, component: str, limit: int = 0) -> List[Dict]:
        """Newest-first transitions from the in-memory flap-window cache.

        Bulk accessor for the predict scorer's hot tick: reads ONLY the
        per-component deque (bounded by the flap window), never the DB,
        and therefore never the BatchWriter flush barrier. Use
        :meth:`history` when the full persisted timeline matters.
        """
        with self._mu:
            recent = self._tx_recent.get(component)
            if not recent:
                return []
            rows = list(recent)
        if limit:
            rows = rows[-limit:]
        return [
            {"component": component, "time": r[0], "from": r[1],
             "to": r[2], "reason": r[3]}
            for r in reversed(rows)
        ]

    def last_state(self, component: str) -> Optional[Dict]:
        """Barrier-free current-episode view from the in-memory map:
        ``{"state", "since", "last_seen"}`` — None before the component's
        first observe() of this process."""
        with self._mu:
            ep = self._last.get(component)
            if ep is None:
                return None
            return {"state": ep[0], "since": ep[1], "last_seen": ep[2]}

    # -- external annotations (predict engine) ------------------------------
    def set_annotation(self, component: str, key: str, value: str) -> None:
        """Attach a marker that rides every subsequent observe() of the
        component (merged into the returned annotation dict, flap marker
        winning key collisions). Owned by external subsystems — the
        predict engine stamps ``predicted`` here."""
        with self._mu:
            self._annotations.setdefault(component, {})[key] = value

    def clear_annotation(self, component: str, key: str) -> None:
        with self._mu:
            ext = self._annotations.get(component)
            if ext is not None:
                ext.pop(key, None)
                if not ext:
                    self._annotations.pop(component, None)

    def _refresh_derived(self, component: str, now: float) -> None:
        # barrier=False: these run inside every observe(); forcing a
        # commit here would serialize the hot path on the writer. The
        # gauges may lag the newest (still-buffered) transition by at most
        # one flush window — acceptable for 15m-cadence derived series.
        av = self.availability(component, now=now, barrier=False)
        if av is not None:
            _g_availability.set(av["ratio"], {"component": component})
        mttr, mtbf = self.mttr_mtbf(component, barrier=False)
        if mttr is not None:
            _g_mttr.set(mttr, {"component": component})
        if mtbf is not None:
            _g_mtbf.set(mtbf, {"component": component})

    # -- read path ---------------------------------------------------------
    def history(
        self,
        component: Optional[str] = None,
        since: float = 0.0,
        limit: int = 0,
    ) -> List[Dict]:
        """Transition timeline, newest first."""
        self.flush()
        sql = (
            f"SELECT component, timestamp, from_state, to_state, reason "
            f"FROM {TABLE} WHERE timestamp>=?"
        )
        params: list = [since]
        if component:
            sql += " AND component=?"
            params.append(component)
        sql += " ORDER BY timestamp DESC"
        if limit:
            sql += " LIMIT ?"
            params.append(limit)
        return [
            {
                "component": r[0],
                "time": r[1],
                "from": r[2],
                "to": r[3],
                "reason": r[4] or "",
            }
            for r in self.db.query(sql, params)
        ]

    def annotate_with_events(
        self, transitions: List[Dict], window: Optional[float] = None
    ) -> List[Dict]:
        """Attach eventstore events within ±window of each transition — the
        'what else happened around that flip' context for timelines."""
        w = self.correlation_window if window is None else window
        es = self.event_store
        if es is not None and w >= 0 and transitions:
            # one event-store barrier for the whole timeline; the
            # per-transition gets below were each re-flushing the shared
            # writer (flow_lint flush-audit, PR 19)
            es.flush()
        for t in transitions:
            events: List[Dict] = []
            if es is not None and w >= 0:
                try:
                    events = [
                        e.to_dict()
                        for e in es.bucket(t["component"]).get(
                            t["time"] - w, barrier=False
                        )
                        if e.time <= t["time"] + w
                    ]
                except Exception:  # noqa: BLE001
                    logger.exception("event correlation failed")
            t["events"] = events
        return transitions

    def availability(
        self,
        component: str,
        window_seconds: Optional[float] = None,
        now: Optional[float] = None,
        barrier: bool = True,
    ) -> Optional[Dict]:
        """Healthy-time ratio over the rolling window, reconstructed from
        the transition timeline plus the current episode. The window is
        clamped to the component's first-seen time so a freshly-registered
        component isn't billed for time before it existed. Returns None
        for unknown components or zero observed time."""
        if barrier:
            self.flush()
        w = self.availability_window if window_seconds is None else window_seconds
        ts_now = self.time_now_fn() if now is None else now
        row = self.db.query_one(
            f"SELECT state, since, first_seen FROM {LAST_TABLE} WHERE component=?",
            (component,),
        )
        if row is None:
            return None
        cur_state, _cur_since, first_seen = row
        start = max(ts_now - w, first_seen)
        observed = ts_now - start
        if observed <= 0:
            return None
        rows = self.db.query(
            f"SELECT timestamp, from_state, to_state FROM {TABLE} "
            "WHERE component=? AND timestamp>? ORDER BY timestamp ASC",
            (component, start),
        )
        state = rows[0][1] if rows else cur_state
        healthy = 0.0
        t = start
        for ts, _from_state, to_state in rows:
            ts = min(ts, ts_now)
            if state == HealthStateType.HEALTHY:
                healthy += ts - t
            t = ts
            state = to_state
        if state == HealthStateType.HEALTHY:
            healthy += ts_now - t
        return {
            "ratio": healthy / observed,
            "healthy_seconds": healthy,
            "observed_seconds": observed,
            "window_seconds": w,
            "state": cur_state,
        }

    def mttr_mtbf(self, component: str, barrier: bool = True):
        """(MTTR, MTBF) from the persisted timeline: MTTR is the mean
        duration of completed Unhealthy episodes; MTBF the mean gap between
        successive entries into Unhealthy. Either is None without enough
        history."""
        if barrier:
            self.flush()
        rows = self.db.query(
            f"SELECT timestamp, from_state, to_state FROM {TABLE} "
            "WHERE component=? ORDER BY timestamp ASC",
            (component,),
        )
        failure_starts: List[float] = []
        repairs: List[float] = []
        fail_at: Optional[float] = None
        for ts, from_state, to_state in rows:
            if to_state == HealthStateType.UNHEALTHY and from_state != HealthStateType.UNHEALTHY:
                failure_starts.append(ts)
                fail_at = ts
            elif from_state == HealthStateType.UNHEALTHY and to_state != HealthStateType.UNHEALTHY:
                if fail_at is not None:
                    repairs.append(ts - fail_at)
                    fail_at = None
        mttr = sum(repairs) / len(repairs) if repairs else None
        mtbf = (
            (failure_starts[-1] - failure_starts[0]) / (len(failure_starts) - 1)
            if len(failure_starts) >= 2
            else None
        )
        return mttr, mtbf

    def components(self, barrier: bool = True) -> List[str]:
        if barrier:
            self.flush()
        return [
            r[0]
            for r in self.db.query(
                f"SELECT component FROM {LAST_TABLE} ORDER BY component"
            )
        ]

    def is_flapping(self, component: str, now: Optional[float] = None) -> bool:
        ts = self.time_now_fn() if now is None else now
        # under _mu: _transitions_in_window prunes the per-component deque
        # in place, so the unlocked call raced observe()'s appends (the
        # old `except IndexError` there papered over exactly this)
        with self._mu:
            return (
                self._transitions_in_window(component, ts)
                >= self.flap_threshold
            )

    def flapping_components(self, now: Optional[float] = None) -> List[str]:
        ts = self.time_now_fn() if now is None else now
        return [c for c in self.components() if self.is_flapping(c, ts)]

    def availability_all(
        self,
        window_seconds: Optional[float] = None,
        now: Optional[float] = None,
    ) -> Dict[str, Dict]:
        # one barrier for the whole sweep: the per-component availability
        # reads see everything this flush committed, so the old N+1
        # re-flushes (one inside components() plus one per availability()
        # call) were pure barrier overhead (flow_lint flush-audit, PR 19)
        self.flush()
        out = {}
        for c in self.components(barrier=False):
            av = self.availability(
                c, window_seconds=window_seconds, now=now, barrier=False
            )
            if av is not None:
                out[c] = av
        return out

    def summary(self, now: Optional[float] = None) -> Dict:
        """Rollup for /v1/info: totals + who is flapping right now."""
        self.flush()
        ts = self.time_now_fn() if now is None else now
        row = self.db.query_one(f"SELECT COUNT(*) FROM {TABLE}")
        comps = self.components(barrier=False)  # fenced by the flush above
        return {
            "transitions_total": int(row[0]) if row else 0,
            "components_tracked": len(comps),
            "flapping": [c for c in comps if self.is_flapping(c, ts)],
        }

    # -- retention ---------------------------------------------------------
    def start_purger(self, scheduler=None) -> None:
        self._purger.start(scheduler)

    def purge_once(self) -> None:
        """One retention pass now (consolidated scheduler job hook)."""
        self._purge_tick()

    def _purge_tick(self) -> None:
        self.flush()  # never let a buffered row dodge the purge cutoff
        cutoff = self.time_now_fn() - self.retention_seconds
        comps = [
            r[0]
            for r in self.db.query(
                f"SELECT DISTINCT component FROM {TABLE} WHERE timestamp<?",
                (cutoff,),
            )
        ]
        total = 0
        for comp in comps:
            n = self.db.execute(
                f"DELETE FROM {TABLE} WHERE component=? AND timestamp<?",
                (comp, cutoff),
            ).rowcount
            if n:
                _c_purged.inc(n, {"component": comp})
                total += n
        # drop last-state rows for components not observed in a whole
        # retention window (deregistered / renamed) so they age out too
        self.db.execute(f"DELETE FROM {LAST_TABLE} WHERE updated<?", (cutoff,))
        if total:
            logger.info("health ledger purged %d transitions", total)

    def close(self) -> None:
        self._purger.close()
