"""Loader for the native C++ hot-path library (native/tpud_native.cpp).

The native library is strictly a fast path: every entry point has a
pure-Python twin with identical semantics (kmsg/watcher.parse_line,
kmsg/deduper.Deduper, components/tpu/ici_store.scan), and tests assert
parity. Binding is ctypes over a C ABI (pybind11 is not in the image).

Search order: ``TPUD_NATIVE_LIB`` env → ``<repo>/native/libtpud_native.so``
→ system loader. Absence is fine.
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Tuple

from gpud_tpu.log import get_logger

logger = get_logger(__name__)

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


class _KmsgRec(ctypes.Structure):
    _fields_ = [
        ("priority", ctypes.c_int32),
        ("facility", ctypes.c_int32),
        ("sequence", ctypes.c_int64),
        ("ts_us", ctypes.c_int64),
        ("msg_offset", ctypes.c_int32),
    ]


class _LinkScan(ctypes.Structure):
    _fields_ = [
        ("drops", ctypes.c_int32),
        ("flaps", ctypes.c_int32),
        ("currently_down", ctypes.c_int32),
        ("samples", ctypes.c_int32),
        ("counter_delta", ctypes.c_int64),
    ]


def _candidates() -> List[str]:
    out = []
    env = os.environ.get("TPUD_NATIVE_LIB", "")
    if env:
        out.append(env)
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out.append(os.path.join(here, "native", "libtpud_native.so"))
    out.append(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "libtpud_native.so"))
    out.append("libtpud_native.so")
    return out


def load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    for path in _candidates():
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            continue
        try:
            lib.tpud_parse_kmsg.restype = ctypes.c_int
            lib.tpud_parse_kmsg.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(_KmsgRec)
            ]
            lib.tpud_scan_links_ragged.restype = None
            lib.tpud_scan_links_ragged.argtypes = [
                ctypes.POINTER(ctypes.c_int8),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int32,
                ctypes.POINTER(_LinkScan),
            ]
            lib.tpud_deduper_new.restype = ctypes.c_void_p
            lib.tpud_deduper_new.argtypes = [ctypes.c_double, ctypes.c_int64]
            lib.tpud_deduper_free.argtypes = [ctypes.c_void_p]
            lib.tpud_deduper_seen.restype = ctypes.c_int
            lib.tpud_deduper_seen.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_double
            ]
            lib.tpud_deduper_len.restype = ctypes.c_int64
            lib.tpud_deduper_len.argtypes = [ctypes.c_void_p]
        except AttributeError:
            continue
        # newer optional symbols: a stale .so keeps every fast path it
        # DOES have — missing ones simply stay on the Python fallback
        try:
            lib.tpud_prefilter_init.restype = ctypes.c_int
            lib.tpud_prefilter_init.argtypes = [ctypes.c_char_p]
            lib.tpud_prefilter_match.restype = ctypes.c_int
            lib.tpud_prefilter_match.argtypes = [ctypes.c_char_p]
        except AttributeError:
            logger.info("native library lacks the prefilter (older build)")
        _LIB = lib
        logger.info("native library loaded from %s", path)
        return _LIB
    return None


def available() -> bool:
    return load() is not None


# -- typed wrappers -----------------------------------------------------------

def parse_kmsg(line: str) -> Optional[Tuple[int, int, int, int, str]]:
    """Returns (priority, facility, sequence, ts_us, message) or None."""
    lib = load()
    if lib is None:
        return None
    raw = line.encode("utf-8", "replace")
    rec = _KmsgRec()
    if not lib.tpud_parse_kmsg(raw, ctypes.byref(rec)):
        return None
    return (
        rec.priority,
        rec.facility,
        rec.sequence,
        rec.ts_us,
        raw[rec.msg_offset:].decode("utf-8", "replace"),
    )


def _scan_results(out) -> List[dict]:
    return [
        {
            "drops": r.drops,
            "flaps": r.flaps,
            "currently_down": bool(r.currently_down),
            "samples": r.samples,
            "counter_delta": r.counter_delta,
        }
        for r in out
    ]


def scan_links_ragged(states: List[int], counters: List[int],
                      offsets: List[int]) -> Optional[List[dict]]:
    """Scan packed per-link sequences. Returns per-link dicts or None when
    the native library is absent."""
    lib = load()
    if lib is None:
        return None
    n_links = len(offsets) - 1
    st = (ctypes.c_int8 * len(states))(*states)
    ct = (ctypes.c_int64 * len(counters))(*counters)
    off = (ctypes.c_int32 * len(offsets))(*offsets)
    out = (_LinkScan * n_links)()
    lib.tpud_scan_links_ragged(st, ct, off, n_links, out)
    return _scan_results(out)


def scan_links_ragged2(
    states: List[int],
    counters_a: List[int],
    counters_b: List[int],
    offsets: List[int],
) -> Optional[Tuple[List[dict], List[dict]]]:
    """Two-counter variant (error + CRC deltas over the same state walk);
    packs states/offsets once instead of marshalling them per call."""
    lib = load()
    if lib is None:
        return None
    n_links = len(offsets) - 1
    st = (ctypes.c_int8 * len(states))(*states)
    off = (ctypes.c_int32 * len(offsets))(*offsets)
    out_a = (_LinkScan * n_links)()
    out_b = (_LinkScan * n_links)()
    lib.tpud_scan_links_ragged(
        st, (ctypes.c_int64 * len(counters_a))(*counters_a), off, n_links, out_a
    )
    lib.tpud_scan_links_ragged(
        st, (ctypes.c_int64 * len(counters_b))(*counters_b), off, n_links, out_b
    )
    return _scan_results(out_a), _scan_results(out_b)


class NativeDeduper:
    """ctypes wrapper over the C++ TTL cache; drop-in for kmsg.Deduper's
    seen_before contract (key = message+second bucket)."""

    def __init__(self, ttl_seconds: float, max_entries: int) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("native library not available")
        self._lib = lib
        self._h = lib.tpud_deduper_new(ttl_seconds, max_entries)

    def seen(self, key: str, now: float) -> bool:
        return bool(self._lib.tpud_deduper_seen(self._h, key.encode(), now))

    def __len__(self) -> int:
        return int(self._lib.tpud_deduper_len(self._h))

    def __del__(self) -> None:
        try:
            self._lib.tpud_deduper_free(self._h)
        except Exception:  # noqa: BLE001
            pass


# -- catalog prefilter ---------------------------------------------------------

_PREFILTER_READY = False


def prefilter_init(tokens: List[str]) -> bool:
    """Push the catalog's coarse-token set into the native scanner.
    Returns True when the native prefilter is armed."""
    global _PREFILTER_READY
    lib = load()
    if lib is None or not hasattr(lib, "tpud_prefilter_init") or not tokens:
        # an EMPTY token set must not arm the native side: zero views
        # would reject every line, the opposite of the empty-regex
        # fallback semantics
        _PREFILTER_READY = False
        return False
    n = lib.tpud_prefilter_init("\n".join(tokens).encode("utf-8"))
    _PREFILTER_READY = n == len(tokens)
    return _PREFILTER_READY


def prefilter_match(line: str) -> Optional[bool]:
    """Native coarse scan; None when unavailable (caller falls back to
    the Python regex)."""
    if not _PREFILTER_READY:
        return None
    lib = _LIB
    if lib is None:
        return None
    try:
        return bool(lib.tpud_prefilter_match(line.encode("utf-8", "replace")))
    except Exception:  # noqa: BLE001 — fall back, never drop a line
        return None
