"""MachineInfo assembly.

Reference: pkg/machine-info/machine_info.go:45-434 — builds the
apiv1.MachineInfo tree (CPU/mem/NIC/disk/accelerator) for login/gossip and
the /machine-info endpoint. TPUInfo replaces GPUInfo and reports slice
topology (SURVEY §5.8).
"""

from __future__ import annotations

import os
import socket
from typing import Optional

import psutil

from gpud_tpu import host as pkghost
from gpud_tpu.api.v1.types import (
    DiskInfo,
    MachineInfo,
    NICInfo,
    TPUChipInfo,
    TPUInfo,
)
from gpud_tpu.blockdev import detect_containerized, read_block_tree
from gpud_tpu.tpu.instance import TPUInstance
from gpud_tpu.version import __version__


def _nic_driver(name: str, sys_class_net: str = "/sys/class/net") -> tuple:
    """(driver, virtual): driver symlink basename; virtual when the NIC
    has no backing device (veth/bridge/tun)."""
    dev = os.path.join(sys_class_net, name, "device")
    if not os.path.exists(dev):
        return "", True
    try:
        return os.path.basename(os.readlink(os.path.join(dev, "driver"))), False
    except OSError:
        return "", False


def _cpu_model() -> str:
    try:
        with open("/proc/cpuinfo", "r", encoding="utf-8") as f:
            for ln in f:
                if ln.lower().startswith("model name"):
                    return ln.split(":", 1)[1].strip()
    except OSError:
        pass
    return ""


def get_tpu_info(tpu: Optional[TPUInstance]) -> Optional[TPUInfo]:
    if tpu is None or not tpu.tpu_lib_exists():
        return None
    topo = tpu.topology()
    chips = [
        TPUChipInfo(
            chip_id=c.chip_id,
            device_path=c.device_path,
            pci_address=c.pci_address,
            serial=c.serial,
            hbm_total_bytes=c.hbm_total_bytes,
            cores_per_chip=c.cores,
        )
        for c in sorted(tpu.devices().values(), key=lambda c: c.chip_id)
    ]
    return TPUInfo(
        product=tpu.product_name(),
        accelerator_type=tpu.accelerator_type(),
        topology=f"{topo.total_chips} chips / {topo.hosts} hosts" if topo else "",
        generation=tpu.generation(),
        chip_count=len(chips),
        hosts_per_slice=topo.hosts if topo else 1,
        worker_id=tpu.worker_id(),
        runtime_version=tpu.runtime_version(),
        driver_version=tpu.driver_version(),
        chips=chips,
    )


def get_machine_info(
    tpu: Optional[TPUInstance] = None,
    machine_id: str = "",
    provider: str = "",
    region: str = "",
    public_ip: str = "",
    private_ip: str = "",
) -> MachineInfo:
    vm = psutil.virtual_memory()
    disks = []
    try:
        for p in psutil.disk_partitions(all=False):
            try:
                u = psutil.disk_usage(p.mountpoint)
            except OSError:
                continue
            disks.append(
                DiskInfo(
                    device=p.device,
                    mount_point=p.mountpoint,
                    fstype=p.fstype,
                    total_bytes=u.total,
                    used_bytes=u.used,
                )
            )
    except OSError:
        pass
    nics = []
    try:
        stats = psutil.net_if_stats()
        for name, addrs in psutil.net_if_addrs().items():
            if name == "lo":
                continue
            mac = ""
            ips = []
            for a in addrs:
                if a.family == psutil.AF_LINK:
                    mac = a.address
                elif a.family in (socket.AF_INET, socket.AF_INET6):
                    ips.append(a.address)
            st = stats.get(name)
            driver, virtual = _nic_driver(name)
            nics.append(
                NICInfo(
                    name=name,
                    mac=mac,
                    addresses=ips,
                    mtu=st.mtu if st else 0,
                    speed_mbps=st.speed if st else 0,
                    driver=driver,
                    virtual=virtual,
                )
            )
    except OSError:
        pass

    return MachineInfo(
        machine_id=machine_id or pkghost.machine_id(),
        hostname=socket.gethostname(),
        os=pkghost.os_name(),
        kernel_version=pkghost.kernel_version(),
        boot_id=pkghost.boot_id(),
        uptime_seconds=int(pkghost.uptime_seconds()),
        cpu_model=_cpu_model(),
        cpu_logical_cores=psutil.cpu_count(logical=True) or 0,
        memory_total_bytes=vm.total,
        provider=provider,
        region=region,
        public_ip=public_ip,
        private_ip=private_ip,
        tpud_version=__version__,
        containerized=detect_containerized(),
        tpu_info=get_tpu_info(tpu),
        disks=disks,
        nics=nics,
        block_devices=read_block_tree(),
    )
