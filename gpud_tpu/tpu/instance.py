"""The TPU accelerator adapter — tpud's native boundary.

This is the analog of ``nvml.Instance`` (reference:
pkg/nvidia/nvml/instance.go:43-97): one interface the rest of the daemon
talks to, with interchangeable backends behind it:

- ``MockBackend`` — full all-success fixture set, enabled with
  ``TPUD_TPU_MOCK_ALL_SUCCESS`` so the entire daemon runs "with TPUs" on a
  CPU-only box (reference: GPUD_NVML_MOCK_ALL_SUCCESS,
  pkg/nvidia/nvml/lib/default.go:14-50); targeted injection envs
  ``TPUD_TPU_INJECT_*`` mirror the reference's injection envs.
- ``SysfsBackend`` — enumerates real /dev/accel* + /sys/class/accel (the
  Google TPU driver's device nodes) and vfio devices; telemetry is read
  from driver sysfs when exposed.
- ``JaxBackend`` — enumerates through a live libtpu via ``jax.devices()``
  (lazy import; opt-in with ``TPUD_TPU_USE_JAX=1`` since loading libtpu
  grabs the chips, which a monitoring daemon must not do by default while
  a training job owns them — the key TPU-vs-NVML design difference: NVML
  is a side-band API, libtpu is exclusive-open).
- ``with_failure_injector`` wraps any backend to simulate chip-lost /
  requires-reset / enumeration failure / product override (reference:
  nvml.NewWithFailureInjector, instance.go:18-38,115).
"""

from __future__ import annotations

import glob
import math
import os
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from gpud_tpu.components.base import FailureInjector
from gpud_tpu.log import get_logger
from gpud_tpu.tpu.topology import (
    GENERATIONS,
    SliceTopology,
    normalize_generation,
    parse_accelerator_type,
)

logger = get_logger(__name__)

ENV_MOCK_ALL_SUCCESS = "TPUD_TPU_MOCK_ALL_SUCCESS"
ENV_MOCK_ACCEL_TYPE = "TPUD_TPU_MOCK_ACCELERATOR_TYPE"
ENV_USE_JAX = "TPUD_TPU_USE_JAX"
ENV_INJECT_HBM_ECC_PENDING = "TPUD_TPU_INJECT_HBM_ECC_PENDING"
ENV_INJECT_THERMAL_SLOWDOWN = "TPUD_TPU_INJECT_THERMAL_SLOWDOWN"
ENV_INJECT_ICI_LINK_DOWN = "TPUD_TPU_INJECT_ICI_LINK_DOWN"
# root overrides for the real-surface readers (tpu/sysfs.py) so fixture
# trees of stock TPU VMs drive the whole daemon (reference pattern:
# --infiniband-class-root-dir flag + KMSG_FILE_PATH env)
ENV_SYSFS_ROOT = "TPUD_SYSFS_ROOT"
ENV_DEV_ROOT = "TPUD_DEV_ROOT"
# root of the ICI link sysfs layout (see SysfsBackend.ici_links): per-link
# dirs <root>/chip<N>/ici<L>/{state,tx_bytes,rx_bytes,tx_errors,rx_errors,
# crc_errors,replays}. Driver exposure varies by runtime version (SURVEY §7
# hard parts); deployments map whatever the driver provides into this
# layout (symlinks or a node agent), and fixtures drive tests.
ENV_ICI_SYSFS_ROOT = "TPUD_ICI_SYSFS_ROOT"

# Google TPU PCI vendor/device ids (accel driver)
TPU_PCI_VENDOR = "0x1ae0"


class LinkState:
    UP = "up"
    DOWN = "down"
    UNKNOWN = "unknown"


@dataclass
class ICILinkSnapshot:
    """One ICI port's state+counters at a point in time — the TPU analog of
    an InfiniBand port snapshot (reference:
    components/accelerator/nvidia/infiniband/class/class.go:14-34)."""

    chip_id: int
    link_id: int
    state: str = LinkState.UP
    tx_bytes: int = 0
    rx_bytes: int = 0
    tx_errors: int = 0
    rx_errors: int = 0
    crc_errors: int = 0
    replays: int = 0
    speed_gbps: float = 0.0

    @property
    def name(self) -> str:
        return f"chip{self.chip_id}/ici{self.link_id}"


@dataclass
class TPUChipTelemetry:
    chip_id: int
    temperature_c: float = 0.0
    hbm_temperature_c: float = 0.0
    power_w: float = 0.0
    hbm_used_bytes: int = 0
    hbm_total_bytes: int = 0
    duty_cycle_pct: float = 0.0      # tensorcore duty cycle
    tensorcore_util_pct: float = 0.0
    hbm_ecc_correctable: int = 0
    hbm_ecc_uncorrectable: int = 0
    hbm_ecc_pending: bool = False
    thermal_slowdown: bool = False
    clock_mhz: float = 0.0


@dataclass
class TPUChip:
    chip_id: int
    device_path: str = ""
    pci_address: str = ""
    serial: str = ""
    generation: str = ""
    cores: int = 2
    hbm_total_bytes: int = 0
    lost: bool = False
    requires_reset: bool = False
    # real-surface attributes (populated by the PCI scan; see tpu/sysfs.py)
    numa_node: int = -1
    driver: str = ""
    iommu_group: str = ""


class TPUInstance:
    """Top interface (reference: pkg/nvidia/nvml/instance.go:43-97)."""

    # -- presence ----------------------------------------------------------
    def tpu_lib_exists(self) -> bool:
        raise NotImplementedError

    def is_mock(self) -> bool:
        """True when this is the CI fixture backend — components that
        assert on-disk artifacts (e.g. libtpu.so) skip themselves then."""
        return False

    def init_error(self) -> str:
        return ""

    # -- identity ----------------------------------------------------------
    def product_name(self) -> str:
        raise NotImplementedError

    def accelerator_type(self) -> str:
        raise NotImplementedError

    def topology(self) -> Optional[SliceTopology]:
        return parse_accelerator_type(self.accelerator_type())

    def generation(self) -> str:
        t = self.topology()
        return t.generation if t else ""

    def driver_version(self) -> str:
        return ""

    def runtime_version(self) -> str:
        return ""

    def worker_id(self) -> int:
        return 0

    # -- devices -----------------------------------------------------------
    def devices(self) -> Dict[int, TPUChip]:
        raise NotImplementedError

    def telemetry(self) -> Dict[int, TPUChipTelemetry]:
        return {}

    def ici_links(self) -> List[ICILinkSnapshot]:
        return []

    # -- capabilities (reference: FabricStateSupported etc.,
    #    nvml/instance.go:77-81) ------------------------------------------
    def telemetry_supported(self) -> bool:
        return False

    def telemetry_source(self) -> str:
        """Where telemetry numbers come from — surfaced in the telemetry
        components' check extra_info (components/tpu/shared.py) so
        operators can tell measurement from inventory (VERDICT r3 #6):
        "runtime-metrics" (libtpu gRPC side-band), "cli" (tpu-info
        exec+parse), "jax" (exclusive libtpu), "mock", or "" (none)."""
        return ""

    def ici_supported(self) -> bool:
        return False

    def shutdown(self) -> None:
        pass


# ---------------------------------------------------------------------------
# Mock backend
# ---------------------------------------------------------------------------

class MockBackend(TPUInstance):
    """All-success fixture backend (reference:
    pkg/nvidia/nvml/lib/mock_fixtures.go:12-149 allSuccessInterface).

    Telemetry is deterministic-but-wobbling (sinusoid over a fake clock) so
    metric pipelines see changing values; the fake clock is injectable.
    """

    def __init__(self, accelerator_type: str = "", worker_id: int = 0) -> None:
        self._accel_type = (
            accelerator_type
            or os.environ.get(ENV_MOCK_ACCEL_TYPE, "")
            or "v5e-8"
        )
        topo = parse_accelerator_type(self._accel_type)
        if topo is None:
            raise ValueError(f"unknown accelerator type {self._accel_type!r}")
        self._topo = topo
        self._worker_id = worker_id
        self.time_now_fn = time.time
        self._chips = {
            i: TPUChip(
                chip_id=i,
                device_path=f"/dev/accel{i}",
                pci_address=f"0000:{0x10 + i:02x}:00.0",
                serial=f"mock-{self._topo.generation}-{worker_id}-{i}",
                generation=self._topo.generation,
                cores=GENERATIONS[self._topo.generation].cores_per_chip,
                hbm_total_bytes=self._topo.hbm_bytes_per_chip,
            )
            for i in range(self._topo.chips_per_host)
        }
        # env-based targeted injections (reference: default.go:33-50)
        self._ecc_pending_chips = _int_set(os.environ.get(ENV_INJECT_HBM_ECC_PENDING, ""))
        self._thermal_chips = _int_set(os.environ.get(ENV_INJECT_THERMAL_SLOWDOWN, ""))
        self._down_links = set(
            x for x in os.environ.get(ENV_INJECT_ICI_LINK_DOWN, "").split(",") if x
        )

    def tpu_lib_exists(self) -> bool:
        return True

    def is_mock(self) -> bool:
        return True

    def product_name(self) -> str:
        return f"TPU {self._topo.generation}"

    def accelerator_type(self) -> str:
        return self._accel_type

    def driver_version(self) -> str:
        return "mock-driver-1.0"

    def runtime_version(self) -> str:
        return "mock-libtpu-0.1"

    def worker_id(self) -> int:
        return self._worker_id

    def devices(self) -> Dict[int, TPUChip]:
        return dict(self._chips)

    def telemetry_supported(self) -> bool:
        return True

    def telemetry_source(self) -> str:
        return "mock"

    def ici_supported(self) -> bool:
        return True

    def telemetry(self) -> Dict[int, TPUChipTelemetry]:
        t = self.time_now_fn()
        out: Dict[int, TPUChipTelemetry] = {}
        for cid, chip in self._chips.items():
            wobble = math.sin(t / 60.0 + cid)
            tel = TPUChipTelemetry(
                chip_id=cid,
                temperature_c=45.0 + 5.0 * wobble,
                hbm_temperature_c=52.0 + 6.0 * wobble,
                power_w=120.0 + 30.0 * wobble,
                hbm_used_bytes=int(chip.hbm_total_bytes * (0.3 + 0.1 * (wobble + 1) / 2)),
                hbm_total_bytes=chip.hbm_total_bytes,
                duty_cycle_pct=50.0 + 40.0 * (wobble + 1) / 2,
                tensorcore_util_pct=40.0 + 30.0 * (wobble + 1) / 2,
                clock_mhz=940.0,
            )
            if cid in self._ecc_pending_chips:
                tel.hbm_ecc_uncorrectable = 1
                tel.hbm_ecc_pending = True
            if cid in self._thermal_chips:
                tel.temperature_c = 95.0
                tel.thermal_slowdown = True
            out[cid] = tel
        return out

    def ici_links(self) -> List[ICILinkSnapshot]:
        t = self.time_now_fn()
        links: List[ICILinkSnapshot] = []
        n_links = self._topo.ici_links_per_chip
        for cid in self._chips:
            for lid in range(n_links):
                name = f"chip{cid}/ici{lid}"
                down = name in self._down_links
                links.append(
                    ICILinkSnapshot(
                        chip_id=cid,
                        link_id=lid,
                        state=LinkState.DOWN if down else LinkState.UP,
                        tx_bytes=int(t * 1e6) + cid * 1000 + lid,
                        rx_bytes=int(t * 1e6) + cid * 1000 + lid + 7,
                        tx_errors=0,
                        rx_errors=0,
                        crc_errors=0,
                        replays=0,
                        speed_gbps=450.0 if self._topo.generation == "v5p" else 200.0,
                    )
                )
        return links


def _int_set(spec: str) -> set:
    out = set()
    for part in spec.split(","):
        part = part.strip()
        if part.isdigit():
            out.add(int(part))
    return out


# ---------------------------------------------------------------------------
# Sysfs backend (real TPU VM, side-band — no libtpu open)
# ---------------------------------------------------------------------------

class SysfsICILinksMixin:
    """ICI link reads for side-band backends, two sources in order:

    1. ``TPUD_ICI_SYSFS_ROOT`` — a deployment-mapped per-link layout
       (override for runtimes/node agents that do expose per-port nodes).
    2. Derived topology inventory (the stock-TPU-VM default): no current
       runtime exposes per-port ICI state in sysfs (SURVEY §7), so the
       link inventory comes from the slice topology and coarse liveness
       from chip presence/driver binding — a chip that vanished from PCI
       or lost its binding reports its links down. Fine-grained link
       faults arrive via the driver kmsg catalog; counters stay zero.

    Shared by every side-band backend: ICI exposure is a driver/sysfs
    property, independent of how chips were enumerated."""

    def _ici_root(self) -> str:
        return os.environ.get(ENV_ICI_SYSFS_ROOT, "")

    def _derived_ici_links(self) -> List[ICILinkSnapshot]:
        """Topology-derived inventory; backends with real-surface
        knowledge override ``_unbound_chip_ids`` for liveness."""
        topo = self.topology()
        if topo is None:
            return []
        unbound = self._unbound_chip_ids()
        out: List[ICILinkSnapshot] = []
        for cid in sorted(self.devices()):
            state = LinkState.DOWN if cid in unbound else LinkState.UP
            for lid in range(topo.ici_links_per_chip):
                out.append(ICILinkSnapshot(chip_id=cid, link_id=lid, state=state))
        return out

    def _unbound_chip_ids(self) -> set:
        return set()

    def ici_source(self) -> str:
        root = self._ici_root()
        if root and os.path.isdir(root):
            return "mapped-sysfs"
        # cheap availability probe — runs on the polling hot path, so it
        # must not materialize the whole derived snapshot list
        if self.topology() is not None and self.devices():
            return "derived-topology"
        return ""

    def ici_supported(self) -> bool:
        return bool(self.ici_source())

    def ici_links(self) -> List[ICILinkSnapshot]:
        root = self._ici_root()
        if not root or not os.path.isdir(root):
            return self._derived_ici_links()
        out: List[ICILinkSnapshot] = []
        for chip_dir in sorted(glob.glob(os.path.join(root, "chip[0-9]*"))):
            cm = re.search(r"chip(\d+)$", chip_dir)
            if not cm:
                continue
            cid = int(cm.group(1))
            for link_dir in sorted(glob.glob(os.path.join(chip_dir, "ici[0-9]*"))):
                lm = re.search(r"ici(\d+)$", link_dir)
                if not lm:
                    continue
                snap = self._read_link(cid, int(lm.group(1)), link_dir)
                if snap is not None:
                    out.append(snap)
        return out

    @staticmethod
    def _read_link(cid: int, lid: int, link_dir: str) -> Optional[ICILinkSnapshot]:
        """One link sample, or None when this poll's reads are unreliable.

        A transient read failure must be *skipped*, never reported as down:
        an OSError mapped to "down" would record a CRITICAL drop event, a
        sticky flap, and a reboot suggestion from one failed file read;
        likewise a counter read falling back to 0 would fake a huge
        positive delta (and a CRC alarm) when the next read recovers.
        FileNotFoundError on a counter means "not mapped" (consistently 0);
        any other failure poisons the sample → skip.
        """
        try:
            with open(os.path.join(link_dir, "state"), "r", encoding="ascii") as f:
                state_raw = f.read().strip().lower()
        except OSError:
            return None  # unreadable this poll — skip, don't fake "down"
        if state_raw in ("up", "active", "1"):
            state = LinkState.UP
        elif state_raw in ("down", "inactive", "0"):
            state = LinkState.DOWN
        else:
            logger.warning(
                "unrecognized ICI state %r at %s; skipping sample",
                state_raw, link_dir,
            )
            return None

        def _num(name: str) -> int:
            path = os.path.join(link_dir, name)
            try:
                with open(path, "r", encoding="ascii") as f:
                    return int(f.read().strip())
            except FileNotFoundError:
                return 0  # counter not mapped by this deployment
            except (OSError, ValueError) as e:
                raise _UnreliableSample(str(e)) from e

        try:
            return ICILinkSnapshot(
                chip_id=cid,
                link_id=lid,
                state=state,
                tx_bytes=_num("tx_bytes"),
                rx_bytes=_num("rx_bytes"),
                tx_errors=_num("tx_errors"),
                rx_errors=_num("rx_errors"),
                crc_errors=_num("crc_errors"),
                replays=_num("replays"),
            )
        except _UnreliableSample:
            return None


class SysfsBackend(SysfsICILinksMixin, TPUInstance):
    """Enumerates the Google TPU driver's device nodes without opening
    libtpu (side-band monitoring only).

    Primary path: the real TPU-VM PCI surface (tpu/sysfs.py — vendor
    0x1ae0 functions with per-generation device ids, accel-class indices,
    vfio/iommu bindings), the same way the public tpu-info tool detects
    chips. Fallback: bare /dev/accel* / /dev/vfio/* globs for minimal
    environments. Roots are parameterized so checked-in fixture trees of
    real TPU VMs drive tests (SURVEY §4.4; reference pattern:
    infiniband/class/testdata/sys-class-infiniband-h100.0)."""

    def __init__(
        self,
        dev_root: str = "/dev",
        sys_accel_root: str = "",
        accelerator_type: str = "",
        worker_id: int = 0,
        sysfs_root: Optional[str] = None,
    ) -> None:
        from gpud_tpu.tpu.sysfs import TpuVmSurface

        self.dev_root = dev_root
        if sysfs_root is None:
            # a caller that redirected dev_root to a fixture but left
            # sysfs_root alone must NOT scan the real /sys — on an actual
            # TPU VM the real PCI chips would win over the fixture nodes
            sysfs_root = "/sys" if dev_root == "/dev" else ""
        self.sysfs_root = sysfs_root
        # legacy explicit accel-class root (older fixtures); derived from
        # sysfs_root when not given
        self.sys_accel_root = sys_accel_root or (
            os.path.join(sysfs_root, "class", "accel") if sysfs_root else ""
        )
        self._worker_id = worker_id
        self._init_error = ""
        self.surface = TpuVmSurface(sysfs_root=sysfs_root, dev_root=dev_root)
        self._unbound: set = set()
        self._chips = self._enumerate()
        self._accel_type = (
            accelerator_type
            or _gce_metadata_accel_type()
            or self._infer_accel_type()
        )
        self._backfill_topology_facts()

    def _backfill_topology_facts(self) -> None:
        """Reconcile per-chip facts with the resolved accelerator type.

        The topology (operator flag or GCE metadata) outranks the PCI
        device id: the legacy id 0x0027 is shared by v2 and v3, so a v3
        host would otherwise be stamped v2 with half its real HBM. Chips
        enumerated from bare device nodes carry no generation at all and
        get everything from the topology."""
        topo = parse_accelerator_type(self._accel_type) if self._accel_type else None
        if topo is None:
            return
        spec = GENERATIONS.get(topo.generation)
        for chip in self._chips.values():
            if chip.generation != topo.generation:
                chip.generation = topo.generation
                chip.hbm_total_bytes = topo.hbm_bytes_per_chip
                if spec is not None:
                    chip.cores = spec.cores_per_chip
            if chip.hbm_total_bytes == 0:
                chip.hbm_total_bytes = topo.hbm_bytes_per_chip
            if spec is not None and chip.cores == 2 and spec.cores_per_chip != 2:
                chip.cores = spec.cores_per_chip

    def _enumerate(self) -> Dict[int, TPUChip]:
        chips = self._enumerate_pci()
        if chips:
            return chips
        return self._enumerate_dev_nodes()

    def _enumerate_pci(self) -> Dict[int, TPUChip]:
        """The stock-TPU-VM path: chips are the vendor-0x1ae0 PCI
        functions; generation comes from the device id table, so this
        works with no metadata server at all."""
        if not self.sysfs_root:
            return {}
        self.surface.scan()
        chips: Dict[int, TPUChip] = {}
        use_accel_ids = self.surface.accel_indices_authoritative()
        for i, fn in enumerate(self.surface.chip_order()):
            cid = fn.accel_index if use_accel_ids else i
            gen = fn.generation
            spec = GENERATIONS.get(gen)
            chip = TPUChip(
                chip_id=cid,
                device_path=fn.accel_dev or fn.vfio_dev or f"pci:{fn.bdf}",
                pci_address=fn.bdf,
                generation=gen,
                cores=spec.cores_per_chip if spec else 2,
                hbm_total_bytes=spec.hbm_bytes_per_chip if spec else 0,
                numa_node=fn.numa_node,
                driver=fn.driver,
                iommu_group=fn.iommu_group,
            )
            if not fn.bound:
                # present on PCI but no driver → unusable by libtpu; keep
                # it enumerated (chip-count stays right) but mark it so
                # derived ICI liveness reports its links down
                chip.requires_reset = True
                self._unbound.add(cid)
            chips[cid] = chip
        return chips

    def _enumerate_dev_nodes(self) -> Dict[int, TPUChip]:
        """Fallback for environments exposing only bare device nodes."""
        chips: Dict[int, TPUChip] = {}
        for path in sorted(glob.glob(os.path.join(self.dev_root, "accel[0-9]*"))):
            m = re.search(r"accel(\d+)$", path)
            if not m:
                continue
            cid = int(m.group(1))
            chip = TPUChip(chip_id=cid, device_path=path)
            # PCI address via /sys/class/accel/accelN/device symlink
            sys_dev = os.path.join(self.sys_accel_root, f"accel{cid}", "device")
            try:
                chip.pci_address = os.path.basename(os.readlink(sys_dev))
            except OSError:
                pass
            chips[cid] = chip
        if not chips:
            # vfio-based runtimes expose chips as /dev/vfio/* instead
            vfio = sorted(glob.glob(os.path.join(self.dev_root, "vfio", "[0-9]*")))
            for i, path in enumerate(vfio):
                chips[i] = TPUChip(chip_id=i, device_path=path)
        return chips

    def _infer_accel_type(self) -> str:
        """Single-host accelerator type synthesized from the PCI-derived
        generation when the metadata server is absent (bare-metal-ish or
        fixture runs). Multi-host slices need the metadata value — a
        local-only guess would understate the topology, so this only
        claims what this host can see."""
        gen = self.surface.generation()  # consensus; warns on a mixed host
        spec = GENERATIONS.get(gen)
        if spec is None or not self._chips:
            return ""
        n = len(self._chips)
        count = n if spec.suffix_counts_chips else n * spec.cores_per_chip
        return f"{gen}-{count}"

    def tpu_lib_exists(self) -> bool:
        return bool(self._chips)

    def init_error(self) -> str:
        return self._init_error

    def product_name(self) -> str:
        t = self.topology()
        return f"TPU {t.generation}" if t else "TPU"

    def accelerator_type(self) -> str:
        return self._accel_type

    def driver_version(self) -> str:
        return self.surface.driver_version()

    def worker_id(self) -> int:
        return self._worker_id

    def devices(self) -> Dict[int, TPUChip]:
        return dict(self._chips)

    def _unbound_chip_ids(self) -> set:
        return set(self._unbound)

    def telemetry_supported(self) -> bool:
        return False  # sysfs telemetry not exposed by current drivers


class _UnreliableSample(Exception):
    pass


def _read_file(path: str) -> str:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return f.read().strip()
    except OSError:
        return ""


def _gce_metadata_accel_type(timeout: float = 1.0) -> str:
    """accelerator-type from the GCE TPU-VM metadata server; empty off-GCE."""
    try:
        import urllib.request

        req = urllib.request.Request(
            "http://metadata.google.internal/computeMetadata/v1/instance/attributes/accelerator-type",
            headers={"Metadata-Flavor": "Google"},
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read().decode().strip()
    except Exception:  # noqa: BLE001 — any failure means "not a TPU VM"
        return ""


# ---------------------------------------------------------------------------
# JAX backend (opt-in: opening libtpu is exclusive)
# ---------------------------------------------------------------------------

class JaxBackend(TPUInstance):
    """Enumerates chips and samples HBM telemetry through a live libtpu via
    JAX. Opt-in (TPUD_TPU_USE_JAX=1): libtpu open is exclusive, so this
    backend must only run where tpud owns the chips (e.g. dedicated health
    probes), never side-band under a training job."""

    def __init__(self, accelerator_type: str = "") -> None:
        self._init_error = ""
        self._accel_type = accelerator_type
        self._devices: Dict[int, TPUChip] = {}
        self._jax_devices = []
        self._lock = threading.Lock()
        try:
            import jax

            devs = [d for d in jax.devices() if d.platform in ("tpu", "axon")]
            self._jax_devices = devs
            for d in devs:
                gen = normalize_generation(getattr(d, "device_kind", ""))
                self._devices[d.id] = TPUChip(
                    chip_id=d.id,
                    device_path=f"jax:{d.id}",
                    generation=gen,
                    cores=getattr(d, "num_cores", 1) if hasattr(d, "num_cores") else 1,
                )
            if not self._accel_type and devs:
                gen = normalize_generation(getattr(devs[0], "device_kind", ""))
                n = len(devs)
                spec = GENERATIONS.get(gen)
                if spec is not None:
                    count = n if spec.suffix_counts_chips else n * spec.cores_per_chip
                    self._accel_type = f"{gen}-{count}"
        except Exception as e:  # noqa: BLE001
            self._init_error = str(e)

    def tpu_lib_exists(self) -> bool:
        return bool(self._devices)

    def init_error(self) -> str:
        return self._init_error

    def product_name(self) -> str:
        if self._jax_devices:
            return getattr(self._jax_devices[0], "device_kind", "TPU")
        return "TPU"

    def accelerator_type(self) -> str:
        return self._accel_type

    def devices(self) -> Dict[int, TPUChip]:
        return dict(self._devices)

    def telemetry_supported(self) -> bool:
        return bool(self._devices)

    def telemetry_source(self) -> str:
        return "jax"

    def telemetry(self) -> Dict[int, TPUChipTelemetry]:
        out: Dict[int, TPUChipTelemetry] = {}
        with self._lock:
            for d in self._jax_devices:
                tel = TPUChipTelemetry(chip_id=d.id)
                try:
                    stats = d.memory_stats() or {}
                    tel.hbm_used_bytes = int(stats.get("bytes_in_use", 0))
                    tel.hbm_total_bytes = int(stats.get("bytes_limit", 0))
                except Exception:  # noqa: BLE001
                    pass
                out[d.id] = tel
        return out


# ---------------------------------------------------------------------------
# Failure-injector wrapper + factory
# ---------------------------------------------------------------------------

class InjectedInstance(TPUInstance):
    """Wraps a real/mock backend and overlays simulated failures
    (reference: nvml.NewWithFailureInjector, instance.go:18-38,115)."""

    def __init__(self, inner: TPUInstance, injector: FailureInjector) -> None:
        self.inner = inner
        self.injector = injector

    def tpu_lib_exists(self) -> bool:
        if self.injector.tpu_enumeration_error:
            return False
        return self.inner.tpu_lib_exists()

    def is_mock(self) -> bool:
        return self.inner.is_mock()

    def init_error(self) -> str:
        if self.injector.tpu_enumeration_error:
            return "injected: TPU enumeration failure"
        return self.inner.init_error()

    def product_name(self) -> str:
        return self.injector.product_name_override or self.inner.product_name()

    def accelerator_type(self) -> str:
        return self.inner.accelerator_type()

    def driver_version(self) -> str:
        return self.inner.driver_version()

    def runtime_version(self) -> str:
        return self.inner.runtime_version()

    def worker_id(self) -> int:
        return self.inner.worker_id()

    def devices(self) -> Dict[int, TPUChip]:
        if self.injector.tpu_enumeration_error:
            return {}
        devs = self.inner.devices()
        out: Dict[int, TPUChip] = {}
        for cid, chip in devs.items():
            if cid in self.injector.chip_ids_lost:
                chip = TPUChip(**{**chip.__dict__, "lost": True})
            if cid in self.injector.chip_ids_requires_reset:
                chip = TPUChip(**{**chip.__dict__, "requires_reset": True})
            out[cid] = chip
        return out

    def telemetry_supported(self) -> bool:
        return self.inner.telemetry_supported()

    def telemetry_source(self) -> str:
        return self.inner.telemetry_source()

    def ici_source(self) -> str:
        src = getattr(self.inner, "ici_source", None)
        return src() if callable(src) else ""

    def ici_supported(self) -> bool:
        return self.inner.ici_supported()

    def telemetry(self) -> Dict[int, TPUChipTelemetry]:
        tel = self.inner.telemetry()
        for cid in self.injector.chip_ids_hbm_ecc_pending:
            if cid in tel:
                tel[cid].hbm_ecc_uncorrectable += 1
                tel[cid].hbm_ecc_pending = True
        for cid in self.injector.chip_ids_thermal_slowdown:
            if cid in tel:
                tel[cid].temperature_c = max(tel[cid].temperature_c, 95.0)
                tel[cid].thermal_slowdown = True
        for cid in self.injector.chip_ids_lost:
            tel.pop(cid, None)
        return tel

    def ici_links(self) -> List[ICILinkSnapshot]:
        links = self.inner.ici_links()
        down = set(self.injector.ici_links_down)
        for ln in links:
            if ln.name in down:
                ln.state = LinkState.DOWN
        return links

    def shutdown(self) -> None:
        self.inner.shutdown()


def new_instance(
    failure_injector: Optional[FailureInjector] = None,
    accelerator_type: str = "",
    worker_id: int = 0,
) -> TPUInstance:
    """Factory (reference: nvml.New / NewWithFailureInjector).

    Order: mock env → JAX (opt-in) → tpu-info CLI (telemetry-capable) →
    sysfs. The returned instance is always usable; absence of TPUs is
    reported through ``tpu_lib_exists()``.
    """
    inst: TPUInstance
    if os.environ.get(ENV_MOCK_ALL_SUCCESS, "").lower() in ("1", "true", "yes"):
        inst = MockBackend(accelerator_type=accelerator_type, worker_id=worker_id)
    elif os.environ.get(ENV_USE_JAX, "").lower() in ("1", "true", "yes"):
        inst = JaxBackend(accelerator_type=accelerator_type)
    else:
        inst = SysfsBackend(
            accelerator_type=accelerator_type,
            worker_id=worker_id,
            # None (not "/sys") when unset: the constructor's guard must
            # still suppress the real-PCI scan if only the dev root was
            # redirected to a fixture
            sysfs_root=os.environ.get(ENV_SYSFS_ROOT) or None,
            dev_root=os.environ.get(ENV_DEV_ROOT, "/dev"),
        )
        # Telemetry upgrade ladder on top of sysfs enumeration:
        #   1. libtpu runtime-metrics gRPC service (true side-band, no
        #      exec, no device ownership — the NVML analog); probed when
        #      its address env is set explicitly, or by default on a host
        #      with chips and no fixture roots.
        #   2. tpu-info CLI when on PATH (exec+parse fallback).
        # Fixture runs (root overrides set) stay on the fixture-driven
        # backend unless the metrics address was set explicitly — the CLI
        # and default-port probes would observe the real hardware instead.
        fixture_roots = bool(
            os.environ.get(ENV_SYSFS_ROOT) or os.environ.get(ENV_DEV_ROOT)
        )
        upgraded = False
        try:
            from gpud_tpu.tpu import runtime_metrics as rtm

            explicit_addr = bool(os.environ.get(rtm.ENV_ADDR))
            if rtm.runtime_metrics_enabled() and (
                explicit_addr or (not fixture_roots and inst.tpu_lib_exists())
            ):
                rm = rtm.RuntimeMetricsBackend(inner=inst)
                if rm.available():
                    inst = rm
                    upgraded = True
        except Exception:  # noqa: BLE001 — sysfs result stands
            pass
        if not upgraded and not fixture_roots:
            try:
                from gpud_tpu.tpu.tpu_info_backend import (
                    TpuInfoBackend,
                    tpu_info_available,
                )

                if tpu_info_available():
                    ti = TpuInfoBackend(
                        accelerator_type=inst.accelerator_type() or accelerator_type,
                        worker_id=worker_id,
                    )
                    if ti.tpu_lib_exists():
                        inst = ti
            except Exception:  # noqa: BLE001 — sysfs result stands
                pass
    if failure_injector is not None and not failure_injector.empty():
        inst = InjectedInstance(inst, failure_injector)
    return inst
