"""Readers for the sysfs surface a stock TPU VM actually exposes.

This is the ground-truth enumeration path (reference:
components/accelerator/nvidia/infiniband/class/class.go:14-34 reads the
real /sys/class/infiniband tree with checked-in fixture snapshots; we do
the same for the TPU-VM PCI/accel/vfio surface, with fixture trees per
generation under tests/fixtures/tpuvm/).

What a stock TPU VM exposes (no node agent, no mapping layer):

- ``/sys/bus/pci/devices/<bdf>/`` — every TPU chip is a PCI function with
  vendor ``0x1ae0`` (Google). The device id identifies the generation; the
  id table below matches the public ``tpu-info`` tool
  (google/cloud-accelerator-diagnostics, tpu_info/device.py), which
  detects chips exactly this way. Standard attributes: ``vendor``,
  ``device``, ``class``, ``revision``, ``subsystem_vendor``,
  ``subsystem_device``, ``numa_node``, plus ``driver`` and ``iommu_group``
  symlinks.
- ``/sys/class/accel/accelN/device`` — on gasket/accel-driver runtimes
  (v2–v4 era) each chip also has an accel class entry whose ``device``
  symlink resolves to the PCI function; the accelN index is the stable
  chip index and ``/dev/accelN`` is the char device.
- ``/dev/vfio/<group>`` + ``/sys/kernel/iommu_groups/<group>/devices/`` —
  on vfio-pci runtimes (v5e/v5p/v6e) chips are bound to ``vfio-pci`` and
  libtpu opens them through their IOMMU-group char device.

Per-port ICI link state is NOT in this tree on any current runtime
(SURVEY §7 hard parts: "per-link counters are less exposed than
/sys/class/infiniband"). The honest default ICI source is therefore
*derived*: the link inventory comes from the slice topology (axis count
per generation), and coarse liveness comes from this surface — a chip
that vanished from PCI or lost its driver binding has its links reported
down. Fine-grained link faults arrive through the driver kmsg catalog,
and deployments that do map per-link nodes keep the ``TPUD_ICI_SYSFS_ROOT``
override (see instance.SysfsICILinksMixin).
"""

from __future__ import annotations

import glob
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from gpud_tpu.log import get_logger

logger = get_logger(__name__)

TPU_PCI_VENDOR = "0x1ae0"

# PCI device id → TPU generation. Source: the public tpu-info tool's chip
# table (google/cloud-accelerator-diagnostics, tpu_info/device.py) — it
# identifies chips by scanning /sys/bus/pci/devices for vendor 0x1ae0 and
# these device ids.
PCI_DEVICE_IDS: Dict[str, str] = {
    "0x0027": "v2",   # legacy gasket-era id (v2/v3 share the TPU-VM id)
    "0x005e": "v4",
    "0x0062": "v5p",
    "0x0063": "v5e",
    "0x006f": "v6e",
}

# kernel modules that carry the TPU driver version, by runtime era
_DRIVER_MODULES = ("google_tpu", "accel", "gasket", "tpu_common", "vfio_pci")


@dataclass
class PciTpuFunction:
    """One TPU chip's PCI function as sysfs exposes it."""

    bdf: str                       # e.g. "0000:00:04.0"
    device_id: str = ""            # e.g. "0x0063"
    generation: str = ""           # derived from device_id
    class_code: str = ""
    revision: str = ""
    subsystem_vendor: str = ""
    subsystem_device: str = ""
    numa_node: int = -1
    driver: str = ""               # basename of the driver symlink ("vfio-pci", "accel", ...)
    iommu_group: str = ""          # basename of the iommu_group symlink
    vfio_dev: str = ""             # /dev/vfio/<group> when it exists
    accel_index: Optional[int] = None  # accelN class index when present
    accel_dev: str = ""            # /dev/accelN when it exists

    @property
    def bound(self) -> bool:
        """A chip whose PCI function lost its driver binding is not usable
        by libtpu — coarse ICI-liveness treats it as down."""
        return bool(self.driver)


@dataclass
class TpuVmSurface:
    """Aggregated view of the TPU-VM sysfs/dev surface.

    ``sysfs_root``/``dev_root`` are parameterized so checked-in fixture
    trees drive tests (SURVEY §4.4 fixture-directory pattern — the same
    mechanism as the reference's --infiniband-class-root-dir).
    """

    sysfs_root: str = "/sys"
    dev_root: str = "/dev"
    functions: List[PciTpuFunction] = field(default_factory=list)

    def scan(self) -> List[PciTpuFunction]:
        self.functions = self._scan_pci()
        self._overlay_accel_class(self.functions)
        self._overlay_vfio(self.functions)
        return self.functions

    # -- PCI ---------------------------------------------------------------
    def _scan_pci(self) -> List[PciTpuFunction]:
        out: List[PciTpuFunction] = []
        pci_root = os.path.join(self.sysfs_root, "bus", "pci", "devices")
        for dev_dir in sorted(glob.glob(os.path.join(pci_root, "*"))):
            if _read(dev_dir, "vendor").lower() != TPU_PCI_VENDOR:
                continue
            fn = PciTpuFunction(bdf=os.path.basename(dev_dir))
            fn.device_id = _read(dev_dir, "device").lower()
            fn.generation = PCI_DEVICE_IDS.get(fn.device_id, "")
            fn.class_code = _read(dev_dir, "class")
            fn.revision = _read(dev_dir, "revision")
            fn.subsystem_vendor = _read(dev_dir, "subsystem_vendor")
            fn.subsystem_device = _read(dev_dir, "subsystem_device")
            numa = _read(dev_dir, "numa_node")
            try:
                fn.numa_node = int(numa)
            except ValueError:
                fn.numa_node = -1
            fn.driver = _link_basename(os.path.join(dev_dir, "driver"))
            fn.iommu_group = _link_basename(os.path.join(dev_dir, "iommu_group"))
            out.append(fn)
        return out

    # -- accel class (gasket/accel driver era) -----------------------------
    def _overlay_accel_class(self, fns: List[PciTpuFunction]) -> None:
        by_bdf = {f.bdf: f for f in fns}
        accel_root = os.path.join(self.sysfs_root, "class", "accel")
        for entry in sorted(glob.glob(os.path.join(accel_root, "accel[0-9]*"))):
            m = re.search(r"accel(\d+)$", entry)
            if not m:
                continue
            idx = int(m.group(1))
            dev_link = os.path.join(entry, "device")
            try:
                bdf = os.path.basename(os.path.realpath(dev_link))
            except OSError:
                continue
            fn = by_bdf.get(bdf)
            if fn is None:
                continue
            fn.accel_index = idx
            dev_node = os.path.join(self.dev_root, f"accel{idx}")
            if os.path.exists(dev_node):
                fn.accel_dev = dev_node

    # -- vfio (v5e/v5p/v6e era) -------------------------------------------
    def _overlay_vfio(self, fns: List[PciTpuFunction]) -> None:
        for fn in fns:
            if not fn.iommu_group:
                continue
            vfio_node = os.path.join(self.dev_root, "vfio", fn.iommu_group)
            if os.path.exists(vfio_node):
                fn.vfio_dev = vfio_node

    # -- aggregate facts ---------------------------------------------------
    def generation(self) -> str:
        """Consensus generation across enumerated functions ('' if mixed
        or none — a mixed host is a hardware fault worth surfacing, not
        silently picking one)."""
        gens = {f.generation for f in self.functions if f.generation}
        if len(gens) == 1:
            return gens.pop()
        if len(gens) > 1:
            logger.warning("mixed TPU generations on one host: %s", sorted(gens))
        return ""

    def driver_version(self) -> str:
        for name in _DRIVER_MODULES:
            v = _read(os.path.join(self.sysfs_root, "module", name), "version")
            if v:
                return v
        return ""

    def accel_indices_authoritative(self) -> bool:
        """True when every function has an accel-class index — only then
        do accelN indices name the chips; a partial set (dangling udev
        symlink) mixed with positional ids could collide."""
        return bool(self.functions) and all(
            f.accel_index is not None for f in self.functions
        )

    def chip_order(self) -> List[PciTpuFunction]:
        """Stable chip ordering: accel-class index when the driver assigns
        one (it is the /dev/accelN index), else BDF order."""
        if self.accel_indices_authoritative():
            return sorted(self.functions, key=lambda f: f.accel_index)
        return sorted(self.functions, key=lambda f: f.bdf)


def _read(dirname: str, name: str) -> str:
    try:
        with open(os.path.join(dirname, name), "r", encoding="ascii") as f:
            return f.read().strip()
    except OSError:
        return ""


def _link_basename(path: str) -> str:
    try:
        return os.path.basename(os.readlink(path))
    except OSError:
        return ""
