"""TPU topology knowledge: accelerator-type parsing and expectations.

This is the analog of the reference's product→capabilities mapping
(reference: pkg/nvidia/product — product name → memory-error-mgmt /
row-remapping / fabric support). For TPUs the product string is the
accelerator type (e.g. ``v5p-256``) and the derived facts are chip counts,
chips-per-host, ICI link counts per chip, and HBM capacity.

Conventions encoded here:
- v2/v3/v4/v5p: the numeric suffix counts TensorCores; chips = N/2.
- v5e (v5litepod) / v6e: the suffix counts chips directly.
- chips per host: v4/v5p → 4; v5e/v6e → 8 (single-host slices may have
  fewer, e.g. v5e-4).
- ICI links per chip: 3D-torus generations (v4, v5p) → 6; 2D-torus
  (v5e, v6e) → 4.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional

_GiB = 1024**3


@dataclass(frozen=True)
class GenerationSpec:
    name: str
    cores_per_chip: int
    suffix_counts_chips: bool   # else counts TensorCores
    chips_per_host: int
    ici_links_per_chip: int
    hbm_bytes_per_chip: int
    supports_ici_fabric: bool   # multi-chip ICI observable


GENERATIONS = {
    "v2": GenerationSpec("v2", 2, False, 4, 4, 8 * _GiB, True),
    "v3": GenerationSpec("v3", 2, False, 4, 4, 16 * _GiB, True),
    "v4": GenerationSpec("v4", 2, False, 4, 6, 32 * _GiB, True),
    "v5e": GenerationSpec("v5e", 1, True, 8, 4, 16 * _GiB, True),
    "v5p": GenerationSpec("v5p", 2, False, 4, 6, 95 * _GiB, True),
    "v6e": GenerationSpec("v6e", 1, True, 8, 4, 32 * _GiB, True),
}

_ACCEL_RE = re.compile(r"^(v\d+(?:e|p|litepod)?)-(\d+)$")

# aliases seen in GCE metadata / jax device kinds
_ALIASES = {
    "v5litepod": "v5e",
    "v5lite": "v5e",
    "tpu v2": "v2",
    "tpu v3": "v3",
    "tpu v4": "v4",
    "tpu v5": "v5e",
    "tpu v5 lite": "v5e",
    "tpu v5e": "v5e",
    "tpu v5 lite0": "v5e",
    "tpu v5p": "v5p",
    "tpu v6e": "v6e",
    "tpu v6 lite": "v6e",
}


def normalize_generation(name: str) -> str:
    n = name.strip().lower()
    if n in GENERATIONS:
        return n
    if n in _ALIASES:
        return _ALIASES[n]
    # e.g. "TPU v5 lite0" (jax device kind) → strip trailing digits
    base = re.sub(r"\d+$", "", n).strip()
    if base in _ALIASES:
        return _ALIASES[base]
    return n


@dataclass
class SliceTopology:
    accelerator_type: str
    generation: str
    total_chips: int
    total_cores: int
    hosts: int
    chips_per_host: int
    ici_links_per_chip: int
    hbm_bytes_per_chip: int

    @property
    def multi_host(self) -> bool:
        return self.hosts > 1


def parse_accelerator_type(accel_type: str) -> Optional[SliceTopology]:
    """``v5p-256`` → SliceTopology(generation=v5p, chips=128, hosts=32, ...).
    Returns None for unknown formats."""
    m = _ACCEL_RE.match(accel_type.strip().lower())
    if not m:
        return None
    gen_name = normalize_generation(m.group(1))
    spec = GENERATIONS.get(gen_name)
    if spec is None:
        return None
    n = int(m.group(2))
    if spec.suffix_counts_chips:
        chips = n
        cores = n * spec.cores_per_chip
    else:
        cores = n
        chips = max(1, n // 2)
    hosts = max(1, (chips + spec.chips_per_host - 1) // spec.chips_per_host)
    chips_per_host = min(chips, spec.chips_per_host)
    return SliceTopology(
        accelerator_type=accel_type,
        generation=gen_name,
        total_chips=chips,
        total_cores=cores,
        hosts=hosts,
        chips_per_host=chips_per_host,
        ici_links_per_chip=spec.ici_links_per_chip,
        hbm_bytes_per_chip=spec.hbm_bytes_per_chip,
    )


def expected_local_chips(accel_type: str) -> int:
    """How many chips this host should see for the given accelerator type —
    the TPU analog of expected GPU counts
    (reference: components/accelerator/nvidia/gpu-counts)."""
    topo = parse_accelerator_type(accel_type)
    if topo is None:
        return 0
    return topo.chips_per_host
