"""Side-band client for libtpu's runtime gRPC metrics service.

This is the true NVML analog for TPU VMs (reference boundary:
pkg/nvidia/nvml/lib/lib.go:11-16, pkg/nvidia/nvml/instance.go:43-97 — an
always-on side-band library API with no exec and no device ownership).
On a TPU VM, libtpu runs a gRPC server (default ``localhost:8431``,
``TPU_RUNTIME_METRICS_PORTS`` when several runtime processes each serve
their own port) exposing ``tpu.monitoring.runtime.RuntimeMetricService``
— the same endpoint the public ``tpu-info`` CLI consumes. Talking to it
directly gives per-poll, per-chip telemetry (HBM used/total, tensorcore
duty cycle, …) without a subprocess fork+parse and without opening
libtpu (which is exclusive).

Wire handling follows the repo's CRI pattern (gpud_tpu/cri.py): gRPC
framing from grpcio with identity serializers, protobuf payloads via the
small hand codec. Message shapes follow the public tpu-info proto
(tpu_metric_service.proto: MetricRequest{metric_name=1} →
MetricResponse{metric=1 TPUMetric{name=1, description=2, metrics=3
repeated Metric{attribute=1 Attribute{key=1, value=2 AttrValue oneof},
gauge=2 Gauge oneof}}}). Because oneof field numbers have drifted across
libtpu versions, the *decoder* keys off the wire type instead of exact
field numbers: a varint in a Gauge is the int value, a fixed64 is the
double value, length-delimited is a string — so the client stays correct
even if the runtime reorders the oneof arms.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from gpud_tpu.cri import (
    _read_varint as _cri_read_varint,
    encode_field_bytes,
    encode_field_str,
    encode_field_varint,
    encode_varint,
    parse_message,
)
from gpud_tpu.log import get_logger

logger = get_logger(__name__)

SERVICE = "tpu.monitoring.runtime.RuntimeMetricService"
DEFAULT_PORT = 8431
DEFAULT_TIMEOUT = 2.0

# libtpu's own env naming the serving port(s); tpud's override wins
ENV_LIBTPU_PORTS = "TPU_RUNTIME_METRICS_PORTS"
ENV_ADDR = "TPUD_RUNTIME_METRICS_ADDR"   # host:port[,host:port...]
ENV_DISABLE = "TPUD_RUNTIME_METRICS"     # "0"/"false" disables the probe

# Metric names served by current libtpu (the tpu-info core set)
METRIC_HBM_TOTAL = "tpu.runtime.hbm.memory.total.bytes"
METRIC_HBM_USAGE = "tpu.runtime.hbm.memory.usage.bytes"
METRIC_DUTY_CYCLE = "tpu.runtime.tensorcore.dutycycle.percent"
# Served by some runtime versions; consumed only when ListSupportedMetrics
# advertises them (capability-gated, SURVEY §7 "metric APIs vary by
# runtime version → isolate behind tpu.Instance with capability flags")
METRIC_TENSORCORE_UTIL = "tpu.runtime.tensorcore.utilization.percent"
METRIC_HBM_ECC_UNCORRECTABLE = "tpu.runtime.uncorrectable.hbm.memory.errors.count"
CORE_METRICS = (METRIC_HBM_TOTAL, METRIC_HBM_USAGE, METRIC_DUTY_CYCLE)
OPTIONAL_METRICS = (METRIC_TENSORCORE_UTIL, METRIC_HBM_ECC_UNCORRECTABLE)

# Optional ICI per-link counters. No public libtpu version serves these
# today; the names define the convention a runtime (or node agent proxy)
# can export so fabric telemetry rides the same side-band channel.
# Attributes: device-id (chip), link-id.
ICI_METRIC_PREFIX = "tpu.runtime.ici."
ICI_METRICS = {
    "tpu.runtime.ici.link.state": "state",          # 1 up / 0 down
    "tpu.runtime.ici.link.tx.bytes": "tx_bytes",
    "tpu.runtime.ici.link.rx.bytes": "rx_bytes",
    "tpu.runtime.ici.link.tx.errors": "tx_errors",
    "tpu.runtime.ici.link.rx.errors": "rx_errors",
    "tpu.runtime.ici.link.crc.errors": "crc_errors",
    "tpu.runtime.ici.link.replays": "replays",
}


class RuntimeMetricsError(Exception):
    """Transport or decode failure against the runtime metrics service."""


@dataclass
class MetricSample:
    """One (attributes, value) row of a runtime metric."""

    value: float = 0.0
    is_int: bool = False
    attrs: Dict[str, object] = field(default_factory=dict)

    @property
    def device_id(self) -> int:
        """The per-chip key: the first integer attribute (tpu-info reads
        ``metric.attribute.value.int_attr`` the same way); -1 if none."""
        for k in ("device-id", "device_id", "chip-id", "chip_id"):
            v = self.attrs.get(k)
            if isinstance(v, int):
                return v
        for v in self.attrs.values():
            if isinstance(v, int):
                return v
        return -1

    @property
    def link_id(self) -> int:
        for k in ("link-id", "link_id", "port-id", "port_id"):
            v = self.attrs.get(k)
            if isinstance(v, int):
                return v
        return -1


# ---------------------------------------------------------------------------
# payload encode (requests + the test fake's responses)
# ---------------------------------------------------------------------------

def encode_field_double(fnum: int, v: float) -> bytes:
    return encode_varint(fnum << 3 | 1) + struct.pack("<d", v)


def encode_field_int64(fnum: int, v: int) -> bytes:
    """Like encode_field_varint but proto3-int64-correct for negatives
    (two's complement 64-bit; a raw negative would loop forever in the
    shift-based varint encoder)."""
    return encode_field_varint(fnum, v & 0xFFFFFFFFFFFFFFFF)


def encode_metric_request(metric_name: str) -> bytes:
    return encode_field_str(1, metric_name)


def encode_attr_value(v: object) -> bytes:
    # public proto oneof: string_attr=1, bool_attr=2, int_attr=3, double_attr=4
    if isinstance(v, bool):
        return encode_field_varint(2, 1 if v else 0)
    if isinstance(v, int):
        return encode_field_int64(3, v)
    if isinstance(v, float):
        return encode_field_double(4, v)
    return encode_field_str(1, str(v))


def encode_metric(attrs: Dict[str, object], value, *,
                  gauge_int_field: int = 2, gauge_double_field: int = 1) -> bytes:
    """One Metric message: attribute=1, gauge=2 (oneof: as_double=1,
    as_int=2 per the public proto; overridable so tests can model a
    runtime that renumbered the oneof — the decoder must not care)."""
    body = b""
    for k, v in attrs.items():
        attr = encode_field_str(1, k) + encode_field_bytes(2, encode_attr_value(v))
        body += encode_field_bytes(1, attr)
    if isinstance(value, bool) or isinstance(value, int):
        gauge = encode_field_int64(gauge_int_field, int(value))
    else:
        gauge = encode_field_double(gauge_double_field, float(value))
    body += encode_field_bytes(2, gauge)
    return body


def encode_metric_response(name: str, samples: List[Tuple[Dict[str, object], object]],
                           **metric_kw) -> bytes:
    tpu_metric = encode_field_str(1, name)
    for attrs, value in samples:
        tpu_metric += encode_field_bytes(3, encode_metric(attrs, value, **metric_kw))
    return encode_field_bytes(1, tpu_metric)


def encode_list_supported_response(names: List[str]) -> bytes:
    out = b""
    for n in names:
        out += encode_field_bytes(1, encode_field_str(1, n))
    return out


# ---------------------------------------------------------------------------
# payload decode (wire-type driven, field-number tolerant)
# ---------------------------------------------------------------------------

def _decode_scalar_oneof(data: bytes) -> Tuple[object, bool]:
    """Decode a one-armed scalar message (AttrValue) by wire type: varint
    → int, fixed64 → double, bytes → utf-8 str. Returns (value, is_int).
    Empty message → (0.0, False)."""
    i = 0
    while i < len(data):
        key, i = _read_varint(data, i)
        wire = key & 0x7
        if wire == 0:
            v, i = _read_varint(data, i)
            return _zigzag_passthrough(v), True
        if wire == 1:
            if i + 8 > len(data):
                raise RuntimeMetricsError("truncated attr fixed64")
            return struct.unpack_from("<d", data, i)[0], False
        if wire == 2:
            ln, i = _read_varint(data, i)
            return data[i:i + ln].decode("utf-8", "replace"), False
        if wire == 5:
            i += 4
        else:
            raise RuntimeMetricsError(f"unsupported attr wire type {wire}")
    return 0.0, False


def decode_metric(data: bytes) -> MetricSample:
    fields = parse_message(data)
    sample = MetricSample()
    for raw in fields.get(1, []):           # attribute
        if not isinstance(raw, bytes):
            continue
        attr = parse_message(raw)
        key_raw = attr.get(1, [b""])[0]
        key = key_raw.decode("utf-8", "replace") if isinstance(key_raw, bytes) else ""
        val_raw = attr.get(2, [b""])[0]
        if isinstance(val_raw, bytes):
            v, _ = _decode_scalar_oneof(val_raw)
            sample.attrs[key] = v
    gauge_raw = fields.get(2, [b""])[0]     # gauge
    if isinstance(gauge_raw, bytes) and gauge_raw:
        # parse_message can't distinguish a varint int64 from a fixed64
        # double (both come back as Python ints), so the gauge is decoded
        # straight off the wire types instead
        sample.value, sample.is_int = _decode_gauge(gauge_raw)
    return sample


def _decode_gauge(data: bytes) -> Tuple[float, bool]:
    """Wire-type-driven gauge decode: varint arm → int, fixed64 arm →
    IEEE-754 double, regardless of which oneof field number carried it."""
    i = 0
    while i < len(data):
        key, i = _read_varint(data, i)
        wire = key & 0x7
        if wire == 0:
            v, i = _read_varint(data, i)
            return float(_zigzag_passthrough(v)), True
        if wire == 1:
            if i + 8 > len(data):
                raise RuntimeMetricsError("truncated gauge fixed64")
            return struct.unpack_from("<d", data, i)[0], False
        if wire == 2:
            ln, i = _read_varint(data, i)
            raw = data[i:i + ln]
            i += ln
            try:
                return float(raw.decode("ascii")), False
            except (UnicodeDecodeError, ValueError):
                continue
        elif wire == 5:
            i += 4
        else:
            raise RuntimeMetricsError(f"unsupported gauge wire type {wire}")
    return 0.0, False


def _zigzag_passthrough(v: int) -> int:
    # proto3 int64 gauges are plain varints (two's complement); interpret
    # huge positives as negatives like protobuf does
    if v >= 1 << 63:
        v -= 1 << 64
    return v


def _read_varint(data: bytes, i: int) -> Tuple[int, int]:
    try:
        return _cri_read_varint(data, i)
    except ValueError as e:
        raise RuntimeMetricsError(str(e)) from e


def decode_metric_response(data: bytes) -> List[MetricSample]:
    resp = parse_message(data)
    metric_raw = resp.get(1, [b""])[0]
    if not isinstance(metric_raw, bytes) or not metric_raw:
        return []
    tpu_metric = parse_message(metric_raw)
    out: List[MetricSample] = []
    for m in tpu_metric.get(3, []):
        if isinstance(m, bytes):
            out.append(decode_metric(m))
    return out


def decode_list_supported_response(data: bytes) -> List[str]:
    resp = parse_message(data)
    names: List[str] = []
    for raw in resp.get(1, []):
        if not isinstance(raw, bytes):
            continue
        f = parse_message(raw)
        v = f.get(1, [b""])[0]
        if isinstance(v, bytes) and v:
            names.append(v.decode("utf-8", "replace"))
    return names


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class RuntimeMetricsClient:
    """One gRPC channel per serving port; results merged by device id
    (each runtime process serves metrics for the chips it owns)."""

    def __init__(self, addrs: Optional[List[str]] = None,
                 timeout: float = DEFAULT_TIMEOUT) -> None:
        self.addrs = addrs or resolve_addrs()
        self.timeout = timeout
        self._channels: Dict[str, object] = {}

    def _chan(self, addr: str):
        ch = self._channels.get(addr)
        if ch is None:
            import grpc

            ch = grpc.insecure_channel(addr)
            self._channels[addr] = ch
        return ch

    def close(self) -> None:
        for ch in self._channels.values():
            ch.close()
        self._channels.clear()

    def _call(self, addr: str, method: str, request: bytes) -> bytes:
        import grpc

        fn = self._chan(addr).unary_unary(
            f"/{SERVICE}/{method}",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        try:
            return fn(request, timeout=self.timeout)
        except grpc.RpcError as e:
            raise RuntimeMetricsError(
                f"{method}@{addr}: {e.code().name}: {e.details()}"
            ) from e

    def list_supported(self) -> List[str]:
        """Union of supported metric names across serving ports; raises
        only if *no* port answers."""
        names: List[str] = []
        seen = set()
        last_err: Optional[Exception] = None
        answered = False
        for addr in self.addrs:
            try:
                got = decode_list_supported_response(
                    self._call(addr, "ListSupportedMetrics", b"")
                )
                answered = True
            except RuntimeMetricsError as e:
                last_err = e
                continue
            for n in got:
                if n not in seen:
                    seen.add(n)
                    names.append(n)
        if not answered:
            raise last_err or RuntimeMetricsError("no metrics port configured")
        return names

    def get_metric(self, name: str) -> List[MetricSample]:
        """Samples merged across ports; a port that errors contributes
        nothing (the others' chips still report — one hung runtime process
        must not blind telemetry for the whole host)."""
        out: List[MetricSample] = []
        errs = 0
        for addr in self.addrs:
            try:
                out.extend(decode_metric_response(
                    self._call(addr, "GetRuntimeMetric", encode_metric_request(name))
                ))
            except RuntimeMetricsError as e:
                errs += 1
                logger.debug("runtime metric %s: %s", name, e)
        if errs and errs == len(self.addrs):
            raise RuntimeMetricsError(f"{name}: all {errs} metrics ports failed")
        return out


def resolve_addrs() -> List[str]:
    """Serving addresses: tpud override → libtpu's ports env → default."""
    override = os.environ.get(ENV_ADDR, "").strip()
    if override:
        return [a if ":" in a else f"localhost:{a}" for a in override.split(",") if a]
    ports = os.environ.get(ENV_LIBTPU_PORTS, "").strip()
    if ports:
        out = []
        for p in ports.split(","):
            p = p.strip()
            if p.isdigit():
                out.append(f"localhost:{p}")
        if out:
            return out
    return [f"localhost:{DEFAULT_PORT}"]


def runtime_metrics_enabled() -> bool:
    return os.environ.get(ENV_DISABLE, "").lower() not in ("0", "false", "no")


# ---------------------------------------------------------------------------
# backend
# ---------------------------------------------------------------------------

class RuntimeMetricsBackend:
    """TPUInstance backend: sysfs enumeration + runtime-service telemetry.

    Chip inventory, PCI facts and driver-binding liveness stay with the
    wrapped side-band backend (SysfsBackend — the runtime service names
    devices but knows nothing about PCI health); telemetry rides the gRPC
    service. This mirrors the reference's split where device *identity*
    comes from PCI (pkg/nvidia/pci) while *telemetry* comes from the
    side-band library (pkg/nvidia/nvml/instance.go:43-97).

    Capability set is whatever ``ListSupportedMetrics`` advertises at
    construction; each capability degrades independently (SURVEY §7).
    """

    def __init__(self, inner, client: Optional[RuntimeMetricsClient] = None,
                 probe_timeout: float = 1.0) -> None:
        self.inner = inner
        self.client = client or RuntimeMetricsClient(timeout=probe_timeout)
        self._supported: List[str] = []
        self._probe_error = ""
        try:
            self._supported = self.client.list_supported()
        except RuntimeMetricsError as e:
            self._probe_error = str(e)

    def available(self) -> bool:
        """True when the service answered and serves at least the HBM or
        duty-cycle core metrics — an empty capability set means this
        runtime gives us nothing the CLI/sysfs paths don't."""
        return any(m in self._supported for m in CORE_METRICS)

    def probe_error(self) -> str:
        return self._probe_error

    def supported_metrics(self) -> List[str]:
        return list(self._supported)

    # -- delegation to the enumeration backend -----------------------------
    def __getattr__(self, name: str):
        return getattr(self.inner, name)

    def shutdown(self) -> None:
        self.client.close()
        self.inner.shutdown()

    def is_mock(self) -> bool:
        return self.inner.is_mock()

    def telemetry_supported(self) -> bool:
        return self.available()

    def telemetry_source(self) -> str:
        return "runtime-metrics"

    def telemetry(self):
        from gpud_tpu.tpu.instance import TPUChipTelemetry

        chips = self.inner.devices()
        out: Dict[int, TPUChipTelemetry] = {
            cid: TPUChipTelemetry(chip_id=cid, hbm_total_bytes=c.hbm_total_bytes)
            for cid, c in chips.items()
        }

        def apply(metric_name: str, setter, fold: str = "sum") -> None:
            if metric_name not in self._supported:
                return
            try:
                samples = self.client.get_metric(metric_name)
            except RuntimeMetricsError as e:
                logger.warning("runtime metric %s failed: %s", metric_name, e)
                return
            for cid, value in _fold_to_chips(samples, sorted(out), fold).items():
                setter(out[cid], value)

        # HBM bytes/error counts sum across a chip's cores; percent
        # metrics take the max core (a chip is as busy as its busiest
        # core; summing would read 200%)
        apply(METRIC_HBM_USAGE,
              lambda t, v: setattr(t, "hbm_used_bytes", int(v)))
        apply(METRIC_HBM_TOTAL,
              lambda t, v: setattr(t, "hbm_total_bytes", int(v)))
        apply(METRIC_DUTY_CYCLE,
              lambda t, v: setattr(t, "duty_cycle_pct", float(v)), fold="max")
        apply(METRIC_TENSORCORE_UTIL,
              lambda t, v: setattr(t, "tensorcore_util_pct", float(v)), fold="max")

        def set_ecc(t, v) -> None:
            t.hbm_ecc_uncorrectable = int(v)
            if int(v) > 0:
                t.hbm_ecc_pending = True
        apply(METRIC_HBM_ECC_UNCORRECTABLE, set_ecc)
        return out

    # -- ICI: runtime counters when advertised, else inner's sysfs/derived -
    def _ici_metric_names(self) -> List[str]:
        return [n for n in self._supported if n in ICI_METRICS]

    def ici_source(self) -> str:
        if self._ici_metric_names():
            return "runtime-metrics"
        src = getattr(self.inner, "ici_source", None)
        return src() if callable(src) else ""

    def ici_supported(self) -> bool:
        return bool(self._ici_metric_names()) or self.inner.ici_supported()

    def ici_links(self):
        from gpud_tpu.tpu.instance import ICILinkSnapshot, LinkState

        names = self._ici_metric_names()
        if not names:
            return self.inner.ici_links()
        links: Dict[Tuple[int, int], ICILinkSnapshot] = {}
        for name in names:
            attr = ICI_METRICS[name]
            try:
                samples = self.client.get_metric(name)
            except RuntimeMetricsError as e:
                logger.warning("runtime ICI metric %s failed: %s", name, e)
                continue
            for s in samples:
                cid, lid = s.device_id, s.link_id
                if cid < 0 or lid < 0:
                    continue
                snap = links.setdefault(
                    (cid, lid), ICILinkSnapshot(chip_id=cid, link_id=lid)
                )
                if attr == "state":
                    snap.state = LinkState.UP if s.value else LinkState.DOWN
                else:
                    setattr(snap, attr, int(s.value))
        return [links[k] for k in sorted(links)]


def _fold_to_chips(samples: List[MetricSample], chip_ids: List[int],
                   fold: str = "sum") -> Dict[int, float]:
    """Map per-device samples onto chip ids.

    Direct id match when the runtime's device ids are the chip ids; rank
    order when counts line up but ids are shifted (global-vs-local
    numbering on multi-host slices); an even per-core fold otherwise
    (v2/v3 report per TensorCore: 2 cores/chip), combining core values
    per ``fold`` — "sum" for bytes/counts, "max" for percents."""
    combine = max if fold == "max" else (lambda a, b: a + b)
    by_dev: Dict[int, float] = {}
    for s in samples:
        d = s.device_id
        if d < 0:
            continue
        by_dev[d] = combine(by_dev[d], s.value) if d in by_dev else s.value
    if not by_dev or not chip_ids:
        return {}
    dev_ids = sorted(by_dev)
    if set(dev_ids) <= set(chip_ids):
        return {d: by_dev[d] for d in dev_ids}
    if len(dev_ids) == len(chip_ids):
        return {c: by_dev[d] for d, c in zip(dev_ids, chip_ids)}
    if len(dev_ids) % len(chip_ids) == 0:
        per = len(dev_ids) // len(chip_ids)
        out: Dict[int, float] = {}
        for i, cid in enumerate(chip_ids):
            group = dev_ids[i * per:(i + 1) * per]
            vals = [by_dev[d] for d in group]
            out[cid] = max(vals) if fold == "max" else sum(vals)
        return out
    logger.warning(
        "runtime metrics device ids %s don't map onto chips %s", dev_ids, chip_ids
    )
    return {}
