"""tpu-info CLI backend.

The ``tpu-info`` tool (Google's libtpu-backed CLI) is the closest thing to
``nvidia-smi`` on TPU VMs: it prints chip inventory, per-chip duty cycle,
HBM usage and TensorCore utilization. Output formats vary by version
(SURVEY §7 hard parts: "tpu-info output formats and libtpu metric APIs
vary by runtime version → isolate behind tpu.Instance with capability
flags"), so this parser is deliberately tolerant: it scans for the stable
tokens (/dev/accel paths, "GiB / GiB" pairs, percentages) rather than
fixed column offsets, and every capability degrades independently.

The runner is injectable so fixture outputs drive the tests without the
binary (reference test strategy: mock external binaries, e2e/mock/common.go).
"""

from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional

from gpud_tpu.log import get_logger
from gpud_tpu.process import RunResult, run_command
from gpud_tpu.tpu.instance import (
    SysfsICILinksMixin,
    TPUChip,
    TPUChipTelemetry,
    TPUInstance,
)
from gpud_tpu.tpu.topology import GENERATIONS, normalize_generation

logger = get_logger(__name__)

TPU_INFO_BIN = "tpu-info"
_GiB = 1024**3

# "/dev/accel0" or "/dev/vfio/0" device paths
_CHIP_ROW = re.compile(r"(?P<path>/dev/(?:accel|vfio/)\d+)", re.IGNORECASE)
# chip generation token appearing in the same row ("v4 chip", "v5e", ...)
_GEN_TOKEN = re.compile(r"\b(v\d+(?:e|p|litepod)?)\b", re.IGNORECASE)
# "1.23 GiB / 31.75 GiB" HBM usage pairs
_HBM_PAIR = re.compile(
    r"(?P<used>[\d.]+)\s*GiB\s*/\s*(?P<total>[\d.]+)\s*GiB", re.IGNORECASE
)
# "12.34%" utilization/duty-cycle cells
_PCT = re.compile(r"([\d.]+)\s*%")
_DEV_INDEX = re.compile(r"(\d+)$")


ENUMERATE_TIMEOUT = 30.0
# the telemetry path runs under the shared sampler lock every TTL (10s):
# a hung CLI must stall the TPU components for far less than that
TELEMETRY_TIMEOUT = 5.0


def default_runner(args: List[str], timeout: float = ENUMERATE_TIMEOUT) -> RunResult:
    return run_command([TPU_INFO_BIN] + args, timeout=timeout)


class TpuInfoBackend(SysfsICILinksMixin, TPUInstance):
    """Side-band enumeration + telemetry via the tpu-info CLI; ICI links
    ride the shared sysfs exposure (SysfsICILinksMixin) since the CLI
    prints no per-link interconnect state."""

    def __init__(
        self,
        accelerator_type: str = "",
        worker_id: int = 0,
        run_fn: Callable[[List[str]], RunResult] = default_runner,
    ) -> None:
        self._accel_type = accelerator_type
        self._worker_id = worker_id
        self.run_fn = run_fn
        self._init_error = ""
        self._chips: Dict[int, TPUChip] = {}
        self._enumerate()

    # -- parsing -----------------------------------------------------------
    def _enumerate(self) -> None:
        r = self.run_fn([])
        if r.exit_code != 0:
            self._init_error = (
                r.error or f"tpu-info exited {r.exit_code}: {r.output[:200]}"
            )
            return
        self._chips = self._parse_chips(r.output)
        if not self._chips:
            self._init_error = "tpu-info ran but no chips parsed"

    def _parse_chips(self, output: str) -> Dict[int, TPUChip]:
        chips: Dict[int, TPUChip] = {}
        gen = ""
        for ln in output.splitlines():
            m = _CHIP_ROW.search(ln)
            if not m or "/dev/" not in ln:
                continue
            path = m.group("path")
            idx_m = _DEV_INDEX.search(path)
            if not idx_m:
                continue
            cid = int(idx_m.group(1))
            gen_m = _GEN_TOKEN.search(ln.replace(path, ""))
            if gen_m:
                gen = normalize_generation(gen_m.group(1)) or gen
            spec = GENERATIONS.get(gen)
            chips[cid] = TPUChip(
                chip_id=cid,
                device_path=path,
                generation=gen,
                cores=spec.cores_per_chip if spec else 1,
                hbm_total_bytes=spec.hbm_bytes_per_chip if spec else 0,
            )
        if chips and not self._accel_type and gen:
            spec = GENERATIONS.get(gen)
            if spec is not None:
                n = len(chips)
                count = n if spec.suffix_counts_chips else n * spec.cores_per_chip
                self._accel_type = f"{gen}-{count}"
        return chips

    def _parse_telemetry(self, output: str) -> Dict[int, TPUChipTelemetry]:
        """Best-effort: associate HBM pairs and percentages with chips in
        row order within the usage/utilization tables."""
        out: Dict[int, TPUChipTelemetry] = {
            cid: TPUChipTelemetry(
                chip_id=cid, hbm_total_bytes=c.hbm_total_bytes
            )
            for cid, c in self._chips.items()
        }
        ordered = sorted(out)
        hbm_i = 0
        for ln in output.splitlines():
            pair = _HBM_PAIR.search(ln)
            if pair is None:
                continue
            # key by the row's Device index when present (the utilization
            # table may be a subset or reordered); fall back to row order
            head = ln[: pair.start()]
            dev_m = re.search(r"(?<![\d/.])(\d+)(?![\d%])", head)
            if dev_m and int(dev_m.group(1)) in out:
                cid = int(dev_m.group(1))
            elif hbm_i < len(ordered):
                cid = ordered[hbm_i]
            else:
                continue
            tel = out[cid]
            tel.hbm_used_bytes = int(float(pair.group("used")) * _GiB)
            tel.hbm_total_bytes = int(float(pair.group("total")) * _GiB)
            # the duty-cycle % sits on the same row, after the memory pair
            pcts = _PCT.findall(ln[pair.end():])
            if pcts:
                tel.duty_cycle_pct = float(pcts[0])
                if len(pcts) > 1:
                    tel.tensorcore_util_pct = float(pcts[1])
            hbm_i += 1
        return out

    # -- TPUInstance surface ----------------------------------------------
    def tpu_lib_exists(self) -> bool:
        return bool(self._chips)

    def init_error(self) -> str:
        return self._init_error

    def product_name(self) -> str:
        t = self.topology()
        return f"TPU {t.generation}" if t else "TPU"

    def accelerator_type(self) -> str:
        return self._accel_type

    def worker_id(self) -> int:
        return self._worker_id

    def devices(self) -> Dict[int, TPUChip]:
        return dict(self._chips)

    def telemetry_supported(self) -> bool:
        return bool(self._chips)

    def telemetry_source(self) -> str:
        return "cli"

    def telemetry(self) -> Dict[int, TPUChipTelemetry]:
        try:
            r = self.run_fn([], timeout=TELEMETRY_TIMEOUT)
        except TypeError:  # injected runner without a timeout parameter
            r = self.run_fn([])
        if r.exit_code != 0:
            logger.warning("tpu-info telemetry read failed: %s", r.error or r.exit_code)
            return {}
        return self._parse_telemetry(r.output)


def tpu_info_available() -> bool:
    import shutil

    return shutil.which(TPU_INFO_BIN) is not None
