"""Unified check scheduler: one deadline-min-heap thread + a bounded
worker pool owning every periodic job in the daemon (docs/scheduler.md)."""

from gpud_tpu.scheduler.core import Job, Scheduler

__all__ = ["Job", "Scheduler"]
