"""Deadline-heap scheduler + bounded worker pool.

One scheduler thread owns a min-heap of (due, job) deadlines for every
periodic job in the daemon — component polls, the metrics scraper and
recorder, retention purges, the remediation scan, the update watcher —
and dispatches due jobs to a small fixed pool of worker threads (default
4). This replaces the one-thread-per-poller shape the Go reference gets
for free from goroutines: in CPython each poller thread costs a stack
plus periodic GIL wakeups, and the count grows linearly with every new
component (BENCH_r05 measured ~26 steady-state threads).

Semantics preserved from the per-thread pollers:

- ``poke(name)`` jumps a job to the front of the heap (or re-runs it
  immediately after the in-flight run finishes);
- the job's interval callable is re-read after EVERY run, so adaptive
  cadences (the ICI component's fast-poll-on-suspicion window) keep
  working;
- first runs happen on the pool, never on the caller of ``start()`` — a
  hung data source cannot wedge daemon startup, and first checks run in
  parallel across the pool instead of 26 sequential-ish thread spawns;
- a job never overlaps itself: the next deadline is computed only after
  the current run returns.

New capabilities per-thread pollers could not have:

- deterministic ±jitter per cadence (keyed on the job name, stable
  across restarts) de-synchronizes the 60s thundering herd;
- a watchdog: a job running past its hang budget fires ``on_hang`` (the
  component marks itself Degraded-stale), the wedged worker thread is
  abandoned as a sacrificial thread, and a replacement worker is spawned
  so the pool keeps draining at full capacity;
- scheduler self-metrics: ready-queue depth, dispatch-lag histogram,
  pool saturation, watchdog fires, startup readiness
  (``tpud_scheduler_*``, docs/observability.md).
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
import zlib
from collections import deque
from typing import Callable, Dict, List, Optional

from gpud_tpu.log import get_logger
from gpud_tpu.metrics.registry import counter, gauge, histogram

logger = get_logger(__name__)

DEFAULT_WORKERS = 4
DEFAULT_HANG_TIMEOUT = 120.0   # a 60s-cadence check running 2 min is wedged
DEFAULT_JITTER_FRACTION = 0.05  # ±5% of the interval
_LAG_SAMPLES = 512              # ring of recent dispatch lags for stats()

_g_jobs = gauge(
    "tpud_scheduler_jobs", "periodic jobs currently registered"
)
_g_queue_depth = gauge(
    "tpud_scheduler_ready_queue_depth",
    "jobs dispatched and waiting for a free worker",
)
_g_workers = gauge(
    "tpud_scheduler_workers", "worker threads in the pool (grows by one "
    "per sacrificial thread while a hung job is in flight)"
)
_g_workers_busy = gauge(
    "tpud_scheduler_workers_busy", "worker threads currently running a job"
)
_g_startup_ready = gauge(
    "tpud_scheduler_startup_ready_seconds",
    "time from scheduler start to every initial job's first completed run",
)
_h_dispatch_lag = histogram(
    "tpud_scheduler_dispatch_lag_seconds",
    "delay between a job's deadline and a worker picking it up",
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0),
)
_c_runs = counter(
    "tpud_scheduler_job_runs_total", "completed job runs, by job"
)
_c_failures = counter(
    "tpud_scheduler_job_failures_total",
    "job runs that raised, by job (the run is rescheduled regardless)",
)
_c_watchdog = counter(
    "tpud_scheduler_watchdog_fires_total",
    "watchdog fires (job exceeded its hang budget), by job",
)
_c_saturation = counter(
    "tpud_scheduler_pool_saturation_total",
    "dispatches that found every worker busy (job had to queue)",
)


class Job:
    """One periodic (or one-shot) unit of scheduled work.

    ``interval_fn`` is consulted after every completed run, so adaptive
    cadences take effect on the very next deadline. ``hang_timeout``
    seconds of a single run elapsing fires ``on_hang(elapsed)`` once and
    sacrifices the worker; 0 disables the watchdog for this job.
    """

    __slots__ = (
        "name", "fn", "interval_fn", "on_hang", "hang_timeout", "one_shot",
        "jitter_fraction",
        # scheduler-owned state (all mutated under the scheduler lock,
        # except run_started/runs reads for stats which tolerate tearing)
        "gen", "due", "queued", "running", "run_started", "runs", "failures",
        "poked", "cancelled", "hang_fired", "worker", "startup", "_sched",
    )

    def __init__(
        self,
        name: str,
        fn: Callable[[], None],
        interval_fn: Callable[[], float],
        on_hang: Optional[Callable[[float], None]] = None,
        hang_timeout: float = DEFAULT_HANG_TIMEOUT,
        one_shot: bool = False,
        jitter_fraction: Optional[float] = None,
    ) -> None:
        self.name = name
        self.fn = fn
        self.interval_fn = interval_fn
        self.on_hang = on_hang
        self.hang_timeout = hang_timeout
        self.one_shot = one_shot
        self.jitter_fraction = jitter_fraction
        self.gen = 0
        self.due = 0.0
        self.queued = False
        self.running = False
        self.run_started = 0.0
        self.runs = 0
        self.failures = 0
        self.poked = False
        self.cancelled = False
        self.hang_fired = False
        self.startup = False  # counts toward startup readiness (see add_job)
        self.worker: Optional[threading.Thread] = None
        self._sched: Optional["Scheduler"] = None

    def cancel(self) -> None:
        if self._sched is not None:
            self._sched.cancel(self.name)

    def poke(self) -> None:
        if self._sched is not None:
            self._sched.poke(self.name)


class Scheduler:
    """The deadline-heap scheduler (see module docstring).

    Lifecycle: construct → ``add_job`` (any time) → ``start`` → ``close``.
    Jobs added before ``start`` form the startup-readiness set: once each
    has completed its first run, ``startup_ready_seconds`` is recorded and
    ``wait_first_runs`` returns. All public methods are thread-safe.
    """

    # _cv wraps _mu (RLock), so `with self._cv` IS the mutex; _thread is
    # written once under start() and joined in close() after _stopped
    # flips — deliberately unguarded, as are the itertools counters
    # (_seq/_worker_seq are internally thread-safe)
    GUARDED_BY = {
        "_heap": "_cv",
        "_jobs": "_cv",
        "_ready": "_cv",
        "_workers": "_cv",
        "_abandoned": "_cv",
        "_busy": "_cv",
        "_stopped": "_cv",
        "_started": "_cv",
        "_startup_pending": "_cv",
        "_startup_t0": "_cv",
        "_startup_ready_seconds": "_cv",
        "_lag_samples": "_cv",
    }
    _LOCK_FREE = {
        "_push": "internal heap insert; every caller (add_job, submit, "
                 "poke, _run) already holds _cv",
        "_startup_discard": "internal readiness bookkeeping; callers "
                            "cancel()/_run hold _cv",
        "_check_watchdogs": "called only from _run's scan, under _cv",
    }

    def __init__(
        self,
        workers: int = DEFAULT_WORKERS,
        hang_timeout: float = DEFAULT_HANG_TIMEOUT,
        jitter_fraction: float = DEFAULT_JITTER_FRACTION,
    ) -> None:
        self.default_hang_timeout = float(hang_timeout)
        self.jitter_fraction = float(jitter_fraction)
        self._target_workers = max(1, int(workers))
        self._mu = threading.RLock()
        self._cv = threading.Condition(self._mu)
        self._heap: List[tuple] = []  # (due, seq, gen, job)
        self._seq = itertools.count()
        self._jobs: Dict[str, Job] = {}
        self._ready: deque = deque()
        self._workers: List[threading.Thread] = []
        self._abandoned: set = set()
        self._busy = 0
        self._stopped = False
        self._started = False
        self._thread: Optional[threading.Thread] = None
        self._worker_seq = itertools.count()
        self._lag_samples: deque = deque(maxlen=_LAG_SAMPLES)
        self._startup_pending: Optional[set] = None
        self._startup_t0 = 0.0
        self._startup_ready_seconds: Optional[float] = None
        self.time_fn: Callable[[], float] = time.monotonic

    # -- job management ----------------------------------------------------
    def add_job(
        self,
        name: str,
        fn: Callable[[], None],
        interval: Optional[float] = None,
        interval_fn: Optional[Callable[[], float]] = None,
        initial_delay: float = 0.0,
        on_hang: Optional[Callable[[float], None]] = None,
        hang_timeout: Optional[float] = None,
        jitter: bool = True,
    ) -> Job:
        """Register a periodic job. Exactly one of ``interval`` /
        ``interval_fn`` must be given; the callable form is re-read after
        every run (adaptive cadences). ``initial_delay=0`` puts the first
        run at the front of the heap immediately — the startup-readiness
        path."""
        if (interval is None) == (interval_fn is None):
            raise ValueError(f"job {name}: give interval OR interval_fn")
        ifn = interval_fn if interval_fn is not None else (lambda: float(interval))
        job = Job(
            name,
            fn,
            ifn,
            on_hang=on_hang,
            hang_timeout=(
                self.default_hang_timeout if hang_timeout is None
                else float(hang_timeout)
            ),
            jitter_fraction=None if jitter else 0.0,
        )
        # only jobs whose first run is immediate belong to the startup
        # readiness set — a deferred first run (initial_delay=interval,
        # e.g. the metrics scraper skipping the noisy boot sample) is a
        # deliberate "not needed for readiness" statement
        job.startup = initial_delay <= 0.0
        with self._cv:
            if name in self._jobs:
                raise ValueError(f"job already scheduled: {name}")
            job._sched = self
            self._jobs[name] = job
            self._push(job, self.time_fn() + max(0.0, initial_delay))
            _g_jobs.set(len(self._jobs))
            self._cv.notify_all()
        return job

    def submit(
        self,
        name: str,
        fn: Callable[[], None],
        hang_timeout: Optional[float] = None,
    ) -> Optional[Job]:
        """One-shot: run ``fn`` on the pool as soon as a worker frees up.
        Used for event-triggered async work (session gossip/diagnostic
        collection) so ad-hoc daemon threads stop accumulating. Returns
        None (work refused) after close(). A name collision with a live
        job gets a unique suffix — one-shots are fire-and-forget."""
        with self._cv:
            if self._stopped:
                return None
            if name in self._jobs:
                name = f"{name}#{next(self._seq)}"
            job = Job(
                name,
                fn,
                lambda: 0.0,
                hang_timeout=(
                    self.default_hang_timeout if hang_timeout is None
                    else float(hang_timeout)
                ),
                one_shot=True,
            )
            job._sched = self
            self._jobs[name] = job
            self._push(job, self.time_fn())
            _g_jobs.set(len(self._jobs))
            self._cv.notify_all()
        return job

    def cancel(self, name: str) -> bool:
        with self._cv:
            job = self._jobs.pop(name, None)
            if job is None:
                return False
            job.cancelled = True
            if job.queued:
                try:
                    self._ready.remove(job)
                except ValueError:
                    pass
                job.queued = False
                _g_queue_depth.set(len(self._ready))
            self._startup_discard(job)
            _g_jobs.set(len(self._jobs))
            self._cv.notify_all()
        return True

    def poke(self, name: str) -> bool:
        """Jump a job to the front: run it now if idle, or immediately
        again after the in-flight run finishes."""
        with self._cv:
            job = self._jobs.get(name)
            if job is None:
                return False
            if job.running or job.queued:
                job.poked = True
            else:
                self._push(job, self.time_fn())
            self._cv.notify_all()
        return True

    def get_job(self, name: str) -> Optional[Job]:
        with self._cv:
            return self._jobs.get(name)

    def job_names(self) -> List[str]:
        with self._cv:
            return sorted(self._jobs)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        with self._cv:
            if self._started or self._stopped:
                return
            self._started = True
            self._startup_t0 = self.time_fn()
            self._startup_pending = {
                j.name for j in self._jobs.values()
                if j.startup and j.runs == 0
            }
            if not self._startup_pending:
                self._startup_done_locked()
            for _ in range(self._target_workers):
                self._spawn_worker_locked()
            self._thread = threading.Thread(
                target=self._run, name="tpud-scheduler", daemon=True
            )
            self._thread.start()

    def close(self) -> None:
        with self._cv:
            if self._stopped:
                return
            self._stopped = True
            self._cv.notify_all()
            workers = list(self._workers)
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        for w in workers:
            if w is not threading.current_thread():
                w.join(timeout=2.0)  # wedged sacrificial threads are daemons

    # -- readiness ---------------------------------------------------------
    def wait_first_runs(self, timeout: float = 30.0) -> Optional[float]:
        """Block until every job registered before ``start()`` has
        completed its first run; returns the elapsed startup-readiness
        seconds, or None on timeout/close."""
        with self._cv:
            self._cv.wait_for(
                lambda: self._startup_ready_seconds is not None or self._stopped,
                timeout,
            )
            return self._startup_ready_seconds

    @property
    def startup_ready_seconds(self) -> Optional[float]:
        with self._cv:
            return self._startup_ready_seconds

    # -- introspection -----------------------------------------------------
    def stats(self) -> Dict:
        with self._cv:
            lags = sorted(self._lag_samples)
            p95 = lags[int(0.95 * (len(lags) - 1))] if lags else 0.0
            return {
                "jobs": len(self._jobs),
                "ready_queue_depth": len(self._ready),
                "workers": len(self._workers),
                "workers_busy": self._busy,
                "dispatch_lag_p95_seconds": p95,
                "startup_ready_seconds": self._startup_ready_seconds,
                "running": sorted(
                    j.name for j in self._jobs.values() if j.running
                ),
            }

    # -- internals (all called under self._cv unless noted) ----------------
    def _push(self, job: Job, due: float) -> None:
        job.gen += 1
        job.due = due
        heapq.heappush(self._heap, (due, next(self._seq), job.gen, job))

    def _jittered(self, job: Job, interval: float) -> float:
        """Deterministic per-job cadence offset: crc32 of the name maps to
        a stable fraction in [-1, 1], scaled by the jitter fraction — the
        fleet's 60s pollers spread out instead of herding, identically
        across restarts (no RNG: a flappy cadence would defeat dashboards
        that align on scrape phase)."""
        frac = job.jitter_fraction
        if frac is None:
            frac = self.jitter_fraction
        if interval <= 0 or frac <= 0:
            return max(0.0, interval)
        unit = (zlib.crc32(job.name.encode()) % 2001 - 1000) / 1000.0
        return max(0.0, interval * (1.0 + frac * unit))

    def _startup_discard(self, job: Job) -> None:
        if self._startup_pending is None:
            return
        self._startup_pending.discard(job.name)
        if not self._startup_pending:
            self._startup_done_locked()

    def _startup_done_locked(self) -> None:
        if self._startup_ready_seconds is None:
            self._startup_pending = set()
            self._startup_ready_seconds = max(
                0.0, self.time_fn() - self._startup_t0
            )
            _g_startup_ready.set(self._startup_ready_seconds)
            self._cv.notify_all()

    def _spawn_worker_locked(self) -> None:
        t = threading.Thread(
            target=self._worker,
            name=f"tpud-sched-worker-{next(self._worker_seq)}",
            daemon=True,
        )
        self._workers.append(t)
        _g_workers.set(len(self._workers))
        t.start()

    # -- scheduler thread --------------------------------------------------
    def _run(self) -> None:
        while True:
            hang_cbs = []
            with self._cv:
                if self._stopped:
                    return
                now = self.time_fn()
                next_wd = self._check_watchdogs(now, hang_cbs)
                while self._heap and self._heap[0][0] <= now:
                    _due, _seq, gen, job = heapq.heappop(self._heap)
                    if (
                        job.cancelled or gen != job.gen
                        or job.queued or job.running
                    ):
                        continue  # stale heap entry (poked/cancelled/rescheduled)
                    job.queued = True
                    # saturation = this job cannot start immediately: every
                    # worker is either busy or spoken for by jobs already
                    # queued ahead of it (at dispatch time workers may not
                    # have woken yet, so _busy alone undercounts)
                    if self._busy + len(self._ready) >= len(self._workers):
                        _c_saturation.inc()
                    self._ready.append(job)
                    _g_queue_depth.set(len(self._ready))
                    self._cv.notify_all()
                timeout = None
                if self._heap:
                    timeout = self._heap[0][0] - now
                if next_wd is not None:
                    wd_in = next_wd - now
                    timeout = wd_in if timeout is None else min(timeout, wd_in)
                if timeout is None:
                    timeout = 5.0
                # cap: a poke/add lands via notify, but a clamped wait
                # bounds the damage of any missed-wakeup bug; 5s keeps the
                # idle wakeup cost negligible (vs 26 threads × cadence).
                # Skip the wait entirely when a watchdog just fired — its
                # callback must run NOW, not after the next wakeup.
                if not hang_cbs:
                    self._cv.wait(min(max(timeout, 0.0), 5.0))
            for cb, name, elapsed in hang_cbs:
                try:
                    cb(elapsed)
                except Exception:  # noqa: BLE001 — a stale-marker bug must
                    logger.exception("on_hang for %s failed", name)  # not kill the loop

    def _check_watchdogs(self, now: float, hang_cbs: list) -> Optional[float]:
        """Fire due watchdogs; returns the next watchdog deadline."""
        next_wd = None
        for job in self._jobs.values():
            if not job.running or job.hang_fired or job.hang_timeout <= 0:
                continue
            deadline = job.run_started + job.hang_timeout
            if deadline <= now:
                job.hang_fired = True
                elapsed = now - job.run_started
                _c_watchdog.inc(labels={"job": job.name})
                logger.warning(
                    "watchdog: job %s running %.1fs (budget %.1fs); "
                    "sacrificing its worker and reclaiming the slot",
                    job.name, elapsed, job.hang_timeout,
                )
                if job.worker is not None:
                    self._abandoned.add(job.worker)
                    self._spawn_worker_locked()
                if job.on_hang is not None:
                    hang_cbs.append((job.on_hang, job.name, elapsed))
            elif next_wd is None or deadline < next_wd:
                next_wd = deadline
        return next_wd

    # -- worker threads ----------------------------------------------------
    def _worker(self) -> None:
        me = threading.current_thread()
        while True:
            with self._cv:
                while not self._ready and not self._stopped:
                    if me in self._abandoned:
                        break
                    self._cv.wait()
                if self._stopped or (me in self._abandoned and not self._ready):
                    self._retire_locked(me)
                    return
                job = self._ready.popleft()
                _g_queue_depth.set(len(self._ready))
                job.queued = False
                job.running = True
                job.hang_fired = False
                job.worker = me
                job.run_started = self.time_fn()
                if job.hang_timeout > 0:
                    # the scheduler may be mid-wait with no watchdog armed;
                    # wake it so it recomputes its sleep against this run's
                    # hang deadline (else a short budget fires only at the
                    # next periodic wakeup)
                    self._cv.notify_all()
                lag = max(0.0, job.run_started - job.due)
                _h_dispatch_lag.observe(lag)
                self._lag_samples.append(lag)
                self._busy += 1
                _g_workers_busy.set(self._busy)
            try:
                job.fn()
            except Exception:  # noqa: BLE001 — a failing job is rescheduled
                job.failures += 1
                _c_failures.inc(labels={"job": job.name})
                logger.exception("scheduled job %s failed", job.name)
            _c_runs.inc(labels={"job": job.name})
            with self._cv:
                self._finish_locked(job)
                if me in self._abandoned:
                    # sacrificial thread: the pool already got a
                    # replacement the moment the watchdog fired; this
                    # thread's only remaining duty was to reschedule the
                    # formerly-hung job, done above
                    self._retire_locked(me)
                    return
                if self._stopped:
                    self._retire_locked(me)
                    return

    def _retire_locked(self, me: threading.Thread) -> None:
        self._abandoned.discard(me)
        try:
            self._workers.remove(me)
        except ValueError:
            pass
        _g_workers.set(len(self._workers))

    def _finish_locked(self, job: Job) -> None:
        job.running = False
        job.worker = None
        job.runs += 1
        self._busy -= 1
        _g_workers_busy.set(self._busy)
        self._startup_discard(job)
        if job.one_shot or job.cancelled:
            if self._jobs.get(job.name) is job:
                del self._jobs[job.name]
            _g_jobs.set(len(self._jobs))
            self._cv.notify_all()
            return
        now = self.time_fn()
        if job.poked:
            job.poked = False
            due = now
        else:
            try:
                interval = float(job.interval_fn())  # re-read: adaptive
            except Exception:  # noqa: BLE001
                logger.exception("interval_fn for %s failed", job.name)
                interval = 60.0
            due = now + self._jittered(job, interval)
        self._push(job, due)
        self._cv.notify_all()
