"""Control-plane registration.

Reference: pkg/login/login.go:157 ``Login`` — builds a LoginRequest with
machine info + provider + location (pkg/machine-info/login_request.go:17-158),
POSTs it to the control plane, persists machineID/token/machineProof to the
metadata table. Machine-id overwrite semantics (login.go:28-71): a
control-plane-assigned machine id replaces the local one so re-imaged
nodes keep their fleet identity. Node labels get the
``user.node.tpud.dev/`` prefix normalization (reference: node_labels.go,
``user.node.lepton.ai/``).
"""

from __future__ import annotations

import json
import time
from typing import Dict, Optional

from gpud_tpu import machine_info as machineinfo
from gpud_tpu.api.v1.types import LoginRequest, LoginResponse
from gpud_tpu.log import audit, get_logger
from gpud_tpu.metadata import (
    KEY_ENDPOINT,
    KEY_LOGIN_SUCCESS_TS,
    KEY_MACHINE_ID,
    KEY_MACHINE_PROOF,
    KEY_NODE_LABELS,
    KEY_PRIVATE_IP,
    KEY_PUBLIC_IP,
    KEY_TOKEN,
    normalize_endpoint,
    Metadata,
)

logger = get_logger(__name__)

NODE_LABEL_PREFIX = "user.node.tpud.dev/"
LOGIN_TIMEOUT = 30.0


def normalize_node_labels(labels: Dict[str, str]) -> Dict[str, str]:
    """Reference: pkg/login/node_labels.go — user labels are namespaced."""
    out = {}
    for k, v in labels.items():
        if not k.startswith(NODE_LABEL_PREFIX):
            k = NODE_LABEL_PREFIX + k
        out[k] = v
    return out


def login(
    endpoint: str,
    token: str,
    metadata: Metadata,
    tpu_instance=None,
    node_labels: Optional[Dict[str, str]] = None,
    provider: str = "",
    region: str = "",
    public_ip: str = "",
    private_ip: str = "",
    post_fn=None,
) -> LoginResponse:
    """POST /api/v1/login; persist identity on success. ``post_fn`` is
    injectable for tests (reference pattern: session.go:262-296)."""
    machine_id = metadata.machine_id() or ""
    req = LoginRequest(
        token=token,
        machine_id=machine_id,
        network={"public_ip": public_ip, "private_ip": private_ip},
        machine_info=machineinfo.get_machine_info(
            tpu=tpu_instance,
            machine_id=machine_id,
            provider=provider,
            region=region,
            public_ip=public_ip,
            private_ip=private_ip,
        ),
        node_labels=normalize_node_labels(node_labels or {}),
        provider=provider,
        region=region,
    )

    if post_fn is None:
        def post_fn(url, body):  # noqa: ANN001
            import requests

            r = requests.post(url, json=body, timeout=LOGIN_TIMEOUT)
            r.raise_for_status()
            return r.json()

    endpoint = normalize_endpoint(endpoint)
    url = endpoint + "/api/v1/login"
    body = post_fn(url, req.to_dict())
    resp = LoginResponse.from_dict(body)
    if resp.error:
        raise RuntimeError(f"login rejected: {resp.error}")

    # persist identity (reference: login.go:28-71 overwrite semantics) in
    # ONE transaction — a crash mid-login must not leave a token paired
    # with a stale endpoint
    identity = {KEY_TOKEN: resp.token or token, KEY_ENDPOINT: endpoint}
    if resp.machine_id:
        identity[KEY_MACHINE_ID] = resp.machine_id
    if resp.machine_proof:
        identity[KEY_MACHINE_PROOF] = resp.machine_proof
    if node_labels:
        identity[KEY_NODE_LABELS] = json.dumps(normalize_node_labels(node_labels))
    if public_ip:
        identity[KEY_PUBLIC_IP] = public_ip
    if private_ip:
        identity[KEY_PRIVATE_IP] = private_ip
    identity[KEY_LOGIN_SUCCESS_TS] = str(time.time())
    metadata.set_many(identity)
    audit("login", endpoint=endpoint, machine_id=resp.machine_id or machine_id)
    logger.info("logged in to %s as %s", endpoint, resp.machine_id or machine_id)
    return resp
