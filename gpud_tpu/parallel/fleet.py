"""Fleet-scale sharded analytics over a device mesh.

The daemon's multi-chip compute path: per-chip/per-link telemetry arrays
are sharded over a ``jax.sharding.Mesh`` and the scan/score/train programs
run SPMD with XLA-inserted collectives (psum over ICI) — the scaling-book
recipe: pick a mesh, annotate shardings, let XLA insert collectives.

Axes:
- ``data``  — fleet/batch axis: chips, links, or telemetry windows.
- ``model`` — tensor-parallel axis for the autoencoder's hidden dim.

The reference daemon has no compute of its own (SURVEY §2.8: monitoring,
not collectives); this module exists because on TPU the natural place to
scan pod-scale ICI/telemetry history is the pod itself.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gpud_tpu.models.anomaly import (
    AEConfig,
    AEParams,
    ae_init,
    ae_loss,
    ae_scores,
    robust_scores,
)
from gpud_tpu.ops.window_scan import WindowScan, classify_links, scan_links


def make_mesh(
    n_devices: Optional[int] = None, model_parallel: int = 1
) -> Mesh:
    """Mesh over the first n devices with (data, model) axes. ``model_parallel``
    must divide n."""
    devs = jax.devices()[: n_devices or len(jax.devices())]
    n = len(devs)
    if n % model_parallel:
        raise ValueError(f"model_parallel={model_parallel} does not divide {n}")
    import numpy as np

    arr = np.array(devs).reshape(n // model_parallel, model_parallel)
    return Mesh(arr, axis_names=("data", "model"))


# ---------------------------------------------------------------------------
# sharded link scan
# ---------------------------------------------------------------------------

def sharded_link_scan(
    mesh: Mesh,
    states,
    counters,
    valid,
    flap_threshold: int = 3,
    crc_threshold: int = 100,
) -> Tuple[WindowScan, jax.Array]:
    """Scan [L, T] link history sharded along L over the ``data`` axis.
    Each device scans its shard independently (no cross-link deps), so the
    only communication is the final gather of per-link classes."""
    link_sharding = NamedSharding(mesh, P("data", None))
    states = jax.device_put(states, link_sharding)
    counters = jax.device_put(counters, link_sharding)
    valid = jax.device_put(valid, link_sharding)
    scan = scan_links(states, counters, valid)
    classes = classify_links(
        scan, flap_threshold=flap_threshold, crc_threshold=crc_threshold
    )
    return scan, classes


def fleet_health_summary(mesh: Mesh, classes: jax.Array) -> dict:
    """Global counts per health class — a psum-style full reduction that
    XLA lowers onto ICI allreduce."""

    @jax.jit
    def _summarize(c):
        return jnp.stack(
            [
                jnp.sum(c == 0),
                jnp.sum(c == 1),
                jnp.sum(c == 2),
            ]
        )

    healthy, degraded, unhealthy = [int(x) for x in _summarize(classes)]
    return {"healthy": healthy, "degraded": degraded, "unhealthy": unhealthy}


# ---------------------------------------------------------------------------
# sharded anomaly scoring + autoencoder training
# ---------------------------------------------------------------------------

def sharded_robust_scores(mesh: Mesh, windows) -> jax.Array:
    """[C, T, F] chip windows sharded along chips."""
    sharding = NamedSharding(mesh, P("data", None, None))
    windows = jax.device_put(windows, sharding)
    return robust_scores(windows)


def ae_param_sharding(mesh: Mesh) -> AEParams:
    """Tensor-parallel layout: hidden dimension split over ``model``
    (column-parallel encoder, row-parallel decoder — XLA inserts the
    reduce-scatter/all-gather pair from these annotations)."""
    s = lambda *spec: NamedSharding(mesh, P(*spec))  # noqa: E731
    return AEParams(
        w_enc=s(None, "model"),
        b_enc=s("model"),
        w_lat=s("model", None),
        b_lat=s(None),
        w_dec1=s(None, "model"),
        b_dec1=s("model"),
        w_dec2=s("model", None),
        b_dec2=s(None),
    )


def make_sharded_train_step(mesh: Mesh, lr: float = 1e-3):
    """jit-compiled dp+tp training step: batch over ``data``, hidden over
    ``model``. Gradient averaging across data shards is XLA-inserted."""
    batch_sharding = NamedSharding(mesh, P("data", None))
    param_shardings = ae_param_sharding(mesh)

    @functools.partial(
        jax.jit,
        in_shardings=(param_shardings, batch_sharding),
        out_shardings=(param_shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )
    def step(params: AEParams, batch: jax.Array):
        loss, grads = jax.value_and_grad(ae_loss)(params, batch)
        new_params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return new_params, loss

    return step


def init_sharded_params(mesh: Mesh, cfg: AEConfig, seed: int = 0) -> AEParams:
    params = ae_init(jax.random.PRNGKey(seed), cfg)
    shardings = ae_param_sharding(mesh)
    return jax.tree_util.tree_map(jax.device_put, params, shardings)


def sharded_ae_scores(mesh: Mesh, params: AEParams, batch) -> jax.Array:
    batch = jax.device_put(batch, NamedSharding(mesh, P("data", None)))
    return ae_scores(params, batch)
