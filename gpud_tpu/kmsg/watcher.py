"""Kernel message (/dev/kmsg) reader.

Reference: pkg/kmsg/watcher.go — ``ReadAll`` non-follow mode (86-187),
``NewWatcher`` follow mode (190-290), line parser extracting priority/
sequence/µs-from-boot (292-332), env override ``KMSG_FILE_PATH``
(watcher.go:46; here ``TPUD_KMSG_FILE_PATH``).

The /dev/kmsg record format is::

    <priority>,<seq>,<usec_from_boot>,<flags>[,...];<message>
     KEY=value   (continuation lines, ignored here)

Follow mode uses non-blocking reads + poll so the watcher thread can stop
promptly, and works both on the real char device and on regular fixture
files (tail -f semantics) so tests and fault injection run without root
(SURVEY §4.4 fixture-directory pattern).
"""

from __future__ import annotations

import errno
import os
import select
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from gpud_tpu.inotify import InotifyWatch as _InotifyWatch
from gpud_tpu.log import get_logger

logger = get_logger(__name__)

# native fast path (optional)
try:
    from gpud_tpu.native import available as _native_available, parse_kmsg

    _native_parse = parse_kmsg if _native_available() else None
except Exception:  # noqa: BLE001 — native is never required
    _native_parse = None

DEFAULT_KMSG_PATH = "/dev/kmsg"
ENV_KMSG_PATH = "TPUD_KMSG_FILE_PATH"


def kmsg_path(override: str = "") -> str:
    return override or os.environ.get(ENV_KMSG_PATH, "") or DEFAULT_KMSG_PATH


def boot_time() -> float:
    """Unix seconds at boot (0.0 when /proc/uptime is unreadable — callers
    branch on >0). Delegates to the host package's uptime reader."""
    from gpud_tpu import host as pkghost

    up = pkghost.uptime_seconds()
    return time.time() - up if up > 0 else 0.0


@dataclass
class Message:
    """One parsed kmsg record (reference: watcher.go:292-332)."""

    priority: int = 0          # syslog priority (0-7), prefix & 7
    facility: int = 0          # prefix >> 3
    sequence: int = 0
    timestamp_us: int = 0      # microseconds since boot
    message: str = ""
    time: float = 0.0          # absolute unix seconds (derived)
    raw: str = field(default="", repr=False)

    @property
    def priority_name(self) -> str:
        names = ("emerg", "alert", "crit", "err", "warning", "notice", "info", "debug")
        return names[self.priority] if 0 <= self.priority < 8 else str(self.priority)


def parse_line(line: str, boot_unix: float = 0.0) -> Optional[Message]:
    """Parse one /dev/kmsg record line; None for continuation/garbage lines.

    Uses the native C++ parser when built (native/tpud_native.cpp, loaded
    via gpud_tpu.native); the Python path below is the reference
    implementation and the fallback.
    """
    if not line or line.startswith(" "):
        return None
    line = line.rstrip("\n")
    if _native_parse is not None:
        parsed = _native_parse(line)
        if parsed is None:
            return None
        prio, fac, seq, ts_us, msg = parsed
        m = Message(
            priority=prio, facility=fac, sequence=seq,
            timestamp_us=ts_us, message=msg, raw=line,
        )
        m.time = boot_unix + ts_us / 1e6 if boot_unix > 0 else time.time()
        return m
    head, sep, msg = line.partition(";")
    if not sep:
        return None
    parts = head.split(",")
    if len(parts) < 3:
        return None
    try:
        prefix = int(parts[0])
        seq = int(parts[1])
        ts_us = int(parts[2])
    except ValueError:
        return None
    m = Message(
        priority=prefix & 7,
        facility=prefix >> 3,
        sequence=seq,
        timestamp_us=ts_us,
        message=msg,
        raw=line,
    )
    if boot_unix > 0:
        m.time = boot_unix + ts_us / 1e6
    else:
        m.time = time.time()
    return m


def read_all(path: str = "", limit: int = 0) -> List[Message]:
    """Non-follow read of the whole ring buffer / fixture file
    (reference: watcher.go:86-187 ReadAll). Used by scan mode."""
    p = kmsg_path(path)
    out: List[Message] = []
    bt = boot_time()
    try:
        fd = os.open(p, os.O_RDONLY | os.O_NONBLOCK)
    except OSError as e:
        logger.warning("kmsg open %s failed: %s", p, e)
        return out
    try:
        st = os.fstat(fd)
        if not _is_char_device(st):
            # regular fixture file: read lines directly
            data = b""
            while True:
                chunk = os.read(fd, 1 << 16)
                if not chunk:
                    break
                data += chunk
            for ln in data.decode("utf-8", "replace").splitlines():
                m = parse_line(ln, bt)
                if m is not None:
                    out.append(m)
                    if limit and len(out) >= limit:
                        break
            return out
        # char device: each read() returns exactly one record;
        # EAGAIN means end of ring buffer in non-blocking mode
        while True:
            try:
                rec = os.read(fd, 8192)
            except OSError as e:
                if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                    break
                if e.errno == errno.EPIPE:  # overwritten record, skip
                    continue
                raise
            if not rec:
                break
            m = parse_line(rec.decode("utf-8", "replace"), bt)
            if m is not None:
                out.append(m)
                if limit and len(out) >= limit:
                    break
        return out
    finally:
        os.close(fd)


def _is_char_device(st: os.stat_result) -> bool:
    import stat as _stat

    return _stat.S_ISCHR(st.st_mode)


class Watcher:
    """Follow-mode kmsg watcher (reference: watcher.go:190-290).

    Spawns one reader thread delivering parsed ``Message``s to ``callback``.
    ``from_now=True`` seeks to the end first (daemon mode: only new lines);
    ``False`` replays the existing buffer first (scan/bootstrap mode).
    """

    def __init__(
        self,
        callback: Callable[[Message], None],
        path: str = "",
        from_now: bool = True,
        poll_timeout_ms: int = 500,
    ) -> None:
        self.path = kmsg_path(path)
        self.callback = callback
        self.from_now = from_now
        self.poll_timeout_ms = poll_timeout_ms
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.boot_unix = boot_time()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="tpud-kmsg-watcher", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=3.0)
            self._thread = None

    # -- internals ---------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._follow_once()
            except Exception:  # noqa: BLE001 — watcher must survive
                logger.exception("kmsg follow error; retrying in 1s")
            if self._stop.wait(1.0):
                return

    def _follow_once(self) -> None:
        try:
            fd = os.open(self.path, os.O_RDONLY | os.O_NONBLOCK)
        except OSError as e:
            logger.warning("kmsg open %s failed: %s", self.path, e)
            self._stop.wait(5.0)
            return
        try:
            st = os.fstat(fd)
            if _is_char_device(st):
                self._follow_device(fd)
            else:
                self._follow_file(fd)
        finally:
            os.close(fd)

    def _follow_device(self, fd: int) -> None:
        if self.from_now:
            os.lseek(fd, 0, os.SEEK_END)
        poller = select.poll()
        poller.register(fd, select.POLLIN)
        while not self._stop.is_set():
            events = poller.poll(self.poll_timeout_ms)
            if not events:
                continue
            while True:
                try:
                    rec = os.read(fd, 8192)
                except OSError as e:
                    if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                        break
                    if e.errno == errno.EPIPE:
                        continue
                    raise
                if not rec:
                    break
                self._deliver(rec.decode("utf-8", "replace"))

    def _follow_file(self, fd: int) -> None:
        """tail -f over a regular fixture file so fault-injection tests can
        append lines and see them flow through the same code path. A
        regular file has no poll() wakeup, so appends are watched via
        inotify (event-driven, same near-zero latency as the char device);
        where inotify is unavailable the loop falls back to a short sleep,
        which then floors fixture-mode detection latency."""
        buf = b""
        sleep_s = min(self.poll_timeout_ms, 50) / 1000.0
        if self.from_now:
            os.lseek(fd, 0, os.SEEK_END)
        ino = _InotifyWatch.create(self.path)
        try:
            while not self._stop.is_set():
                chunk = b""
                try:
                    chunk = os.read(fd, 1 << 16)
                except OSError as e:
                    if e.errno not in (errno.EAGAIN, errno.EWOULDBLOCK):
                        raise
                if chunk:
                    buf += chunk
                    while b"\n" in buf:
                        ln, buf = buf.split(b"\n", 1)
                        self._deliver(ln.decode("utf-8", "replace"))
                else:
                    if ino is not None:
                        # block until the file is modified; capped so the
                        # stop event is honored within ~200ms regardless of
                        # the configured poll timeout
                        ino.wait(min(self.poll_timeout_ms, 200))
                        if self._stop.is_set():
                            return
                    elif self._stop.wait(sleep_s):
                        return
                    # handle truncation/rotation
                    pos = os.lseek(fd, 0, os.SEEK_CUR)
                    size = os.fstat(fd).st_size
                    if size < pos:
                        os.lseek(fd, 0, os.SEEK_SET)
        finally:
            if ino is not None:
                ino.close()

    def _deliver(self, line: str) -> None:
        m = parse_line(line, self.boot_unix)
        if m is None:
            return
        try:
            self.callback(m)
        except Exception:  # noqa: BLE001
            logger.exception("kmsg callback failed")


