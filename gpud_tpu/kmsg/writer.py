"""Kmsg writer — the fault-injection mechanism.

Reference: pkg/kmsg/writer/kmsg.go:35,69 — writes ``<prio>message`` records
into /dev/kmsg (or the override file), which then flow through the normal
watcher → syncer → eventstore detection path. This makes fault injection a
product feature that doubles as the e2e test harness (SURVEY §4.7).
"""

from __future__ import annotations

import os
import time
from typing import Optional

from gpud_tpu.kmsg.watcher import ENV_KMSG_PATH, DEFAULT_KMSG_PATH, boot_time
from gpud_tpu.log import audit, get_logger

logger = get_logger(__name__)

MAX_PRINTK_RECORD = 1024 - 48  # kernel printk record size limit (reference: writer/kmsg.go)


class KmsgWriter:
    def __init__(self, path: str = "") -> None:
        self.path = path or os.environ.get(ENV_KMSG_PATH, "") or DEFAULT_KMSG_PATH
        self._seq = 0

    def write(self, message: str, priority: int = 3) -> Optional[str]:
        """Write one record. Returns an error string or None.

        Writing to the real /dev/kmsg takes just ``<prio>msg``; the kernel
        stamps seq/time. When the target is a regular file (fixture mode) we
        emit a fully-formed record line so the watcher can parse it back.
        """
        if len(message) > MAX_PRINTK_RECORD:
            message = message[:MAX_PRINTK_RECORD]
        message = message.replace("\n", " ")
        try:
            import stat as _stat

            is_dev = False
            try:
                is_dev = _stat.S_ISCHR(os.stat(self.path).st_mode)
            except FileNotFoundError:
                pass
            if is_dev:
                payload = f"<{priority}>{message}\n".encode()
                fd = os.open(self.path, os.O_WRONLY)
                try:
                    os.write(fd, payload)
                finally:
                    os.close(fd)
            else:
                bt = boot_time()
                ts_us = int((time.time() - bt) * 1e6) if bt else int(time.time() * 1e6)
                self._seq += 1
                line = f"{priority},{self._seq},{ts_us},-;{message}\n"
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(line)
            audit("kmsg_write", path=self.path, priority=priority, message=message)
            return None
        except OSError as e:
            logger.warning("kmsg write failed: %s", e)
            return str(e)
