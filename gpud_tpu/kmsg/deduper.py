"""Dedup cache for kmsg-derived events (reference: pkg/kmsg/deduper.go).

When the daemon re-reads the ring buffer (restart, scan after daemon) the
same line must not produce duplicate events; the cache remembers seen
(message, timestamp-bucket) keys with a TTL.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable

DEFAULT_TTL = 15 * 60.0  # seconds
DEFAULT_MAX_ENTRIES = 4096


class Deduper:
    def __init__(
        self,
        ttl_seconds: float = DEFAULT_TTL,
        max_entries: int = DEFAULT_MAX_ENTRIES,
        time_now_fn: Callable[[], float] = time.time,
    ) -> None:
        self.ttl = ttl_seconds
        self.max_entries = max_entries
        self.time_now_fn = time_now_fn
        self._mu = threading.Lock()
        self._seen: "OrderedDict[str, float]" = OrderedDict()

    def _key(self, message: str, ts: float) -> str:
        # bucket timestamps to the second: kmsg µs timestamps of the same
        # record differ between ring re-reads only below this resolution
        return f"{int(ts)}|{message}"

    def seen_before(self, message: str, ts: float) -> bool:
        """Mark-and-test: returns True if this (message, second) was already
        observed within the TTL."""
        now = self.time_now_fn()
        k = self._key(message, ts)
        with self._mu:
            self._evict(now)
            if k in self._seen and self._seen[k] > now:
                return True
            self._seen[k] = now + self.ttl
            self._seen.move_to_end(k)
            while len(self._seen) > self.max_entries:
                self._seen.popitem(last=False)
            return False

    def _evict(self, now: float) -> None:
        while self._seen:
            k, exp = next(iter(self._seen.items()))
            if exp <= now or len(self._seen) > self.max_entries:
                self._seen.popitem(last=False)
            else:
                break
        while len(self._seen) > self.max_entries:
            self._seen.popitem(last=False)

    def __len__(self) -> int:
        with self._mu:
            return len(self._seen)


class NativeBackedDeduper:
    """Same ``seen_before`` contract over the C++ TTL cache
    (native/tpud_native.cpp) — the product fast path; parity with the
    Python Deduper is asserted in tests (incl. lockstep LRU eviction)."""

    def __init__(
        self,
        ttl_seconds: float = DEFAULT_TTL,
        max_entries: int = DEFAULT_MAX_ENTRIES,
        time_now_fn: Callable[[], float] = time.time,
    ) -> None:
        from gpud_tpu import native

        self._nd = native.NativeDeduper(ttl_seconds, max_entries)
        self.time_now_fn = time_now_fn
        self._mu = threading.Lock()  # the C++ cache is not thread-safe

    def seen_before(self, message: str, ts: float) -> bool:
        with self._mu:
            return self._nd.seen(f"{int(ts)}|{message}", self.time_now_fn())

    def __len__(self) -> int:
        with self._mu:
            return len(self._nd)


def default_deduper():
    """The native cache when the library is loaded, else the Python one."""
    try:
        from gpud_tpu import native

        if native.available():
            return NativeBackedDeduper()
    except Exception:  # noqa: BLE001 — native is never required
        pass
    return Deduper()
