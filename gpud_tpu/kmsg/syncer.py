"""Kmsg → event-store pump.

Reference: pkg/kmsg/syncer.go:26-100 — a Syncer owns a Watcher, applies a
component-supplied match function to each kernel line, and inserts matching
lines as events into the component's bucket (deduped).

Multiple components share one underlying watcher through ``SharedWatcher``
to keep the steady-state cost at one reader for the whole daemon
(footprint discipline, SURVEY §7 hard parts).
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from gpud_tpu.api.v1.types import Event
from gpud_tpu.eventstore import Bucket
from gpud_tpu.kmsg.deduper import default_deduper
from gpud_tpu.kmsg.watcher import Message, Watcher
from gpud_tpu.log import get_logger

logger = get_logger(__name__)

# a match function returns (event_name, event_type, message) or
# (event_name, event_type, message, extra_info_dict) or None
MatchFunc = Callable[[str], Optional[tuple]]


class Syncer:
    """One component's kmsg subscription (reference: syncer.go:26-100)."""

    def __init__(
        self,
        match_fn: MatchFunc,
        bucket: Bucket,
        deduper=None,  # any object with the seen_before contract
        on_event: Optional[Callable[[Event], None]] = None,
    ) -> None:
        self.match_fn = match_fn
        self.bucket = bucket
        self.deduper = deduper or default_deduper()
        self.on_event = on_event

    def process(self, msg: Message) -> Optional[Event]:
        matched = self.match_fn(msg.message)
        if matched is None:
            return None
        name, ev_type, text = matched[:3]
        extra = dict(matched[3]) if len(matched) > 3 and matched[3] else {}
        if self.deduper.seen_before(msg.message, msg.time):
            return None
        extra.update({"kmsg": msg.message, "priority": msg.priority_name})
        # stable error taxonomy stamped at ingest: downstream featurizers
        # (predict n-gram novelty) read this instead of re-regexing raw
        # lines; a match_fn-supplied class wins
        extra.setdefault("error_class", name)
        ev = Event(
            component=self.bucket.name(),
            time=msg.time,
            name=name,
            type=ev_type,
            message=text,
            extra_info=extra,
        )
        # event-level dedupe against the store as well (restart safety;
        # reference: xid/component.go:545-570 Find-before-Insert)
        if self.bucket.find(ev) is None:
            self.bucket.insert(ev)
        if self.on_event is not None:
            try:
                self.on_event(ev)
            except Exception:  # noqa: BLE001
                logger.exception("on_event callback failed")
        return ev


class SharedWatcher:
    """Fan-out of one kmsg Watcher to many Syncers."""

    def __init__(self, path: str = "", from_now: bool = True) -> None:
        self._mu = threading.Lock()
        self._syncers: List[Syncer] = []
        self._watcher = Watcher(self._dispatch, path=path, from_now=from_now)
        self._started = False

    def register(self, syncer: Syncer) -> None:
        with self._mu:
            self._syncers.append(syncer)

    def start(self) -> None:
        with self._mu:
            if not self._started:
                self._watcher.start()
                self._started = True

    def close(self) -> None:
        with self._mu:
            if self._started:
                self._watcher.close()
                self._started = False

    def _dispatch(self, msg: Message) -> None:
        with self._mu:
            syncers = list(self._syncers)
        for s in syncers:
            try:
                s.process(msg)
            except Exception:  # noqa: BLE001
                logger.exception("syncer process failed")
