"""Built-in self-update install pipeline.

Reference: pkg/update/update.go:19-50 — the reference downloads the release
tarball from pkg.gpud.dev, verifies it, and swaps the running executable
in-process, so a pushed target version works on a stock node with no
operator-side tooling. This module is that pipeline for the Python build:

  download  {base}/tpud-{version}.tar.gz  (+ .tar.gz.sig)
  verify    ed25519 via gpud_tpu/release/distsign.py — either a locally
            pinned signing key, or a pinned ROOT key + a downloaded
            signing key endorsed by it ({base}/signing.pub + .rootsig)
  install   extract into a staging dir, atomic rename into
            <install_dir>/versions/<version>, atomic `current` symlink swap
  restart   the caller (VersionFileWatcher) exits 244 so systemd / the
            DaemonSet restarts into the new version

`TPUD_UPDATE_HOOK` remains an operator override for bespoke installs
(gpud_tpu/update.py); when unset and a base URL + trust anchor are
configured, this pipeline runs instead.
"""

from __future__ import annotations

import os
import re
import shutil
import tarfile
import tempfile
import urllib.error
import urllib.request
from typing import Callable, Optional

from gpud_tpu.log import audit, get_logger
from gpud_tpu.release import distsign

logger = get_logger(__name__)

ENV_BASE_URL = "TPUD_UPDATE_BASE_URL"
ENV_SIGNING_PUB = "TPUD_UPDATE_SIGNING_PUB"
ENV_ROOT_PUB = "TPUD_UPDATE_ROOT_PUB"
ENV_INSTALL_DIR = "TPUD_UPDATE_INSTALL_DIR"

# package-name contract on the distribution server
PACKAGE_FMT = "tpud-{version}.tar.gz"
SIGNING_PUB_NAME = "signing.pub"

DOWNLOAD_TIMEOUT = 120.0
# target versions ride into download URLs and filesystem paths: whitelist
# instead of blacklisting — `?`/`#`/whitespace would alter URL semantics
VERSION_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")
MAX_PACKAGE_BYTES = 1 << 30  # 1 GiB hard cap on any downloaded artifact
CURRENT_LINK = "current"
VERSIONS_DIR = "versions"


def _download(url: str, dest: str, max_bytes: int = MAX_PACKAGE_BYTES) -> Optional[str]:
    """Fetch ``url`` into ``dest``. Returns an error string or None."""
    try:
        req = urllib.request.Request(url, headers={"User-Agent": "tpud-update"})
        with urllib.request.urlopen(req, timeout=DOWNLOAD_TIMEOUT) as resp:  # noqa: S310
            with open(dest, "wb") as f:
                total = 0
                while True:
                    chunk = resp.read(1 << 20)
                    if not chunk:
                        break
                    total += len(chunk)
                    if total > max_bytes:
                        return f"artifact exceeds {max_bytes} bytes: {url}"
                    f.write(chunk)
        return None
    except (urllib.error.URLError, OSError, ValueError) as e:
        return f"download failed: {url}: {e}"


def _safe_extract(tar_path: str, dest_dir: str) -> Optional[str]:
    """Extract a tarball refusing path traversal, absolute names, links
    escaping the tree, and device/FIFO members."""
    dest_real = os.path.realpath(dest_dir)
    try:
        with tarfile.open(tar_path, "r:gz") as tf:
            for m in tf.getmembers():
                name = m.name
                target = os.path.realpath(os.path.join(dest_real, name))
                if target != dest_real and not target.startswith(dest_real + os.sep):
                    return f"unsafe member path in package: {name!r}"
                if m.issym() or m.islnk():
                    link_target = os.path.realpath(
                        os.path.join(os.path.dirname(target), m.linkname)
                    )
                    if not link_target.startswith(dest_real + os.sep):
                        return f"unsafe link in package: {name!r} -> {m.linkname!r}"
                elif not (m.isreg() or m.isdir()):
                    return f"unsupported member type in package: {name!r}"
            # Python 3.10.0–3.10.11 predate the filter= parameter and raise
            # TypeError on it; the member validation above already enforces
            # the safety properties, so plain extract is equivalent there
            use_filter = True
            for m in tf.getmembers():
                if use_filter:
                    try:
                        tf.extract(m, dest_real, set_attrs=True, filter="data")
                        continue
                    except TypeError:
                        use_filter = False
                tf.extract(m, dest_real, set_attrs=True)
        return None
    except (tarfile.TarError, OSError) as e:
        return f"package extraction failed: {e}"


def resolve_signing_pub(
    base_url: str,
    workdir: str,
    signing_pub: str = "",
    root_pub: str = "",
) -> tuple[str, Optional[str]]:
    """Resolve the signing public key to verify the package with.

    Either a pinned signing key path is given directly, or a pinned ROOT
    key verifies a downloaded signing key (the reference's distsign chain:
    root keys stay offline, signing keys rotate with releases).
    Returns (signing_pub_path, error).
    """
    if signing_pub:
        if not os.path.isfile(signing_pub):
            return "", f"signing public key not found: {signing_pub}"
        return signing_pub, None
    if not root_pub:
        return "", "no trust anchor: set a signing or root public key"
    if not os.path.isfile(root_pub):
        return "", f"root public key not found: {root_pub}"
    pub_path = os.path.join(workdir, SIGNING_PUB_NAME)
    sig_path = pub_path + ".rootsig"
    for url, dest in (
        (f"{base_url}/{SIGNING_PUB_NAME}", pub_path),
        (f"{base_url}/{SIGNING_PUB_NAME}.rootsig", sig_path),
    ):
        err = _download(url, dest)
        if err:
            return "", err
    try:
        endorsed = distsign.verify_key(root_pub, pub_path, sig_path)
    except (ValueError, RuntimeError, OSError) as e:
        # malformed PEM / missing cryptography package must surface as the
        # documented error-string contract, not a traceback up the watcher
        return "", f"signing key verification failed: {e}"
    if not endorsed:
        return "", "downloaded signing key is not endorsed by the pinned root key"
    return pub_path, None


def install_tree(extracted_dir: str, install_dir: str, version: str) -> Optional[str]:
    """Atomically install an extracted tree as ``versions/<version>`` and
    swap the ``current`` symlink (the executable-swap step of
    update.go:19-50, done dir-wise for a package distribution)."""
    versions = os.path.join(install_dir, VERSIONS_DIR)
    os.makedirs(versions, exist_ok=True)
    final = os.path.join(versions, version)
    staging = final + f".staging-{os.getpid()}"
    aside = final + f".old-{os.getpid()}"
    moved_aside = False
    try:
        if os.path.exists(staging):
            shutil.rmtree(staging)
        shutil.move(extracted_dir, staging)
        if os.path.exists(final):
            # reinstall of an already-installed version: move the live tree
            # aside instead of deleting it, so a failure between here and
            # the rename below can roll back — `current` must point at a
            # live tree on every path out of this function
            if os.path.exists(aside):
                shutil.rmtree(aside)
            os.rename(final, aside)
            moved_aside = True
        try:
            os.rename(staging, final)
        except OSError:
            if moved_aside:
                try:
                    os.rename(aside, final)
                    moved_aside = False
                except OSError:
                    # leave the aside tree on disk for manual recovery —
                    # the cleanup below must not delete the only survivor
                    moved_aside = False
                    logger.exception(
                        "rollback of %s failed; previous tree left at %s",
                        final, aside,
                    )
            raise
        # atomic symlink swap: build aside, replace over
        link = os.path.join(install_dir, CURRENT_LINK)
        tmp_link = link + f".tmp-{os.getpid()}"
        if os.path.lexists(tmp_link):
            os.unlink(tmp_link)
        os.symlink(os.path.join(VERSIONS_DIR, version), tmp_link)
        os.replace(tmp_link, link)
        return None
    except OSError as e:
        return f"install failed: {e}"
    finally:
        if os.path.exists(staging):
            shutil.rmtree(staging, ignore_errors=True)
        if moved_aside and os.path.exists(aside):
            shutil.rmtree(aside, ignore_errors=True)


def perform_update(
    target_version: str,
    base_url: str = "",
    install_dir: str = "",
    signing_pub: str = "",
    root_pub: str = "",
) -> Optional[str]:
    """Download → verify → install ``target_version``. Returns an error
    string (daemon stays on the current version) or None on success (the
    caller restart-exits). Every failure path leaves the installed tree
    and `current` symlink untouched."""
    base_url = (base_url or os.environ.get(ENV_BASE_URL, "")).rstrip("/")
    install_dir = install_dir or os.environ.get(ENV_INSTALL_DIR, "")
    signing_pub = signing_pub or os.environ.get(ENV_SIGNING_PUB, "")
    root_pub = root_pub or os.environ.get(ENV_ROOT_PUB, "")
    if not base_url:
        return "no package base URL configured"
    if not install_dir:
        return "no install dir configured"
    if not target_version or not VERSION_RE.match(target_version):
        return f"invalid target version {target_version!r}"

    workdir = tempfile.mkdtemp(prefix="tpud-update-")
    try:
        pub_path, err = resolve_signing_pub(base_url, workdir, signing_pub, root_pub)
        if err:
            return err
        pkg_name = PACKAGE_FMT.format(version=target_version)
        pkg_path = os.path.join(workdir, pkg_name)
        sig_path = pkg_path + ".sig"
        for url, dest in (
            (f"{base_url}/{pkg_name}", pkg_path),
            (f"{base_url}/{pkg_name}.sig", sig_path),
        ):
            err = _download(url, dest)
            if err:
                return err
        try:
            err = distsign.verify_package(pub_path, pkg_path, sig_path)
        except (ValueError, RuntimeError, OSError) as e:
            # a corrupt/hostile PEM or an env without the cryptography
            # package raises; keep the Optional[str] error contract
            err = str(e)
        if err:
            audit("self_update_verify_failed", target=target_version, error=err)
            return f"package signature rejected: {err}"
        extracted = os.path.join(workdir, "extracted")
        os.makedirs(extracted)
        err = _safe_extract(pkg_path, extracted)
        if err:
            audit("self_update_extract_failed", target=target_version, error=err)
            return err
        err = install_tree(extracted, install_dir, target_version)
        if err:
            return err
        audit("self_update_installed", target=target_version, install_dir=install_dir)
        logger.warning("installed %s into %s", target_version, install_dir)
        return None
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def installer_from_env() -> Optional[Callable[[str], Optional[str]]]:
    """Build the watcher's installer callable from the environment; None
    when the pipeline is not configured (the watcher then warns-and-stays,
    preserving the crash-loop guard)."""
    base_url = os.environ.get(ENV_BASE_URL, "")
    install_dir = os.environ.get(ENV_INSTALL_DIR, "")
    if not base_url or not install_dir:
        return None
    return lambda target: perform_update(target)
