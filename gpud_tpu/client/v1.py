"""Typed HTTP client for the tpud local API.

Reference: client/v1/v1.go:23-543 — GetComponents/GetInfo/GetHealthStates/
GetEvents/GetMetrics/Deregister/SetHealthy/TriggerCheck; used by the CLI
subcommands and the e2e suite.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import requests
import urllib3

from gpud_tpu.api.v1.types import (
    ComponentEvents,
    ComponentHealthStates,
    ComponentInfo,
    ComponentMetrics,
    MachineInfo,
)

# the local API uses a self-signed cert by design (reference: server.go:507)
urllib3.disable_warnings(urllib3.exceptions.InsecureRequestWarning)

DEFAULT_TIMEOUT = 30.0


class ClientError(Exception):
    def __init__(self, status: int, body: str) -> None:
        super().__init__(f"HTTP {status}: {body[:200]}")
        self.status = status
        self.body = body


class Client:
    def __init__(
        self,
        base_url: str = "https://localhost:15132",
        timeout: float = DEFAULT_TIMEOUT,
        session: Optional[requests.Session] = None,
        admin_token: str = "",
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.http = session or requests.Session()
        if admin_token:
            # manager operator endpoints (fleet rollup/history/traces)
            # require the admin bearer when the manager is started with one
            self.http.headers["Authorization"] = f"Bearer {admin_token}"
        self.http.verify = False
        # REQUESTS_CA_BUNDLE/CURL_CA_BUNDLE in the environment would
        # override verify=False on merge; the local API is always
        # self-signed so ignore the environment entirely
        self.http.trust_env = False

    # -- plumbing ----------------------------------------------------------
    def _req(self, method: str, path: str, params=None, body=None):
        resp = self.http.request(
            method,
            self.base_url + path,
            params=params,
            json=body,
            timeout=self.timeout,
        )
        if resp.status_code >= 400:
            raise ClientError(resp.status_code, resp.text)
        ctype = resp.headers.get("content-type", "")
        if "json" in ctype:
            return resp.json()
        return resp.text

    # -- API (reference: client/v1/v1.go) ---------------------------------
    def healthz(self) -> Dict:
        return self._req("GET", "/healthz")

    def get_components(self) -> List[str]:
        return self._req("GET", "/v1/components")

    def deregister_component(self, name: str) -> Dict:
        return self._req("DELETE", "/v1/components", params={"componentName": name})

    def trigger_check(self, component: str = "", tag: str = "") -> List[ComponentHealthStates]:
        params = {}
        if component:
            params["componentName"] = component
        if tag:
            params["tagName"] = tag
        data = self._req("GET", "/v1/components/trigger-check", params=params)
        return [ComponentHealthStates.from_dict(d) for d in data]

    def set_healthy(self, component: str) -> Dict:
        return self._req(
            "POST", "/v1/components/set-healthy", params={"componentName": component}
        )

    def get_health_states(
        self, components: Optional[List[str]] = None
    ) -> List[ComponentHealthStates]:
        params = {"components": ",".join(components)} if components else None
        data = self._req("GET", "/v1/states", params=params)
        return [ComponentHealthStates.from_dict(d) for d in data]

    def get_events(
        self,
        start_time: Optional[float] = None,
        end_time: Optional[float] = None,
        components: Optional[List[str]] = None,
    ) -> List[ComponentEvents]:
        params = {}
        if start_time is not None:
            params["startTime"] = start_time
        if end_time is not None:
            params["endTime"] = end_time
        if components:
            params["components"] = ",".join(components)
        data = self._req("GET", "/v1/events", params=params or None)
        return [ComponentEvents.from_dict(d) for d in data]

    def get_metrics(
        self,
        since: Optional[float] = None,
        components: Optional[List[str]] = None,
    ) -> List[ComponentMetrics]:
        params = {}
        if since is not None:
            params["since"] = since
        if components:
            params["components"] = ",".join(components)
        data = self._req("GET", "/v1/metrics", params=params or None)
        return [ComponentMetrics.from_dict(d) for d in data]

    def get_state_history(
        self,
        component: str = "",
        since: Optional[float] = None,
        limit: Optional[int] = None,
    ) -> Dict:
        """Persisted health-transition timeline (``/v1/states/history``):
        ``{"transitions": [...], "count": n, "flapping": [...]}`` plus an
        ``availability`` block when a single component is requested."""
        params: Dict = {}
        if component:
            params["component"] = component
        if since is not None:
            params["since"] = since
        if limit is not None:
            params["limit"] = limit
        return self._req("GET", "/v1/states/history", params=params or None)

    def get_remediation_audit(
        self,
        component: str = "",
        action: str = "",
        outcome: str = "",
        since: Optional[float] = None,
        limit: Optional[int] = None,
    ) -> Dict:
        """Remediation audit ledger (``/v1/remediation/audit``):
        ``{"attempts": [...], "count": n, "status": {...}}``."""
        params: Dict = {}
        for k, v in (
            ("component", component), ("action", action), ("outcome", outcome)
        ):
            if v:
                params[k] = v
        if since is not None:
            params["since"] = since
        if limit is not None:
            params["limit"] = limit
        return self._req("GET", "/v1/remediation/audit", params=params or None)

    def get_predict_scores(
        self,
        component: str = "",
        history: Optional[int] = None,
    ) -> Dict:
        """Precursor scores (``/v1/predict/scores``): per-component fused
        score, feature breakdown, armed/warned state, and measured lead
        times; ``history=N`` appends the last N in-memory score points
        per component."""
        params: Dict = {}
        if component:
            params["component"] = component
        if history is not None:
            params["history"] = history
        return self._req("GET", "/v1/predict/scores", params=params or None)

    def get_predict_calibration(self, refit: bool = False) -> Dict:
        """Learned-threshold calibration state
        (``/v1/predict/calibration``): per-component-class fitted
        thresholds and feature weights replayed from the node's own
        ledger history; ``refit=True`` re-fits synchronously before
        returning."""
        params: Dict = {}
        if refit:
            params["refit"] = 1
        return self._req(
            "GET", "/v1/predict/calibration", params=params or None,
        )

    def get_fabric(
        self,
        link: str = "",
        since: Optional[float] = None,
        limit: Optional[int] = None,
    ) -> Dict:
        """Fabric matrix (``GET /v1/fabric``): discovered mesh, sweep
        status, and the current per-link (src, dst, axis, latency,
        state) matrix; any of ``link``/``since``/``limit`` appends
        matrix history rows from the durable store."""
        params: Dict = {}
        if link:
            params["link"] = link
        if since is not None:
            params["since"] = since
        if limit is not None:
            params["limit"] = limit
        return self._req("GET", "/v1/fabric", params=params or None)

    def get_remediation_policy(self) -> Dict:
        """Current remediation policy + guard state."""
        return self._req("GET", "/v1/remediation/policy")

    def set_remediation_policy(self, policy: Dict) -> Dict:
        """Partial policy update (``POST /v1/remediation/policy``) — e.g.
        ``{"enforce_actions": ["restart_runtime"]}`` graduates an action
        out of dry-run."""
        return self._req("POST", "/v1/remediation/policy", body=policy)

    def get_info(self, components: Optional[List[str]] = None) -> List[ComponentInfo]:
        params = {"components": ",".join(components)} if components else None
        data = self._req("GET", "/v1/info", params=params)
        return [ComponentInfo.from_dict(d) for d in data]

    def get_machine_info(self) -> MachineInfo:
        return MachineInfo.from_dict(self._req("GET", "/machine-info"))

    def get_prometheus_metrics(self) -> str:
        return self._req("GET", "/metrics")

    def inject_fault(
        self,
        tpu_error_name: str = "",
        chip_id: int = 0,
        detail: str = "",
        kernel_message: str = "",
        repeat: int = 1,
        interval_seconds: float = 0.0,
    ) -> Dict:
        """One kmsg fault write — or a burst/flap of ``repeat`` writes
        spaced ``interval_seconds`` apart. Returns the structured
        injection result (line, writes, timestamp)."""
        return self._req(
            "POST",
            "/inject-fault",
            body={
                "tpu_error_name": tpu_error_name,
                "chip_id": chip_id,
                "detail": detail,
                "kernel_message": kernel_message,
                "repeat": repeat,
                "interval_seconds": interval_seconds,
            },
        )

    def run_chaos(self, scenario, wait: bool = True) -> Dict:
        """Run a chaos campaign (``POST /v1/chaos/run``). ``scenario`` is
        a shipped scenario name, a file path on the daemon host, or an
        inline scenario mapping; ``wait=False`` launches it and returns
        the running-campaign status immediately."""
        return self._req(
            "POST", "/v1/chaos/run", body={"scenario": scenario, "wait": wait}
        )

    def get_chaos_campaigns(self, limit: Optional[int] = None) -> Dict:
        """Chaos campaign history + available scenarios
        (``/v1/chaos/campaigns``)."""
        params = {"limit": limit} if limit is not None else None
        return self._req("GET", "/v1/chaos/campaigns", params=params)

    def get_session_status(self) -> Dict:
        """Control-plane session health (``/v1/session/status``):
        connection + auth state, circuit breaker, and the
        store-and-forward outbox backlog/watermark."""
        return self._req("GET", "/v1/session/status")

    # -- fleet observability (manager operator API) ------------------------
    # These speak to a *manager* (manager/control_plane.py), not an agent:
    # construct the Client with the manager's endpoint as base_url (and
    # admin_token when the manager enforces one).

    def get_fleet_rollup(self) -> Dict:
        """Fleet-wide rollup aggregates (``GET /v1/fleet/rollup``):
        availability, MTTR/MTBF, flap leaders, per-kind record counts."""
        return self._req("GET", "/v1/fleet/rollup")

    def get_fleet_fabric(self, since: Optional[float] = None) -> Dict:
        """Fleet-wide ICI fabric rollup (``GET /v1/fleet/fabric``):
        per-agent link aggregates — which links degraded since ``since``
        across every agent, from one query."""
        params: Dict = {}
        if since is not None:
            params["since"] = since
        return self._req("GET", "/v1/fleet/fabric", params=params or None)

    def get_fleet_predict(self, top: Optional[int] = None) -> Dict:
        """Fleet-ranked predictive pane (``GET /v1/fleet/predict``):
        the top-K (agent, component) series by time-decayed predicted-
        failure risk, with lead-time distribution and risk buckets."""
        params: Dict = {}
        if top is not None:
            params["top"] = top
        return self._req("GET", "/v1/fleet/predict", params=params or None)

    def get_fleet_agents(self, offset: int = 0, limit: int = 100) -> Dict:
        """One paginated page of per-agent rollups
        (``GET /v1/fleet/agents``); ``next_offset`` is None on the last
        page."""
        return self._req(
            "GET", "/v1/fleet/agents",
            params={"offset": offset, "limit": limit},
        )

    def get_fleet_history(
        self,
        agent_id: str,
        since: Optional[float] = None,
        limit: Optional[int] = None,
        offset: Optional[int] = None,
    ) -> Dict:
        """One agent's journaled record history, newest first
        (``GET /v1/fleet/agents/{id}/history``)."""
        params = {}
        if since is not None:
            params["since"] = since
        if limit is not None:
            params["limit"] = limit
        if offset is not None:
            params["offset"] = offset
        return self._req(
            "GET", f"/v1/fleet/agents/{agent_id}/history",
            params=params or None,
        )

    def get_fleet_traces(self, correlation_id: str) -> Dict:
        """Every fleet record stitched to one agent-side check trace
        (``GET /v1/fleet/traces?correlation_id=``)."""
        return self._req(
            "GET", "/v1/fleet/traces",
            params={"correlation_id": correlation_id},
        )

    def get_fleet_peers(self) -> Dict:
        """The manager peer map (``GET /v1/fleet/peers``): ring order,
        per-peer health, rendezvous cohort counts, and replication
        watermarks; ``{"federation": false, ...}`` from a standalone
        manager."""
        return self._req("GET", "/v1/fleet/peers")
