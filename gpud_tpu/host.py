"""Host identity and lifecycle.

Reference: pkg/host — machine-id/boot-id readers, virtualization detection,
``RebootEventStore`` (records boot-time-derived reboot events,
pkg/host/event.go:44-85), ``Reboot()`` via systemctl/shutdown
(pkg/host/reboot.go:46+), uptime helpers.
"""

from __future__ import annotations

import os
import time
import uuid as _uuid
from typing import List, Optional

from gpud_tpu.api.v1.types import Event, EventType
from gpud_tpu.eventstore import EventStore
from gpud_tpu.log import audit, get_logger
from gpud_tpu.process import run_command

logger = get_logger(__name__)

REBOOT_COMPONENT = "os"
EVENT_NAME_REBOOT = "reboot"


def _read_first_line(path: str) -> str:
    try:
        with open(path, "r", encoding="ascii") as f:
            return f.read().strip()
    except OSError:
        return ""


def machine_id() -> str:
    """Stable machine identity (reference: pkg/host machine-id reader)."""
    for p in ("/etc/machine-id", "/var/lib/dbus/machine-id"):
        v = _read_first_line(p)
        if v:
            return v
    # last resort: stable-ish ID derived from the MAC
    return f"{_uuid.getnode():012x}"


def boot_id() -> str:
    return _read_first_line("/proc/sys/kernel/random/boot_id")


def uptime_seconds() -> float:
    v = _read_first_line("/proc/uptime")
    try:
        return float(v.split()[0])
    except (ValueError, IndexError):
        return 0.0


def boot_time() -> float:
    return time.time() - uptime_seconds()


def kernel_version() -> str:
    return _read_first_line("/proc/sys/kernel/osrelease")


def os_name() -> str:
    try:
        with open("/etc/os-release", "r", encoding="utf-8") as f:
            for ln in f:
                if ln.startswith("PRETTY_NAME="):
                    return ln.split("=", 1)[1].strip().strip('"')
    except OSError:
        pass
    return _read_first_line("/proc/sys/kernel/ostype")


def virtualization() -> str:
    """Best-effort virtualization detection (reference: pkg/host virt detect)."""
    r = run_command(["systemd-detect-virt"], timeout=5.0)
    if r.exit_code == 0:
        return r.output.strip()
    product = _read_first_line("/sys/class/dmi/id/product_name").lower()
    if "google" in product:
        return "gce"
    if product:
        return product
    return "unknown" if r.error else "none"


class RebootEventStore:
    """Persists reboot events derived from boot time so event-sourced health
    can merge them with error events (reference: pkg/host/event.go:44-85).
    """

    def __init__(self, event_store: EventStore) -> None:
        self._bucket = event_store.bucket(REBOOT_COMPONENT)
        self.time_now_fn = time.time

    def record_reboot(self) -> None:
        """Called once at daemon boot: if the current boot isn't recorded
        yet, insert a reboot event stamped at boot time
        (reference: pkg/server/server.go:203-221 RecordReboot)."""
        bt = boot_time()
        ev = Event(
            component=REBOOT_COMPONENT,
            time=round(bt, 0),  # second resolution: boot_time jitters between reads
            name=EVENT_NAME_REBOOT,
            type=EventType.WARNING,
            message=f"system boot detected (boot_id={boot_id()})",
        )
        # dedupe across daemon restarts within the same boot
        for existing in self._bucket.get(bt - 120):
            if existing.name == EVENT_NAME_REBOOT and abs(existing.time - ev.time) < 120:
                return
        self._bucket.insert(ev)
        logger.info("recorded reboot event at %s", ev.time)

    def get_reboot_events(self, since: float) -> List[Event]:
        return [e for e in self._bucket.get(since) if e.name == EVENT_NAME_REBOOT]


def reboot(use_systemctl: bool = True, dry_run: bool = False) -> Optional[str]:
    """Reboot the machine (reference: pkg/host/reboot.go:46+). Returns error
    string or None. Audited — this is a privileged remediation action."""
    audit("reboot", dry_run=dry_run)
    if dry_run:
        return None
    cmds = (["systemctl", "reboot"], ["shutdown", "-r", "now"], ["reboot"])
    if not use_systemctl:
        cmds = (["shutdown", "-r", "now"], ["reboot"])
    last_err = ""
    for argv in cmds:
        r = run_command(list(argv), timeout=10.0)
        if r.exit_code == 0:
            return None
        last_err = r.error or r.output.strip() or f"exit {r.exit_code}"
    return f"all reboot commands failed: {last_err}"


def stop_daemon_systemd(unit: str = "tpud.service") -> Optional[str]:
    r = run_command(["systemctl", "stop", unit], timeout=30.0)
    return None if r.exit_code == 0 else (r.error or r.output.strip())
