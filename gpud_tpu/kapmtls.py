"""Node-local mTLS credential manager.

Reference: pkg/kapmtls/manager.go:29-50 — installs short-lived client
certificates pushed by the control plane into atomic release directories
with a ``current`` symlink, supports activation, readiness probing and
rollback, so the node-local agent's identity can be rotated without
downtime.

Layout::

    <root>/releases/<version>/{client.crt,client.key}
    <root>/current -> releases/<version>
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import List, Optional

from gpud_tpu.log import audit, get_logger

logger = get_logger(__name__)

DEFAULT_ROOT = "/var/lib/tpud/kapmtls"


@dataclass
class Status:
    current_version: str = ""
    versions: List[str] = None
    ready: bool = False
    error: str = ""

    def to_dict(self) -> dict:
        return {
            "current_version": self.current_version,
            "versions": list(self.versions or []),
            "ready": self.ready,
            "error": self.error,
        }


class CertManager:
    def __init__(self, root: str = DEFAULT_ROOT) -> None:
        self.root = root
        self.releases_dir = os.path.join(root, "releases")

    def _release_dir(self, version: str) -> str:
        if not version or "/" in version or version.startswith("."):
            raise ValueError(f"invalid version {version!r}")
        return os.path.join(self.releases_dir, version)

    # -- install -----------------------------------------------------------
    def install(self, version: str, cert_pem: str, key_pem: str) -> Optional[str]:
        """Write a release atomically (tmp dir + rename). Returns error or
        None. Does NOT activate (reference: install then Activate)."""
        try:
            d = self._release_dir(version)
        except ValueError as e:
            return str(e)
        tmp = d + f".tmp-{int(time.time() * 1e6)}"
        try:
            os.makedirs(tmp, exist_ok=True)
            with open(os.path.join(tmp, "client.crt"), "w", encoding="utf-8") as f:
                f.write(cert_pem)
            key_path = os.path.join(tmp, "client.key")
            with open(key_path, "w", encoding="utf-8") as f:
                f.write(key_pem)
            os.chmod(key_path, 0o600)
            old = None
            active_repush = False
            if os.path.isdir(d):
                # re-push of an existing version: move the old dir aside so
                # the version path is free for the new release
                old = d + f".old-{int(time.time() * 1e6)}"
                link = os.path.join(self.root, "current")
                try:
                    active_repush = os.path.realpath(link) == os.path.realpath(d)
                except OSError:
                    active_repush = False
                if active_repush:
                    # pivot `current` onto the fully-written tmp dir BEFORE
                    # vacating the version path: every crash point below
                    # except the final rename→retarget gap (two syscalls)
                    # leaves `current` pointing at existing credentials
                    self._retarget_current(os.path.relpath(tmp, self.root))
                try:
                    os.rename(d, old)
                except OSError:
                    if active_repush:
                        self._retarget_current(os.path.join("releases", version))
                    raise
            try:
                os.rename(tmp, d)
            except OSError:
                if old is not None:
                    os.rename(old, d)  # restore the previous release
                if active_repush:
                    self._retarget_current(os.path.join("releases", version))
                raise
            if active_repush:
                self._retarget_current(os.path.join("releases", version))
            if old is not None:
                import shutil

                shutil.rmtree(old, ignore_errors=True)
        except OSError as e:
            return str(e)
        audit("kapmtls_install", version=version)
        return None

    def _retarget_current(self, target: str) -> None:
        """Atomic symlink replace of ``current`` → *target* (relative to
        root); cleans up the staging link on failure."""
        link = os.path.join(self.root, "current")
        tmp_link = link + f".tmp-{int(time.time() * 1e6)}"
        try:
            os.symlink(target, tmp_link)
            os.replace(tmp_link, link)
        except OSError:
            try:
                os.unlink(tmp_link)
            except OSError:
                pass
            raise

    # -- activate / rollback ----------------------------------------------
    def activate(self, version: str) -> Optional[str]:
        """Atomic ``current`` symlink swap (symlink-at-temp-path + rename,
        reference: atomic release dirs + current symlink)."""
        d = self._release_dir(version)
        if not os.path.isdir(d):
            return f"release {version!r} not installed"
        if not self._release_ready(d):
            return f"release {version!r} failed readiness probe"
        try:
            self._retarget_current(os.path.join("releases", version))
        except OSError as e:
            return str(e)
        audit("kapmtls_activate", version=version)
        return None

    @staticmethod
    def _version_key(v: str):
        """Natural ordering so v10 > v9 (lexicographic would invert them)."""
        import re as _re

        return [int(p) if p.isdigit() else p for p in _re.split(r"(\d+)", v)]

    def rollback(self) -> Optional[str]:
        """Activate the newest release strictly older than current — a
        newer-but-inactive release must never be "rolled back" to."""
        st = self.status()
        if not st.current_version:
            return "nothing active to roll back from"
        cur_key = self._version_key(st.current_version)
        older = [v for v in st.versions if self._version_key(v) < cur_key]
        if not older:
            return "no older release to roll back to"
        target = sorted(older, key=self._version_key)[-1]
        err = self.activate(target)
        if err is None:
            audit("kapmtls_rollback", to=target)
        return err

    # -- status ------------------------------------------------------------
    @staticmethod
    def _release_ready(d: str) -> bool:
        """Readiness: both files exist, key is private, cert parses."""
        crt = os.path.join(d, "client.crt")
        key = os.path.join(d, "client.key")
        if not (os.path.isfile(crt) and os.path.isfile(key)):
            return False
        try:
            from cryptography import x509

            with open(crt, "rb") as f:
                x509.load_pem_x509_certificate(f.read())
            return True
        except Exception:  # noqa: BLE001
            return False

    def status(self) -> Status:
        versions: List[str] = []
        if os.path.isdir(self.releases_dir):
            versions = sorted(
                v for v in os.listdir(self.releases_dir)
                if os.path.isdir(os.path.join(self.releases_dir, v))
                and ".tmp-" not in v and ".old-" not in v
            )
        current = ""
        link = os.path.join(self.root, "current")
        try:
            current = os.path.basename(os.readlink(link))
        except OSError:
            pass
        ready = bool(current) and self._release_ready(os.path.join(self.root, "current"))
        return Status(current_version=current, versions=versions, ready=ready)
