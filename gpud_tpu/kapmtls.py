"""Node-local mTLS credential manager.

Reference: pkg/kapmtls/manager.go:29-50 — installs short-lived client
certificates pushed by the control plane into atomic release directories
with a ``current`` symlink, supports activation, readiness probing and
rollback, so the node-local agent's identity can be rotated without
downtime.

Layout::

    <root>/releases/<version>/{client.crt,client.key}
    <root>/current -> releases/<version>

Consumer contract (how an agent must read the credentials): resolve
``current`` ONCE, open the resolved directory, and read both files
through that directory handle (``openat``-style). Two independent path
opens through the symlink can straddle a rotation and pair a cert with
the wrong key. Re-pushes of the ACTIVE version swap the release
directory's content with ``renameat2(RENAME_EXCHANGE)`` where the kernel
supports it, so a held directory handle keeps serving the complete OLD
pair for its lifetime — a dirfd consumer never observes a torn pair.
Vacated release dirs are garbage-collected only after GC_GRACE_SECONDS
so an in-flight load through a just-replaced handle still completes.
On filesystems WITHOUT RENAME_EXCHANGE the re-push falls back to a
move-aside dance; there a loader can transiently hit ENOENT and must
retry once (tests/test_kapmtls_agent.py models the dirfd consumer).
"""

from __future__ import annotations

import ctypes
import os
import re
import shutil
import time
from dataclasses import dataclass
from typing import List, Optional

from gpud_tpu.log import audit, get_logger

logger = get_logger(__name__)

DEFAULT_ROOT = "/var/lib/tpud/kapmtls"

_RENAME_EXCHANGE = 2  # linux/fs.h
_AT_FDCWD = -100


def _exchange_dirs(a: str, b: str) -> bool:
    """Atomically swap two paths via renameat2(RENAME_EXCHANGE); False
    when the kernel/filesystem doesn't support it (caller falls back to
    the move-aside dance)."""
    try:
        libc = ctypes.CDLL(None, use_errno=True)
        ret = libc.renameat2(
            _AT_FDCWD, os.fsencode(a), _AT_FDCWD, os.fsencode(b),
            _RENAME_EXCHANGE,
        )
        return ret == 0
    except (OSError, AttributeError):
        return False


@dataclass
class Status:
    current_version: str = ""
    versions: List[str] = None
    ready: bool = False
    error: str = ""

    def to_dict(self) -> dict:
        return {
            "current_version": self.current_version,
            "versions": list(self.versions or []),
            "ready": self.ready,
            "error": self.error,
        }


# vacated release dirs (.old-*/.tmp-*) survive this long so in-flight
# dirfd loads complete; collected at the next install
GC_GRACE_SECONDS = 60.0

# strict staging suffix: <version>.(tmp|old)-<usec-stamp>
_STAGING_RE = re.compile(r"\.(?:tmp|old)-(\d+)$")


class CertManager:
    def __init__(self, root: str = DEFAULT_ROOT) -> None:
        self.root = root
        self.releases_dir = os.path.join(root, "releases")
        self.gc_grace_seconds = GC_GRACE_SECONDS

    @staticmethod
    def _staging_stamp(name: str) -> Optional[float]:
        """Unix time (seconds) a staging/old dir was created, parsed from
        its `<version>.(tmp|old)-<usec>` suffix — mtime is useless here
        (rename preserves the ORIGINAL install mtime, which would make a
        just-vacated dir look ancient and defeat the grace period)."""
        m = _STAGING_RE.search(name)
        if m is None:
            return None
        return int(m.group(1)) / 1e6

    def _gc_stale_dirs(self, grace: Optional[float] = None) -> None:
        """Collect vacated staging/old dirs older than the grace period."""
        if grace is None:
            grace = self.gc_grace_seconds
        try:
            entries = os.listdir(self.releases_dir)
        except OSError:
            return
        now = time.time()
        for e in entries:
            stamp = self._staging_stamp(e)
            if stamp is None:
                continue  # not a staging dir (strict suffix match)
            if now - stamp >= grace:
                shutil.rmtree(
                    os.path.join(self.releases_dir, e), ignore_errors=True
                )

    def _release_dir(self, version: str) -> str:
        if not version or "/" in version or version.startswith("."):
            raise ValueError(f"invalid version {version!r}")
        if ".tmp-" in version or ".old-" in version:
            # the same substring filter status() uses to hide staging
            # dirs: anything installable must be visible in status() and
            # never GC-eligible — reject the whole namespace up front
            raise ValueError(f"version {version!r} uses the staging-dir namespace")
        return os.path.join(self.releases_dir, version)

    # -- install -----------------------------------------------------------
    def install(self, version: str, cert_pem: str, key_pem: str) -> Optional[str]:
        """Write a release atomically (tmp dir + rename). Returns error or
        None. Does NOT activate (reference: install then Activate)."""
        try:
            d = self._release_dir(version)
        except ValueError as e:
            return str(e)
        self._gc_stale_dirs()
        tmp = d + f".tmp-{int(time.time() * 1e6)}"
        try:
            os.makedirs(tmp, exist_ok=True)
            with open(os.path.join(tmp, "client.crt"), "w", encoding="utf-8") as f:
                f.write(cert_pem)
            key_path = os.path.join(tmp, "client.key")
            with open(key_path, "w", encoding="utf-8") as f:
                f.write(key_pem)
            os.chmod(key_path, 0o600)
            old = None
            active_repush = False
            if os.path.isdir(d):
                # re-push of an existing version. Preferred path: atomic
                # content swap — `current` never moves, and a consumer
                # holding the directory open keeps the complete old pair
                # (see the consumer contract in the module docstring)
                if _exchange_dirs(tmp, d):
                    # tmp now holds the OLD release; park it for deferred
                    # GC — deleting immediately would unlink files under
                    # a consumer that resolved just before the exchange
                    try:
                        os.rename(tmp, d + f".old-{int(time.time() * 1e6)}")
                    except OSError:
                        # parking failed: leave it — the .tmp- name is
                        # already GC-eligible after the grace period, and
                        # deleting now is the unlink-under-a-consumer
                        # this whole path exists to avoid
                        pass
                    audit("kapmtls_install", version=version)
                    return None
                # fallback (no RENAME_EXCHANGE): move the old dir aside so
                # the version path is free for the new release
                old = d + f".old-{int(time.time() * 1e6)}"
                link = os.path.join(self.root, "current")
                try:
                    active_repush = os.path.realpath(link) == os.path.realpath(d)
                except OSError:
                    active_repush = False
                if active_repush:
                    # pivot `current` onto the fully-written tmp dir BEFORE
                    # vacating the version path: every crash point below
                    # except the final rename→retarget gap (two syscalls)
                    # leaves `current` pointing at existing credentials
                    self._retarget_current(os.path.relpath(tmp, self.root))
                try:
                    os.rename(d, old)
                except OSError:
                    if active_repush:
                        self._retarget_current(os.path.join("releases", version))
                    raise
            try:
                os.rename(tmp, d)
            except OSError:
                if old is not None:
                    os.rename(old, d)  # restore the previous release
                if active_repush:
                    self._retarget_current(os.path.join("releases", version))
                raise
            if active_repush:
                self._retarget_current(os.path.join("releases", version))
            # `old` (if any) is left for deferred GC — same in-flight
            # consumer rationale as the exchange path
        except OSError as e:
            return str(e)
        audit("kapmtls_install", version=version)
        return None

    def _retarget_current(self, target: str) -> None:
        """Atomic symlink replace of ``current`` → *target* (relative to
        root); cleans up the staging link on failure."""
        link = os.path.join(self.root, "current")
        tmp_link = link + f".tmp-{int(time.time() * 1e6)}"
        try:
            os.symlink(target, tmp_link)
            os.replace(tmp_link, link)
        except OSError:
            try:
                os.unlink(tmp_link)
            except OSError:
                pass
            raise

    # -- activate / rollback ----------------------------------------------
    def activate(self, version: str) -> Optional[str]:
        """Atomic ``current`` symlink swap (symlink-at-temp-path + rename,
        reference: atomic release dirs + current symlink)."""
        try:
            d = self._release_dir(version)
        except ValueError as e:
            return str(e)  # same error-string contract as install()
        if not os.path.isdir(d):
            return f"release {version!r} not installed"
        if not self._release_ready(d):
            return f"release {version!r} failed readiness probe"
        try:
            self._retarget_current(os.path.join("releases", version))
        except OSError as e:
            return str(e)
        audit("kapmtls_activate", version=version)
        return None

    @staticmethod
    def _version_key(v: str):
        """Natural ordering so v10 > v9 (lexicographic would invert them)."""
        return [int(p) if p.isdigit() else p for p in re.split(r"(\d+)", v)]

    def rollback(self) -> Optional[str]:
        """Activate the newest release strictly older than current — a
        newer-but-inactive release must never be "rolled back" to."""
        st = self.status()
        if not st.current_version:
            return "nothing active to roll back from"
        cur_key = self._version_key(st.current_version)
        older = [v for v in st.versions if self._version_key(v) < cur_key]
        if not older:
            return "no older release to roll back to"
        target = sorted(older, key=self._version_key)[-1]
        err = self.activate(target)
        if err is None:
            audit("kapmtls_rollback", to=target)
        return err

    # -- status ------------------------------------------------------------
    @staticmethod
    def _release_ready(d: str) -> bool:
        """Readiness: both files exist, key is private, cert parses."""
        crt = os.path.join(d, "client.crt")
        key = os.path.join(d, "client.key")
        if not (os.path.isfile(crt) and os.path.isfile(key)):
            return False
        try:
            from cryptography import x509

            with open(crt, "rb") as f:
                x509.load_pem_x509_certificate(f.read())
            return True
        except Exception:  # noqa: BLE001
            return False

    def status(self) -> Status:
        versions: List[str] = []
        if os.path.isdir(self.releases_dir):
            versions = sorted(
                v for v in os.listdir(self.releases_dir)
                if os.path.isdir(os.path.join(self.releases_dir, v))
                and ".tmp-" not in v and ".old-" not in v
            )
        current = ""
        link = os.path.join(self.root, "current")
        try:
            current = os.path.basename(os.readlink(link))
        except OSError:
            pass
        ready = bool(current) and self._release_ready(os.path.join(self.root, "current"))
        return Status(current_version=current, versions=versions, ready=ready)
