"""Network utilities.

Reference: pkg/netutil — public/private IP discovery, port checks, and
edge-latency measurement (latency/edge/edge.go measures RTT to the global
Tailscale DERP map; here the edge set is configurable TCP targets since a
TPU fleet's relevant edges are the GCP metadata service, DNS, and the
control plane itself).
"""

from __future__ import annotations

import socket
import time
from typing import Dict, List, Optional, Tuple

from gpud_tpu.log import get_logger

logger = get_logger(__name__)

# (name, host, port) — reachable edges whose RTT approximates egress health
DEFAULT_EDGES: List[Tuple[str, str, int]] = [
    ("gcp-metadata", "metadata.google.internal", 80),
    ("google-dns", "8.8.8.8", 53),
    ("cloudflare-dns", "1.1.1.1", 53),
]


def private_ip() -> str:
    """Primary outbound interface's address (no packets are sent: connect
    on a UDP socket only resolves routing)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return ""


def public_ip(timeout: float = 3.0) -> str:
    """Public IP via the GCE metadata service (first choice on TPU VMs),
    empty when unavailable (reference: pkg/netutil public-IP discovery)."""
    try:
        import urllib.request

        req = urllib.request.Request(
            "http://metadata.google.internal/computeMetadata/v1/instance/"
            "network-interfaces/0/access-configs/0/external-ip",
            headers={"Metadata-Flavor": "Google"},
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read().decode().strip()
    except Exception:  # noqa: BLE001
        return ""


def is_port_open(host: str, port: int, timeout: float = 2.0) -> bool:
    try:
        with socket.create_connection((host, port), timeout=timeout):
            return True
    except OSError:
        return False


def tcp_rtt_ms(host: str, port: int, timeout: float = 2.0) -> Optional[float]:
    t0 = time.perf_counter()
    try:
        with socket.create_connection((host, port), timeout=timeout):
            return (time.perf_counter() - t0) * 1000.0
    except OSError:
        return None


def measure_edges(
    edges: Optional[List[Tuple[str, str, int]]] = None,
    timeout: float = 2.0,
) -> Dict[str, Optional[float]]:
    """RTT per edge (None = unreachable) — the DERP-map analog
    (reference: pkg/netutil/latency/edge/edge.go:1-9)."""
    out: Dict[str, Optional[float]] = {}
    for name, host, port in edges or DEFAULT_EDGES:
        out[name] = tcp_rtt_ms(host, port, timeout=timeout)
    return out
