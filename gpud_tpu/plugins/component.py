"""Plugin components: execute spec steps, parse output, evaluate health.

Reference: pkg/custom-plugins/component.go — exit-code contract (non-zero ⇒
Unhealthy), component naming, registration into the init or component
registry at pkg/server/server.go:344-387 (init plugins run once at boot and
an unhealthy result fails the boot).
"""

from __future__ import annotations

import json
import re
from typing import Dict, List, Optional

from gpud_tpu.api.v1.types import (
    ComponentType,
    HealthStateType,
    SuggestedActions,
)
from gpud_tpu.components.base import CheckResult, PollingComponent, TpudInstance
from gpud_tpu.log import get_logger
from gpud_tpu.plugins.spec import PluginSpec, PluginType, RunMode, extract_path
from gpud_tpu.process import ExclusiveRunner

logger = get_logger(__name__)

# one shared runner: plugin scripts never run concurrently
# (reference: pkg/process ExclusiveRunner)
_RUNNER = ExclusiveRunner()


def _find_json(output: str) -> Optional[object]:
    """Best-effort: parse the last JSON object/array found in the output."""
    for line in reversed(output.strip().splitlines()):
        line = line.strip()
        if line.startswith("{") or line.startswith("["):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


class PluginComponent(PollingComponent):
    """One spec → one component (or one per list item)."""

    def __init__(
        self,
        instance: TpudInstance,
        spec: PluginSpec,
        item: str = "",
        runner: Optional[ExclusiveRunner] = None,
    ) -> None:
        self.spec = spec
        self.item = item
        self.NAME = spec.name if not item else f"{spec.name}.{item}"
        self.TAGS = list(spec.tags) or ["custom-plugin"]
        self.POLL_INTERVAL = spec.interval_seconds
        super().__init__(instance)
        self.runner = runner or _RUNNER

    # custom plugins are deregisterable (reference: components/types.go:69-75)
    def can_deregister(self) -> bool:
        return True

    def start(self) -> None:
        if self.spec.run_mode == RunMode.MANUAL:
            return  # manual plugins only run via trigger-check
        super().start()

    def check_once(self) -> CheckResult:
        env = {"TPUD_PLUGIN_NAME": self.spec.name}
        if self.item:
            env["TPUD_PLUGIN_ITEM"] = self.item
        combined_output = []
        for step in self.spec.steps:
            r = self.runner.run_script(
                self.NAME,
                step.resolved_script(),
                timeout=self.spec.timeout_seconds,
                env=env,
            )
            combined_output.append(r.output)
            if r.timed_out:
                return self._result(
                    HealthStateType.UNHEALTHY,
                    f"step {step.name or '?'} timed out after {self.spec.timeout_seconds}s",
                    "\n".join(combined_output),
                )
            if r.exit_code != 0:
                # exit-code contract: non-zero ⇒ Unhealthy
                return self._result(
                    HealthStateType.UNHEALTHY,
                    f"step {step.name or '?'} exited {r.exit_code}",
                    "\n".join(combined_output),
                )
        output = "\n".join(combined_output)
        return self._parse(output)

    def _parse(self, output: str) -> CheckResult:
        parser = self.spec.parser
        extracted: Dict[str, str] = {}
        if parser.json_paths:
            doc = _find_json(output)
            if doc is not None:
                for fname, path in parser.json_paths.items():
                    v = extract_path(doc, path)
                    if v is not None:
                        extracted[fname] = v if isinstance(v, str) else json.dumps(v)
        for rule in parser.match_rules:
            target = extracted.get(rule.field, "") if rule.field else output
            if re.search(rule.regex, target):
                sa = None
                if rule.suggested_actions:
                    sa = SuggestedActions(
                        description=rule.description or f"plugin {self.NAME} matched {rule.regex!r}",
                        repair_actions=list(rule.suggested_actions),
                    )
                return self._result(
                    rule.health,
                    rule.description or f"matched {rule.regex!r}",
                    output,
                    extracted,
                    sa,
                )
        return self._result(HealthStateType.HEALTHY, "ok", output, extracted)

    def _result(
        self,
        health: str,
        reason: str,
        output: str,
        extracted: Optional[Dict[str, str]] = None,
        sa: Optional[SuggestedActions] = None,
    ) -> CheckResult:
        return CheckResult(
            self.NAME,
            health=health,
            reason=reason,
            suggested_actions=sa,
            extra_info=extracted or {},
            component_type=ComponentType.CUSTOM_PLUGIN,
            run_mode=self.spec.run_mode,
            raw_output=output,
        )


def build_components(
    instance: TpudInstance, specs: List[PluginSpec]
) -> List[PluginComponent]:
    """Expand specs into components (component_list fans out one per item,
    reference: types.go component_list semantics)."""
    out: List[PluginComponent] = []
    for spec in specs:
        if spec.plugin_type == PluginType.COMPONENT_LIST:
            for item in spec.component_list:
                out.append(PluginComponent(instance, spec, item=item))
        elif spec.plugin_type == PluginType.COMPONENT:
            out.append(PluginComponent(instance, spec))
    return out


def run_init_plugins(
    instance: TpudInstance, specs: List[PluginSpec]
) -> Optional[str]:
    """Run init-type plugins once; an unhealthy result fails daemon boot
    (reference: pkg/server/server.go:343-387). Returns error or None."""
    for spec in specs:
        if spec.plugin_type != PluginType.INIT:
            continue
        comp = PluginComponent(instance, spec)
        cr = comp.check()
        if cr.health_state_type() != HealthStateType.HEALTHY:
            return f"init plugin {spec.name!r} unhealthy: {cr.summary()}"
    return None
