"""Custom-plugin specs — YAML-defined dynamic components.

Reference: pkg/custom-plugins/types.go —
- plugin types init / component / component_list (types.go:20-28),
- run modes auto / manual (types.go:55-72),
- steps = bash scripts, plaintext or base64 (types.go:108-130),
- output parser: JSONPath extraction + match rules mapping to health
  states and suggested actions (types.go:132-176+),
- LoadSpecs/SaveSpecs (spec.go:52,78).
"""

from __future__ import annotations

import base64
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import yaml

from gpud_tpu.log import get_logger

logger = get_logger(__name__)


class PluginType:
    INIT = "init"
    COMPONENT = "component"
    COMPONENT_LIST = "component_list"

    _ALL = (INIT, COMPONENT, COMPONENT_LIST)


class RunMode:
    AUTO = "auto"
    MANUAL = "manual"

    _ALL = (AUTO, MANUAL)


@dataclass
class PluginStep:
    name: str = ""
    script: str = ""           # plaintext bash
    script_base64: str = ""    # alternative encoding

    def resolved_script(self) -> str:
        if self.script_base64:
            return base64.b64decode(self.script_base64).decode("utf-8")
        return self.script

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"name": self.name}
        if self.script_base64:
            d["script_base64"] = self.script_base64
        else:
            d["script"] = self.script
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PluginStep":
        if not isinstance(d, dict):
            raise ValueError(f"plugin step must be an object, got {type(d).__name__}")
        return cls(
            name=d.get("name", ""),
            script=d.get("script", ""),
            script_base64=d.get("script_base64", ""),
        )


@dataclass
class MatchRule:
    """If ``regex`` matches the extracted field (or raw output when no
    field), apply health/suggested actions."""

    regex: str = ""
    health: str = "Unhealthy"
    suggested_actions: List[str] = field(default_factory=list)
    description: str = ""
    # extracted-field name; empty = match the raw output. Declared last:
    # the attribute name shadows dataclasses.field inside the class body.
    field: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {
            "regex": self.regex,
            "field": self.field,
            "health": self.health,
            "suggested_actions": list(self.suggested_actions),
            "description": self.description,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "MatchRule":
        return cls(
            regex=d.get("regex", ""),
            field=d.get("field", ""),
            health=d.get("health", "Unhealthy"),
            suggested_actions=list(d.get("suggested_actions", []) or []),
            description=d.get("description", ""),
        )


@dataclass
class OutputParser:
    """``json_paths`` extract named fields from the last step's JSON output
    (dot-path syntax: ``$.a.b[0].c``); ``match_rules`` evaluate them."""

    json_paths: Dict[str, str] = field(default_factory=dict)  # field → path
    match_rules: List[MatchRule] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "json_paths": dict(self.json_paths),
            "match_rules": [r.to_dict() for r in self.match_rules],
        }

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "OutputParser":
        if not d:
            return cls()
        return cls(
            json_paths=dict(d.get("json_paths", {}) or {}),
            match_rules=[MatchRule.from_dict(r) for r in d.get("match_rules", []) or []],
        )


@dataclass
class PluginSpec:
    name: str = ""
    plugin_type: str = PluginType.COMPONENT
    run_mode: str = RunMode.AUTO
    interval_seconds: float = 60.0
    timeout_seconds: float = 60.0
    steps: List[PluginStep] = field(default_factory=list)
    parser: OutputParser = field(default_factory=OutputParser)
    tags: List[str] = field(default_factory=list)
    component_list: List[str] = field(default_factory=list)  # for component_list

    def validate(self) -> Optional[str]:
        if not self.name:
            return "plugin name required"
        if not re.fullmatch(r"[a-zA-Z0-9_.-]+", self.name):
            return f"invalid plugin name {self.name!r}"
        if self.plugin_type not in PluginType._ALL:
            return f"invalid plugin type {self.plugin_type!r}"
        if self.run_mode not in RunMode._ALL:
            return f"invalid run mode {self.run_mode!r}"
        if not self.steps:
            return "at least one step required"
        if self.plugin_type == PluginType.COMPONENT_LIST and not self.component_list:
            return "component_list plugins need a component_list"
        for rule in self.parser.match_rules:
            # a broken rule regex must be rejected here, at push time —
            # not explode inside the poller at 3am. An EMPTY regex matches
            # everything (a typoed YAML key silently defaults to "") and
            # would fire the rule on every poll — equally rejected.
            if not rule.regex:
                return "match rule with empty regex (typoed 'regex:' key?)"
            try:
                re.compile(rule.regex)
            except re.error as e:
                return f"invalid match-rule regex {rule.regex!r}: {e}"
        for s in self.steps:
            if not s.resolved_script().strip():
                return f"step {s.name!r} has an empty script"
        if self.interval_seconds < 1:
            return "interval must be >= 1s"
        return None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "plugin_type": self.plugin_type,
            "run_mode": self.run_mode,
            "interval_seconds": self.interval_seconds,
            "timeout_seconds": self.timeout_seconds,
            "steps": [s.to_dict() for s in self.steps],
            "parser": self.parser.to_dict(),
            "tags": list(self.tags),
            "component_list": list(self.component_list),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PluginSpec":
        if not isinstance(d, dict):
            raise ValueError(f"plugin spec must be an object, got {type(d).__name__}")
        steps_raw = d.get("steps", []) or []
        if not isinstance(steps_raw, list):
            raise ValueError("plugin steps must be a list")
        return cls(
            name=d.get("name", ""),
            plugin_type=d.get("plugin_type", PluginType.COMPONENT),
            run_mode=d.get("run_mode", RunMode.AUTO),
            interval_seconds=float(d.get("interval_seconds", 60.0)),
            timeout_seconds=float(d.get("timeout_seconds", 60.0)),
            steps=[PluginStep.from_dict(s) for s in steps_raw],
            parser=OutputParser.from_dict(d.get("parser")),
            tags=list(d.get("tags", []) or []),
            component_list=list(d.get("component_list", []) or []),
        )


def specs_from_list(
    items: List[Dict[str, Any]], on_invalid: str = "raise"
) -> List[PluginSpec]:
    """``on_invalid="raise"`` is the push-time contract (setPluginSpecs
    rejects the whole batch); ``"skip"`` is boot-time leniency — an older
    or hand-edited plugins.yaml with one bad spec must degrade that
    plugin, not crash-loop the daemon (same rationale as the built-in
    name-clash skip in server.py)."""
    out: List[PluginSpec] = []
    names = set()
    for d in items:
        try:
            s = PluginSpec.from_dict(d)
            err = s.validate()
            if err:
                raise ValueError(f"plugin {s.name!r}: {err}")
            if s.name in names:
                raise ValueError(f"duplicate plugin name {s.name!r}")
        except (ValueError, KeyError):
            if on_invalid == "skip":
                logger.error("skipping invalid plugin spec: %r", d)
                continue
            raise
        names.add(s.name)
        out.append(s)
    return out


def load_specs(path: str, on_invalid: str = "raise") -> List[PluginSpec]:
    """Reference: pkg/custom-plugins/spec.go:52 LoadSpecs."""
    with open(path, "r", encoding="utf-8") as f:
        data = yaml.safe_load(f) or []
    if not isinstance(data, list):
        raise ValueError("plugin specs file must contain a YAML list")
    return specs_from_list(data, on_invalid=on_invalid)


def save_specs(path: str, specs: List[PluginSpec]) -> None:
    """Reference: pkg/custom-plugins/spec.go:78 SaveSpecs."""
    import os

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        yaml.safe_dump([s.to_dict() for s in specs], f, sort_keys=False)


# ---------------------------------------------------------------------------
# dot-path extraction (JSONPath-lite)
# ---------------------------------------------------------------------------

_PATH_TOKEN = re.compile(r"\.([A-Za-z0-9_-]+)|\[(\d+)\]")


def extract_path(obj: Any, path: str) -> Optional[Any]:
    """``$.a.b[0].c`` over parsed JSON. Returns None when absent."""
    if not path.startswith("$"):
        return None
    cur = obj
    for m in _PATH_TOKEN.finditer(path[1:]):
        key, idx = m.group(1), m.group(2)
        try:
            if key is not None:
                cur = cur[key]
            else:
                cur = cur[int(idx)]
        except (KeyError, IndexError, TypeError):
            return None
    return cur
