"""Block-device tree from /sys/block (the reference's lsblk analog).

Reference: pkg/machine-info/machine_info.go:45-434 builds a per-disk
filesystem tree by exec'ing lsblk/findmnt (pkg/disk); here the same tree
is read from the kernel's own surface — /sys/block/<dev>/ for geometry
and /proc/self/mounts for filesystem placement — with no subprocesses.
Roots are parameterized so checked-in fixture trees drive tests (the
same pattern as tpu/sysfs.py), and ``host_root`` supports containerized
deployments where the host's /sys and /proc are mounted under a prefix
(reference: nsenter-prefix overrides, components/registry.go:46-64).
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Tuple

from gpud_tpu.api.v1.types import BlockDeviceInfo
from gpud_tpu.log import get_logger

logger = get_logger(__name__)

# loop/ram/zram and device-mapper internals are noise for fleet health
_SKIP_PREFIXES = ("loop", "ram", "zram", "fd")

ENV_HOST_ROOT = "TPUD_HOST_ROOT"


def _read(path: str) -> str:
    try:
        with open(path, "r", encoding="ascii", errors="replace") as f:
            return f.read().strip()
    except OSError:
        return ""


def _read_int(path: str) -> int:
    v = _read(path)
    try:
        return int(v)
    except ValueError:
        return 0


_OCTAL_ESCAPE = re.compile(r"\\([0-7]{3})")


def _unescape_mount(s: str) -> str:
    """Expand fstab(5) octal escapes (\\040 = space) ONLY — a blanket
    unicode_escape pass would mojibake non-ASCII mount points (UTF-8
    reinterpreted as latin-1)."""
    return _OCTAL_ESCAPE.sub(lambda m: chr(int(m.group(1), 8)), s)


class MountEntry:
    """One /proc/mounts row (octal escapes expanded)."""

    __slots__ = ("device", "mount_point", "fstype", "options")

    def __init__(self, device: str, mount_point: str, fstype: str,
                 options: List[str]) -> None:
        self.device = device
        self.mount_point = mount_point
        self.fstype = fstype
        self.options = options


def read_mount_table(
    proc_mounts: str = "", host_root: Optional[str] = None
) -> List[MountEntry]:
    """All /dev/*-backed rows of the mount table, options included.

    ``host_root`` (default: the TPUD_HOST_ROOT env; pass "" to suppress)
    redirects to the host's table in containerized deployments — the
    container's own /proc/self/mounts shows an overlay root, not the
    node's disks."""
    if host_root is None:
        host_root = os.environ.get(ENV_HOST_ROOT, "")
    path = proc_mounts or (
        os.path.join(host_root, "proc", "mounts")
        if host_root
        else "/proc/self/mounts"
    )
    out: List[MountEntry] = []
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                parts = line.split()
                if len(parts) < 4 or not parts[0].startswith("/dev/"):
                    continue
                out.append(MountEntry(
                    device=parts[0],
                    mount_point=_unescape_mount(parts[1]),
                    fstype=parts[2],
                    options=parts[3].split(","),
                ))
    except OSError:
        pass
    return out


def read_mounts(proc_mounts: str = "") -> Dict[str, Tuple[str, str]]:
    """device path → (mount_point, fstype) from /proc/self/mounts.
    First mount of a device wins (matches lsblk's MOUNTPOINT)."""
    out: Dict[str, Tuple[str, str]] = {}
    # host_root="": callers (read_block_tree) already resolved any host
    # prefix into proc_mounts — applying the env again would double it
    for e in read_mount_table(proc_mounts, host_root=""):
        dev = os.path.basename(e.device)
        if dev not in out:
            out[dev] = (e.mount_point, e.fstype)
    return out


def _statvfs_used(mount_point: str) -> int:
    try:
        st = os.statvfs(mount_point)
        return (st.f_blocks - st.f_bfree) * st.f_frsize
    except OSError:
        return 0


def read_block_tree(
    sys_block_root: str = "",
    proc_mounts: str = "",
    host_root: str = "",
) -> List[BlockDeviceInfo]:
    """Disk → partition tree with mounts and usage attached.

    ``host_root`` (or the TPUD_HOST_ROOT env) prefixes the default /sys
    and /proc paths for containerized deployments that bind-mount the
    host's trees under e.g. /host.
    """
    host_root = host_root or os.environ.get(ENV_HOST_ROOT, "")
    root = sys_block_root or os.path.join(host_root or "/", "sys", "block")
    mounts_path = proc_mounts or (
        os.path.join(host_root, "proc", "mounts") if host_root else ""
    )
    mounts = read_mounts(mounts_path)
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return []
    out: List[BlockDeviceInfo] = []
    for name in names:
        if name.startswith(_SKIP_PREFIXES):
            continue
        dev_dir = os.path.join(root, name)
        disk = BlockDeviceInfo(
            name=name,
            type="disk",
            size_bytes=_read_int(os.path.join(dev_dir, "size")) * 512,
            model=_read(os.path.join(dev_dir, "device", "model")),
            rotational=_read(os.path.join(dev_dir, "queue", "rotational")) == "1",
            removable=_read(os.path.join(dev_dir, "removable")) == "1",
        )
        _attach_mount(disk, mounts, host_root)
        # partitions are subdirectories whose name extends the disk's
        # (sda → sda1; nvme0n1 → nvme0n1p1) and carry a `partition` file
        try:
            entries = sorted(os.listdir(dev_dir))
        except OSError:
            entries = []
        for sub in entries:
            sub_dir = os.path.join(dev_dir, sub)
            if not sub.startswith(name):
                continue
            if not os.path.isfile(os.path.join(sub_dir, "partition")):
                continue
            part = BlockDeviceInfo(
                name=sub,
                type="part",
                size_bytes=_read_int(os.path.join(sub_dir, "size")) * 512,
                rotational=disk.rotational,
            )
            _attach_mount(part, mounts, host_root)
            disk.children.append(part)
        out.append(disk)
    return out


def _attach_mount(
    node: BlockDeviceInfo,
    mounts: Dict[str, Tuple[str, str]],
    host_root: str = "",
) -> None:
    m = mounts.get(node.name)
    if m is None:
        return
    node.mount_point, node.fstype = m
    # stat the host's filesystem, not the container's own namespace: with
    # a host_root bind-mount the host path is visible under the prefix
    stat_path = (
        os.path.join(host_root, node.mount_point.lstrip("/"))
        if host_root
        else node.mount_point
    )
    node.used_bytes = _statvfs_used(stat_path)


def detect_containerized(host_root: str = "/") -> bool:
    """Best-effort container detection: a /.dockerenv marker or a
    non-root cgroup for PID 1 (docker/containerd/kubepods slices)."""
    if os.path.exists(os.path.join(host_root, ".dockerenv")):
        return True
    cg = _read("/proc/1/cgroup")
    return any(tok in cg for tok in ("docker", "containerd", "kubepods"))


__all__ = [
    "MountEntry",
    "read_block_tree",
    "read_mount_table",
    "read_mounts",
    "detect_containerized",
    "ENV_HOST_ROOT",
]
