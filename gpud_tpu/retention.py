"""Shared retention-purge loop.

One pattern for every SQLite-backed store that ages out rows (eventstore,
health-transition ledger, …): a daemon thread that calls a purge callback
at ``retention/5`` cadence (reference: pkg/eventstore/database.go:85-90),
stoppable via ``close()`` so daemon shutdown never leaves a purger running
against a closed DB.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from gpud_tpu.log import get_logger

logger = get_logger(__name__)

MIN_INTERVAL = 60.0


class RetentionPurger:
    """Run ``purge_fn`` every ``interval_seconds`` (floored at 60 s) on a
    named daemon thread. ``start`` is idempotent; ``close`` stops and joins.
    A purge callback that raises is logged and retried next tick — a
    transient DB error must not end retention for the process's life."""

    def __init__(
        self, name: str, interval_seconds: float, purge_fn: Callable[[], None]
    ) -> None:
        self.name = name
        self.interval = max(MIN_INTERVAL, float(interval_seconds))
        self._purge_fn = purge_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name=self.name, daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self._purge_fn()
            except Exception:  # noqa: BLE001 — retention must outlive one bad tick
                logger.exception("%s purge failed", self.name)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
