"""Shared retention-purge loop.

One pattern for every SQLite-backed store that ages out rows (eventstore,
health-transition ledger, …): a daemon thread that calls a purge callback
at ``retention/5`` cadence (reference: pkg/eventstore/database.go:85-90),
stoppable via ``close()`` so daemon shutdown never leaves a purger running
against a closed DB.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from gpud_tpu.log import get_logger

logger = get_logger(__name__)

MIN_INTERVAL = 60.0


class RetentionPurger:
    """Run ``purge_fn`` every ``interval_seconds`` (floored at 60 s).

    With a scheduler (the daemon path), ``start(scheduler)`` registers a
    heap job on the shared pool — no thread. Without one, a named daemon
    thread is spawned (stores opened standalone by the CLI/tests).
    ``start`` is idempotent; ``close`` stops and joins/cancels. A purge
    callback that raises is logged and retried next tick — a transient DB
    error must not end retention for the process's life. (The daemon
    itself goes one step further and consolidates all its purgers into a
    single ``retention-purge`` scheduler job — see server.Server.)"""

    def __init__(
        self, name: str, interval_seconds: float, purge_fn: Callable[[], None]
    ) -> None:
        self.name = name
        self.interval = max(MIN_INTERVAL, float(interval_seconds))
        self._purge_fn = purge_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._job = None

    def purge_once(self) -> None:
        """One purge pass now (what each tick runs) — public so a
        consolidated scheduler job can drive several purgers on one
        cadence without each costing a thread or a job."""
        self._purge_fn()

    def start(self, scheduler=None) -> None:
        if scheduler is not None:
            if self._job is None and self._thread is None:
                # the scheduler traps + counts exceptions itself, matching
                # the legacy loop's log-and-retry contract
                self._job = scheduler.add_job(
                    self.name,
                    self._purge_fn,
                    interval=self.interval,
                    initial_delay=self.interval,
                )
            return
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name=self.name, daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self._purge_fn()
            except Exception:  # noqa: BLE001 — retention must outlive one bad tick
                logger.exception("%s purge failed", self.name)

    def close(self) -> None:
        if self._job is not None:
            self._job.cancel()
            self._job = None
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
