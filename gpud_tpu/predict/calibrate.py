"""Threshold calibration: fit per-component-class warning thresholds and
feature weights by replaying the node's own health-ledger history.

The global ``predict_threshold`` default (0.6) is a fleet-wide
compromise: it must sit above the benign score noise of the *noisiest*
component class anywhere, which leaves quiet classes with headroom a
lower threshold could convert into earlier warnings. The calibrator
closes that gap per node, per class, with a zero-false-positive
guarantee against the node's own recorded past:

1. Replay the component class's full persisted transition timeline
   (:meth:`HealthLedger.history` — the durable twin of the in-memory
   deques the live scorer reads) and score every transition instant with
   the same cadence + trajectory extractors the engine runs online.
2. Label each sample *benign* unless the component transitions into
   Unhealthy within ``horizon_seconds`` after it; samples that precede a
   failure are the precursor shoulder the threshold must stay below.
3. The calibrated threshold is the benign score quantile-max plus a
   margin, clamped to ``[min_threshold, global default]`` — it only ever
   *lowers* the bar, and never below any benign sample, so replaying the
   same history through the calibrated threshold arms zero times on
   benign samples by construction.
4. Feature weights are fitted the same way: a feature whose benign
   replay maximum is historically noisy gets its weight scaled down so
   that feature alone can never cross the calibrated threshold — the
   per-class restatement of the "no single weak signal convicts"
   structural rule in features.py.

Thin history (< ``min_history`` transitions for the class) falls back to
the global defaults: a node that has never misbehaved has nothing to
calibrate against, and a freshly imaged node must not inherit a
hair-trigger threshold from noise.

Deterministic and clock-injectable like everything else in this package:
the replay is a pure function of the ledger rows and the knobs.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from gpud_tpu.api.v1.types import HealthStateType
from gpud_tpu.predict.features import (
    FEATURE_WEIGHTS,
    cadence_score,
    clamp01,
    fuse,
    trajectory_score,
)

# outbox payload schema for ``predict_score`` records: bump when the
# payload shape changes incompatibly. The manager ingests any schema it
# knows (<= this) and counts-but-never-drops newer ones (docs/fleet.md).
PREDICT_SCHEMA = 1

DEFAULT_MIN_HISTORY = 8
DEFAULT_MIN_THRESHOLD = 0.35
DEFAULT_MARGIN = 0.05
DEFAULT_HORIZON = 900.0
DEFAULT_CALIBRATE_INTERVAL = 3600.0

# fitted weights never drop below this fraction of their default: a
# weight scaled to ~0 would silently delete a feature from the fusion,
# which is a config decision, not a calibration outcome
MIN_WEIGHT_FRACTION = 0.3

# the replayed feature subset: cadence + trajectory are pure functions
# of the transition timeline the ledger persists. Latency/ngram state
# lives in unlogged online extractors and cannot be replayed from the
# ledger, so their weights are never fitted here.
REPLAYED_FEATURES = ("cadence", "trajectory")


def component_class(name: str) -> str:
    """Map a component name to its class: the name with any trailing
    instance index stripped (``accelerator-tpu-3`` → ``accelerator-tpu``;
    un-indexed names are their own class). Calibration and the fleet
    pane both group by this."""
    base = str(name).rstrip("0123456789")
    base = base.rstrip("-_.")
    return base or str(name)


def _replay_samples(
    rows: List[Dict],
    window_seconds: float,
    saturation: int,
    horizon_seconds: float,
) -> List[Tuple[Dict[str, float], bool]]:
    """Score every transition instant of one component's ascending
    timeline. Returns ``(features, benign)`` per sample; a sample is
    benign iff no later transition lands in Unhealthy within the
    horizon."""
    times = [r["time"] for r in rows]
    unhealthy_ts = [
        r["time"] for r in rows if r["to"] == HealthStateType.UNHEALTHY
    ]
    out: List[Tuple[Dict[str, float], bool]] = []
    for i, row in enumerate(rows):
        now = row["time"]
        seen = [
            (r["time"], r["from"], r["to"]) for r in rows[: i + 1]
        ]
        feats = {
            "cadence": cadence_score(
                times[: i + 1], now, window_seconds, saturation=saturation
            ),
            "trajectory": trajectory_score(row["to"], seen, now,
                                           window_seconds),
        }
        # the failure instant itself is ground truth, not benign noise —
        # a threshold firing AT the Unhealthy transition is the reactive
        # signal, never a false positive to calibrate above
        benign = row["to"] != HealthStateType.UNHEALTHY and not any(
            now < ts <= now + horizon_seconds for ts in unhealthy_ts
        )
        out.append((feats, benign))
    return out


class ClassCalibration:
    """One class's fitted threshold + weights and its provenance."""

    __slots__ = (
        "threshold", "weights", "source", "samples", "benign_samples",
        "benign_max", "precursor_min", "components", "fitted_at",
    )

    def __init__(self, threshold: float, weights: Dict[str, float]) -> None:
        self.threshold = threshold
        self.weights = weights
        self.source = "default"
        self.samples = 0
        self.benign_samples = 0
        self.benign_max = 0.0
        self.precursor_min: Optional[float] = None
        self.components = 0
        self.fitted_at = 0.0

    def as_dict(self) -> Dict:
        return {
            "threshold": round(self.threshold, 4),
            "weights": {
                k: round(v, 4) for k, v in sorted(self.weights.items())
            },
            "source": self.source,
            "samples": self.samples,
            "benign_samples": self.benign_samples,
            "benign_max": round(self.benign_max, 4),
            "precursor_min": (
                None if self.precursor_min is None
                else round(self.precursor_min, 4)
            ),
            "components": self.components,
            "fitted_at": self.fitted_at,
        }


class ThresholdCalibrator:
    """Fit per-class thresholds/weights from one ledger's history.

    Stateless between :meth:`calibrate` calls — the engine owns the
    fitted map and swaps it atomically under its own lock."""

    def __init__(
        self,
        ledger=None,
        default_threshold: float = 0.6,
        window_seconds: float = 600.0,
        min_history: int = DEFAULT_MIN_HISTORY,
        min_threshold: float = DEFAULT_MIN_THRESHOLD,
        margin: float = DEFAULT_MARGIN,
        horizon_seconds: float = DEFAULT_HORIZON,
    ) -> None:
        self.ledger = ledger
        self.default_threshold = float(default_threshold)
        self.window = float(window_seconds)
        self.min_history = max(1, int(min_history))
        self.min_threshold = float(min_threshold)
        self.margin = float(margin)
        self.horizon = float(horizon_seconds)

    # -- fitting -----------------------------------------------------------
    def calibrate(
        self, now: float, components: Optional[Iterable[str]] = None
    ) -> Dict[str, ClassCalibration]:
        """Fit every class present in the ledger history (optionally
        restricted to ``components``). Returns {class: ClassCalibration};
        classes with thin history get a default-sourced entry so views
        can show *why* a class is uncalibrated."""
        if self.ledger is None:
            return {}
        rows = self.ledger.history()
        rows.reverse()  # history() is newest-first; replay wants ascending
        wanted = None if components is None else {
            component_class(c) for c in components
        }
        by_comp: Dict[str, List[Dict]] = {}
        for r in rows:
            by_comp.setdefault(r["component"], []).append(r)
        by_class: Dict[str, List[Tuple[str, List[Dict]]]] = {}
        for comp, comp_rows in sorted(by_comp.items()):
            cls = component_class(comp)
            if wanted is not None and cls not in wanted:
                continue
            by_class.setdefault(cls, []).append((comp, comp_rows))
        saturation = 5
        if self.ledger is not None:
            saturation = max(2, int(getattr(self.ledger, "flap_threshold", 5)))
        out: Dict[str, ClassCalibration] = {}
        for cls, members in sorted(by_class.items()):
            out[cls] = self._fit_class(cls, members, saturation, now)
        return out

    def _fit_class(
        self,
        cls: str,
        members: List[Tuple[str, List[Dict]]],
        saturation: int,
        now: float,
    ) -> ClassCalibration:
        cal = ClassCalibration(self.default_threshold, dict(FEATURE_WEIGHTS))
        cal.components = len(members)
        cal.fitted_at = now
        samples: List[Tuple[Dict[str, float], bool]] = []
        for _comp, comp_rows in members:
            samples.extend(
                _replay_samples(comp_rows, self.window, saturation,
                                self.horizon)
            )
        cal.samples = len(samples)
        if cal.samples < self.min_history:
            return cal  # thin history: global defaults, source="default"
        benign_scores: List[float] = []
        benign_feat_max: Dict[str, float] = {f: 0.0 for f in REPLAYED_FEATURES}
        precursor_scores: List[float] = []
        for feats, benign in samples:
            score = fuse(feats)
            if benign:
                benign_scores.append(score)
                for f in REPLAYED_FEATURES:
                    if feats[f] > benign_feat_max[f]:
                        benign_feat_max[f] = feats[f]
            else:
                precursor_scores.append(score)
        cal.benign_samples = len(benign_scores)
        cal.benign_max = max(benign_scores) if benign_scores else 0.0
        cal.precursor_min = (
            min(precursor_scores) if precursor_scores else None
        )
        # threshold: one margin above the benign maximum (the 100th
        # benign quantile — zero historical false positives by
        # construction), clamped so calibration only ever lowers the
        # global bar, never raises it, and never below the floor
        fitted = clamp01(cal.benign_max + self.margin)
        cal.threshold = min(
            self.default_threshold, max(self.min_threshold, fitted)
        )
        # weights: scale down any replayed feature whose benign maximum
        # could alone cross the fitted threshold (w * benign_max must
        # stay below threshold - margin), floored so no feature is
        # silently deleted from the fusion
        for f in REPLAYED_FEATURES:
            default_w = FEATURE_WEIGHTS[f]
            peak = benign_feat_max[f]
            if peak <= 0.0:
                continue
            cap = (cal.threshold - self.margin) / peak
            floor = default_w * MIN_WEIGHT_FRACTION
            cal.weights[f] = min(default_w, max(floor, cap))
        cal.source = "calibrated"
        return cal
