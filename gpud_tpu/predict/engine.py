"""Predict engine: online precursor scoring that warns before hard faults.

One scheduler job (``predict-scan``) ticks every ``interval_seconds``,
pulls per-component features from traces the daemon already keeps —
check-latency drift from the ``tpud_component_check_duration_seconds``
histogram, transition cadence + state trajectory from the health ledger's
in-memory deques (:meth:`HealthLedger.recent_transitions`, barrier-free),
and kmsg error-class bigram novelty over a bounded eventstore window —
fuses them into a bounded [0, 1] precursor score, and runs the score
through per-component hysteresis:

  score >= threshold for ``arm_ticks`` consecutive ticks   → WARN
  score <= threshold - hysteresis for ``clear_ticks`` ticks → CLEAR

A warning emits, atomically from the operator's point of view:

- a ``predicted_degraded`` Warning event into the component's bucket;
- a ``predicted`` annotation the ledger merges into every subsequent
  check result (``Degraded(predicted)`` in /v1/states extra_info);
- a dry-run audit row (action ``predicted_warning``, suggested
  ``PREDICTED_DEGRADATION``) in the remediation ledger — predicted
  actions are NEVER auto-enforced: the suggestion maps to no executable
  action, the row pre-arms only the predict lane's own cooldown, and the
  reactive engine's cooldown anchor explicitly excludes it;
- an outbox publish (kind ``predict_score``) so the fleet plane can rank
  nodes most likely to fail next.

Lead time is measured per armed episode: the first reactive hard signal
after the warning (a ledger transition into Unhealthy, or the flap
window reaching the reactive flap threshold) closes the measurement and
lands in ``tpud_predict_lead_time_seconds``.

Deterministic by construction: injectable clock, no randomness, and
``tick_once`` is synchronous — tests and the chaos runner drive it
directly.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from gpud_tpu.api.v1.types import (
    Event,
    EventType,
    HealthStateType,
    RepairActionType,
)
from gpud_tpu.components.base import _h_check_duration
from gpud_tpu.log import get_logger
from gpud_tpu.metrics.registry import counter, gauge, histogram
from gpud_tpu.predict.calibrate import (
    DEFAULT_CALIBRATE_INTERVAL,
    DEFAULT_HORIZON,
    DEFAULT_MARGIN,
    DEFAULT_MIN_HISTORY,
    DEFAULT_MIN_THRESHOLD,
    PREDICT_SCHEMA,
    ClassCalibration,
    ThresholdCalibrator,
    component_class,
)
from gpud_tpu.predict.features import (
    FEATURE_WEIGHTS,
    LatencyDrift,
    NgramNovelty,
    cadence_score,
    fuse,
    peer_corroboration,
    trajectory_score,
)
from gpud_tpu.remediation.policy import (
    ACTION_PREDICTED,
    DECISION_DRY_RUN,
    OUTCOME_DRY_RUN,
)

logger = get_logger(__name__)

DEFAULT_INTERVAL = 15.0
DEFAULT_THRESHOLD = 0.6
DEFAULT_HYSTERESIS = 0.15
DEFAULT_ARM_TICKS = 2
DEFAULT_CLEAR_TICKS = 3
DEFAULT_WINDOW = 600.0
DEFAULT_HISTORY_LIMIT = 256
DEFAULT_WARN_COOLDOWN = 300.0
DEFAULT_PUBLISH_INTERVAL = 60.0

EVENT_NAME_PREDICTED = "predicted_degraded"

_g_score = gauge(
    "tpud_predict_precursor_score",
    "fused precursor score in [0,1] (latency drift + transition cadence "
    "+ state trajectory + kmsg error-class novelty), by component",
)
_c_warnings = counter(
    "tpud_predict_warnings_total",
    "predictive Degraded(predicted) warnings emitted, by component",
)
_h_lead = histogram(
    "tpud_predict_lead_time_seconds",
    "seconds from a predictive warning to the first reactive hard signal "
    "(Unhealthy transition or flap-threshold trip), by component",
)
_h_tick = histogram(
    "tpud_predict_tick_duration_seconds",
    "wall time of one full predict scan over every component",
)
_g_threshold = gauge(
    "tpud_predict_threshold",
    "effective warning threshold (calibrated per component class, or "
    "the global default), by component",
)
_c_calibrations = counter(
    "tpud_predict_calibrations_total",
    "ledger-history calibration passes completed",
)


class _CompState:
    """Per-component scorer state: feature extractors, hysteresis
    counters, the armed-episode bookkeeping, and bounded score history."""

    __slots__ = (
        "latency", "ngram", "score", "features", "above", "below",
        "armed", "warned_at", "warn_score", "lead_seconds", "warnings",
        "history", "last_publish", "cleared_at",
    )

    def __init__(self, history_limit: int) -> None:
        self.latency = LatencyDrift()
        self.ngram = NgramNovelty()
        self.score = 0.0
        self.features: Dict[str, float] = {}
        self.above = 0
        self.below = 0
        self.armed = False
        self.warned_at: Optional[float] = None
        self.warn_score = 0.0
        self.lead_seconds: Optional[float] = None
        self.warnings = 0
        self.history: deque = deque(maxlen=max(1, history_limit))
        self.last_publish = 0.0
        self.cleared_at: Optional[float] = None


class PredictEngine:
    """One engine per daemon, wired like the remediation engine:
    constructed in ``server.Server``, ``start(scheduler)`` in the
    assembly block, ``close()`` on stop."""

    GUARDED_BY = {
        "_st": "_mu",
        "_ticks": "_mu",
        "_last_tick": "_mu",
        "_calib": "_mu",
        "_last_calibrate": "_mu",
    }
    _LOCK_FREE = {
        "_component_features": "caller tick_once() holds _mu across "
                               "the whole scoring pass",
        "_threshold_for": "callers hold _mu (tick pass / view methods)",
        "_weights_for": "callers hold _mu (tick pass / view methods)",
    }

    def __init__(
        self,
        registry=None,
        ledger=None,
        event_store=None,
        remediation=None,
        enabled: bool = True,
        interval_seconds: float = DEFAULT_INTERVAL,
        threshold: float = DEFAULT_THRESHOLD,
        hysteresis: float = DEFAULT_HYSTERESIS,
        arm_ticks: int = DEFAULT_ARM_TICKS,
        clear_ticks: int = DEFAULT_CLEAR_TICKS,
        window_seconds: float = DEFAULT_WINDOW,
        history_limit: int = DEFAULT_HISTORY_LIMIT,
        warn_cooldown_seconds: float = DEFAULT_WARN_COOLDOWN,
        publish_interval_seconds: float = DEFAULT_PUBLISH_INTERVAL,
        calibrate_enabled: bool = True,
        calibrate_interval_seconds: float = DEFAULT_CALIBRATE_INTERVAL,
        calibrate_min_history: int = DEFAULT_MIN_HISTORY,
        calibrate_min_threshold: float = DEFAULT_MIN_THRESHOLD,
        calibrate_margin: float = DEFAULT_MARGIN,
        calibrate_horizon_seconds: float = DEFAULT_HORIZON,
    ) -> None:
        self.registry = registry
        self.ledger = ledger
        self.event_store = event_store
        self.remediation = remediation
        self.enabled = enabled
        self.interval = interval_seconds
        self.threshold = threshold
        self.hysteresis = hysteresis
        self.arm_ticks = max(1, int(arm_ticks))
        self.clear_ticks = max(1, int(clear_ticks))
        self.window = window_seconds
        self.history_limit = history_limit
        self.warn_cooldown = warn_cooldown_seconds
        self.publish_interval = publish_interval_seconds
        self.calibrate_enabled = calibrate_enabled
        self.calibrate_interval = calibrate_interval_seconds
        self.calibrate_min_history = max(1, int(calibrate_min_history))
        self.calibrate_min_threshold = calibrate_min_threshold
        self.calibrate_margin = calibrate_margin
        self.calibrate_horizon = calibrate_horizon_seconds
        self.time_now_fn = time.time
        # optional score publisher (the server wires the session outbox
        # here); must never fail the tick
        self.on_publish = None
        # optional fabric plane (gpud_tpu/fabric): when attached, the ICI
        # component's feature set gains the neighbor co-occurrence signal
        self.fabric = None
        self._mu = threading.Lock()
        self._st: Dict[str, _CompState] = {}
        self._ticks = 0
        self._last_tick: Optional[float] = None
        self._calib: Dict[str, ClassCalibration] = {}
        self._last_calibrate: Optional[float] = None
        self._job = None  # scheduler Job when scheduler-driven
        self._calib_job = None

    # -- lifecycle ---------------------------------------------------------
    def start(self, scheduler=None) -> None:
        """Scheduler-driven only: the daemon always has one, and tests
        call :meth:`tick_once` directly. First tick waits out one
        interval so component first-checks land before scoring."""
        if not self.enabled or scheduler is None:
            return
        if self._job is None:
            self._job = scheduler.add_job(
                "predict-scan",
                self.tick_once,
                interval=self.interval,
                initial_delay=self.interval,
            )
        if self.calibrate_enabled and self._calib_job is None:
            # first fit runs one scan-interval after boot (the ledger's
            # persisted history is already there), then re-fits on the
            # calibrate cadence as new history accrues
            self._calib_job = scheduler.add_job(
                "predict-calibrate",
                self.calibrate_now,
                interval=self.calibrate_interval,
                initial_delay=self.interval,
            )

    def poke(self) -> None:
        """Scan now: poke the scheduler job, or tick synchronously when
        not scheduler-driven (tests, chaos expectation evaluation)."""
        if self._job is not None:
            self._job.poke()
        elif self.enabled:
            self.tick_once()

    def close(self) -> None:
        if self._job is not None:
            self._job.cancel()
            self._job = None
        if self._calib_job is not None:
            self._calib_job.cancel()
            self._calib_job = None

    def reset(self, component: str = "") -> None:
        """Drop the in-memory scorer state (one component, or all) and
        its ledger annotations. Chaos campaigns use this for isolation:
        a fresh drill must not inherit armed warnings or trained
        baselines from faults an earlier campaign injected."""
        with self._mu:
            names = (
                [component] if component else list(self._st.keys())
            )
            for name in names:
                self._st.pop(name, None)
        if self.ledger is not None:
            for name in names:
                self.ledger.clear_annotation(name, "predicted")
                self.ledger.clear_annotation(name, "predicted_score")

    # -- one tick ----------------------------------------------------------
    def tick_once(self) -> Dict[str, float]:
        """Score every registered component once; returns {name: score}."""
        if not self.enabled:
            return {}
        now = self.time_now_fn()
        t0 = time.monotonic()
        names: List[str] = []
        if self.registry is not None:
            try:
                names = list(self.registry.names())
            except Exception:  # noqa: BLE001
                logger.exception("predict: registry walk failed")
        out: Dict[str, float] = {}
        with self._mu:
            # pass 1: per-component base features + base score (no
            # co-occurrence yet — cooccur needs every peer's base)
            staged: List[tuple] = []
            bases: Dict[str, float] = {}
            for name in names:
                try:
                    st, features, transitions = self._component_features(
                        name, now
                    )
                except Exception:  # noqa: BLE001 — one component's
                    # featurizer bug must not end prediction for the rest
                    logger.exception("predict tick failed for %s", name)
                    continue
                bases[name] = fuse(features, self._weights_for(name))
                staged.append((name, st, features, transitions))
            # pass 2: cross-component co-occurrence, then fuse + hysteresis
            fab = self.fabric
            fabric_comp = (
                getattr(fab, "component_name", None)
                if fab is not None else None
            )
            for name, st, features, transitions in staged:
                try:
                    co = peer_corroboration(
                        name, bases,
                        self._cooccur_peers(name, bases, fabric_comp),
                    )
                    if co > 0.0:
                        features["cooccur"] = co
                    out[name] = self._score_component(
                        name, st, features, transitions, now, bases[name]
                    )
                except Exception:  # noqa: BLE001
                    logger.exception("predict tick failed for %s", name)
            self._ticks += 1
            self._last_tick = now
        _h_tick.observe(time.monotonic() - t0)
        return out

    @staticmethod
    def _cooccur_peers(
        name: str, bases: Dict[str, float], fabric_comp: Optional[str]
    ) -> List[str]:
        """Adjacency for cross-component co-occurrence: siblings of the
        same component class always corroborate each other; accelerator
        components and the ICI fabric component corroborate both ways
        (they share the physical fabric the PR-16 link adjacency maps —
        a precursor on an ICI-adjacent link and a precursor on the chip
        behind it are one story, not two)."""
        cls = component_class(name)
        peers = [
            p for p in bases
            if p != name and component_class(p) == cls
        ]
        accel = name.startswith("accelerator")
        if fabric_comp is not None and name != fabric_comp and accel:
            peers.append(fabric_comp)
        elif fabric_comp is not None and name == fabric_comp:
            peers.extend(
                p for p in bases
                if p != name and p.startswith("accelerator")
            )
        return peers

    def _threshold_for(self, name: str) -> float:
        cal = self._calib.get(component_class(name))
        if cal is not None and cal.source == "calibrated":
            return cal.threshold
        return self.threshold

    def _weights_for(self, name: str) -> Optional[Dict[str, float]]:
        cal = self._calib.get(component_class(name))
        if cal is not None and cal.source == "calibrated":
            return cal.weights
        return None

    def _component_features(self, name: str, now: float):
        """Base feature extraction for one component (no co-occurrence)."""
        st = self._st.get(name)
        if st is None:
            st = self._st[name] = _CompState(self.history_limit)
        labels = {"component": name}
        lat = st.latency.update(
            _h_check_duration.get_sum(labels),
            _h_check_duration.get_count(labels),
        )
        transitions: List[Dict] = []
        state_now: Optional[str] = None
        saturation = 5
        if self.ledger is not None:
            transitions = self.ledger.recent_transitions(name)
            ls = self.ledger.last_state(name)
            state_now = ls["state"] if ls else None
            saturation = max(2, int(self.ledger.flap_threshold))
        times = [t["time"] for t in transitions]
        cad = cadence_score(times, now, self.window, saturation=saturation)
        traj = trajectory_score(
            state_now,
            [(t["time"], t["from"], t["to"]) for t in transitions],
            now,
            self.window,
        )
        ng = st.ngram.update(self._error_classes(name, now))
        features = {
            "latency": lat, "cadence": cad, "trajectory": traj, "ngram": ng,
        }
        fab = self.fabric
        if fab is not None and name == getattr(fab, "component_name", None):
            try:
                features["fabric"] = fab.cooccurrence_score()
            except Exception:  # noqa: BLE001 — fabric must not fail the tick
                features["fabric"] = 0.0
        return st, features, transitions

    def _score_component(
        self, name: str, st: _CompState, features: Dict[str, float],
        transitions: List[Dict], now: float, base: float,
    ) -> float:
        # the base fusion already covered every feature unless pass 2
        # added co-occurrence evidence; only re-fuse when it did
        score = (
            fuse(features, self._weights_for(name))
            if "cooccur" in features else base
        )
        st.score = score
        st.features = features
        st.history.append((now, score))
        _g_score.set(score, {"component": name})
        thr = self._threshold_for(name)
        _g_threshold.set(thr, {"component": name})

        # hysteresis: the dead band between (threshold - hysteresis) and
        # threshold resets both streaks, so a score dithering inside it
        # can neither arm nor clear — the no-flap property
        if score >= thr:
            st.above += 1
            st.below = 0
        elif score <= thr - self.hysteresis:
            st.below += 1
            st.above = 0
        else:
            st.above = 0
            st.below = 0
        if not st.armed and st.above >= self.arm_ticks:
            self._warn(name, st, now, thr)
        elif st.armed and st.below >= self.clear_ticks:
            self._clear(name, st, now, thr)
        if st.armed:
            self._measure_lead(name, st, transitions)
            if self.ledger is not None:
                self.ledger.set_annotation(
                    name, "predicted_score", f"{score:.3f}"
                )
            if (
                self.publish_interval > 0
                and now - st.last_publish >= self.publish_interval
            ):
                self._publish(name, st, now, "snapshot")
        return score

    def _error_classes(self, name: str, now: float):
        """(ts, error_class) of kmsg-sourced events in the feature window,
        oldest first. Only rows carrying the raw ``kmsg`` line count as
        error events — that excludes the daemon's own accounting events
        (health_flapping, remediation, predicted_degraded) and makes the
        read backfill-safe: rows ingested before the ``error_class``
        stamp fall back to the event name."""
        if self.event_store is None:
            return []
        try:
            events = self.event_store.bucket(name).get(now - self.window)
        except Exception:  # noqa: BLE001
            logger.exception("predict: eventstore read failed for %s", name)
            return []
        out = []
        for ev in events:
            extra = ev.extra_info or {}
            if "kmsg" not in extra:
                continue
            out.append((ev.time, extra.get("error_class") or ev.name))
        out.sort(key=lambda p: p[0])
        return out

    # -- warning lifecycle -------------------------------------------------
    def _warn(
        self, name: str, st: _CompState, now: float, thr: float
    ) -> None:
        st.armed = True
        st.warned_at = now
        st.warn_score = st.score
        st.lead_seconds = None
        st.cleared_at = None
        st.warnings += 1
        _c_warnings.inc(labels={"component": name})
        detail = ", ".join(
            f"{k}={v:.3f}" for k, v in sorted(st.features.items())
        )
        logger.warning(
            "predict: %s precursor score %.3f >= %.2f (%s)",
            name, st.score, thr, detail,
        )
        if self.ledger is not None:
            self.ledger.set_annotation(name, "predicted", "true")
        self._emit_event(name, st, now, detail, thr)
        self._audit(name, st, now, detail, thr)
        self._publish(name, st, now, "warn")

    def _clear(
        self, name: str, st: _CompState, now: float, thr: float
    ) -> None:
        st.armed = False
        st.above = 0
        st.below = 0
        st.cleared_at = now
        if self.ledger is not None:
            self.ledger.clear_annotation(name, "predicted")
            self.ledger.clear_annotation(name, "predicted_score")
        logger.info(
            "predict: %s cleared (score %.3f <= %.3f)",
            name, st.score, thr - self.hysteresis,
        )
        self._publish(name, st, now, "clear")

    def _measure_lead(
        self, name: str, st: _CompState, transitions: List[Dict]
    ) -> None:
        """Close the armed episode's lead-time measurement on the first
        reactive hard signal at-or-after the warning: a transition into
        Unhealthy, or the in-window transition count reaching the
        reactive flap threshold."""
        if st.lead_seconds is not None or st.warned_at is None:
            return
        candidates: List[float] = []
        for t in transitions:
            if (
                t["to"] == HealthStateType.UNHEALTHY
                and t["time"] >= st.warned_at
            ):
                candidates.append(t["time"])
        if self.ledger is not None:
            thr = int(self.ledger.flap_threshold)
            asc = sorted(t["time"] for t in transitions)
            if len(asc) >= thr and asc[thr - 1] >= st.warned_at:
                candidates.append(asc[thr - 1])
        if not candidates:
            return
        st.lead_seconds = min(candidates) - st.warned_at
        _h_lead.observe(st.lead_seconds, {"component": name})
        logger.info(
            "predict: %s warning led the reactive detector by %.3fs",
            name, st.lead_seconds,
        )
        self._publish(name, st, self.time_now_fn(), "lead")

    def _emit_event(
        self, name: str, st: _CompState, now: float, detail: str,
        thr: float,
    ) -> None:
        if self.event_store is None:
            return
        try:
            self.event_store.bucket(name).insert(
                Event(
                    component=name,
                    time=now,
                    name=EVENT_NAME_PREDICTED,
                    type=EventType.WARNING,
                    message=(
                        f"precursor score {st.score:.3f} crossed "
                        f"{thr:g} ({detail})"
                    ),
                    extra_info={
                        "score": f"{st.score:.3f}",
                        "threshold": f"{thr:g}",
                        **{
                            k: f"{v:.3f}"
                            for k, v in sorted(st.features.items())
                        },
                    },
                )
            )
        except Exception:  # noqa: BLE001 — accounting must not kill ticks
            logger.exception("predict event emit failed for %s", name)

    def _audit(
        self, name: str, st: _CompState, now: float, detail: str,
        thr: float,
    ) -> None:
        """Dry-run audit row in the predict lane. Never consults the
        enforce allowlist and never executes anything: the suggestion is
        unmappable by design (policy.map_suggested_action returns None
        for PREDICTED_DEGRADATION). The row pre-arms the predict lane's
        own cooldown — anchored on the newest predicted row, surviving
        restarts via the ledger — so an oscillating score cannot spam
        audit rows; reactive cooldowns ignore this lane entirely."""
        rem = self.remediation
        if rem is None:
            return
        try:
            last = rem.audit.last_attempt_time(name, action=ACTION_PREDICTED)
            if last is not None and now - last < self.warn_cooldown:
                return
            rem.audit.record(
                component=name,
                action=ACTION_PREDICTED,
                suggested=RepairActionType.PREDICTED_DEGRADATION,
                trigger_health=HealthStateType.DEGRADED,
                trigger_reason=(
                    f"precursor score {st.score:.3f} >= {thr:g}"
                ),
                decision=DECISION_DRY_RUN,
                outcome=OUTCOME_DRY_RUN,
                detail=detail,
                ts=now,
            )
        except Exception:  # noqa: BLE001
            logger.exception("predict audit record failed for %s", name)

    def _publish(
        self, name: str, st: _CompState, now: float, kind: str
    ) -> None:
        hook = self.on_publish
        if hook is None:
            return
        st.last_publish = now
        try:
            hook({
                # versioned payload (satellite of PR 17): the manager
                # ingests any schema <= PREDICT_SCHEMA and counts-but-
                # keeps newer ones, so a mixed-version fleet degrades to
                # accounting, never silent drops
                "schema": PREDICT_SCHEMA,
                "component": name,
                "component_class": component_class(name),
                "event": kind,
                "ts": now,
                "score": round(st.score, 4),
                "threshold": round(self._threshold_for(name), 4),
                "features": {
                    k: round(v, 4) for k, v in sorted(st.features.items())
                },
                "armed": st.armed,
                "warned_at": st.warned_at,
                "lead_seconds": st.lead_seconds,
            })
        except Exception:  # noqa: BLE001
            logger.exception("predict publish hook failed")

    # -- calibration -------------------------------------------------------
    def calibrate_now(self) -> Dict:
        """Fit per-class thresholds/weights by replaying the ledger's
        persisted transition history (docs/predict.md). The DB read runs
        outside ``_mu``; the fitted map swaps in atomically. Returns a
        {classes, calibrated} summary (scheduler job + tests + bench)."""
        if self.ledger is None:
            return {"classes": 0, "calibrated": 0}
        now = self.time_now_fn()
        calibrator = ThresholdCalibrator(
            ledger=self.ledger,
            default_threshold=self.threshold,
            window_seconds=self.window,
            min_history=self.calibrate_min_history,
            min_threshold=self.calibrate_min_threshold,
            margin=self.calibrate_margin,
            horizon_seconds=self.calibrate_horizon,
        )
        try:
            fitted = calibrator.calibrate(now)
        except Exception:  # noqa: BLE001 — calibration must never take
            # down the scan job; stale thresholds beat no thresholds
            logger.exception("predict calibration failed")
            return {"classes": 0, "calibrated": 0}
        with self._mu:
            self._calib = fitted
            self._last_calibrate = now
        _c_calibrations.inc()
        calibrated = sum(
            1 for c in fitted.values() if c.source == "calibrated"
        )
        if calibrated:
            logger.info(
                "predict: calibrated %d/%d component classes from "
                "ledger history", calibrated, len(fitted),
            )
        return {"classes": len(fitted), "calibrated": calibrated}

    def calibration(self) -> Dict:
        """Per-class fitted thresholds/weights + knobs + provenance —
        the one view behind /v1/predict/calibration, the session verb,
        SDK, and CLI."""
        with self._mu:
            classes = {
                cls: cal.as_dict()
                for cls, cal in sorted(self._calib.items())
            }
            last = self._last_calibrate
        return {
            "enabled": self.calibrate_enabled,
            "schema": PREDICT_SCHEMA,
            "default_threshold": self.threshold,
            "interval_seconds": self.calibrate_interval,
            "min_history": self.calibrate_min_history,
            "min_threshold": self.calibrate_min_threshold,
            "margin": self.calibrate_margin,
            "horizon_seconds": self.calibrate_horizon,
            "last_calibrate": last,
            "classes": classes,
        }

    # -- views -------------------------------------------------------------
    def scores(
        self, component: str = "", history_limit: int = 0
    ) -> Dict:
        """Per-component score snapshot (+ bounded per-component score
        history when ``history_limit`` > 0). The HTTP/session/SDK/CLI
        surfaces all serve this one view."""
        with self._mu:
            items = (
                {component: self._st[component]}
                if component and component in self._st
                else ({} if component else dict(self._st))
            )
            comps = {}
            for name, st in sorted(items.items()):
                d = {
                    "score": round(st.score, 4),
                    "component_class": component_class(name),
                    "threshold": round(self._threshold_for(name), 4),
                    "features": {
                        k: round(v, 4)
                        for k, v in sorted(st.features.items())
                    },
                    "armed": st.armed,
                    "warned_at": st.warned_at,
                    "cleared_at": st.cleared_at,
                    "warn_score": round(st.warn_score, 4),
                    "lead_seconds": st.lead_seconds,
                    "warnings": st.warnings,
                }
                if history_limit:
                    d["history"] = [
                        {"time": ts, "score": round(s, 4)}
                        for ts, s in list(st.history)[-history_limit:]
                    ]
                comps[name] = d
        return {
            "enabled": self.enabled,
            "threshold": self.threshold,
            "hysteresis": self.hysteresis,
            "components": comps,
        }

    def status(self) -> Dict:
        """Config + run-state rollup for status views."""
        with self._mu:
            armed = sorted(n for n, st in self._st.items() if st.armed)
            warnings_total = sum(st.warnings for st in self._st.values())
            tracked = len(self._st)
            ticks = self._ticks
            last_tick = self._last_tick
            calibrated = sum(
                1 for c in self._calib.values() if c.source == "calibrated"
            )
            last_calibrate = self._last_calibrate
        return {
            "enabled": self.enabled,
            "interval_seconds": self.interval,
            "threshold": self.threshold,
            "hysteresis": self.hysteresis,
            "arm_ticks": self.arm_ticks,
            "clear_ticks": self.clear_ticks,
            "window_seconds": self.window,
            "warn_cooldown_seconds": self.warn_cooldown,
            "feature_weights": dict(FEATURE_WEIGHTS),
            "schema": PREDICT_SCHEMA,
            "calibrate_enabled": self.calibrate_enabled,
            "classes_calibrated": calibrated,
            "last_calibrate": last_calibrate,
            "ticks": ticks,
            "last_tick": last_tick,
            "components_tracked": tracked,
            "armed": armed,
            "warnings_total": warnings_total,
        }
