"""Predictive health: online precursor scoring (docs/predict.md)."""

from gpud_tpu.predict.engine import PredictEngine

__all__ = ["PredictEngine"]
