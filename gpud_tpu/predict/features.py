"""Precursor feature extractors: the non-numeric early-warning signals.

"When GPUs Fail Quietly" (arxiv 2509.19575) and eACGM (PAPERS.md) argue
that accelerator failures announce themselves in *system-level* traces —
check-latency drift, health-transition cadence, kernel-log error
sequences — before any telemetry threshold trips. Each extractor here
turns one of those already-persisted traces into a bounded [0, 1]
evidence score; :func:`fuse` combines them with a weighted noisy-OR so
no single weak signal can cross the warning threshold alone, but two
agreeing signals (or one strong state signal) can.

Everything is deterministic and injectable-clock friendly: no wall-clock
reads, no randomness — the seeded unit tests replay the same input
stream and assert bit-identical score trajectories.
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, List, Optional, Tuple

from gpud_tpu.api.v1.types import HealthStateType

# fusion weights: each feature's maximum contribution to the noisy-OR.
# Latency drift alone is deliberately capped BELOW the default warning
# threshold (0.6) so scheduler jitter on an otherwise healthy component
# can never fire a warning without corroboration from a second signal —
# the bench's zero-false-positive gate leans on this structurally.
WEIGHT_LATENCY = 0.5
WEIGHT_CADENCE = 0.7
WEIGHT_TRAJECTORY = 0.75
WEIGHT_NGRAM = 0.6
# fabric neighbor co-occurrence: correlated latency deviations on
# adjacent ICI links (gpud_tpu/fabric). Capped below the warning
# threshold for the same no-single-signal reason as latency drift — one
# deviating link pair corroborates, it doesn't convict.
WEIGHT_FABRIC = 0.55
# cross-component co-occurrence: a peer component in the same class (or
# a coupled fabric neighbor) scoring high at the same tick. Capped below
# the threshold — corroboration only, never a conviction on its own.
WEIGHT_COOCCUR = 0.5

FEATURE_WEIGHTS: Dict[str, float] = {
    "latency": WEIGHT_LATENCY,
    "cadence": WEIGHT_CADENCE,
    "trajectory": WEIGHT_TRAJECTORY,
    "ngram": WEIGHT_NGRAM,
    "fabric": WEIGHT_FABRIC,
    "cooccur": WEIGHT_COOCCUR,
}


def clamp01(x: float) -> float:
    if x != x:  # NaN guard: a poisoned feature must not poison the score
        return 0.0
    return 0.0 if x < 0.0 else (1.0 if x > 1.0 else x)


def fuse(
    features: Dict[str, float],
    weights: Optional[Dict[str, float]] = None,
) -> float:
    """Weighted noisy-OR over per-feature evidence scores.

    ``1 - prod(1 - w_i * s_i)`` — monotone in every input, bounded [0, 1],
    and saturating: independent weak evidence accumulates, redundant
    strong evidence doesn't overshoot. ``weights`` overrides individual
    defaults (the calibrator fits per-component-class weights; absent
    keys fall back to :data:`FEATURE_WEIGHTS`).
    """
    acc = 1.0
    for name, s in features.items():
        w = None if weights is None else weights.get(name)
        if w is None:
            w = FEATURE_WEIGHTS.get(name, 0.5)
        acc *= 1.0 - clamp01(w) * clamp01(s)
    return clamp01(1.0 - acc)


def peer_corroboration(
    name: str, scores: Dict[str, float], peers: Iterable[str]
) -> float:
    """Cross-component co-occurrence evidence: the strongest *pair*
    formed by this component and one adjacent peer, scored by the weaker
    member — the same min-of-pair rule as :func:`neighbor_cooccurrence`,
    lifted from links to components. One elevated component scores
    nothing; two coupled components elevating together (the correlated-
    precursor pattern across a shared fabric) score as the weaker of the
    two. Inputs are [0, 1] base scores; output is [0, 1]."""
    own = scores.get(name, 0.0)
    if own <= 0.0:
        return 0.0
    best = 0.0
    for peer in peers:
        if peer == name:
            continue
        pair = min(own, scores.get(peer, 0.0))
        if pair > best:
            best = pair
    return clamp01(best)


def neighbor_cooccurrence(
    deviations: Dict[str, float], adjacency: Dict[str, Iterable[str]]
) -> float:
    """Co-occurrence evidence over a link graph: the strongest *pair* of
    adjacent deviations, scored by the weaker member (min), so one noisy
    link scores nothing but two neighbors deviating together — the
    correlated-precursor pattern "When GPUs Fail Quietly" reports for
    NVLink — scores as high as the weaker of the two. Inputs are [0, 1]
    per-link deviation scores; output is [0, 1]."""
    best = 0.0
    for name, score in deviations.items():
        if score <= best:
            continue
        for peer in adjacency.get(name, ()):
            pair = min(score, deviations.get(peer, 0.0))
            if pair > best:
                best = pair
    return clamp01(best)


class Ewma:
    """Exponentially-weighted mean + variance (West's incremental form)."""

    __slots__ = ("alpha", "mean", "var", "n")

    def __init__(self, alpha: float = 0.3) -> None:
        self.alpha = alpha
        self.mean: Optional[float] = None
        self.var = 0.0
        self.n = 0

    def update(self, x: float) -> None:
        self.n += 1
        if self.mean is None:
            self.mean = x
            self.var = 0.0
            return
        d = x - self.mean
        incr = self.alpha * d
        self.mean += incr
        self.var = (1.0 - self.alpha) * (self.var + d * incr)

    def z(self, x: float, floor: float = 1e-9) -> float:
        """|z|-score of x against the current baseline (0 before any
        history). The scale floor is relative to the mean's magnitude so a
        near-constant series doesn't turn LSB jitter into huge z-scores
        (same trick as models/anomaly_np.py)."""
        if self.mean is None or self.n < 2:
            return 0.0
        scale = math.sqrt(self.var) + floor + 1e-3 * abs(self.mean)
        return abs(x - self.mean) / scale


class LatencyDrift:
    """EWMA + CUSUM changepoint over per-tick mean check latency.

    Fed the cumulative (sum, count) of the component's
    ``tpud_component_check_duration_seconds`` series each tick; the delta
    gives the mean latency of checks that landed since the last tick with
    zero extra instrumentation on the check path. A one-sided CUSUM over
    the |z| stream accumulates *persistent* drift and forgives single
    spikes — the changepoint score is the normalized CUSUM statistic.
    """

    def __init__(
        self,
        alpha: float = 0.3,
        warmup: int = 5,
        cusum_drift: float = 1.0,
        cusum_limit: float = 8.0,
    ) -> None:
        self.ewma = Ewma(alpha)
        self.warmup = warmup
        self.cusum_drift = cusum_drift
        self.cusum_limit = cusum_limit
        self.cusum = 0.0
        self._last_sum = 0.0
        self._last_count = 0
        self.score = 0.0

    def update(self, total_sum: float, total_count: int) -> float:
        new = total_count - self._last_count
        if new <= 0:
            return self.score  # no checks landed this tick: hold
        x = (total_sum - self._last_sum) / new
        self._last_sum = total_sum
        self._last_count = total_count
        if x < 0:  # counter reset (registry cleared in tests)
            self.ewma = Ewma(self.ewma.alpha)
            self.cusum = 0.0
            self.score = 0.0
            return self.score
        if self.ewma.n < self.warmup:
            # warmup: train the baseline, never score — a component's
            # very first checks (cold caches, lazy imports) are not drift
            self.ewma.update(x)
            self.score = 0.0
            return self.score
        z = self.ewma.z(x)
        self.cusum = max(0.0, self.cusum + z - self.cusum_drift)
        self.cusum = min(self.cusum, 2.0 * self.cusum_limit)
        self.ewma.update(x)
        self.score = clamp01(self.cusum / self.cusum_limit)
        return self.score


def cadence_score(
    transition_times: Iterable[float],
    now: float,
    window_seconds: float,
    saturation: int = 5,
) -> float:
    """Transition-cadence evidence from the ledger's recent-transition
    window: how close the component is to the reactive flap detector's
    threshold, plus an acceleration term when the cadence is *rising*
    (more transitions in the recent half-window than the older half) —
    that ordering is exactly what lets the score cross before the
    reactive detector trips."""
    cutoff = now - window_seconds
    recent = [t for t in transition_times if t > cutoff]
    n = len(recent)
    if n == 0:
        return 0.0
    base = n / float(max(1, saturation))
    half = now - window_seconds / 2.0
    newer = sum(1 for t in recent if t > half)
    older = n - newer
    accel = 0.2 if newer > older and n >= 2 else 0.0
    return clamp01(base + accel)


def trajectory_score(
    state: Optional[str],
    transitions: List[Tuple[float, str, str]],
    now: float,
    window_seconds: float,
) -> float:
    """State-trajectory evidence: being (or very recently having been)
    in a degraded band is itself a precursor — a slow telemetry ramp
    walks Healthy → Degraded → Unhealthy, and the Degraded shoulder is
    the early-warning window the reactive detector ignores until the
    hard threshold. The evidence is *deterioration*, so it requires a
    recent in-window transition into a bad state: a component that has
    sat Degraded since boot (a chronically flaky NFS mount, a
    misconfigured probe) is the reactive detector's settled business,
    not news. ``transitions`` is (ts, from, to), any order."""
    newest = 0.0
    for ts, _from_state, to_state in transitions:
        if to_state in (HealthStateType.DEGRADED, HealthStateType.UNHEALTHY):
            newest = max(newest, ts)
    if newest <= 0.0 or newest <= now - window_seconds:
        return 0.0
    if state in (HealthStateType.UNHEALTHY, HealthStateType.DEGRADED):
        return 1.0
    # healthy now: decayed evidence from the newest excursion in-window
    tau = max(1.0, window_seconds / 4.0)
    return clamp01(0.6 * math.exp(-(now - newest) / tau))


class NgramNovelty:
    """Error-class bigram novelty over the component's kmsg event stream.

    The stable ``error_class`` stamped at ingest (kmsg/syncer.py) forms a
    sequence per component; consecutive pairs (bigrams) that have never
    been seen on this host before are the "new failure shape" signal the
    quiet-failure literature calls out. Volume rides along weakly: a
    burst of even *known* error classes is mild evidence. The seen-set is
    bounded and the instantaneous score decays through an exponential
    hold so a one-tick novelty spike survives hysteresis.
    """

    def __init__(
        self,
        max_seen: int = 4096,
        volume_saturation: int = 10,
        hold_decay: float = 0.85,
    ) -> None:
        self.seen: set = set()
        self.max_seen = max_seen
        self.volume_saturation = volume_saturation
        self.hold_decay = hold_decay
        self.score = 0.0
        self._last_ts = 0.0

    def update(self, classes_oldest_first: List[Tuple[float, str]]) -> float:
        """``classes_oldest_first``: (ts, error_class) within the feature
        window, oldest first. Only events newer than the last processed
        timestamp mint novelty (replay-safe across ticks)."""
        seq = [c for _ts, c in classes_oldest_first]
        fresh = [
            (ts, c) for ts, c in classes_oldest_first if ts > self._last_ts
        ]
        if fresh:
            self._last_ts = max(ts for ts, _c in fresh)
        new_bigrams = 0
        for i in range(1, len(seq)):
            bg = (seq[i - 1], seq[i])
            if bg not in self.seen:
                new_bigrams += 1
                if len(self.seen) < self.max_seen:
                    self.seen.add(bg)
        # unigram novelty: the very first event of a class counts too
        # (a single never-seen error class needs no pair to be news)
        for ts, c in fresh:
            if ("", c) not in self.seen:
                new_bigrams += 1
                if len(self.seen) < self.max_seen:
                    self.seen.add(("", c))
        volume = min(
            1.0, len(fresh) / float(max(1, self.volume_saturation))
        )
        instant = clamp01(
            (0.5 * min(new_bigrams, 4) / 2.0 if new_bigrams else 0.0)
            + 0.3 * volume
        )
        self.score = max(instant, self.score * self.hold_decay)
        if self.score < 1e-3:
            self.score = 0.0
        return self.score
