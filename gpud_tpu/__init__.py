"""gpud_tpu — ``tpud``: a TPU-native fleet-health monitoring daemon.

A ground-up re-design of the capability surface of leptonai/gpud
(reference mounted at /root/reference) for TPU fleets: libtpu/tpu-info/ICI
in place of NVML/NVLink/InfiniBand, with a JAX/Pallas analytics path for
on-chip telemetry scanning (models/, ops/, parallel/).
"""

from gpud_tpu.version import __version__

__all__ = ["__version__"]
