"""Self-update via the target-version file.

Reference: pkg/update — ``UpdateTargetVersion`` watches a version file
(version_file.go:16, polled every 30s at pkg/server/server.go:814-832);
when the target differs from the running version the daemon exits with a
dedicated code so systemd/DaemonSet restarts it into the new binary. The
binary-download path (update.go:19-50, pkg.gpud.dev tarballs + ed25519
verification) is the built-in pipeline in gpud_tpu/update_install.py
(download → distsign verify → atomic install); ``TPUD_UPDATE_HOOK``
remains an operator override for bespoke installs.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Optional

from gpud_tpu.log import audit, get_logger
from gpud_tpu.version import __version__

logger = get_logger(__name__)

POLL_INTERVAL = 30.0   # reference: server.go:814-832
EXIT_CODE_UPDATE = 244 # supervisor restarts into the new version
# failed-target backoff: a target that keeps failing to install must not be
# re-downloaded (and re-fail-logged) every 30s poll — back off exponentially
# until the target file changes or the backoff window lapses
BACKOFF_INITIAL = 300.0
BACKOFF_MAX = 4 * 3600.0
# script invoked with TARGET_VERSION env to install the new version before
# the restart-exit (the reference's tarball-download step, update.go:19-50)
ENV_UPDATE_HOOK = "TPUD_UPDATE_HOOK"


def read_target_version(path: str) -> str:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return f.read().strip()
    except OSError:
        return ""


def write_target_version(path: str, version: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(version + "\n")
    os.replace(tmp, path)
    audit("set_target_version", version=version)


class VersionFileWatcher:
    def __init__(
        self,
        path: str,
        current_version: str = __version__,
        on_update: Optional[Callable[[str], None]] = None,
        interval: float = POLL_INTERVAL,
        installer: Optional[Callable[[str], Optional[str]]] = None,
    ) -> None:
        self.path = path
        self.current_version = current_version
        self.on_update = on_update or self._default_on_update
        # built-in install pipeline (update_install.perform_update); when
        # None and no hook is set the watcher warns-and-stays
        if installer is None:
            from gpud_tpu.update_install import installer_from_env

            installer = installer_from_env()
        self.installer = installer
        self._exit: Callable[[int], None] = os._exit  # injectable for tests
        # env override so lifecycle e2e tests don't wait the 30s cadence;
        # clamped (a zero would busy-spin the loop) and logged so it can't
        # silently shadow an explicit interval in production
        self.interval = interval
        env_interval = os.environ.get("TPUD_UPDATE_POLL_SECONDS", "")
        if env_interval:
            try:
                self.interval = max(0.25, float(env_interval))
                logger.info(
                    "update watcher poll interval overridden to %.2fs "
                    "(TPUD_UPDATE_POLL_SECONDS)", self.interval,
                )
            except ValueError:
                pass
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._job = None  # scheduler Job when scheduler-driven
        # failed-target memo: (target, failed_at, current_backoff)
        import time as _time

        self._now = _time.time  # injectable for tests
        self._failed_target = ""
        self._failed_at = 0.0
        self._backoff = 0.0

    def _default_on_update(self, target: str) -> None:
        """Install (hook override, else the built-in pipeline), then
        restart-exit. On install failure — or with nothing configured —
        we must NOT exit: the restarted process would still be the old
        version and see the same mismatch — a permanent 30-second crash
        loop on every node the update was pushed to."""
        hook = os.environ.get(ENV_UPDATE_HOOK, "")
        if hook:
            from gpud_tpu.process import run_command

            r = run_command(
                ["bash", hook], timeout=15 * 60.0, env={"TARGET_VERSION": target}
            )
            if r.exit_code != 0:
                logger.error(
                    "update hook failed (exit %d): %s", r.exit_code, r.output[-500:]
                )
                self._note_failure(target)
                return
            logger.warning("update hook installed %s", target)
        elif self.installer is not None:
            err = self.installer(target)
            if err:
                logger.error(
                    "built-in update to %s failed: %s; staying on %s",
                    target, err, self.current_version,
                )
                self._note_failure(target)
                return
        else:
            if not getattr(self, "_warned_no_hook", False):
                logger.warning(
                    "target version %s != running %s but no update hook or "
                    "built-in pipeline is configured; staying on the "
                    "current version",
                    target, self.current_version,
                )
                self._warned_no_hook = True
            return
        logger.warning(
            "installed %s; exiting %d for supervisor restart",
            target, EXIT_CODE_UPDATE,
        )
        audit("self_update_exit", target=target, current=self.current_version)
        self._exit(EXIT_CODE_UPDATE)  # noqa: SLF001 — immediate, like the reference

    def _note_failure(self, target: str) -> None:
        """Record a failed install so ``check_once`` backs off this target
        (doubling per consecutive failure) instead of re-downloading it
        every poll. A different target resets the memo."""
        if target == self._failed_target and self._backoff:
            self._backoff = min(self._backoff * 2, BACKOFF_MAX)
        else:
            self._failed_target = target
            self._backoff = BACKOFF_INITIAL
        self._failed_at = self._now()
        logger.warning(
            "update to %s failed; next attempt in %.0fs unless the target "
            "changes", target, self._backoff,
        )

    def check_once(self) -> bool:
        """Returns True if an update was triggered."""
        target = read_target_version(self.path)
        if not target or target == self.current_version:
            return False
        if (
            target == self._failed_target
            and self._now() - self._failed_at < self._backoff
        ):
            return False  # persistently failing target: in backoff
        self.on_update(target)
        return True

    def start(self, scheduler=None) -> None:
        if scheduler is not None:
            if self._job is None and self._thread is None:
                self._job = scheduler.add_job(
                    "update-watcher",
                    self._scheduled_check,
                    interval=self.interval,
                    initial_delay=self.interval,
                )
            return
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="tpud-update-watcher", daemon=True
        )
        self._thread.start()

    def _scheduled_check(self) -> None:
        # the legacy loop exits once an update is triggered (the daemon is
        # about to restart-exec); the job equivalent is self-cancellation
        if self.check_once() and self._job is not None:
            self._job.cancel()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                if self.check_once():
                    return
            except Exception:  # noqa: BLE001
                logger.exception("update check failed")

    def close(self) -> None:
        if self._job is not None:
            self._job.cancel()
            self._job = None
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
