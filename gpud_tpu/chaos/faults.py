"""Chaos fault actions: what a scenario step does to the live daemon.

Every action is ``fn(server, step, ctx) -> Optional[str]`` (error string
or None). ``ctx`` is the runner's campaign context: it carries the
injectable clock (``ctx.time_fn``), the optional fake control plane
handle (``ctx.plane``) and a ``ctx.cleanups`` list — every action that
mutates daemon state MUST register an undo there so a campaign always
leaves the daemon as it found it, pass or fail.

Fault classes beyond the classic one-shot kmsg write:

  - ``inject``       — kmsg write, with burst/flap via ``repeat`` +
                       ``interval_seconds`` (fault_injector.Request)
  - ``metric_ramp``  — slow-ramp telemetry fault through the
                       ``telemetry_fn`` override hook on the hbm /
                       temperature components (gradual HBM temp climb)
  - ``runtime_crash``— the runtime component reports its unit failed for
                       ``duration`` seconds (kill/restart race against
                       the remediation engine)
  - ``clock_skew``   — shifts a component's (or the remediation
                       engine's) injectable clock by ``offset`` seconds
  - ``plane_disconnect`` — drops control-plane sessions on the fake
                       plane harness (disconnect/reconnect storms)
  - ``fabric_latency_ramp`` — slow-ramp ONE mesh link's probe latency
                       through the fabric plane's ``telemetry_fn`` hook
                       (quiet ICI degradation)
  - ``fabric_link_down`` — hard-down one physical ICI port (sysfs state
                       flip when a tree is attached, else a ``links_fn``
                       snapshot rewrite on the mock backend)

plus campaign helpers: ``trigger`` (poke a check), ``set_healthy``,
``remediation_scan`` (poke the engine), ``predict_scan`` (synchronous
precursor-scoring tick), ``fabric_sweep`` (one all-links sweep now),
``purge`` (retention pass now).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, Optional

from gpud_tpu.fault_injector import Request as InjectRequest
from gpud_tpu.log import get_logger

logger = get_logger(__name__)


def _component(server, step: Dict):
    name = step.get("component", "")
    comp = server.registry.get(name)
    if comp is None:
        return None, f"component {name!r} not registered"
    return comp, None


def act_inject(server, step: Dict, ctx) -> Optional[str]:
    req = InjectRequest(
        tpu_error_name=step.get("name", ""),
        chip_id=int(step.get("chip_id", 0)),
        detail=str(step.get("detail", "")),
        kernel_message=step.get("kernel_message", ""),
        repeat=int(step.get("repeat", 1)),
        interval_seconds=float(step.get("interval_seconds", 0.0)),
    )
    res = server.fault_injector.inject(req)
    return None if res.ok else res.error


def act_metric_ramp(server, step: Dict, ctx) -> Optional[str]:
    """Gradual metric climb: wraps the component's ``telemetry_fn`` hook
    so every chip's ``field`` reads as a start→end interpolation over
    ``ramp_seconds`` (then holds at ``end`` until cleared). Telemetry
    objects are copied per call — the sampler's cache is never mutated."""
    comp, err = _component(server, step)
    if err:
        return err
    if not hasattr(comp, "telemetry_fn"):
        return f"component {step.get('component')!r} has no telemetry hook"
    prev_fn = comp.telemetry_fn  # may be None = "read the live sampler"
    base_fn = prev_fn or comp.sampler.telemetry
    fld = step.get("field", "temperature_c")
    start = float(step.get("start", 0.0))
    end = float(step.get("end", 0.0))
    ramp = float(step.get("ramp_seconds", 0.0))
    chip = step.get("chip_id")  # None = every chip
    t0 = ctx.time_fn()
    time_fn = ctx.time_fn

    def ramped():
        tel = base_fn()
        frac = 1.0 if ramp <= 0 else min(1.0, (time_fn() - t0) / ramp)
        val = start + (end - start) * frac
        out = {}
        for cid, t in tel.items():
            if chip is None or cid == int(chip):
                if not hasattr(t, fld):
                    out[cid] = t
                    continue
                out[cid] = dataclasses.replace(t, **{fld: val})
            else:
                out[cid] = t
        return out

    comp.telemetry_fn = ramped
    ctx.cleanups.append(lambda: setattr(comp, "telemetry_fn", prev_fn))
    _poke(comp, server)
    return None


def act_metric_clear(server, step: Dict, ctx) -> Optional[str]:
    comp, err = _component(server, step)
    if err:
        return err
    if not hasattr(comp, "telemetry_fn"):
        return f"component {step.get('component')!r} has no telemetry hook"
    comp.telemetry_fn = None  # back to the live sampler read
    _poke(comp, server)
    return None


def act_runtime_crash(server, step: Dict, ctx) -> Optional[str]:
    """The runtime component reports its unit failed until ``duration``
    elapses — the mid-remediation race: the engine's scan sees the
    failure, decides (dry-run by default) a restart, and the 'crash'
    clears underneath it."""
    name = step.get("component", "accelerator-tpu-runtime")
    comp = server.registry.get(name)
    if comp is None:
        return f"component {name!r} not registered"
    if not hasattr(comp, "chaos_fail_until"):
        return f"component {name!r} has no crash hook"
    duration = float(step.get("duration", 2.0))
    prev = comp.chaos_fail_until
    comp.chaos_fail_until = ctx.time_fn() + duration
    ctx.cleanups.append(lambda: setattr(comp, "chaos_fail_until", prev))
    _poke(comp, server)
    return None


def act_clock_skew(server, step: Dict, ctx) -> Optional[str]:
    """Shift an injectable clock by ``offset`` seconds. ``target`` is a
    component name or ``remediation``. The daemon must keep its cadence
    and never crash under skew — that is what the invariants assert."""
    offset = float(step.get("offset", 0.0))
    target = step.get("target", "") or step.get("component", "")
    if target == "remediation":
        eng = server.remediation
        if eng is None:
            return "remediation engine disabled"
        holder = eng
    else:
        holder = server.registry.get(target)
        if holder is None:
            return f"clock_skew target {target!r} not found"
    base: Callable[[], float] = getattr(holder, "time_now_fn", None)
    if base is None:
        return f"clock_skew target {target!r} has no injectable clock"
    holder.time_now_fn = lambda: base() + offset
    ctx.cleanups.append(lambda: setattr(holder, "time_now_fn", base))
    return None


def act_plane_disconnect(server, step: Dict, ctx) -> Optional[str]:
    """Drop every live control-plane session on the fake plane harness
    (the agent's session loop must reconnect). Requires the campaign to
    be driven with a ``FakeControlPlane`` handle (bench --chaos or the
    e2e tests); a daemon with no plane attached reports the gap."""
    plane = ctx.plane
    if plane is None:
        return "no fake control plane attached to this campaign"
    dropped = plane.drop_all()
    logger.info("chaos: dropped %d control-plane session(s)", dropped)
    return None


def act_plane_refuse(server, step: Dict, ctx) -> Optional[str]:
    """Hard-down manager: the fake plane 503s every session connect for
    ``duration`` seconds (0 = until phase cleanup), then live sessions
    are dropped so the agent actually re-enters its connect loop and the
    circuit breaker sees consecutive failures. Cleanup always un-refuses."""
    plane = ctx.plane
    if plane is None:
        return "no fake control plane attached to this campaign"
    if not hasattr(plane, "refuse_connects"):
        return "attached control plane has no refuse_connects knob"
    if step.get("resume"):
        # scripted recovery mid-campaign (cleanups only run at the end)
        plane.refuse_connects = False
        logger.info("chaos: control plane accepting connects again")
        return None
    duration = float(step.get("duration", 0.0))
    plane.refuse_connects = True
    plane.drop_all()

    def _recover() -> None:
        plane.refuse_connects = False

    ctx.cleanups.append(_recover)
    if duration > 0:
        timer = threading.Timer(duration, _recover)
        timer.daemon = True
        timer.start()
        ctx.cleanups.append(timer.cancel)
    logger.info("chaos: control plane refusing connects (duration=%gs)", duration)
    return None


def _fabric_plane(server):
    plane = getattr(server, "fabric", None)
    if plane is None:
        return None, "fabric plane disabled (fabric_sweep_enabled)"
    return plane, None


def act_fabric_latency_ramp(server, step: Dict, ctx) -> Optional[str]:
    """Quiet ICI degradation: wraps the fabric plane's ``telemetry_fn``
    probe so ``link``'s latency reads as a start→end interpolation over
    ``ramp_seconds`` (then holds at ``end``) while every other link keeps
    its base reading — the EWMA baseline must flag exactly that link."""
    plane, err = _fabric_plane(server)
    if err:
        return err
    target = str(step.get("link", ""))
    if not target:
        return "fabric_latency_ramp needs a `link` (e.g. c0-c1/x)"
    prev_fn = plane.telemetry_fn  # may be None = synthetic probe
    base_fn = prev_fn or plane.synthetic_latency
    start = float(step.get("start", 0.0))
    end = float(step.get("end", 0.0))
    ramp = float(step.get("ramp_seconds", 0.0))
    t0 = ctx.time_fn()
    time_fn = ctx.time_fn

    def ramped(link):
        if link.name != target:
            return base_fn(link)
        frac = 1.0 if ramp <= 0 else min(1.0, (time_fn() - t0) / ramp)
        return start + (end - start) * frac

    plane.telemetry_fn = ramped
    ctx.cleanups.append(lambda: setattr(plane, "telemetry_fn", prev_fn))
    return None


def act_fabric_link_down(server, step: Dict, ctx) -> Optional[str]:
    """Hard-down one physical ICI port (``port: chipN/iciL``). With a
    sysfs tree attached (``TPUD_ICI_SYSFS_ROOT``) the port's ``state``
    file is flipped to ``down`` — the real inventory walk sees it. On the
    mock backend the plane's ``links_fn`` is wrapped to rewrite that one
    snapshot instead. Either way cleanup restores the port."""
    import os

    plane, err = _fabric_plane(server)
    if err:
        return err
    port = str(step.get("port", ""))
    if not port or "/" not in port:
        return "fabric_link_down needs a `port` (e.g. chip5/ici1)"
    root = os.environ.get("TPUD_ICI_SYSFS_ROOT", "")
    state_path = os.path.join(root, *port.split("/"), "state") if root else ""
    if state_path and os.path.isfile(state_path):
        with open(state_path, encoding="ascii", errors="replace") as f:
            prev_state = f.read()

        def _restore() -> None:
            with open(state_path, "w", encoding="ascii") as f:
                f.write(prev_state)

        with open(state_path, "w", encoding="ascii") as f:
            f.write("down")
        ctx.cleanups.append(_restore)
        return None
    from gpud_tpu.tpu.instance import LinkState

    prev_fn = plane.links_fn  # may be None = backend port walk
    base_fn = prev_fn or plane.default_links

    def downed():
        out = []
        for snap in base_fn():
            if snap.name == port:
                snap = dataclasses.replace(snap, state=LinkState.DOWN)
            out.append(snap)
        return out

    plane.links_fn = downed
    ctx.cleanups.append(lambda: setattr(plane, "links_fn", prev_fn))
    return None


def act_fabric_sweep(server, step: Dict, ctx) -> Optional[str]:
    """Run one all-links fabric sweep now: campaigns pin the sweep
    timeline to the fault timeline instead of racing the cadence."""
    plane, err = _fabric_plane(server)
    if err:
        return err
    plane.sweep_once()
    return None


def act_trigger(server, step: Dict, ctx) -> Optional[str]:
    comp, err = _component(server, step)
    if err:
        return err
    _poke(comp, server, block=bool(step.get("block", False)))
    return None


def act_set_healthy(server, step: Dict, ctx) -> Optional[str]:
    comp, err = _component(server, step)
    if err:
        return err
    fn = getattr(comp, "set_healthy", None)
    if fn is None:
        return f"component {step.get('component')!r} has no set_healthy"
    fn()
    _poke(comp, server)
    return None


def act_remediation_scan(server, step: Dict, ctx) -> Optional[str]:
    eng = server.remediation
    if eng is None:
        return "remediation engine disabled"
    eng.poke()
    return None


def act_predict_scan(server, step: Dict, ctx) -> Optional[str]:
    """Run a precursor-scoring tick now: campaigns pin the scan timeline
    to the fault timeline instead of racing the configured cadence."""
    eng = getattr(server, "predictor", None)
    if eng is None:
        return "predict engine disabled"
    eng.tick_once()
    return None


def act_predict_reset(server, step: Dict, ctx) -> Optional[str]:
    """Drop the predictor's in-memory scorer state for ``component`` (or
    all components): campaign isolation — a drill must not inherit armed
    warnings from faults an earlier campaign injected."""
    eng = getattr(server, "predictor", None)
    if eng is None:
        return "predict engine disabled"
    eng.reset(component=str(step.get("component", "")))
    return None


def act_purge(server, step: Dict, ctx) -> Optional[str]:
    fn = getattr(server, "_purge_retention", None)
    if fn is None:
        return "server has no retention purge"
    scheduler = getattr(server, "scheduler", None)
    if scheduler is not None and scheduler.submit("chaos:purge", fn):
        return None
    fn()
    return None


def act_ingest_burst(server, step: Dict, ctx) -> Optional[str]:
    """Observation firehose against the live daemon's stores: ``events``
    events into ``component``'s bucket (default name ``chaos_ingest``)
    plus one metric row per event — the storm half of the
    ingest-storm-crash drill. (``count`` is taken by the step-timeline
    expansion, hence the ``events`` spelling.) Rows ride the write-behind
    layer when enabled; no cleanup is registered (retention purges them
    like any other telemetry)."""
    from gpud_tpu.api.v1.types import Event, EventType

    component = step.get("component", "chaos-ingest")
    name = step.get("name", "chaos_ingest")
    count = int(step.get("events", 100))
    bucket = server.event_store.bucket(component)
    now = ctx.time_fn()
    for i in range(count):
        bucket.insert(Event(
            component=component, time=now, name=name,
            type=EventType.INFO, message=f"chaos ingest burst {i}",
        ))
        server.metrics_store.record([
            (int(now), "tpud_chaos_ingest", {"component": component}, float(i))
        ])
    return None


def act_storage_flush(server, step: Dict, ctx) -> Optional[str]:
    """Drive the write-behind flush barrier: everything buffered is
    committed before the step returns (the pre-crash durability line)."""
    writer = getattr(server, "storage_writer", None)
    if writer is None:
        return "storage batching disabled (no write-behind writer)"
    if not writer.flush(timeout=10.0):
        return "storage flush barrier timed out"
    return None


def act_storage_crash(server, step: Dict, ctx) -> Optional[str]:
    """Simulated SIGKILL mid-batch: discard the writer's in-memory buffer
    WITHOUT committing — exactly the loss window a process kill between
    group commits costs (the commits themselves are atomic; torn rows are
    impossible, which tests/test_crash_consistency.py proves with a real
    SIGKILL). The daemon keeps running so post-crash expectations can
    assert the stores stay consistent and ingest keeps working."""
    writer = getattr(server, "storage_writer", None)
    if writer is None:
        return "storage batching disabled (no write-behind writer)"
    n = writer.drop_pending(reason="chaos_crash")
    logger.info("chaos: storage_crash discarded %d buffered ops", n)
    return None


def act_manager_kill_rebuild(server, step: Dict, ctx) -> Optional[str]:
    """SIGKILL-style manager restart mid-ingest: throw away every
    in-memory rollup aggregate and dedupe LRU, then rebuild a fresh
    ``FleetRollupStore`` from the *same* journal DB via the parallel
    per-shard replay — exactly what a manager restart against the same
    ``--data-dir`` does. ``shards: N`` on the step restarts with a
    different shard count (the journal's stable crc32 slot column makes
    that safe; this is the re-partitioning oracle).

    The swap runs ON the fake plane's event loop, which is also where
    outbox ingest runs — so it is atomic with respect to ingest (no
    record can land in the dying store after the rebuild snapshotted
    the journal), and the loop blocking for the rebuild's duration IS
    the manager's dead window: deliveries queue in the socket buffers
    and ingest resumes against the rebuilt store, deduped by the
    reseeded LRUs + the journal's unique index."""
    import asyncio

    from gpud_tpu.manager.rollup import FleetRollupStore

    plane = ctx.plane
    if plane is None:
        return "no fake control plane attached to this campaign"
    rollup = getattr(plane, "rollup", None)
    if rollup is None:
        return "no fleet rollup store attached (plane.attach_rollup())"
    loop = getattr(plane, "_loop", None)
    if loop is None or not loop.is_running():
        return "fake control plane loop not running"
    shards = int(step.get("shards", 0)) or rollup.shard_count

    async def _kill_and_rebuild():
        old = plane.rollup
        writer = getattr(old, "writer", None)
        if writer is not None:
            # the kill window: buffered-but-uncommitted rows die with
            # the process (same loss model as act_storage_crash)
            writer.drop_pending(reason="chaos_manager_kill")
        plane.rollup = FleetRollupStore(
            old.db, writer,
            cache_ttl_seconds=old.cache_ttl,
            dedupe_keys_max=old.dedupe_keys_max,
            max_journal_rows=old.max_journal_rows,
            shard_count=shards,
        )
        return plane.rollup.records_total()

    try:
        fut = asyncio.run_coroutine_threadsafe(_kill_and_rebuild(), loop)
        recovered = fut.result(timeout=30.0)
    except Exception as e:  # noqa: BLE001 — the failure is the finding
        return f"manager kill/rebuild failed: {e}"
    logger.info(
        "chaos: manager killed and rebuilt from journal — %d records "
        "recovered across %d shard(s)", recovered, shards,
    )
    return None


def act_peer_plane_boot(server, step: Dict, ctx) -> Optional[str]:
    """HA manager tier stand-in: boot a SECOND fake control plane that
    shares the primary's delivery ledgers (outbox keys/frames/acks,
    rollup store, connected event) — two managers over one logical
    journal, like a real peer that replicated the primary's journal —
    and hand the agent's circuit breaker a ``peers`` list so its next
    trip to OPEN rotates to the peer with an immediate probe
    (docs/session.md "Peer failover"). A later ``plane_refuse`` on the
    primary then IS the manager SIGKILL: the agent must fail over to
    the surviving peer inside the breaker cooldown, and ``zero_loss`` /
    ``fleet`` expectations hold across both planes because the ledgers
    are one. Cleanup retargets the session at the primary, restores the
    breaker's peer list, and stops the peer plane."""
    from gpud_tpu.chaos.fake_plane import FakeControlPlane

    plane = ctx.plane
    if plane is None:
        return "no fake control plane attached to this campaign"
    cb = getattr(server, "session_circuit", None)
    session = getattr(server, "session", None)
    if cb is None or session is None:
        return "peer failover needs a live session + circuit breaker"
    if getattr(ctx, "peer_plane", None) is not None:
        return "peer plane already booted"

    peer = FakeControlPlane()
    # one logical manager tier: the peer serves the same ledgers, so a
    # record delivered to EITHER plane counts once, dedupes once, and
    # lands in the same rollup — the chaos analogue of the replicated
    # journal a real surviving peer rebuilds from
    peer.outbox_keys = plane.outbox_keys
    peer.outbox_frames = plane.outbox_frames
    peer.outbox_acked = plane.outbox_acked
    peer.rollup = plane.rollup
    peer.connected = plane.connected
    peer.start()
    ctx.peer_plane = peer
    peer_endpoint = f"http://127.0.0.1:{peer.port}"

    primary_endpoint = session.endpoint
    old_peers = list(cb.peers)
    cb.peers = [primary_endpoint, peer_endpoint]

    def _undo() -> None:
        with cb._mu:  # noqa: SLF001 — chaos harness resets breaker state
            cb.peers = old_peers
            cb._peer_index = 0
            cb._failover_probe = False
            cb._sweep = 0
        session._apply_peer(primary_endpoint)  # noqa: SLF001
        ctx.peer_plane = None
        peer.stop()

    ctx.cleanups.append(_undo)
    logger.info(
        "chaos: peer manager up at %s (primary %s); breaker owns failover",
        peer_endpoint, primary_endpoint,
    )
    return None


def _poke(comp, server, block: bool = False) -> None:
    """Run the component's check now: poked to the front of the heap when
    scheduler-driven, else a direct (or one-shot) check."""
    job = getattr(comp, "_job", None)
    if job is not None and not block:
        job.poke()
        return
    if block:
        try:
            comp.check()
        except Exception:  # noqa: BLE001 — a failing check is the campaign's finding
            logger.exception("chaos trigger check failed for %s", comp.name())
        return
    scheduler = getattr(server, "scheduler", None)
    if scheduler is not None and scheduler.submit(f"chaos:check:{comp.name()}", comp.check):
        return
    try:
        comp.check()
    except Exception:  # noqa: BLE001
        logger.exception("chaos trigger check failed for %s", comp.name())


ACTIONS: Dict[str, Callable] = {
    "inject": act_inject,
    "metric_ramp": act_metric_ramp,
    "metric_clear": act_metric_clear,
    "runtime_crash": act_runtime_crash,
    "clock_skew": act_clock_skew,
    "plane_disconnect": act_plane_disconnect,
    "plane_refuse": act_plane_refuse,
    "fabric_latency_ramp": act_fabric_latency_ramp,
    "fabric_link_down": act_fabric_link_down,
    "fabric_sweep": act_fabric_sweep,
    "trigger": act_trigger,
    "set_healthy": act_set_healthy,
    "remediation_scan": act_remediation_scan,
    "predict_scan": act_predict_scan,
    "predict_reset": act_predict_reset,
    "purge": act_purge,
    "ingest_burst": act_ingest_burst,
    "storage_flush": act_storage_flush,
    "storage_crash": act_storage_crash,
    "manager_kill_rebuild": act_manager_kill_rebuild,
    "peer_plane_boot": act_peer_plane_boot,
}
