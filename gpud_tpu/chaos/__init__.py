"""Chaos campaign harness (docs/chaos.md).

A campaign is a declarative scenario file — a timeline of timed fault
steps grouped into phases, each phase closed by an expectation block —
executed against a *live* daemon through the unified scheduler. The fault
injector has always been both product feature and test harness (SURVEY
§4.7); this package extends that stance from one-shot kmsg writes to
compound failure storms: bursts/flaps, slow-ramp metric faults, runtime
crashes mid-remediation, clock skew, and control-plane disconnect storms.

Surface:
  - :mod:`gpud_tpu.chaos.scenario` — schema, loading, timeline expansion
  - :mod:`gpud_tpu.chaos.faults` — the injectable fault actions
  - :mod:`gpud_tpu.chaos.expectations` — per-phase assertion evaluation
  - :mod:`gpud_tpu.chaos.runner` — CampaignRunner + ChaosManager (wired
    into the server, HTTP, session, SDK, CLI)
  - :mod:`gpud_tpu.chaos.fake_plane` — reusable fake control plane
  - ``gpud_tpu/chaos/scenarios/`` — shipped canonical campaigns
"""

from gpud_tpu.chaos.runner import CampaignRunner, ChaosManager  # noqa: F401
from gpud_tpu.chaos.scenario import (  # noqa: F401
    Scenario,
    expand_steps,
    load_scenario,
    shipped_scenarios,
)
