"""Per-phase chaos expectations: did the whole chain actually hold?

After a phase's timeline drains, the runner evaluates the phase's
``expect`` block against the live daemon. Each kind asserts one link of
the detect→ledger→remediate→audit chain, plus the graceful-degradation
invariants that hold the daemon itself to account:

  detect:       an event (eventstore) or a ledger transition appears
                within the latency bound — detection latency is measured
                from the phase's first fault step and histogrammed
  ledger:       health_history.py recorded the expected transitions
  remediation:  the engine's policy decided as expected and the audit
                ledger has the rows to prove it
  events:       eventstore contents (name/message/count)
  plane:        the agent's control-plane session reconnected
  outbox:       store-and-forward delivery held — zero loss across the
                partition, circuit-breaker transitions in order, connect
                attempts flat while the breaker is open
  fleet:        the manager-side rollup store (manager/rollup.py) agrees
                with the plane's ingest ledger — one row per accepted
                record, redeliveries deduped, per-kind counts matching
  fabric:       the fabric plane's mesh matrix blames exactly the
                faulted ICI links (Degraded on latency deviation, Down
                on port loss) and leaves every other link Healthy
  predict:      the predict engine warned before the reactive hard
                signal (ordering + lead-time floor), and stayed silent
                on un-faulted components
  predict_lead: the manager-side fleet pane reflects the prediction —
                the faulted component ranks in the top-K of
                ``fleet_predict`` by decayed risk, warn/lead records
                survived ingest, and the fleet lead distribution
                clears its floor
  invariants:   zero unhandled worker exceptions (scheduler failure +
                watchdog counters flat), un-faulted job cadence within
                slack, thread-count and RSS gates

Everything polls on the campaign context's injectable clock
(``ctx.time_fn`` / ``ctx.sleep_fn``) so the evaluation logic itself is
unit-testable under a fake clock (tests/test_chaos.py).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

from gpud_tpu.log import get_logger

logger = get_logger(__name__)

POLL_INTERVAL = 0.02

# how far before the phase start the evidence queries reach: kmsg event
# times are reconstructed from boot-relative stamps (writer and watcher
# each read /proc/uptime at centisecond resolution), so an event for a
# phase-offset-0 fault can carry a timestamp a few tens of ms before the
# runner's phase_start
SINCE_SLACK = 0.25


@dataclass
class ExpectationResult:
    kind: str
    ok: bool
    detail: str = ""
    latency_seconds: Optional[float] = None
    timed_out: bool = False

    def to_dict(self) -> Dict:
        out = {"kind": self.kind, "ok": self.ok, "detail": self.detail}
        if self.latency_seconds is not None:
            out["latency_seconds"] = round(self.latency_seconds, 6)
        if self.timed_out:
            out["timed_out"] = True
        return out


def _poll(pred, deadline: float, ctx):
    """Run ``pred`` until it returns a truthy value or ``deadline``
    passes; returns the value or None."""
    while True:
        got = pred()
        if got:
            return got
        if ctx.time_fn() >= deadline:
            return None
        ctx.sleep_fn(POLL_INTERVAL)


def counter_total(registry, name: str) -> float:
    """Sum of a counter across all label sets (0.0 when unregistered)."""
    for m in registry.all_metrics():
        if m.name == name:
            return sum(v for _k, v in m.labels_values())
    return 0.0


def rss_mb() -> Optional[float]:
    try:
        with open("/proc/self/status", encoding="ascii", errors="replace") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) / 1024.0
    except OSError:
        pass
    return None


def _eval_detect(server, spec: Dict, ctx) -> ExpectationResult:
    component = spec.get("component", "")
    want_event = spec.get("event", "")
    want_state = spec.get("to", "")
    contains = spec.get("contains", "")
    within = float(spec.get("within", ctx.detect_timeout))
    since = ctx.phase_start - SINCE_SLACK
    ref = ctx.fault_t0 if ctx.fault_t0 is not None else ctx.phase_start
    deadline = ref + within

    def find():
        if want_event:
            bucket = server.event_store.bucket(component)
            for e in bucket.get(since):
                if e.name == want_event and (not contains or contains in e.message):
                    return e.time or ctx.time_fn()
        if want_state:
            for t in server.health_ledger.history(component=component, since=since):
                if t["to"] == want_state:
                    return t["time"] or ctx.time_fn()
        return None

    hit = _poll(find, deadline, ctx)
    what = want_event or f"transition→{want_state}"
    if hit is None:
        return ExpectationResult(
            "detect", False, timed_out=True,
            detail=f"{component}: {what} not detected within {within:g}s",
        )
    latency = max(0.0, float(hit) - ref)
    return ExpectationResult(
        "detect", True, latency_seconds=latency,
        detail=f"{component}: {what} detected in {latency * 1000.0:.1f}ms",
    )


def _eval_ledger(server, specs: List[Dict], ctx) -> List[ExpectationResult]:
    out = []
    since = ctx.phase_start - SINCE_SLACK
    for spec in specs:
        component = spec.get("component", "")
        to = spec.get("to", "")
        frm = spec.get("from", "")
        min_count = int(spec.get("min_count", 1))
        deadline = ctx.time_fn() + float(spec.get("within", ctx.detect_timeout))

        def matches(spec_c=component, spec_to=to, spec_from=frm, n=min_count):
            rows = [
                t
                for t in server.health_ledger.history(component=spec_c, since=since)
                if (not spec_to or t["to"] == spec_to)
                and (not spec_from or t["from"] == spec_from)
            ]
            return rows if len(rows) >= n else None

        rows = _poll(matches, deadline, ctx)
        desc = f"{component}: {frm or '*'}→{to or '*'} x{min_count}"
        if rows is None:
            out.append(ExpectationResult(
                "ledger", False, timed_out=True,
                detail=f"{desc} — not recorded",
            ))
        else:
            out.append(ExpectationResult(
                "ledger", True, detail=f"{desc} — {len(rows)} recorded",
            ))
    return out


def _eval_remediation(server, specs: List[Dict], ctx) -> List[ExpectationResult]:
    eng = server.remediation
    if eng is None:
        return [ExpectationResult(
            "remediation", False, detail="remediation engine disabled",
        )]
    eng.poke()  # the scan cadence (30s default) must not gate a campaign
    out = []
    since = ctx.phase_start - SINCE_SLACK
    for spec in specs:
        component = spec.get("component", "")
        decision = spec.get("decision", "")
        outcome = spec.get("outcome", "")
        action = spec.get("action", "")
        min_count = int(spec.get("min_count", 1))
        deadline = ctx.time_fn() + float(spec.get("within", ctx.detect_timeout))

        def matches(c=component, d=decision, o=outcome, a=action, n=min_count):
            rows = [
                r
                for r in eng.audit.read(component=c or None, since=since)
                if (not d or r["decision"] == d)
                and (not o or r["outcome"] == o)
                and (not a or r["action"] == a)
            ]
            return rows if len(rows) >= n else None

        rows = _poll(matches, deadline, ctx)
        desc = (
            f"{component or '*'}: decision={decision or '*'} "
            f"outcome={outcome or '*'} action={action or '*'} x{min_count}"
        )
        if rows is None:
            out.append(ExpectationResult(
                "remediation", False, timed_out=True,
                detail=f"{desc} — no matching audit row",
            ))
        else:
            out.append(ExpectationResult(
                "remediation", True,
                detail=f"{desc} — {len(rows)} audit row(s)",
            ))
    return out


def _eval_events(server, specs: List[Dict], ctx) -> List[ExpectationResult]:
    out = []
    since = ctx.phase_start - SINCE_SLACK
    for spec in specs:
        component = spec.get("component", "")
        name = spec.get("name", "")
        contains = spec.get("contains", "")
        count_min = int(spec.get("count_min", 1))
        deadline = ctx.time_fn() + float(spec.get("within", ctx.detect_timeout))

        def matches(c=component, nm=name, sub=contains, n=count_min):
            evs = [
                e
                for e in server.event_store.bucket(c).get(since)
                if (not nm or e.name == nm) and (not sub or sub in e.message)
            ]
            return evs if len(evs) >= n else None

        evs = _poll(matches, deadline, ctx)
        desc = f"{component} events name={name or '*'} >= {count_min}"
        if evs is None:
            out.append(ExpectationResult(
                "events", False, timed_out=True, detail=f"{desc} — absent",
            ))
        else:
            out.append(ExpectationResult(
                "events", True, detail=f"{desc} — {len(evs)} present",
            ))
    return out


def _eval_plane(server, spec: Dict, ctx) -> ExpectationResult:
    if ctx.plane is None:
        return ExpectationResult(
            "plane", False, detail="no fake control plane attached",
        )
    within = float(spec.get("within", ctx.detect_timeout))
    deadline = ctx.time_fn() + within
    if spec.get("reconnected", True):
        ok = _poll(lambda: ctx.plane.connected.is_set() or None, deadline, ctx)
        if ok is None:
            return ExpectationResult(
                "plane", False, timed_out=True,
                detail=f"session did not reconnect within {within:g}s",
            )
        return ExpectationResult("plane", True, detail="session reconnected")
    return ExpectationResult("plane", True, detail="no plane assertion")


def _eval_outbox(server, spec: Dict, ctx) -> List[ExpectationResult]:
    """Store-and-forward delivery assertions (session/outbox.py):

      zero_loss:       every record journaled since the campaign started
                       is observable at the fake control plane (dedupe
                       keys ⊆ plane.outbox_keys) and the backlog drains
                       to the acked watermark
      circuit_states:  ordered-subsequence check against the breaker's
                       transition history (e.g. [open, half_open, closed])
      connects_flat_while_open: while the circuit reads open, the plane's
                       connect/refusal counters must not move — the
                       breaker provably suppresses attempts
      replay_paced:    after a circuit recovery, the server applied a
                       non-zero replay jitter before poking the outbox
                       drain (server.last_replay_jitter_seconds > 0) —
                       the reconnect-storm stagger provably engaged
      max_total_connects: ceiling on total connect attempts that reached
                       the plane (accepted + refused) across the whole
                       campaign — an unpaced reconnect/replay storm
                       blows through it
    """
    out: List[ExpectationResult] = []
    outbox = getattr(server, "outbox", None)
    if outbox is None:
        return [ExpectationResult(
            "outbox", False, detail="outbox disabled (outbox_enabled)",
        )]
    within = float(spec.get("within", ctx.detect_timeout))

    if spec.get("zero_loss", False):
        if ctx.plane is None or not hasattr(ctx.plane, "outbox_keys"):
            out.append(ExpectationResult(
                "outbox", False,
                detail="zero_loss needs an outbox-aware fake control plane",
            ))
        else:
            from gpud_tpu.session.outbox import TABLE as OUTBOX_TABLE

            since = getattr(ctx, "campaign_start", ctx.phase_start) - SINCE_SLACK
            deadline = ctx.time_fn() + within

            def journaled_keys():
                outbox.flush()
                rows = outbox.db.query(
                    f"SELECT dedupe_key FROM {OUTBOX_TABLE} WHERE ts >= ?",
                    (since,),
                )
                return {r[0] for r in rows}

            def drained():
                keys = journaled_keys()
                missing = keys - ctx.plane.outbox_keys
                if missing or outbox.backlog() > 0:
                    return None
                return (len(keys),)

            got = _poll(drained, deadline, ctx)
            if got is None:
                keys = journaled_keys()
                missing = keys - ctx.plane.outbox_keys
                out.append(ExpectationResult(
                    "outbox", False, timed_out=True,
                    detail=(
                        f"zero_loss: {len(missing)} of {len(keys)} journaled "
                        f"record(s) undelivered, backlog={outbox.backlog()} "
                        f"after {within:g}s"
                    ),
                ))
            else:
                out.append(ExpectationResult(
                    "outbox", True,
                    detail=(
                        f"zero_loss: all {got[0]} journaled record(s) "
                        "delivered, backlog drained"
                    ),
                ))

    want_states = spec.get("circuit_states")
    if want_states:
        circuit = getattr(server, "session_circuit", None)
        if circuit is None:
            out.append(ExpectationResult(
                "outbox", False, detail="no session circuit breaker",
            ))
        else:
            deadline = ctx.time_fn() + within

            def seen_in_order():
                seen = circuit.states_seen()
                i = 0
                for s in seen:
                    if i < len(want_states) and s == want_states[i]:
                        i += 1
                return (seen,) if i >= len(want_states) else None

            got = _poll(seen_in_order, deadline, ctx)
            desc = "circuit " + "→".join(want_states)
            if got is None:
                out.append(ExpectationResult(
                    "outbox", False, timed_out=True,
                    detail=f"{desc} not observed (saw: "
                           f"{'→'.join(circuit.states_seen())})",
                ))
            else:
                out.append(ExpectationResult("outbox", True, detail=desc))

    flat_seconds = float(spec.get("connects_flat_while_open", 0.0))
    if flat_seconds > 0:
        circuit = getattr(server, "session_circuit", None)
        if circuit is None or ctx.plane is None:
            out.append(ExpectationResult(
                "outbox", False,
                detail="connects_flat_while_open needs a circuit + plane",
            ))
        else:
            # wait for the breaker to open WITH enough cooldown left to
            # fit the whole sampling window, then hold it: attempts
            # reaching the plane (accepted OR refused) must stay flat
            # while it reads open. Anchoring to seconds_until_probe
            # keeps the legitimate half-open probe out of the window —
            # a probe firing mid-sample is recovery, not a leak
            def window_ready():
                if circuit.state != "open":
                    return None
                if circuit.seconds_until_probe() < flat_seconds + 0.25:
                    return None
                return (True,)

            opened = _poll(window_ready, ctx.time_fn() + within, ctx)
            if opened is None:
                out.append(ExpectationResult(
                    "outbox", False, timed_out=True,
                    detail=(
                        f"circuit never held an open window >= "
                        f"{flat_seconds:g}s within {within:g}s "
                        f"(state now: {circuit.state})"
                    ),
                ))
            else:
                def attempts():
                    return (
                        int(getattr(ctx.plane, "connects", 0))
                        + int(getattr(ctx.plane, "refused", 0))
                    )

                before = attempts()
                end = ctx.time_fn() + flat_seconds
                moved = 0
                while ctx.time_fn() < end and circuit.state == "open":
                    moved = attempts() - before
                    if moved:
                        break
                    ctx.sleep_fn(POLL_INTERVAL)
                ok = moved == 0
                out.append(ExpectationResult(
                    "outbox", ok,
                    detail=(
                        f"connect attempts flat for {flat_seconds:g}s while open"
                        if ok
                        else f"{moved} connect attempt(s) leaked while circuit open"
                    ),
                ))
    if spec.get("replay_paced", False):
        deadline = ctx.time_fn() + within

        def paced():
            j = getattr(server, "last_replay_jitter_seconds", None)
            return (j,) if j is not None and j > 0 else None

        got = _poll(paced, deadline, ctx)
        if got is None:
            j = getattr(server, "last_replay_jitter_seconds", None)
            out.append(ExpectationResult(
                "outbox", False, timed_out=True,
                detail=(
                    f"replay_paced: no post-recovery jitter within "
                    f"{within:g}s (last jitter: {j})"
                ),
            ))
        else:
            out.append(ExpectationResult(
                "outbox", True,
                detail=f"replay paced: {got[0] * 1000.0:.0f}ms jitter "
                       "applied after circuit recovery",
            ))

    max_connects = spec.get("max_total_connects")
    if max_connects is not None:
        if ctx.plane is None:
            out.append(ExpectationResult(
                "outbox", False,
                detail="max_total_connects needs a fake control plane",
            ))
        else:
            total = (
                int(getattr(ctx.plane, "connects", 0))
                + int(getattr(ctx.plane, "refused", 0))
                - int(ctx.baseline.get("plane_attempts", 0.0))
            )
            ok = total <= int(max_connects)
            out.append(ExpectationResult(
                "outbox", ok,
                detail=(
                    f"{total} connect attempt(s) reached the plane this "
                    f"campaign (ceiling {int(max_connects)})"
                ),
            ))

    if not out:
        out.append(ExpectationResult(
            "outbox", True, detail="no outbox assertion",
        ))
    return out


def _eval_fleet(server, spec: Dict, ctx) -> List[ExpectationResult]:
    """Fleet rollup consistency (manager/rollup.py) against the fake
    control plane's ingest ledger:

      consistent:  the rollup journal holds exactly one row per deduped
                   record the plane accepted (``plane.outbox_keys``) —
                   redeliveries across a disconnect storm must not
                   double-count, and nothing the plane accepted may be
                   missing from the rollup. Cumulative across campaigns
                   sharing the plane, like the plane's own dedupe set.
      kinds_match: per-kind record counts in the rollup equal a recount
                   over the plane's accepted frames — no torn aggregates.
    """
    out: List[ExpectationResult] = []
    plane = ctx.plane
    rollup = getattr(plane, "rollup", None) if plane is not None else None
    if rollup is None:
        return [ExpectationResult(
            "fleet", False,
            detail="no fleet rollup store attached to the fake control plane",
        )]
    within = float(spec.get("within", ctx.detect_timeout))

    if spec.get("consistent", True):
        deadline = ctx.time_fn() + within

        def agree():
            delivered = len(plane.outbox_keys)
            journaled = rollup.journal_count()
            if delivered and journaled == delivered == rollup.records_total():
                return (delivered,)
            return None

        got = _poll(agree, deadline, ctx)
        if got is None:
            out.append(ExpectationResult(
                "fleet", False, timed_out=True,
                detail=(
                    f"rollup/plane divergence after {within:g}s: plane "
                    f"accepted {len(plane.outbox_keys)} record(s), rollup "
                    f"journaled {rollup.journal_count()}, applied "
                    f"{rollup.records_total()}"
                ),
            ))
        else:
            out.append(ExpectationResult(
                "fleet", True,
                detail=(
                    f"rollup consistent: {got[0]} record(s) journaled == "
                    "accepted == applied, redeliveries deduped"
                ),
            ))

    if spec.get("kinds_match", False):
        from collections import Counter

        want = Counter(f.get("kind") or "" for f in plane.outbox_frames)
        have: Counter = Counter()
        for agent_id in rollup.agent_ids():
            snap = rollup.agent_snapshot(agent_id)
            have.update(snap["records_by_kind"])
        ok = have == want
        out.append(ExpectationResult(
            "fleet", ok,
            detail=(
                f"per-kind counts match across {len(want)} kind(s)"
                if ok
                else f"per-kind mismatch: plane={dict(want)} rollup={dict(have)}"
            ),
        ))

    if not out:
        out.append(ExpectationResult("fleet", True, detail="no fleet assertion"))
    return out


def _eval_fabric(server, spec: Dict, ctx) -> List[ExpectationResult]:
    """Mesh matrix assertions (fabric/plane.py, docs/fabric.md):

      degraded:       link names that must read Degraded (EWMA latency
                      deviation) in the current matrix
      down:           link names that must read Down (endpoint port down)
      others_healthy: true — every OTHER swept link must read Up; the
                      matrix blames exactly the faulted links, nothing
                      adjacent (blast-radius containment)

    The plane is swept once per poll so the configured sweep cadence
    never gates a campaign; fault-to-matrix latency is measured from the
    phase's first fault step."""
    plane = getattr(server, "fabric", None)
    if plane is None:
        return [ExpectationResult(
            "fabric", False, detail="fabric plane disabled (fabric_sweep_enabled)",
        )]
    from gpud_tpu.fabric.plane import STATE_DEGRADED, STATE_DOWN, STATE_UP

    want_degraded = set(spec.get("degraded") or [])
    want_down = set(spec.get("down") or [])
    others = bool(spec.get("others_healthy", False))
    within = float(spec.get("within", ctx.detect_timeout))
    ref = ctx.fault_t0 if ctx.fault_t0 is not None else ctx.phase_start
    deadline = ctx.time_fn() + within

    def states_now() -> Dict[str, str]:
        plane.sweep_once()  # the sweep cadence must never gate a campaign
        return {r["link"]: r["state"] for r in plane.matrix()}

    def settled():
        states = states_now()
        degraded = {n for n, s in states.items() if s == STATE_DEGRADED}
        down = {n for n, s in states.items() if s == STATE_DOWN}
        if not want_degraded <= degraded or not want_down <= down:
            return None
        if others and (degraded - want_degraded or down - want_down):
            return None
        return (states,)

    got = _poll(settled, deadline, ctx)
    if got is None:
        states = states_now()
        by_state: Dict[str, List[str]] = {}
        for name, s in sorted(states.items()):
            by_state.setdefault(s or "unswept", []).append(name)
        return [ExpectationResult(
            "fabric", False, timed_out=True,
            detail=(
                f"matrix never settled within {within:g}s — wanted "
                f"degraded={sorted(want_degraded)} down={sorted(want_down)} "
                f"others_healthy={others}; matrix now: "
                + "; ".join(f"{s}={v}" for s, v in sorted(by_state.items()))
            ),
        )]
    states = got[0]
    latency = max(0.0, ctx.time_fn() - ref)
    healthy = sum(1 for s in states.values() if s == STATE_UP)
    out = [ExpectationResult(
        "fabric", True, latency_seconds=latency,
        detail=(
            f"matrix blames exactly the faulted links in "
            f"{latency * 1000.0:.0f}ms: {len(want_degraded)} degraded, "
            f"{len(want_down)} down, {healthy} healthy of {len(states)}"
        ),
    )]
    return out


def _eval_predict(server, specs: List[Dict], ctx) -> List[ExpectationResult]:
    """Predictive-health assertions (gpud_tpu/predict/, docs/predict.md):

      warned: true   a ``predicted_degraded`` warning for the component
                     appears within the bound (the engine is poked each
                     poll so the scan cadence never gates a campaign)
      before:        state name (e.g. Unhealthy) — the warning's event
                     timestamp must precede the phase's first ledger
                     transition INTO that state (warning-before-fault
                     ordering, the subsystem's reason to exist)
      before_event:  event name (e.g. health_flapping) — same ordering
                     against the reactive detector's own event
      before_flap:   true — the warning must precede the IN-PHASE
                     transition that carries the ledger past the
                     reactive flap threshold (transition records have no
                     emission cooldown, so this ordering stays valid
                     when an earlier campaign already tripped the
                     flap event's cooldown)
      lead_min:      floor on the engine's measured lead time (seconds
                     from warning to the first reactive hard signal)
      warned: false  NO predictive warning for the component since the
                     campaign started — the zero-false-positive gate
    """
    eng = getattr(server, "predictor", None)
    if eng is None:
        return [ExpectationResult(
            "predict", False, detail="predict engine disabled",
        )]
    from gpud_tpu.predict.engine import EVENT_NAME_PREDICTED

    out: List[ExpectationResult] = []
    since = ctx.phase_start - SINCE_SLACK
    campaign_since = getattr(ctx, "campaign_start", ctx.phase_start) - SINCE_SLACK

    def first_warn_ts(component: str, lookback: float) -> Optional[float]:
        ts = None
        for e in server.event_store.bucket(component).get(lookback):
            if e.name == EVENT_NAME_PREDICTED:
                ts = e.time if ts is None else min(ts, e.time)
        return ts

    for spec in specs:
        component = spec.get("component", "")
        within = float(spec.get("within", ctx.detect_timeout))

        if not spec.get("warned", True):
            # negative gate, evaluated after the phase timeline drained:
            # one extra synchronous scan, then zero tolerance
            eng.poke()
            ts = first_warn_ts(component, campaign_since)
            ok = ts is None
            out.append(ExpectationResult(
                "predict", ok,
                detail=(
                    f"{component}: no predictive warning (un-faulted)"
                    if ok
                    else f"{component}: unexpected predictive warning at {ts:.3f}"
                ),
            ))
            continue

        deadline = ctx.time_fn() + within

        def warned(c=component):
            eng.poke()  # scan cadence must never gate a campaign
            ts = first_warn_ts(c, since)
            return (ts,) if ts is not None else None

        got = _poll(warned, deadline, ctx)
        if got is None:
            out.append(ExpectationResult(
                "predict", False, timed_out=True,
                detail=f"{component}: no predictive warning within {within:g}s",
            ))
            continue
        warn_ts = got[0]
        out.append(ExpectationResult(
            "predict", True,
            detail=f"{component}: predictive warning at +"
                   f"{max(0.0, warn_ts - ctx.phase_start):.3f}s",
        ))

        before_state = spec.get("before", "")
        if before_state:
            def hard_fault(c=component, st=before_state):
                rows = [
                    t["time"]
                    for t in server.health_ledger.history(
                        component=c, since=since
                    )
                    if t["to"] == st
                ]
                return (min(rows),) if rows else None

            hit = _poll(hard_fault, deadline, ctx)
            if hit is None:
                out.append(ExpectationResult(
                    "predict", False, timed_out=True,
                    detail=(
                        f"{component}: no transition→{before_state} to "
                        f"order the warning against"
                    ),
                ))
            else:
                ok = warn_ts <= hit[0]
                out.append(ExpectationResult(
                    "predict", ok,
                    detail=(
                        f"{component}: warning preceded {before_state} by "
                        f"{hit[0] - warn_ts:.3f}s"
                        if ok
                        else f"{component}: warning came {warn_ts - hit[0]:.3f}s "
                             f"AFTER {before_state}"
                    ),
                ))

        before_event = spec.get("before_event", "")
        if before_event:
            def reactive_event(c=component, nm=before_event):
                rows = [
                    e.time
                    for e in server.event_store.bucket(c).get(since)
                    if e.name == nm
                ]
                return (min(rows),) if rows else None

            hit = _poll(reactive_event, deadline, ctx)
            if hit is None:
                out.append(ExpectationResult(
                    "predict", False, timed_out=True,
                    detail=f"{component}: reactive event {before_event} absent",
                ))
            else:
                ok = warn_ts <= hit[0]
                out.append(ExpectationResult(
                    "predict", ok,
                    detail=(
                        f"{component}: warning preceded {before_event} by "
                        f"{hit[0] - warn_ts:.3f}s"
                        if ok
                        else f"{component}: warning came {warn_ts - hit[0]:.3f}s "
                             f"AFTER {before_event}"
                    ),
                ))

        if spec.get("before_flap", False):
            thr = int(server.health_ledger.flap_threshold)

            def flap_crossing(c=component, n=thr):
                rows = sorted(
                    t["time"]
                    for t in server.health_ledger.history(
                        component=c, since=since
                    )
                )
                return (rows[n - 1],) if len(rows) >= n else None

            hit = _poll(flap_crossing, deadline, ctx)
            if hit is None:
                out.append(ExpectationResult(
                    "predict", False, timed_out=True,
                    detail=(
                        f"{component}: fewer than {thr} in-phase "
                        f"transitions — flap threshold never crossed"
                    ),
                ))
            else:
                ok = warn_ts <= hit[0]
                out.append(ExpectationResult(
                    "predict", ok,
                    detail=(
                        f"{component}: warning preceded the flap-threshold "
                        f"crossing by {hit[0] - warn_ts:.3f}s"
                        if ok
                        else f"{component}: warning came "
                             f"{warn_ts - hit[0]:.3f}s AFTER the "
                             f"flap-threshold crossing"
                    ),
                ))

        lead_min = spec.get("lead_min")
        if lead_min is not None:
            def measured(c=component):
                eng.poke()
                d = eng.scores(component=c)["components"].get(c) or {}
                lead = d.get("lead_seconds")
                return (lead,) if lead is not None else None

            hit = _poll(measured, deadline, ctx)
            if hit is None:
                out.append(ExpectationResult(
                    "predict", False, timed_out=True,
                    detail=(
                        f"{component}: lead time never measured within "
                        f"{within:g}s"
                    ),
                ))
            else:
                ok = hit[0] >= float(lead_min)
                out.append(ExpectationResult(
                    "predict", ok,
                    detail=(
                        f"{component}: lead {hit[0]:.3f}s "
                        f"(floor {float(lead_min):g}s)"
                    ),
                ))
    return out


def _eval_invariants(server, spec: Dict, ctx) -> List[ExpectationResult]:
    out = []
    reg = server.metrics_registry
    if spec.get("no_worker_exceptions", True):
        failures = counter_total(reg, "tpud_scheduler_job_failures_total")
        watchdog = counter_total(reg, "tpud_scheduler_watchdog_fires_total")
        df = failures - ctx.baseline.get("failures", 0.0)
        dw = watchdog - ctx.baseline.get("watchdog", 0.0)
        ok = df <= 0 and dw <= 0
        out.append(ExpectationResult(
            "invariants", ok,
            detail=(
                "no unhandled worker exceptions"
                if ok
                else f"{df:g} job failure(s), {dw:g} watchdog fire(s) during campaign"
            ),
        ))
    # un-faulted periodic jobs must still be keeping cadence: a job whose
    # deadline is further in the past than the slack means the scheduler
    # fell over or the pool starved — graceful degradation failed
    if spec.get("cadence", True):
        scheduler = getattr(server, "scheduler", None)
        late = []
        if scheduler is not None:
            now = scheduler.time_fn()
            for jname in scheduler.job_names():
                if jname.startswith("chaos"):
                    continue
                job = scheduler.get_job(jname)
                if job is None or job.one_shot or job.running:
                    continue
                try:
                    interval = float(job.interval_fn())
                except Exception:  # noqa: BLE001
                    continue
                if interval <= 0:
                    continue
                slack = float(
                    spec.get("cadence_slack_seconds", max(2.0, interval))
                )
                if now - job.due > slack:
                    late.append(f"{jname} ({now - job.due:.1f}s late)")
        out.append(ExpectationResult(
            "invariants", not late,
            detail=(
                "un-faulted job cadence within slack"
                if not late
                else "cadence broken: " + ", ".join(late)
            ),
        ))
    max_threads = spec.get("max_threads")
    if max_threads is not None:
        n = threading.active_count()
        out.append(ExpectationResult(
            "invariants", n <= int(max_threads),
            detail=f"threads {n} (gate <= {int(max_threads)})",
        ))
    max_rss = spec.get("max_rss_mb")
    if max_rss is not None:
        mb = rss_mb()
        if mb is None:
            out.append(ExpectationResult(
                "invariants", True, detail="RSS unreadable; gate skipped",
            ))
        else:
            out.append(ExpectationResult(
                "invariants", mb <= float(max_rss),
                detail=f"RSS {mb:.1f}MB (gate <= {float(max_rss):g}MB)",
            ))
    return out


def _eval_predict_lead(server, spec: Dict, ctx) -> List[ExpectationResult]:
    """Fleet-level predictive assertions against the manager-side rollup
    (manager/rollup.py ``fleet_predict``), closing the predict→fleet
    loop end-to-end: the agent's ``predict_score`` outbox records must
    survive ingest and surface in the ranked pane:

      component:      the faulted component name
      in_top:         K — the component must rank within the top-K rows
                      by decayed risk (default 1: it must LEAD the pane)
      warns_min:      floor on fleet-wide journaled warn records (>=1)
      lead_count_min: floor on journaled lead records fleet-wide
      lead_min:       floor on the fleet's minimum measured lead time —
                      the pane must agree the warning landed BEFORE the
                      reactive hard signal, from the manager's view
      within:         poll bound (defaults to the detect timeout)
    """
    plane = ctx.plane
    rollup = getattr(plane, "rollup", None) if plane is not None else None
    if rollup is None:
        return [ExpectationResult(
            "predict_lead", False,
            detail="no fleet rollup store attached to the fake control plane",
        )]
    component = spec.get("component", "")
    in_top = int(spec.get("in_top", 1))
    warns_min = int(spec.get("warns_min", 1))
    lead_count_min = int(spec.get("lead_count_min", 0))
    lead_min = spec.get("lead_min")
    within = float(spec.get("within", ctx.detect_timeout))
    deadline = ctx.time_fn() + within

    def pane_ready():
        # explicit now bypasses the pane's TTL cache so each poll sees
        # the freshest ingested records (and decay at the poll instant)
        pane = rollup.fleet_predict(top=max(in_top, 5), now=ctx.time_fn())
        if pane["warns_total"] < warns_min:
            return None
        if pane["lead"]["count"] < lead_count_min:
            return None
        rank = None
        for i, row in enumerate(pane["top"]):
            if row["component"] == component:
                rank = i
                break
        if rank is None or rank >= in_top:
            return None
        return (pane, rank)

    got = _poll(pane_ready, deadline, ctx)
    if got is None:
        pane = rollup.fleet_predict(top=max(in_top, 5), now=ctx.time_fn())
        ranked = [
            f'{r["agent"]}/{r["component"]}@{r["risk"]:.3f}'
            for r in pane["top"]
        ]
        return [ExpectationResult(
            "predict_lead", False, timed_out=True,
            detail=(
                f"{component}: never ranked in the fleet pane top-{in_top} "
                f"within {within:g}s (warns={pane['warns_total']}, "
                f"leads={pane['lead']['count']}, top={ranked})"
            ),
        )]
    pane, rank = got
    out = [ExpectationResult(
        "predict_lead", True,
        detail=(
            f"{component}: rank #{rank + 1} in the fleet pane "
            f"(risk={pane['top'][rank]['risk']:.3f}, "
            f"warns={pane['warns_total']}, leads={pane['lead']['count']})"
        ),
    )]
    if lead_min is not None:
        have = pane["lead"]["min_seconds"]
        ok = pane["lead"]["count"] > 0 and have >= float(lead_min)
        out.append(ExpectationResult(
            "predict_lead", ok,
            detail=(
                f"fleet lead floor: min={have:g}s over "
                f"{pane['lead']['count']} lead record(s) "
                f"(gate >= {float(lead_min):g}s)"
                if pane["lead"]["count"]
                else "fleet lead floor: no lead records journaled"
            ),
        ))
    return out


def evaluate_phase(server, expect: Dict, ctx) -> List[ExpectationResult]:
    """Evaluate a phase's full expectation block, in chain order."""
    results: List[ExpectationResult] = []
    if "detect" in expect:
        results.append(_eval_detect(server, expect["detect"] or {}, ctx))
    if "ledger" in expect:
        results.extend(_eval_ledger(server, expect["ledger"] or [], ctx))
    if "remediation" in expect:
        results.extend(_eval_remediation(server, expect["remediation"] or [], ctx))
    if "events" in expect:
        results.extend(_eval_events(server, expect["events"] or [], ctx))
    if "plane" in expect:
        results.append(_eval_plane(server, expect["plane"] or {}, ctx))
    if "outbox" in expect:
        results.extend(_eval_outbox(server, expect["outbox"] or {}, ctx))
    if "fleet" in expect:
        results.extend(_eval_fleet(server, expect["fleet"] or {}, ctx))
    if "fabric" in expect:
        results.extend(_eval_fabric(server, expect["fabric"] or {}, ctx))
    if "predict" in expect:
        results.extend(_eval_predict(server, expect["predict"] or [], ctx))
    if "predict_lead" in expect:
        results.extend(
            _eval_predict_lead(server, expect["predict_lead"] or {}, ctx)
        )
    if "invariants" in expect:
        results.extend(_eval_invariants(server, expect["invariants"] or {}, ctx))
    return results
