"""Chaos scenario model: declarative campaign files and their timeline.

A scenario is YAML (or JSON — YAML is a superset) with this shape::

    name: thermal-ici-cascade
    description: cascading ICI link loss during a thermal excursion
    defaults:
      detect_timeout: 8.0        # per-phase expectation wait ceiling
    phases:
      - name: thermal-ramp
        steps:
          - at: 0.0              # seconds from phase start
            action: metric_ramp
            component: accelerator-tpu-temperature
            field: temperature_c
            start: 80.0
            end: 98.0
            ramp_seconds: 1.5
          - at: 0.2
            every: 0.4           # repeat spacing …
            count: 5             # … this many occurrences
            jitter: 0.1          # ± fraction of `every`, deterministic
            action: trigger
            component: accelerator-tpu-temperature
        expect:
          ledger:
            - component: accelerator-tpu-temperature
              to: Unhealthy
          invariants:
            no_worker_exceptions: true

The ``every``+``count``+``jitter`` expansion is resolved *before* the
campaign runs (:func:`expand_steps`), with the same crc32-keyed
deterministic jitter the scheduler uses for cadence spreading: the same
scenario expands to the same timeline on every host and every run, so a
failing campaign replays exactly.
"""

from __future__ import annotations

import json
import os
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

SCENARIOS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "scenarios")

# actions the runner knows how to execute (gpud_tpu/chaos/faults.py)
KNOWN_ACTIONS = (
    "inject",          # kmsg fault write (burst via repeat/interval_seconds)
    "metric_ramp",     # slow-ramp telemetry override (hbm/temperature hook)
    "metric_clear",    # remove a component's telemetry override
    "runtime_crash",   # runtime unit reported failed for `duration` seconds
    "clock_skew",      # shift a component's / the engine's clock by `offset`
    "plane_disconnect",  # drop control-plane sessions (fake_plane harness)
    "plane_refuse",    # hard-down manager: 503 every connect for `duration`
    "fabric_latency_ramp",  # slow-ramp one mesh link's probe latency
    "fabric_link_down",     # hard-down one physical ICI port
    "fabric_sweep",    # run one all-links fabric sweep now
    "trigger",         # poke a component check to the front of the heap
    "set_healthy",     # clear a component's sticky state
    "remediation_scan",  # poke the remediation engine's scan job
    "predict_scan",    # run a synchronous precursor-scoring tick now
    "predict_reset",   # drop predictor scorer state (campaign isolation)
    "purge",           # run the consolidated retention purge now
    "ingest_burst",    # observation firehose: `count` events + metric rows
    "storage_flush",   # write-behind flush barrier (pre-crash durability line)
    "storage_crash",   # discard the write-behind buffer uncommitted (SIGKILL sim)
    "manager_kill_rebuild",  # SIGKILL the manager: rebuild rollups from journal
    "peer_plane_boot",  # HA tier: boot a peer manager + breaker failover list
)

# expectation kinds evaluated after each phase (gpud_tpu/chaos/expectations.py)
KNOWN_EXPECTATIONS = (
    "detect", "ledger", "remediation", "events", "invariants", "plane",
    "outbox", "fleet", "fabric", "predict", "predict_lead",
)

MAX_STEP_OCCURRENCES = 1000  # per phase — runaway `count` backstop

DEFAULT_DETECT_TIMEOUT = 10.0


class ScenarioError(ValueError):
    """Raised for a scenario file the runner refuses to execute."""


@dataclass
class StepOccurrence:
    """One resolved point on a phase's timeline."""

    offset: float          # seconds from phase start (jitter applied)
    step: Dict             # the raw step mapping (shared across occurrences)
    step_index: int        # position of the step in the phase
    occurrence: int        # 0..count-1 within the step's expansion

    @property
    def action(self) -> str:
        return self.step.get("action", "")


@dataclass
class Phase:
    name: str
    steps: List[Dict] = field(default_factory=list)
    expect: Dict = field(default_factory=dict)
    # extra settle time after the last step before expectations run
    settle_seconds: float = 0.0


@dataclass
class Scenario:
    name: str
    description: str = ""
    phases: List[Phase] = field(default_factory=list)
    detect_timeout: float = DEFAULT_DETECT_TIMEOUT
    source: str = ""  # file path when loaded from disk

    def validate(self) -> Optional[str]:
        """Returns an error string, or None when executable."""
        if not self.name:
            return "scenario needs a name"
        if not self.phases:
            return "scenario needs at least one phase"
        if self.detect_timeout <= 0:
            return "defaults.detect_timeout must be > 0"
        for p in self.phases:
            if not p.name:
                return "every phase needs a name"
            for i, s in enumerate(p.steps):
                action = s.get("action", "")
                if action not in KNOWN_ACTIONS:
                    return (
                        f"phase {p.name!r} step {i}: unknown action "
                        f"{action!r}; known: {', '.join(KNOWN_ACTIONS)}"
                    )
                if float(s.get("at", 0.0)) < 0:
                    return f"phase {p.name!r} step {i}: negative `at`"
                every = float(s.get("every", 0.0))
                count = int(s.get("count", 1))
                if every < 0 or count < 1:
                    return (
                        f"phase {p.name!r} step {i}: `every` must be >= 0 "
                        "and `count` >= 1"
                    )
                if count > 1 and every <= 0:
                    return (
                        f"phase {p.name!r} step {i}: `count` > 1 needs "
                        "`every` > 0"
                    )
                if not (0.0 <= float(s.get("jitter", 0.0)) <= 1.0):
                    return f"phase {p.name!r} step {i}: jitter must be in [0, 1]"
            for kind in p.expect:
                if kind not in KNOWN_EXPECTATIONS:
                    return (
                        f"phase {p.name!r}: unknown expectation {kind!r}; "
                        f"known: {', '.join(KNOWN_EXPECTATIONS)}"
                    )
        try:
            if self.duration_estimate() > 24 * 3600:
                return "scenario timeline exceeds 24h"
        except ScenarioError as e:
            return str(e)
        return None

    def duration_estimate(self) -> float:
        """Upper-bound step-timeline length (expectation waits excluded)."""
        total = 0.0
        for p in self.phases:
            occ = expand_steps(p.steps, key_prefix=f"{self.name}:{p.name}")
            total += (occ[-1].offset if occ else 0.0) + p.settle_seconds
        return total

    def to_dict(self) -> Dict:
        return {
            "name": self.name,
            "description": self.description,
            "detect_timeout": self.detect_timeout,
            "phases": [
                {
                    "name": p.name,
                    "steps": p.steps,
                    "expect": p.expect,
                    "settle_seconds": p.settle_seconds,
                }
                for p in self.phases
            ],
        }


def _jitter_unit(key: str) -> float:
    """Deterministic fraction in [-1, 1] — same crc32 mapping the
    scheduler's cadence jitter uses (scheduler/core.py:_jittered), so a
    scenario's spread is stable across runs and hosts."""
    return (zlib.crc32(key.encode()) % 2001 - 1000) / 1000.0


def expand_steps(
    steps: List[Dict], key_prefix: str = ""
) -> List[StepOccurrence]:
    """Resolve ``at``/``every``/``count``/``jitter`` into a sorted
    timeline of occurrences. Jitter displaces each *repeat* occurrence by
    up to ``jitter * every`` (the first occurrence of a step keeps its
    exact ``at`` so phase-relative ordering intent survives)."""
    out: List[StepOccurrence] = []
    for i, s in enumerate(steps):
        at = float(s.get("at", 0.0))
        every = float(s.get("every", 0.0))
        count = int(s.get("count", 1))
        frac = float(s.get("jitter", 0.0))
        for k in range(count):
            offset = at + k * every
            if k > 0 and frac > 0 and every > 0:
                offset += every * frac * _jitter_unit(f"{key_prefix}:{i}:{k}")
            out.append(
                StepOccurrence(
                    offset=max(0.0, offset),
                    step=s,
                    step_index=i,
                    occurrence=k,
                )
            )
    if len(out) > MAX_STEP_OCCURRENCES:
        raise ScenarioError(
            f"phase expands to {len(out)} step occurrences "
            f"(max {MAX_STEP_OCCURRENCES})"
        )
    out.sort(key=lambda o: (o.offset, o.step_index, o.occurrence))
    return out


def _parse(data: Dict, source: str = "") -> Scenario:
    if not isinstance(data, dict):
        raise ScenarioError("scenario must be a mapping")
    defaults = data.get("defaults") or {}
    phases = []
    for p in data.get("phases") or []:
        if not isinstance(p, dict):
            raise ScenarioError("every phase must be a mapping")
        phases.append(
            Phase(
                name=str(p.get("name", "")),
                steps=list(p.get("steps") or []),
                expect=dict(p.get("expect") or {}),
                settle_seconds=float(p.get("settle_seconds", 0.0)),
            )
        )
    sc = Scenario(
        name=str(data.get("name", "")),
        description=str(data.get("description", "")),
        phases=phases,
        detect_timeout=float(
            defaults.get("detect_timeout", DEFAULT_DETECT_TIMEOUT)
        ),
        source=source,
    )
    err = sc.validate()
    if err:
        raise ScenarioError(f"{source or sc.name or 'scenario'}: {err}")
    return sc


def load_scenario(spec, extra_dirs: Optional[List[str]] = None) -> Scenario:
    """Load a scenario from an inline mapping, a file path, or a shipped
    scenario name (resolved under ``gpud_tpu/chaos/scenarios/`` and any
    ``extra_dirs``)."""
    if isinstance(spec, dict):
        return _parse(spec)
    if not isinstance(spec, str) or not spec:
        raise ScenarioError(f"bad scenario spec: {spec!r}")
    path = spec
    if not os.path.isfile(path):
        for d in list(extra_dirs or []) + [SCENARIOS_DIR]:
            for ext in ("", ".yaml", ".yml", ".json"):
                cand = os.path.join(d, spec + ext)
                if os.path.isfile(cand):
                    path = cand
                    break
            else:
                continue
            break
    if not os.path.isfile(path):
        known = ", ".join(sorted(shipped_scenarios()))
        raise ScenarioError(
            f"scenario {spec!r} not found (shipped: {known})"
        )
    with open(path, encoding="utf-8") as f:
        raw = f.read()
    if path.endswith(".json"):
        data = json.loads(raw)
    else:
        import yaml

        data = yaml.safe_load(raw)
    return _parse(data, source=path)


def shipped_scenarios() -> Dict[str, str]:
    """name → path of every scenario shipped with the package."""
    out: Dict[str, str] = {}
    if not os.path.isdir(SCENARIOS_DIR):
        return out
    for fn in sorted(os.listdir(SCENARIOS_DIR)):
        base, ext = os.path.splitext(fn)
        if ext in (".yaml", ".yml", ".json"):
            out[base] = os.path.join(SCENARIOS_DIR, fn)
    return out


def first_fault_offset(occurrences: List[StepOccurrence]) -> Optional[Tuple[float, str]]:
    """(offset, action) of the first fault-class step in a phase — the
    reference point detection latency is measured from."""
    for o in occurrences:
        if o.action in ("inject", "metric_ramp", "runtime_crash",
                        "plane_disconnect", "fabric_latency_ramp",
                        "fabric_link_down"):
            return o.offset, o.action
    return None
