"""Reusable fake control plane: tpud session protocol + chaos knobs.

A minimal control plane implementing the dual chunked-ndjson session
streams and ``/api/v1/login``, shared by the e2e tests
(``tests/fake_control_plane.py`` re-exports this class) and the chaos
campaign runner (``plane_disconnect`` steps). Beyond the protocol it
carries the fault knobs a disconnect/latency campaign needs:

  - ``reject_auth`` / ``accept_token``: 401 storms and token rotation
  - ``latency_seconds``: injected delay before a session stream starts
    serving and before each pushed frame (slow-control-plane modelling)
  - ``drop_session`` / ``drop_all`` / ``disconnect_storm``: scripted
    disconnect/reconnect churn against the agent's session loop
  - ``refuse_connects``: 503 every session stream — a hard-down manager
    for circuit-breaker drills (connect attempts are still counted)
  - outbox ingest: frames carrying ``outbox_seq`` on the write stream
    are recorded (``outbox_keys`` / ``outbox_frames``) and auto-acked
    via an ``outboxAck`` request on the read stream — the manager half
    of the store-and-forward contract (session/outbox.py)

Run standalone: ``python -m gpud_tpu.chaos.fake_plane <port>``.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from typing import Dict, List, Optional

from aiohttp import web


class FakeControlPlane:
    def __init__(self, port: int = 0) -> None:
        self.port = port
        self.sessions: Dict[str, asyncio.Queue] = {}   # machine_id → outbound q
        self.responses: List[dict] = []
        self.logins: List[dict] = []
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.connected = threading.Event()
        self.reject_auth = False   # return 401 on session streams
        self.accept_token: Optional[str] = None  # 401 any other bearer token
        self.auth_rejects = 0
        # chaos knobs
        self.latency_seconds = 0.0  # injected delay per stream-start/frame
        self.connects = 0           # read-stream accepts (reconnect counting)
        self.drops = 0              # sessions dropped via drop_session/drop_all
        self.refuse_connects = False  # 503 every session stream (hard-down)
        self.refused = 0
        # store-and-forward outbox ingest (auto-acked; see module docstring)
        self.outbox_frames: List[dict] = []
        self.outbox_keys: set = set()
        self.outbox_acked: Dict[str, int] = {}  # machine_id → highest seq
        self._ack_seq = 0
        # per-machine delta decoders for batched delivery frames
        # (session/wire.py); reset on reconnect like the real manager's
        # per-connection AgentHandle decoder
        self._outbox_decoders: Dict[str, object] = {}
        # optional fleet rollup store (manager/rollup.py): when attached,
        # every fresh (deduped) record is forwarded exactly like the real
        # control plane's AgentHandle.on_records hook, so chaos campaigns
        # can assert rollup/ingest consistency (`fleet` expectations)
        self.rollup = None

    def attach_rollup(self, data_dir=None, shard_count=None):
        """Attach a FleetRollupStore fed by the outbox ingest path;
        returns the store. Synchronous writes (no BatchWriter) — chaos
        asserts consistency, not throughput — which also means every
        journaled row is durable the instant ``ingest`` returns, so the
        ``manager_kill_rebuild`` fault can rebuild from the same DB at
        any point with zero durability window. ``data_dir`` persists
        the journal to ``<data_dir>/fleet.db`` (default in-memory);
        ``shard_count`` overrides the default shard striping."""
        import os

        from gpud_tpu.manager.rollup import FleetRollupStore
        from gpud_tpu.manager.shard import DEFAULT_SHARD_COUNT
        from gpud_tpu.sqlite import DB

        db_path = ":memory:"
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            db_path = os.path.join(data_dir, "fleet.db")
        self.rollup = FleetRollupStore(
            DB(db_path), writer=None,
            shard_count=shard_count or DEFAULT_SHARD_COUNT,
        )
        return self.rollup

    # -- server ------------------------------------------------------------
    async def _login(self, req: web.Request) -> web.Response:
        body = await req.json()
        self.logins.append(body)
        return web.json_response(
            {
                "machine_id": body.get("machine_id") or "cp-assigned-1",
                "token": "cp-session-token",
                "machine_proof": "cp-proof",
            }
        )

    async def _session(self, req: web.Request) -> web.StreamResponse:
        if self.refuse_connects:
            # hard-down manager: the attempt reached us (counted) but no
            # stream is served — drives the agent's circuit breaker open
            self.refused += 1
            return web.Response(status=503, text="unavailable")
        if self.reject_auth:
            self.auth_rejects += 1
            return web.Response(status=401, text="unauthorized")
        if self.accept_token is not None:
            bearer = req.headers.get("Authorization", "")
            if bearer.removeprefix("Bearer ").strip() != self.accept_token:
                self.auth_rejects += 1
                return web.Response(status=401, text="unauthorized")
        if self.latency_seconds > 0:
            await asyncio.sleep(self.latency_seconds)
        stype = req.headers.get("X-TPUD-Session-Type", "")
        machine = req.headers.get("X-TPUD-Machine-ID", "")
        if stype == "read":
            resp = web.StreamResponse()
            resp.headers["Content-Type"] = "application/x-ndjson"
            await resp.prepare(req)
            q: asyncio.Queue = asyncio.Queue()
            self.sessions[machine] = q
            # fresh connection = fresh delta streams (the agent resets
            # its encoder on reconnect; mirror the real manager handle)
            self._outbox_decoders.pop(machine, None)
            self.connects += 1
            self.connected.set()
            try:
                while True:
                    frame = await q.get()
                    if frame is None:
                        break
                    if self.latency_seconds > 0:
                        await asyncio.sleep(self.latency_seconds)
                    if isinstance(frame, bytes):
                        # raw bytes (hostile-manager tests): sent verbatim
                        await resp.write(frame)
                    else:
                        await resp.write((json.dumps(frame) + "\n").encode())
            except (ConnectionResetError, asyncio.CancelledError):
                pass
            return resp
        if stype == "write":
            async for line in req.content:
                line = line.strip()
                if not line:
                    continue
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                self.responses.append(d)
                data = d.get("data") if isinstance(d, dict) else None
                if isinstance(data, dict) and (
                    "outbox_seq" in data or "outbox_batch" in data
                ):
                    self._ingest_outbox(machine, data)
            return web.json_response({"ok": True})
        return web.json_response({"error": "bad session type"}, status=400)

    def _ingest_outbox(self, machine: str, data: dict) -> None:
        """Record one store-and-forward delivery frame — a batched
        delta-encoded ``outbox_batch`` (docs/session.md wire format) or a
        legacy per-record payload — and auto-ack ONE cumulative watermark
        on the machine's read stream (dedupe is by key — at-least-once
        means redeliveries are normal and must not double-record)."""
        from gpud_tpu.session import wire

        batch = wire.parse_batch(data)
        if batch is not None:
            decoder = self._outbox_decoders.get(machine)
            if decoder is None:
                decoder = self._outbox_decoders[machine] = wire.DeltaDecoder()
            records = []
            for rec in batch.get("records") or []:
                try:
                    seq, ts, kind, key, body = decoder.decode_record(rec)
                except (wire.DeltaDecodeError, TypeError, ValueError):
                    break  # ack the decoded prefix only
                records.append({
                    "outbox_seq": seq,
                    "ts": ts,
                    "kind": kind,
                    "dedupe_key": key,
                    "payload": body,
                })
            if not records:
                return
            ack_to = records[-1]["outbox_seq"]
        else:
            try:
                ack_to = int(data.get("outbox_seq", 0))
            except (TypeError, ValueError):
                return
            records = [data]
        fresh = []
        for rec in records:
            key = str(rec.get("dedupe_key") or "")
            if key not in self.outbox_keys:
                self.outbox_keys.add(key)
                self.outbox_frames.append(rec)
                fresh.append((
                    rec.get("outbox_seq") or 0,
                    rec.get("ts") or 0.0,
                    rec.get("kind") or "",
                    key,
                    rec.get("payload"),
                ))
        if self.rollup is not None and fresh:
            try:
                self.rollup.ingest(machine or "chaos-agent", fresh)
            except Exception:  # noqa: BLE001 — observability is best-effort
                pass
        if ack_to > self.outbox_acked.get(machine, 0):
            self.outbox_acked[machine] = ack_to
        q = self.sessions.get(machine)
        if q is not None:
            self._ack_seq += 1
            q.put_nowait(
                {
                    "req_id": f"fcp-ack-{self._ack_seq}",
                    "data": {"method": "outboxAck",
                             "seq": self.outbox_acked[machine]},
                }
            )

    # -- control API for tests / campaigns -----------------------------------
    def send_request(self, machine_id: str, req_id: str, data: dict) -> None:
        q = self.sessions.get(machine_id)
        if q is None:
            raise RuntimeError(f"no session for {machine_id}")
        asyncio.run_coroutine_threadsafe(
            q.put({"req_id": req_id, "data": data}), self._loop
        ).result(timeout=5)

    def send_raw(self, machine_id: str, payload: bytes) -> None:
        """Push raw bytes down the read stream (malformed-frame tests)."""
        q = self.sessions.get(machine_id)
        if q is None:
            raise RuntimeError(f"no session for {machine_id}")
        asyncio.run_coroutine_threadsafe(q.put(payload), self._loop).result(
            timeout=5
        )

    def drop_session(self, machine_id: str) -> None:
        """End the read stream, forcing the agent to reconnect (used with
        accept_token changes to model a mid-stream revocation)."""
        q = self.sessions.pop(machine_id, None)
        if q is None:
            raise RuntimeError(f"no session for {machine_id}")
        self.connected.clear()
        self.drops += 1
        asyncio.run_coroutine_threadsafe(q.put(None), self._loop).result(
            timeout=5
        )

    def drop_all(self) -> int:
        """Drop every live session (chaos ``plane_disconnect`` step);
        returns how many were dropped."""
        n = 0
        for machine in list(self.sessions):
            try:
                self.drop_session(machine)
                n += 1
            except RuntimeError:
                continue
        return n

    def disconnect_storm(self, count: int, interval: float = 0.5) -> int:
        """Scripted churn: drop all sessions ``count`` times, waiting out
        ``interval`` between rounds (and for the agent to reconnect, up
        to the same interval). Returns total sessions dropped."""
        total = 0
        for i in range(count):
            total += self.drop_all()
            if i < count - 1:
                self.connected.wait(timeout=max(interval, 0.05))
                time.sleep(interval)
        return total

    def wait_response(self, req_id: str, timeout: float = 10.0) -> Optional[dict]:
        deadline = time.time() + timeout
        while time.time() < deadline:
            for r in self.responses:
                if r.get("req_id") == req_id:
                    return r
            time.sleep(0.02)
        return None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("fake control plane failed to start")

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        app = web.Application()
        app.router.add_post("/api/v1/login", self._login)
        app.router.add_post("/api/v1/session", self._session)
        runner = web.AppRunner(app)

        async def go():
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", self.port)
            await site.start()
            for s in site._server.sockets:  # noqa: SLF001
                self.port = s.getsockname()[1]
            self._started.set()

        try:
            loop.run_until_complete(go())
            loop.run_forever()
        finally:
            # Tear down in-loop so no aiohttp object outlives its loop
            # (otherwise GC-time __del__ raises "Event loop is closed").
            try:
                loop.run_until_complete(runner.cleanup())
                loop.run_until_complete(loop.shutdown_asyncgens())
            except Exception:  # noqa: BLE001
                pass
            loop.close()

    def stop(self) -> None:
        if self._loop is not None and self._loop.is_running():
            # End open read-stream handlers first: they park on q.get(),
            # and runner.cleanup() would otherwise wait out its shutdown
            # timeout on them (leaving the loop thread alive for a minute)
            async def _drain() -> None:
                for q in self.sessions.values():
                    q.put_nowait(None)
                self.sessions.clear()

            try:
                asyncio.run_coroutine_threadsafe(_drain(), self._loop).result(
                    timeout=2
                )
            except Exception:  # noqa: BLE001 — loop may be stopping already
                pass
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5)


if __name__ == "__main__":
    import sys

    cp = FakeControlPlane(port=int(sys.argv[1]) if len(sys.argv) > 1 else 0)
    cp.start()
    print(f"fake control plane on http://127.0.0.1:{cp.port}", flush=True)
    try:
        while True:
            time.sleep(0.5)
    except KeyboardInterrupt:
        cp.stop()
