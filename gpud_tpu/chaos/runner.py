"""Chaos campaign runner: execute a scenario against the live daemon.

The runner expands each phase's timeline (scenario.py), fires every step
as a one-shot job on the unified scheduler pool (so chaos work is
watchdogged and accounted like any other daemon work), then evaluates the
phase's expectation block (expectations.py). Every mutation a fault makes
is undone through the campaign context's cleanup stack — a campaign
always leaves the daemon as it found it, pass or fail.

``ChaosManager`` is the server-side owner wired like every subsystem:
constructed by ``server.Server``, closed on stop, and surfaced through
``POST /v1/chaos/run`` + ``GET /v1/chaos/campaigns``, the
``chaosRun``/``chaosStatus`` session methods, the SDK, and ``tpud chaos``.
One campaign runs at a time; results land in a bounded in-memory history.

Self-metrics (docs/observability.md):
  tpud_chaos_steps_injected_total{scenario,action}
  tpud_chaos_expectations_total{scenario,outcome}
  tpud_chaos_detection_latency_seconds{scenario}
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from gpud_tpu.chaos.expectations import counter_total, evaluate_phase
from gpud_tpu.chaos.faults import ACTIONS
from gpud_tpu.chaos.scenario import (
    Scenario,
    ScenarioError,
    expand_steps,
    load_scenario,
    shipped_scenarios,
)
from gpud_tpu.log import audit as audit_log
from gpud_tpu.log import get_logger
from gpud_tpu.metrics.registry import counter, histogram

logger = get_logger(__name__)

# actions that *start* a fault: the phase's detection-latency clock is
# anchored at the first of these to fire
FAULT_ACTIONS = ("inject", "metric_ramp", "runtime_crash", "clock_skew",
                 "plane_disconnect", "plane_refuse",
                 "fabric_latency_ramp", "fabric_link_down")

STEP_WAIT_SECONDS = 60.0  # per-step completion ceiling on the pool

_c_steps = counter(
    "tpud_chaos_steps_injected_total",
    "chaos campaign steps executed, by scenario and action",
)
_c_expect = counter(
    "tpud_chaos_expectations_total",
    "chaos expectation evaluations, by scenario and outcome (passed|failed)",
)
_h_detect = histogram(
    "tpud_chaos_detection_latency_seconds",
    "fault-to-detection latency measured by chaos campaigns, by scenario",
)


class CampaignAborted(RuntimeError):
    """The daemon is shutting down mid-campaign."""


class _Context:
    """Mutable campaign state shared by faults and expectations."""

    def __init__(self, time_fn, sleep_fn, plane, detect_timeout: float) -> None:
        self.time_fn = time_fn
        self.sleep_fn = sleep_fn
        self.plane = plane
        self.detect_timeout = detect_timeout
        self.cleanups: List = []
        self.baseline: Dict[str, float] = {}
        self.campaign_start = 0.0
        self.phase_start = 0.0
        self.fault_t0: Optional[float] = None


class CampaignRunner:
    """Executes ONE scenario synchronously. ``time_fn``/``sleep_fn`` are
    injectable so the timeline logic is fake-clock testable."""

    def __init__(
        self,
        server,
        scenario: Scenario,
        plane=None,
        time_fn=None,
        sleep_fn=None,
        stop_event: Optional[threading.Event] = None,
    ) -> None:
        self.server = server
        self.scenario = scenario
        self.plane = plane
        self.time_fn = time_fn or time.time
        self._raw_sleep = sleep_fn or time.sleep
        self.stop_event = stop_event or threading.Event()

    def _sleep(self, seconds: float) -> None:
        """Chunked sleep that aborts promptly on daemon shutdown."""
        deadline = self.time_fn() + seconds
        while True:
            if self.stop_event.is_set():
                raise CampaignAborted("daemon stopping")
            remaining = deadline - self.time_fn()
            if remaining <= 0:
                return
            self._raw_sleep(min(0.05, remaining))

    def run(self) -> Dict:
        sc = self.scenario
        ctx = _Context(self.time_fn, self._sleep, self.plane, sc.detect_timeout)
        reg = self.server.metrics_registry
        ctx.baseline = {
            "failures": counter_total(reg, "tpud_scheduler_job_failures_total"),
            "watchdog": counter_total(reg, "tpud_scheduler_watchdog_fires_total"),
        }
        if self.plane is not None:
            # connect-attempt baseline: max_total_connects ceilings are
            # per-campaign deltas, not absolutes — a `--chaos all` run
            # accumulates plane counters across scenarios
            ctx.baseline["plane_attempts"] = (
                float(getattr(self.plane, "connects", 0))
                + float(getattr(self.plane, "refused", 0))
            )
        started = self.time_fn()
        ctx.campaign_start = started
        audit_log("chaos_campaign", scenario=sc.name)
        result: Dict = {
            "scenario": sc.name,
            "description": sc.description,
            "started": started,
            "phases": [],
            "passed": False,
            "error": "",
        }
        try:
            for phase in sc.phases:
                result["phases"].append(self._run_phase(phase, ctx))
        except CampaignAborted as e:
            result["error"] = str(e)
        except ScenarioError as e:
            result["error"] = str(e)
        finally:
            # undo every fault mutation, newest first, even on abort
            for undo in reversed(ctx.cleanups):
                try:
                    undo()
                except Exception:  # noqa: BLE001 — one undo must not skip the rest
                    logger.exception("chaos cleanup failed (%s)", sc.name)
            ctx.cleanups.clear()
        result["finished"] = self.time_fn()
        result["duration_seconds"] = round(result["finished"] - started, 3)
        result["passed"] = (
            not result["error"]
            and bool(result["phases"])
            and all(p["passed"] for p in result["phases"])
        )
        logger.info(
            "chaos campaign %s: %s (%d phase(s), %.1fs)",
            sc.name,
            "PASS" if result["passed"] else "FAIL",
            len(result["phases"]),
            result["duration_seconds"],
        )
        return result

    def _run_phase(self, phase, ctx: _Context) -> Dict:
        occurrences = expand_steps(
            phase.steps, key_prefix=f"{self.scenario.name}:{phase.name}"
        )
        ctx.phase_start = self.time_fn()
        ctx.fault_t0 = None
        step_errors: List[str] = []
        for occ in occurrences:
            due = ctx.phase_start + occ.offset
            now = self.time_fn()
            if due > now:
                self._sleep(due - now)
            if ctx.fault_t0 is None and occ.action in FAULT_ACTIONS:
                ctx.fault_t0 = self.time_fn()
            err = self._execute_step(occ, ctx)
            _c_steps.inc(
                labels={"scenario": self.scenario.name, "action": occ.action}
            )
            if err:
                step_errors.append(
                    f"step {occ.step_index}.{occ.occurrence} "
                    f"({occ.action}): {err}"
                )
        if phase.settle_seconds > 0:
            self._sleep(phase.settle_seconds)
        results = evaluate_phase(self.server, phase.expect, ctx)
        for r in results:
            _c_expect.inc(labels={
                "scenario": self.scenario.name,
                "outcome": "passed" if r.ok else "failed",
            })
            if r.kind == "detect" and r.latency_seconds is not None:
                _h_detect.observe(
                    r.latency_seconds, {"scenario": self.scenario.name}
                )
        passed = not step_errors and all(r.ok for r in results)
        return {
            "name": phase.name,
            "steps_executed": len(occurrences),
            "step_errors": step_errors,
            "expectations": [r.to_dict() for r in results],
            "passed": passed,
        }

    def _execute_step(self, occ, ctx: _Context) -> Optional[str]:
        """One step runs as a one-shot scheduler job (pool + watchdog);
        the runner waits for it so timeline ordering holds. Direct call
        when no scheduler exists (unit tests, scheduler-less servers)."""
        fn = ACTIONS.get(occ.action)
        if fn is None:
            return f"unknown action {occ.action!r}"
        holder: Dict[str, Optional[str]] = {"err": None}
        done = threading.Event()

        def run_step() -> None:
            try:
                holder["err"] = fn(self.server, occ.step, ctx)
            except Exception as e:  # noqa: BLE001 — a step crash is a finding, not a runner crash
                logger.exception(
                    "chaos step %s.%d (%s) raised",
                    occ.step_index, occ.occurrence, occ.action,
                )
                holder["err"] = f"{type(e).__name__}: {e}"
            finally:
                done.set()

        scheduler = getattr(self.server, "scheduler", None)
        name = (
            f"chaos:{self.scenario.name}:"
            f"{occ.step_index}.{occ.occurrence}:{occ.action}"
        )
        if scheduler is not None and scheduler.submit(name, run_step):
            if not done.wait(STEP_WAIT_SECONDS):
                return f"step did not complete within {STEP_WAIT_SECONDS:g}s"
        else:
            run_step()
        return holder["err"]


class ChaosManager:
    """Server-side campaign owner: loads scenarios, runs one campaign at
    a time (inline or as a scheduler job), keeps a bounded result
    history. ``plane`` may be attached by the bench/e2e harness to give
    plane_disconnect steps a fake control plane to storm."""

    # _stop is a threading.Event (internally synchronized); plane /
    # on_result are wired once at server construction, before any
    # campaign thread exists
    GUARDED_BY = {
        "_history": "_mu",
        "_running": "_mu",
        "_seq": "_mu",
    }

    def __init__(
        self,
        server,
        history_limit: int = 32,
        max_campaign_seconds: float = 300.0,
        extra_dirs: Optional[List[str]] = None,
    ) -> None:
        self.server = server
        self.max_campaign_seconds = max_campaign_seconds
        self.extra_dirs = list(extra_dirs or [])
        self.plane = None
        # optional campaign-result observer (the server wires the session
        # outbox here); must never fail the campaign path
        self.on_result = None
        self._mu = threading.Lock()
        self._history: deque = deque(maxlen=max(1, history_limit))
        self._running: Optional[Dict] = None
        self._seq = 0
        self._stop = threading.Event()

    # -- runs --------------------------------------------------------------
    def run_campaign(
        self, spec, wait: bool = True
    ) -> Tuple[Optional[Dict], Optional[str]]:
        """Run (wait=True) or launch (wait=False) a campaign. Returns
        (result-or-status, error)."""
        if self._stop.is_set():
            return None, "daemon stopping"
        try:
            sc = load_scenario(spec, extra_dirs=self.extra_dirs)
        except (ScenarioError, ValueError) as e:
            return None, str(e)
        except Exception as e:  # noqa: BLE001 — bad YAML/JSON must be a clean error
            return None, f"unreadable scenario: {e}"
        budget = sc.duration_estimate() + sc.detect_timeout * max(
            1, len(sc.phases)
        )
        if budget > self.max_campaign_seconds:
            return None, (
                f"scenario needs up to {budget:.0f}s; over the "
                f"{self.max_campaign_seconds:g}s campaign budget "
                "(chaos_max_campaign_seconds)"
            )
        with self._mu:
            if self._running is not None:
                return None, (
                    f"campaign {self._running['scenario']!r} already running"
                )
            self._seq += 1
            cid = self._seq
            status = {
                "id": cid,
                "scenario": sc.name,
                "running": True,
                "started": time.time(),
            }
            self._running = status
        runner = CampaignRunner(
            self.server, sc, plane=self.plane, stop_event=self._stop
        )

        def execute() -> Dict:
            try:
                result = runner.run()
            except Exception as e:  # noqa: BLE001 — the manager must survive any campaign
                logger.exception("chaos campaign %s crashed", sc.name)
                result = {
                    "scenario": sc.name,
                    "passed": False,
                    "error": f"{type(e).__name__}: {e}",
                    "phases": [],
                }
            result["id"] = cid
            with self._mu:
                self._running = None
                self._history.appendleft(result)
            hook = self.on_result
            if hook is not None:
                try:
                    hook(result)
                except Exception:  # noqa: BLE001
                    logger.exception("chaos on_result hook failed")
            return result

        if wait:
            return execute(), None
        scheduler = getattr(self.server, "scheduler", None)
        if scheduler is None or scheduler.submit(
            f"chaos-campaign:{sc.name}", execute,
            hang_timeout=0.0,  # campaigns legitimately outlast the watchdog
        ) is None:
            threading.Thread(
                target=execute, name=f"tpud-chaos-{sc.name}", daemon=True
            ).start()
        return dict(status), None

    # -- views -------------------------------------------------------------
    def campaigns(self, limit: int = 0) -> Dict:
        with self._mu:
            results = list(self._history)
            running = dict(self._running) if self._running else None
        if limit > 0:
            results = results[:limit]
        return {
            "running": running,
            "campaigns": results,
            "count": len(results),
            "scenarios": sorted(self.list_scenarios()),
        }

    def list_scenarios(self) -> Dict[str, str]:
        out = shipped_scenarios()
        import os

        for d in self.extra_dirs:
            if not os.path.isdir(d):
                continue
            for fn in sorted(os.listdir(d)):
                base, ext = os.path.splitext(fn)
                if ext in (".yaml", ".yml", ".json"):
                    out.setdefault(base, os.path.join(d, fn))
        return out

    def close(self) -> None:
        self._stop.set()
