"""Event store: per-component event buckets in one SQLite DB.

Reference: pkg/eventstore/database.go:18-90, pkg/eventstore/types.go:55-70.
Schema columns timestamp/name/type/message/extra_info; retention purge runs
at retention/5 intervals per bucket; buckets expose
insert/find/get/latest/purge.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional

from gpud_tpu.api.v1.types import Event
from gpud_tpu.log import get_logger
from gpud_tpu.metrics.registry import counter
from gpud_tpu.retention import RetentionPurger
from gpud_tpu.sqlite import DB

logger = get_logger(__name__)

_c_purged = counter(
    "tpud_eventstore_purged_total",
    "events deleted by the retention purger, by component",
)


def _row_to_event(component: str, row) -> Event:
    """row = (timestamp, name, type, message, extra_info)."""
    extra = {}
    if len(row) > 4 and row[4]:
        try:
            extra = json.loads(row[4])
        except ValueError:
            extra = {}
    return Event(
        component=component, time=row[0], name=row[1], type=row[2],
        message=row[3], extra_info=extra,
    )


TABLE = "tpud_events_v0_1"  # schema version in table name (reference: database.go:18)

DEFAULT_RETENTION = 14 * 86400  # 14d (reference: pkg/config/default.go:28)

# write-behind contract (tools/storage_lint.py): these methods must route
# through the BatchWriter, never commit per-row via db.execute directly
HOT_WRITE_METHODS = ("_insert",)


class Bucket:
    """Per-component view over the shared events table
    (reference: pkg/eventstore/types.go:59-70)."""

    def __init__(self, store: "EventStore", component: str) -> None:
        self._store = store
        self.component = component

    def name(self) -> str:
        return self.component

    def insert(self, ev: Event) -> None:
        self._store._insert(self.component, ev)

    def find(self, ev: Event) -> Optional[Event]:
        """Find an identical event (same time/name/type/message) — used for
        dedupe before insert (reference: xid/component.go:545-570)."""
        return self._store._find(self.component, ev)

    def get(self, since: float, barrier: bool = True) -> List[Event]:
        """All events at/after ``since``, newest first. ``barrier=False``
        skips the writer flush — for callers that already flushed once
        and fan out over many components (health-timeline correlation)."""
        return self._store._get(self.component, since, barrier=barrier)

    def latest(self) -> Optional[Event]:
        evs = self._store._get(self.component, 0.0, limit=1)
        return evs[0] if evs else None

    def purge(self, before: float) -> int:
        return self._store._purge(self.component, before)

    def close(self) -> None:
        pass


class EventStore:
    """Reference: pkg/eventstore/database.go:71 New().

    One store per daemon; buckets share the table keyed by component name.
    A background purger per bucket runs at retention/5 cadence
    (reference: database.go:85-90) — implemented as one shared
    ``RetentionPurger`` thread (the pattern the health ledger shares) to
    keep thread count flat, stoppable via ``close()``.

    With a ``writer`` (write-behind BatchWriter), inserts append into the
    shared group-commit buffer and every read runs the flush barrier first
    — ``find`` is the kmsg watcher's dedupe-before-insert check, so it must
    see events inserted a moment ago or every fault would double-record.
    """

    def __init__(
        self,
        db: DB,
        retention_seconds: int = DEFAULT_RETENTION,
        writer=None,
    ) -> None:
        self.db = db
        self.writer = writer
        self.retention_seconds = retention_seconds
        # optional post-insert observer (the server wires the session
        # outbox here so every event is journaled for delivery); must
        # never fail the insert path
        self.on_insert = None
        self._buckets: Dict[str, Bucket] = {}
        self._mu = threading.Lock()
        self._purger = RetentionPurger(
            "tpud-eventstore-purger", retention_seconds / 5.0, self._purge_tick
        )
        self.time_now_fn = time.time
        db.execute(
            f"""CREATE TABLE IF NOT EXISTS {TABLE} (
                component TEXT NOT NULL,
                timestamp REAL NOT NULL,
                name TEXT NOT NULL,
                type TEXT NOT NULL,
                message TEXT,
                extra_info TEXT
            )"""
        )
        db.execute(
            f"CREATE INDEX IF NOT EXISTS idx_{TABLE}_comp_ts ON {TABLE} (component, timestamp)"
        )
        # covering index for the cross-component since-scan
        # (latest_events / the bench's 2ms detect loop): without it the
        # (component, timestamp) index is useless for a bare
        # ``timestamp>=?`` predicate and the query table-scans — a cost
        # that grows with retention (14d of events)
        db.execute(
            f"CREATE INDEX IF NOT EXISTS idx_{TABLE}_ts ON {TABLE} (timestamp)"
        )

    def bucket(self, component: str) -> Bucket:
        with self._mu:
            b = self._buckets.get(component)
            if b is None:
                b = Bucket(self, component)
                self._buckets[component] = b
            return b

    def flush(self) -> None:
        """Read-after-write barrier (no-op without a writer)."""
        if self.writer is not None:
            self.writer.flush()

    # -- internal ops ------------------------------------------------------
    def _insert(self, component: str, ev: Event) -> None:
        extra = json.dumps(ev.extra_info, sort_keys=True) if ev.extra_info else ""
        sql = (
            f"INSERT INTO {TABLE} (component, timestamp, name, type, message, extra_info) "
            "VALUES (?, ?, ?, ?, ?, ?)"
        )
        params = (component, ev.time, ev.name, ev.type, ev.message, extra)
        if self.writer is not None:
            self.writer.submit("events", sql, params)
        else:
            self.db.execute(sql, params)
        hook = self.on_insert
        if hook is not None:
            try:
                hook(component, ev)
            except Exception:  # noqa: BLE001
                logger.exception("event on_insert hook failed")

    def _find(self, component: str, ev: Event) -> Optional[Event]:
        self.flush()
        row = self.db.query_one(
            f"SELECT timestamp, name, type, message, extra_info FROM {TABLE} "
            "WHERE component=? AND timestamp=? AND name=? AND type=? AND message=? LIMIT 1",
            (component, ev.time, ev.name, ev.type, ev.message),
        )
        if row is None:
            return None
        return _row_to_event(component, row)

    def _get(self, component: str, since: float, limit: int = 0,
             barrier: bool = True) -> List[Event]:
        if barrier:
            self.flush()
        sql = (
            f"SELECT timestamp, name, type, message, extra_info FROM {TABLE} "
            "WHERE component=? AND timestamp>=? ORDER BY timestamp DESC"
        )
        params: list = [component, since]
        if limit:
            sql += " LIMIT ?"
            params.append(limit)
        rows = self.db.query(sql, params)
        return [_row_to_event(component, r) for r in rows]

    def _purge(self, component: str, before: float,
               barrier: bool = True) -> int:
        if barrier:
            self.flush()
        cur = self.db.execute(
            f"DELETE FROM {TABLE} WHERE component=? AND timestamp<?",
            (component, before),
        )
        return cur.rowcount

    def latest_events(self, since: float) -> Dict[str, List[Event]]:
        self.flush()
        rows = self.db.query(
            f"SELECT component, timestamp, name, type, message, extra_info FROM {TABLE} "
            "WHERE timestamp>=? ORDER BY timestamp DESC",
            (since,),
        )
        out: Dict[str, List[Event]] = {}
        for r in rows:
            out.setdefault(r[0], []).append(_row_to_event(r[0], r[1:]))
        return out

    # -- retention ---------------------------------------------------------
    def start_purger(self, scheduler=None) -> None:
        self._purger.start(scheduler)

    def purge_once(self) -> None:
        """One retention pass now — the daemon's consolidated
        ``retention-purge`` scheduler job calls this instead of running a
        dedicated purger (docs/scheduler.md)."""
        self._purge_tick()

    def _purge_tick(self) -> None:
        """One purge pass, per component so the purge counter attributes
        deletions (reference cadence: database.go:85-90)."""
        self.flush()  # never let a buffered row dodge the purge cutoff
        cutoff = self.time_now_fn() - self.retention_seconds
        comps = [
            r[0]
            for r in self.db.query(
                f"SELECT DISTINCT component FROM {TABLE} WHERE timestamp<?",
                (cutoff,),
            )
        ]
        total = 0
        for comp in comps:
            # barrier=False: the single flush above already fenced every
            # buffered row behind the cutoff — N per-component re-flushes
            # bought nothing (flow_lint flush-audit, PR 19)
            n = self._purge(comp, cutoff, barrier=False)
            if n:
                _c_purged.inc(n, {"component": comp})
                total += n
        if total:
            logger.info("eventstore purged %d events", total)

    def close(self) -> None:
        self._purger.close()
