"""Public-IP → ASN/provider lookup.

Reference: pkg/asn/asn.go:18-24 — queries ip.guide for the ASN owning the
node's public IP, used as the provider-detection fallback when no cloud
IMDS answers (pkg/providers/detect.go). The lookup function is injectable
and failures degrade to "unknown" — zero-egress environments simply skip.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Callable, Optional

from gpud_tpu.log import get_logger

logger = get_logger(__name__)

LOOKUP_URL = "https://ip.guide/{ip}"
# ip.guide with no path resolves the caller's own IP — usable even when
# the node can't discover its public IP via any cloud metadata service
LOOKUP_URL_SELF = "https://ip.guide/"
TIMEOUT = 5.0

# ASN org substrings → canonical provider names
_ORG_PROVIDERS = {
    "google": "gcp",
    "amazon": "aws",
    "aws": "aws",
    "microsoft": "azure",
    "oracle": "oci",
    "nebius": "nebius",
}


@dataclass
class ASNInfo:
    asn: int = 0
    org: str = ""
    provider: str = ""


def _default_fetch(url: str) -> Optional[dict]:
    import urllib.request

    with urllib.request.urlopen(url, timeout=TIMEOUT) as resp:
        return json.loads(resp.read().decode())


def lookup(ip: str = "", fetch_fn: Callable[[str], Optional[dict]] = _default_fetch) -> Optional[ASNInfo]:
    """Returns None when the lookup fails (offline, bad IP). Empty ``ip``
    asks ip.guide about the caller's own address."""
    try:
        d = fetch_fn(LOOKUP_URL.format(ip=ip) if ip else LOOKUP_URL_SELF)
    except Exception as e:  # noqa: BLE001
        logger.debug("asn lookup failed: %s", e)
        return None
    if not d:
        return None
    # "network": null appears for unrouted/bogon IPs — `or {}` both layers
    asn_obj = (d.get("network") or {}).get("autonomous_system") or d.get(
        "autonomous_system"
    ) or {}
    org = str(asn_obj.get("organization", "") or asn_obj.get("name", ""))
    info = ASNInfo(asn=int(asn_obj.get("asn", 0) or 0), org=org)
    lower = org.lower()
    for needle, provider in _ORG_PROVIDERS.items():
        if needle in lower:
            info.provider = provider
            break
    return info
