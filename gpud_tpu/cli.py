"""tpud command-line interface.

Reference: cmd/gpud/command/command.go:51-913 — subcommands up/down/run/
scan/status/compact/inject-fault/set-healthy/metadata/update/release/... .
This module grows with the build; each subcommand cites its reference
analog.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import time
from typing import List, Optional

from gpud_tpu import config as cfgmod
from gpud_tpu.api.v1.types import HealthStateType
from gpud_tpu.log import AuditLogger, set_audit_logger, setup as log_setup
from gpud_tpu.version import __version__


def _add_common_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--data-dir", default="", help="state directory (default /var/lib/tpud or ~/.tpud)")
    p.add_argument("--log-level", default="info")
    p.add_argument("--kmsg-path", default="", help="override /dev/kmsg (or env TPUD_KMSG_FILE_PATH)")


def _build_config(args) -> "cfgmod.Config":
    cfg = cfgmod.default_config()
    if getattr(args, "data_dir", ""):
        cfg.data_dir = args.data_dir
    if getattr(args, "kmsg_path", ""):
        cfg.kmsg_path = args.kmsg_path
    if getattr(args, "port", None):
        cfg.port = args.port
    if getattr(args, "db_in_memory", False):
        cfg.db_in_memory = True
    if getattr(args, "no_tls", False):
        cfg.tls = False
    if getattr(args, "accelerator_type", ""):
        cfg.accelerator_type_override = args.accelerator_type
    if getattr(args, "expected_chip_count", 0):
        cfg.expected_chip_count = args.expected_chip_count
    if getattr(args, "plugin_specs", ""):
        cfg.plugin_specs_file = args.plugin_specs
    if getattr(args, "endpoint", ""):
        cfg.endpoint = args.endpoint
    if getattr(args, "token", ""):
        cfg.token = args.token
    if getattr(args, "disable_components", ""):
        cfg.components_disabled = [
            c.strip() for c in args.disable_components.split(",") if c.strip()
        ]
    if getattr(args, "pprof", False):
        cfg.pprof = True
    cfg.log_level = getattr(args, "log_level", "info")
    return cfg


def cmd_scan(args) -> int:
    """Reference: cmd/gpud scan → pkg/scan/scan.go:33."""
    import io
    import json as _json
    import os

    from gpud_tpu.scan import scan

    if args.kmsg_path:
        # scan-mode components resolve the kmsg path via the env override
        os.environ["TPUD_KMSG_FILE_PATH"] = args.kmsg_path
    as_json = getattr(args, "as_json", False)
    sink = io.StringIO() if as_json else sys.stdout
    # scan itself stays stateless, but when a daemon has run here before,
    # its persisted ledger adds the rolling-availability column for free
    availability = {}
    cfg = _build_config(args)
    if not cfg.db_in_memory and os.path.isfile(cfg.state_file()):
        try:
            from gpud_tpu.health_history import HealthLedger
            from gpud_tpu.sqlite import DB

            availability = HealthLedger(DB(cfg.state_file())).availability_all()
        except Exception:  # noqa: BLE001 — a corrupt DB must not block scan
            availability = {}
    results = scan(
        accelerator_type=args.accelerator_type, out=sink,
        availability=availability,
    )
    if as_json:
        rows = []
        for r in results:
            row = {
                "component": r.component_name(),
                "health": r.health_state_type(),
                "reason": r.summary(),
                "extra_info": dict(r.extra_info),
            }
            # optional key: present only when a prior daemon run left a ledger
            av = availability.get(r.component_name())
            if av is not None:
                row["availability"] = av
            rows.append(row)
        print(_json.dumps(rows, indent=2))
    unhealthy = [
        r for r in results if r.health_state_type() != HealthStateType.HEALTHY
    ]
    return 1 if unhealthy and args.strict else 0


def cmd_fleet_scan(args) -> int:
    """Fleet-wide ICI history sweep on the accelerator — the pod-scale
    companion to the per-host ici component (gpud_tpu/fleet_scan.py)."""
    import json as _json

    from gpud_tpu.fleet_scan import fleet_scan

    res = fleet_scan(
        args.dbs,
        window_seconds=args.window,
        flap_threshold=args.flap_threshold,
        crc_threshold=args.crc_threshold,
    )
    if args.as_json:
        print(_json.dumps(res, indent=2, sort_keys=True))
    else:
        s = res["summary"]
        print(
            f"{len(res['links'])} links across {len(args.dbs)} host DB(s) "
            f"on {res['devices']} device(s): "
            f"{s['healthy']} healthy, {s['degraded']} degraded, "
            f"{s['unhealthy']} unhealthy"
        )
        for name, label in sorted(res["links"].items()):
            if label != "healthy":
                print(f"  {label:9s}  {name}")
    return 1 if res["summary"]["unhealthy"] else 0


def cmd_run(args) -> int:
    """Reference: cmd/gpud run → pkg/server.New (SURVEY §3.1)."""
    cfg = _build_config(args)
    log_setup(cfg.log_level, cfg.log_file)
    # main() already wired the default data-dir audit logger; only an
    # explicit audit_log_file config overrides it here
    if cfg.audit_log_file:
        set_audit_logger(AuditLogger(cfg.audit_log_file))

    from gpud_tpu.server.server import Server

    # handlers installed BEFORE boot: a SIGTERM during the (multi-second)
    # start sequence must still run the clean shutdown path
    stop = {"flag": False}

    def _sig(_s, _f):
        stop["flag"] = True

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)

    srv = Server(config=cfg)
    srv.start()
    print(f"tpud {__version__} listening on {srv.base_url()}", flush=True)
    try:
        while not stop["flag"]:
            time.sleep(0.5)
    finally:
        srv.stop()
    return 0


def cmd_inject_fault(args) -> int:
    """Reference: cmd/gpud inject-fault → pkg/fault-injector."""
    from gpud_tpu.fault_injector import Injector, Request

    req = Request(
        tpu_error_name=args.name or "",
        chip_id=args.chip_id,
        detail=args.detail or "",
        kernel_message=args.kernel_message or "",
        repeat=getattr(args, "repeat", 1),
        interval_seconds=getattr(args, "interval_seconds", 0.0),
    )
    inj = Injector(kmsg_path=args.kmsg_path)
    res = inj.inject(req)
    if not res.ok:
        print(f"error: {res.error}", file=sys.stderr)
        return 1
    print(f"fault injected ({res.writes} write(s)): {res.line or res.entry}")
    return 0


def _client(args):
    from gpud_tpu.client.v1 import Client

    scheme = "http" if getattr(args, "no_tls", False) else "https"
    return Client(base_url=f"{scheme}://localhost:{args.port}")


def cmd_status(args) -> int:
    """Reference: cmd/gpud status — queries the running daemon."""
    try:
        c = _client(args)
        hz = c.healthz()
        states = c.get_health_states()
    except Exception as e:  # noqa: BLE001
        print(f"tpud unreachable on port {args.port}: {e}", file=sys.stderr)
        return 1
    bad = sum(
        1
        for comp in states
        for st in comp.states
        if st.health != HealthStateType.HEALTHY
    )
    if getattr(args, "as_json", False):
        import json as _json

        print(_json.dumps({
            "version": hz.get("version", ""),
            "unhealthy": bad,
            "components": [
                {"component": comp.component, "health": st.health,
                 "reason": st.reason}
                for comp in states for st in comp.states
            ],
        }, indent=2))
        return 1 if bad else 0
    print(f"tpud {hz.get('version', '?')} healthy")
    for comp in states:
        for st in comp.states:
            glyph = "✔" if st.health == HealthStateType.HEALTHY else "✘"
            print(f"  {glyph} {comp.component}: {st.health} {st.reason}")
    return 1 if bad else 0


def cmd_compact(args) -> int:
    """Reference: cmd/gpud compact (command.go:629) — offline VACUUM."""
    from gpud_tpu.sqlite import DB

    cfg = _build_config(args)
    db = DB(cfg.state_file())
    secs = db.compact()
    print(f"compacted {cfg.state_file()} in {secs:.2f}s "
          f"({db.size_bytes()} bytes)")
    return 0


def cmd_set_healthy(args) -> int:
    from gpud_tpu.log import audit

    audit("cli_set_healthy", component=args.component)
    try:
        c = _client(args)
        c.set_healthy(args.component)
    except Exception as e:  # noqa: BLE001
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(f"set-healthy: {args.component}")
    return 0


def cmd_metadata(args) -> int:
    """Reference: cmd/gpud metadata — dump the metadata table."""
    from gpud_tpu.metadata import Metadata
    from gpud_tpu.sqlite import DB

    cfg = _build_config(args)
    md = Metadata(DB(cfg.state_file()))
    print(json.dumps(md.all(), indent=2, sort_keys=True))
    return 0


def cmd_history(args) -> int:
    """Health-transition timeline from the persisted ledger. Reads the
    state DB directly (WAL mode), so it works whether or not the daemon is
    up — the offline analog of ``GET /v1/states/history``."""
    import os
    import time as _time
    from datetime import datetime

    from gpud_tpu.health_history import HealthLedger
    from gpud_tpu.sqlite import DB

    cfg = _build_config(args)
    path = cfg.state_file()
    if not os.path.isfile(path):
        print(f"no state DB at {path} (has the daemon ever run?)",
              file=sys.stderr)
        return 1
    ledger = HealthLedger(DB(path))
    since = _time.time() - args.since_hours * 3600.0
    component = args.component or None
    transitions = ledger.history(
        component=component, since=since, limit=args.limit
    )
    availability = ledger.availability_all()
    if getattr(args, "as_json", False):
        print(json.dumps(
            {"transitions": transitions, "availability": availability},
            indent=2, sort_keys=True,
        ))
        return 0
    if not transitions:
        print(f"no transitions in the last {args.since_hours:g}h")
    else:
        comp_w = max(len(t["component"]) for t in transitions)
        for t in transitions:
            when = datetime.fromtimestamp(t["time"]).strftime("%Y-%m-%d %H:%M:%S")
            line = (f"  {when}  {t['component']:<{comp_w}}  "
                    f"{t['from']} → {t['to']}")
            if t["reason"]:
                line += f"  ({t['reason']})"
            print(line)
    rows = sorted(availability.items())
    if component:
        rows = [(c, av) for c, av in rows if c == component]
    if rows:
        print()
        comp_w = max(len(c) for c, _ in rows)
        for c, av in rows:
            flap = "  FLAPPING" if ledger.is_flapping(c) else ""
            print(f"  {c:<{comp_w}}  {av['state']:<11}  "
                  f"availability {av['ratio'] * 100:6.2f}% "
                  f"over {av['window_seconds'] / 3600:g}h{flap}")
    return 0


def cmd_remediation(args) -> int:
    """Remediation audit ledger: what was diagnosed, what the policy
    decided, what ran. Reads the state DB directly (WAL mode), daemon up
    or not — the offline analog of ``GET /v1/remediation/audit``."""
    import os
    import time as _time
    from datetime import datetime

    from gpud_tpu.remediation.audit import AuditStore
    from gpud_tpu.sqlite import DB

    cfg = _build_config(args)
    path = cfg.state_file()
    if not os.path.isfile(path):
        print(f"no state DB at {path} (has the daemon ever run?)",
              file=sys.stderr)
        return 1
    store = AuditStore(DB(path))
    since = _time.time() - args.since_hours * 3600.0
    attempts = store.read(
        component=args.component or None,
        action=args.action or None,
        outcome=args.outcome or None,
        since=since,
        limit=args.limit,
    )
    summary = store.summary()
    if getattr(args, "as_json", False):
        print(json.dumps(
            {"attempts": attempts, "summary": summary},
            indent=2, sort_keys=True,
        ))
        return 0
    if not attempts:
        print(f"no remediation attempts in the last {args.since_hours:g}h")
    else:
        comp_w = max(len(a["component"]) for a in attempts)
        act_w = max(len(a["action"]) for a in attempts)
        for a in attempts:
            when = datetime.fromtimestamp(a["time"]).strftime(
                "%Y-%m-%d %H:%M:%S"
            )
            line = (f"  {when}  {a['component']:<{comp_w}}  "
                    f"{a['action']:<{act_w}}  {a['outcome']}")
            if a["detail"]:
                line += f"  ({a['detail']})"
            print(line)
    if summary["by_outcome"]:
        print()
        parts = ", ".join(
            f"{k}={v}" for k, v in sorted(summary["by_outcome"].items())
        )
        print(f"  total {summary['attempts_total']}  ({parts})")
    return 0


def cmd_chaos(args) -> int:
    """Drive the running daemon's chaos campaign runner (docs/chaos.md):
    ``chaos list`` shows scenarios + past results, ``chaos run`` executes
    one and exits nonzero unless every expectation passed."""
    from gpud_tpu.client.v1 import Client, ClientError

    scheme = "http" if getattr(args, "no_tls", False) else "https"
    # a waited campaign holds the HTTP request for its whole duration
    c = Client(
        base_url=f"{scheme}://localhost:{args.port}",
        timeout=float(args.timeout),
    )
    try:
        if args.chaos_cmd == "list":
            out = c.get_chaos_campaigns(limit=args.limit)
            if getattr(args, "as_json", False):
                print(json.dumps(out, indent=2, sort_keys=True))
                return 0
            print("scenarios:")
            for name in out.get("scenarios", []):
                print(f"  {name}")
            running = out.get("running")
            if running:
                print(f"running: {running['scenario']} (id {running['id']})")
            for res in out.get("campaigns", []):
                verdict = "PASS" if res.get("passed") else "FAIL"
                print(
                    f"  #{res.get('id', '?')} {res.get('scenario', '?')}: "
                    f"{verdict} ({len(res.get('phases', []))} phase(s), "
                    f"{res.get('duration_seconds', 0):g}s)"
                )
            return 0
        out = c.run_chaos(args.scenario, wait=not args.no_wait)
    except ClientError as e:
        print(f"error: {e.body[:500]}", file=sys.stderr)
        return 1
    except Exception as e:  # noqa: BLE001
        print(f"tpud unreachable on port {args.port}: {e}", file=sys.stderr)
        return 1
    if getattr(args, "as_json", False):
        print(json.dumps(out, indent=2, sort_keys=True))
    elif args.no_wait:
        print(f"campaign {out.get('scenario', '?')} launched (id {out.get('id', '?')})")
    else:
        for ph in out.get("phases", []):
            mark = "✔" if ph.get("passed") else "✘"
            print(f"{mark} phase {ph['name']}")
            for exp in ph.get("expectations", []):
                emark = "✔" if exp.get("ok") else "✘"
                print(f"    {emark} [{exp['kind']}] {exp.get('detail', '')}")
            for err in ph.get("step_errors", []):
                print(f"    ✘ step error: {err}")
        verdict = "PASS" if out.get("passed") else "FAIL"
        print(
            f"{verdict}: {out.get('scenario', '?')} "
            f"({out.get('duration_seconds', 0):g}s)"
        )
        if out.get("error"):
            print(f"campaign error: {out['error']}", file=sys.stderr)
    if args.no_wait:
        return 0
    return 0 if out.get("passed") else 1


def cmd_session(args) -> int:
    """Show the daemon's control-plane session health: connection state,
    circuit breaker, and the store-and-forward outbox backlog."""
    from gpud_tpu.client.v1 import Client, ClientError

    scheme = "http" if getattr(args, "no_tls", False) else "https"
    c = Client(
        base_url=f"{scheme}://localhost:{args.port}",
        timeout=float(args.timeout),
    )
    try:
        out = c.get_session_status()
    except ClientError as e:
        print(f"error: {e.body[:500]}", file=sys.stderr)
        return 1
    except Exception as e:  # noqa: BLE001
        print(f"tpud unreachable on port {args.port}: {e}", file=sys.stderr)
        return 1
    if getattr(args, "as_json", False):
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0
    if not out.get("configured"):
        print("session: not configured (no control-plane endpoint/token)")
        return 0
    sess = out.get("session") or {}
    state = "connected" if sess.get("connected") else "disconnected"
    if sess.get("auth_failed"):
        state += " (auth failed; replay parked until token rotation)"
    print(f"session: {state}  endpoint={sess.get('endpoint', '?')}")
    if sess.get("last_connect_error"):
        print(f"  last connect error: {sess['last_connect_error']}")
    circuit = out.get("circuit") or {}
    if circuit:
        print(
            f"circuit: {circuit.get('state', '?')}  "
            f"failures={circuit.get('consecutive_failures', 0)}/"
            f"{circuit.get('failure_threshold', '?')}  "
            f"blocked_attempts={circuit.get('blocked_attempts', 0)}"
        )
    outbox = out.get("outbox") or {}
    if outbox:
        print(
            f"outbox: backlog={outbox.get('backlog', 0)}  "
            f"acked_seq={outbox.get('acked_seq', 0)}/"
            f"{outbox.get('last_seq', 0)}  "
            f"dropped(journal_full={outbox.get('dropped_journal_full', 0)}, "
            f"retention={outbox.get('dropped_retention', 0)})"
        )
    print(f"degraded: {str(bool(out.get('degraded'))).lower()}")
    return 0


def cmd_predict(args) -> int:
    """Show the predict engine's precursor scores: fused score, feature
    breakdown, armed/warned state, and measured lead times."""
    from gpud_tpu.client.v1 import Client, ClientError

    scheme = "http" if getattr(args, "no_tls", False) else "https"
    c = Client(
        base_url=f"{scheme}://localhost:{args.port}",
        timeout=float(args.timeout),
    )
    try:
        if getattr(args, "calibration", False):
            out = c.get_predict_calibration(
                refit=getattr(args, "refit", False)
            )
            print(json.dumps(out, indent=2, sort_keys=True))
            return 0
        out = c.get_predict_scores(
            component=args.component, history=args.history or None
        )
    except ClientError as e:
        print(f"error: {e.body[:500]}", file=sys.stderr)
        return 1
    except Exception as e:  # noqa: BLE001
        print(f"tpud unreachable on port {args.port}: {e}", file=sys.stderr)
        return 1
    if getattr(args, "as_json", False):
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0
    status = out.get("status") or {}
    print(
        f"predict: threshold={status.get('threshold', '?')}  "
        f"hysteresis={status.get('hysteresis', '?')}  "
        f"ticks={status.get('ticks', 0)}  "
        f"warnings={status.get('warnings_total', 0)}"
    )
    comps = out.get("components") or {}
    if not comps:
        print("no components scored yet")
        return 0
    for name, d in sorted(comps.items()):
        mark = " ARMED" if d.get("armed") else ""
        lead = d.get("lead_seconds")
        lead_s = f"  lead={lead:.1f}s" if lead is not None else ""
        feats = d.get("features") or {}
        feat_s = " ".join(f"{k}={v:g}" for k, v in sorted(feats.items()))
        print(
            f"  {name}: score={d.get('score', 0):.3f}"
            f"{mark}{lead_s}  [{feat_s}]"
        )
    return 0


def cmd_fabric(args) -> int:
    """Show the fabric plane's mesh-wide per-link health matrix:
    discovered mesh shape, sweep status, and each logical link's state,
    latency, and EWMA deviation (docs/fabric.md)."""
    from gpud_tpu.client.v1 import Client, ClientError

    scheme = "http" if getattr(args, "no_tls", False) else "https"
    c = Client(
        base_url=f"{scheme}://localhost:{args.port}",
        timeout=float(args.timeout),
    )
    try:
        out = c.get_fabric(
            link=args.link,
            since=args.since or None,
            limit=args.limit or None,
        )
    except ClientError as e:
        print(f"error: {e.body[:500]}", file=sys.stderr)
        return 1
    except Exception as e:  # noqa: BLE001
        print(f"tpud unreachable on port {args.port}: {e}", file=sys.stderr)
        return 1
    if getattr(args, "as_json", False):
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0
    status = out.get("status") or {}
    mesh = status.get("mesh") or {}
    shape = "x".join(str(d) for d in (mesh.get("shape") or [])) or "?"
    print(
        f"fabric: mesh={shape} ({mesh.get('source', 'unknown')})  "
        f"links={status.get('links', 0)}  "
        f"sweeps={status.get('sweeps', 0)}  "
        f"degraded={len(status.get('degraded') or [])}  "
        f"down={len(status.get('down') or [])}"
    )
    matrix = out.get("matrix") or []
    if not matrix:
        print("no links observed (degraded 1x1 mesh or no sweep yet)")
        return 0
    for row in matrix:
        state = row.get("state") or "unswept"
        print(
            f"  {row.get('link')}: {state}"
            f"  latency={row.get('latency_seconds', 0):.6f}s"
            f"  deviation={row.get('deviation', 0):.2f}"
        )
    for row in out.get("history") or []:
        print(
            f"  [history] {row.get('ts', 0):.3f} {row.get('link')}: "
            f"{row.get('state')} latency={row.get('latency_seconds', 0):.6f}s"
        )
    return 0


def cmd_machine_info(args) -> int:
    from gpud_tpu.machine_info import get_machine_info
    from gpud_tpu.tpu.instance import new_instance

    mi = get_machine_info(tpu=new_instance(accelerator_type=args.accelerator_type))
    print(json.dumps(mi.to_dict(), indent=2, sort_keys=True))
    return 0


def cmd_up(args) -> int:
    """Install + enroll (reference: cmd/gpud/up/command.go:25, SURVEY §3.5):
    optional login, systemd unit install, token hand-off via FIFO."""
    import os

    cfg = _build_config(args)
    if args.token and args.endpoint:
        from gpud_tpu.login import login as do_login
        from gpud_tpu.metadata import Metadata
        from gpud_tpu.sqlite import DB
        from gpud_tpu.tpu.instance import new_instance
        from gpud_tpu.providers.detect import detect

        prov = detect(timeout=3.0)
        md = Metadata(DB(cfg.state_file()))
        try:
            do_login(
                args.endpoint, args.token, md,
                tpu_instance=new_instance(),
                provider=prov.provider, region=prov.region,
            )
        except Exception as e:  # noqa: BLE001
            print(f"login failed: {e}", file=sys.stderr)
            return 1
        print("login ok")
    if args.no_systemd:
        print("skipping systemd install (--no-systemd)")
        return 0
    if os.geteuid() != 0:
        print("error: tpud up requires root for systemd install "
              "(use --no-systemd to skip)", file=sys.stderr)
        return 1
    from gpud_tpu.manager.systemd import install_unit
    from gpud_tpu.server.server import Server

    flags = []
    if cfg.data_dir:
        flags.append(f"--data-dir {cfg.data_dir}")
    err = install_unit(flags=" ".join(flags))
    if err:
        print(f"error: {err}", file=sys.stderr)
        return 1
    # hand a fresh token to the (possibly already-running) daemon; the
    # daemon creates the FIFO at boot, so retry briefly
    if args.token:
        import time as _time

        err = "daemon fifo not ready"
        for _ in range(10):
            err = Server.write_token(args.token, cfg.fifo_file())
            if err is None:
                break
            _time.sleep(1.0)
        if err is not None:
            print(f"warning: token hand-off failed: {err} — "
                  "run `tpud up --token ... --endpoint ...` to enroll",
                  file=sys.stderr)
            return 1
    print("tpud installed and started (systemd)")
    return 0


def cmd_down(args) -> int:
    """Reference: cmd/gpud down — stop + disable the unit."""
    from gpud_tpu.manager.systemd import uninstall_unit

    err = uninstall_unit()
    if err:
        print(f"warning: {err}", file=sys.stderr)
    print("tpud stopped")
    return 0


def cmd_list_plugins(args) -> int:
    """Reference: cmd/gpud list-plugins."""
    import os

    from gpud_tpu.plugins.spec import load_specs

    cfg = _build_config(args)
    path = cfg.resolved_plugin_specs_file()
    if not os.path.isfile(path):
        print(f"no plugin specs at {path}")
        return 0
    try:
        specs = load_specs(path)
    except Exception as e:  # noqa: BLE001
        print(f"INVALID specs file {path}: {e}", file=sys.stderr)
        return 1
    for s in specs:
        print(f"{s.name}\t{s.plugin_type}\t{s.run_mode}\t"
              f"every {s.interval_seconds:.0f}s\t{len(s.steps)} step(s)")
    return 0


def cmd_release(args) -> int:
    """Reference: cmd/gpud release subcommands (command.go:446-570)."""
    from gpud_tpu.release import distsign

    sub = args.release_cmd
    if sub == "gen-root-key":
        priv, pub = distsign.write_keypair(args.dir, "root")
        print(f"root key: {priv}\nroot pub: {pub}")
    elif sub == "gen-signing-key":
        priv, pub = distsign.write_keypair(args.dir, "signing")
        print(f"signing key: {priv}\nsigning pub: {pub}")
    elif sub == "sign-key":
        out = distsign.sign_key(args.root_key, args.signing_pub)
        print(f"key endorsement: {out}")
    elif sub == "sign-package":
        out = distsign.sign_package(args.signing_key, args.package)
        print(f"package signature: {out}")
    elif sub == "verify-package":
        err = distsign.verify_package(
            args.signing_pub, args.package,
            sig_path=args.sig or "",
            root_pub_path=args.root_pub or "",
            key_sig_path=args.key_sig or "",
        )
        if err:
            print(f"FAIL: {err}", file=sys.stderr)
            return 1
        print("OK: signature valid")
    return 0


def cmd_update(args) -> int:
    """Reference: cmd/gpud update(+check) — set/inspect the target-version
    file the watcher acts on, or (``--install``) run the built-in
    download→verify→install pipeline synchronously (update.go:19-50)."""
    from gpud_tpu.update import read_target_version, write_target_version

    cfg = _build_config(args)
    path = cfg.target_version_file()
    if args.check:
        target = read_target_version(path)
        print(f"running: {__version__}\ntarget:  {target or '(none)'}")
        return 0
    if not args.target_version:
        print("error: --target-version required (or --check)", file=sys.stderr)
        return 1
    if args.install:
        from gpud_tpu.update_install import perform_update

        err = perform_update(
            args.target_version,
            base_url=args.base_url,
            install_dir=args.install_dir,
            signing_pub=args.signing_pub,
            root_pub=args.root_pub,
        )
        if err:
            print(f"FAIL: {err}", file=sys.stderr)
            return 1
        print(f"installed {args.target_version}")
        return 0
    write_target_version(path, args.target_version)
    print(f"target version set to {args.target_version}; "
          "the running daemon restarts within 30s")
    return 0


def cmd_custom_plugins(args) -> int:
    """Reference: cmd/gpud custom-plugins — validate a specs file."""
    from gpud_tpu.plugins.spec import load_specs

    try:
        specs = load_specs(args.file)
    except Exception as e:  # noqa: BLE001 — any parse failure is "invalid"
        print(f"INVALID: {e}", file=sys.stderr)
        return 1
    print(f"OK: {len(specs)} valid plugin spec(s)")
    for s in specs:
        print(f"  {s.name} ({s.plugin_type}, {s.run_mode})")
    return 0


def cmd_run_plugin_group(args) -> int:
    """Reference: cmd/gpud run-plugin-group — run all plugins with a tag
    once and print results."""
    from gpud_tpu.components.base import TpudInstance
    from gpud_tpu.plugins.component import build_components
    from gpud_tpu.plugins.spec import load_specs

    specs = load_specs(args.file)
    comps = build_components(TpudInstance(), specs)
    if args.tag:
        comps = [c for c in comps if args.tag in c.tags()]
    bad = 0
    for c in comps:
        cr = c.check()
        glyph = "✔" if cr.health_state_type() == HealthStateType.HEALTHY else "✘"
        if cr.health_state_type() != HealthStateType.HEALTHY:
            bad += 1
        print(f"{glyph} {c.name()}: {cr.summary()}")
    return 1 if bad else 0


def cmd_notify(args) -> int:
    """Reference: cmd/gpud notify startup/shutdown — record a lifecycle
    event in the os bucket so the control plane sees planned transitions."""
    from gpud_tpu.api.v1.types import Event, EventType
    from gpud_tpu.eventstore import EventStore
    from gpud_tpu.sqlite import DB

    cfg = _build_config(args)
    es = EventStore(DB(cfg.state_file()))
    es.bucket("os").insert(
        Event(
            component="os",
            name=f"daemon_{args.phase}",
            type=EventType.INFO,
            message=f"tpud {args.phase} notification",
        )
    )
    print(f"recorded {args.phase} notification")
    return 0


def cmd_manager(args) -> int:
    """Standalone dev control plane (manager/control_plane.py): serve a
    manager process, or drive one over its operator API."""
    import json as _json

    if args.manager_cmd == "serve":
        import signal
        import threading

        from gpud_tpu.manager.control_plane import ControlPlane

        cp = ControlPlane(
            port=args.port,
            grpc_port=args.grpc_port,
            session_token=args.session_token or None,
            admin_token=args.admin_token or None,
            instance_id=args.peer_id or None,
            data_dir=args.data_dir or None,
            shards=args.shards or None,
        )
        # handlers go in before the endpoint line: the printed JSON is the
        # readiness contract, and a supervisor may SIGTERM immediately after
        # reading it — the default disposition in that window would kill us
        # with a nonzero status
        stop = threading.Event()
        for sig in (signal.SIGINT, signal.SIGTERM):
            signal.signal(sig, lambda *_: stop.set())
        cp.start()
        if args.peers:
            # HA tier (docs/fleet.md "Federation & failover"): join the
            # peer set. The knob defaults mirror Config.federation_* —
            # the daemon-side dataclass is the documented reference for
            # these, even though the manager is configured by flags
            from gpud_tpu.config import Config as _Cfg

            defaults = _Cfg()
            cp.attach_peers(
                args.peer_id or cp.instance_id,
                list(args.peers),
                replication_interval=(
                    args.replication_interval
                    if args.replication_interval > 0
                    else defaults.federation_replication_interval_seconds
                ),
                probe_interval=(
                    args.probe_interval if args.probe_interval > 0
                    else defaults.federation_probe_interval_seconds
                ),
                fanout_timeout=(
                    args.fanout_timeout if args.fanout_timeout > 0
                    else defaults.federation_fanout_timeout_seconds
                ),
                dead_after_probes=(
                    args.dead_after_probes if args.dead_after_probes > 0
                    else defaults.federation_dead_after_probes
                ),
                auto_adopt=(
                    defaults.federation_auto_adopt
                    and not args.no_auto_adopt
                ),
            )
        print(
            _json.dumps(
                {
                    "endpoint": cp.endpoint,
                    "grpc_port": cp.grpc_port,
                    "instance_id": cp.instance_id,
                }
            ),
            flush=True,
        )
        stop.wait()
        cp.stop()
        return 0

    # operator subcommands speak the manager's HTTP API
    import requests

    try:
        return _manager_operator_cmd(args, requests, _json)
    except Exception as e:  # noqa: BLE001 - CLI boundary: no tracebacks
        print(f"error: {e}", file=sys.stderr)
        return 1


def cmd_fleet(args) -> int:
    """Fleet observability against a manager's operator API: rollup
    aggregates, paginated per-agent views, journaled history, and
    correlation-id trace stitching (docs/fleet.md)."""
    import json as _json

    import requests

    headers = {}
    if args.admin_token:
        headers["Authorization"] = f"Bearer {args.admin_token}"
    base = args.endpoint.rstrip("/")

    def get(path: str, params=None) -> Optional[dict]:
        r = requests.get(
            f"{base}{path}", headers=headers, params=params, timeout=30
        )
        if r.status_code != 200:
            print(f"error {r.status_code}: {r.text}", file=sys.stderr)
            return None
        return r.json()

    try:
        if args.fleet_cmd == "rollup":
            data = get("/v1/fleet/rollup")
        elif args.fleet_cmd == "fabric":
            params = {}
            if args.since:
                params["since"] = args.since
            data = get("/v1/fleet/fabric", params=params or None)
        elif args.fleet_cmd == "predict":
            data = get("/v1/fleet/predict", params={"top": args.top})
        elif args.fleet_cmd == "agents":
            data = get(
                "/v1/fleet/agents",
                params={"offset": args.offset, "limit": args.limit},
            )
            if data is not None and args.peer:
                # cohort placement view: keep only rows the named peer
                # owns. Rows carry "peer" on federated managers; on a
                # standalone manager the filter matches nothing
                rows = [
                    a for a in data.get("agents", [])
                    if a.get("peer", "") == args.peer
                ]
                data["agents"] = rows
                data["peer_filter"] = args.peer
                data["filtered"] = len(rows)
        elif args.fleet_cmd == "history":
            params = {"limit": args.limit, "offset": args.offset}
            if args.since:
                params["since"] = args.since
            data = get(
                f"/v1/fleet/agents/{args.machine_id}/history", params=params
            )
        elif args.fleet_cmd == "traces":
            data = get(
                "/v1/fleet/traces",
                params={"correlation_id": args.correlation_id},
            )
        elif args.fleet_cmd == "peers":
            data = get("/v1/fleet/peers")
        else:
            return 2
    except Exception as e:  # noqa: BLE001 - CLI boundary: no tracebacks
        print(f"error: {e}", file=sys.stderr)
        return 1
    if data is None:
        return 1
    print(_json.dumps(data, indent=2))
    return 0


def _manager_operator_cmd(args, requests, _json) -> int:
    headers = {}
    if args.admin_token:
        headers["Authorization"] = f"Bearer {args.admin_token}"
    base = args.endpoint.rstrip("/")
    if args.manager_cmd == "machines":
        r = requests.get(f"{base}/v1/machines", headers=headers, timeout=10)
        if r.status_code != 200:
            print(f"error {r.status_code}: {r.text}", file=sys.stderr)
            return 1
        print(_json.dumps(r.json(), indent=2))
        return 0
    if args.manager_cmd == "request":
        body = {}
        if args.params:
            params = _json.loads(args.params)
            if not isinstance(params, dict):
                print("--params must be a JSON object", file=sys.stderr)
                return 2
            body.update(params)
        # the positional method always wins over a "method" key smuggled
        # into --params
        body["method"] = args.method
        r = requests.post(
            f"{base}/v1/machines/{args.machine_id}/request",
            json=body,
            headers=headers,
            params={"timeout": str(args.timeout)},
            timeout=args.timeout + 10,
        )
        if r.status_code != 200:
            print(f"error {r.status_code}: {r.text}", file=sys.stderr)
            return 1
        print(_json.dumps(r.json(), indent=2))
        return 0
    return 2


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpud", description="TPU fleet-health monitoring daemon"
    )
    p.add_argument("--version", action="version", version=f"tpud {__version__}")
    sub = p.add_subparsers(dest="cmd", required=True)

    pu = sub.add_parser("up", help="install as systemd service + enroll")
    _add_common_flags(pu)
    pu.add_argument("--token", default="", help="control-plane join token")
    pu.add_argument("--endpoint", default="", help="control-plane endpoint URL")
    pu.add_argument("--no-systemd", action="store_true")
    pu.set_defaults(fn=cmd_up, audited=True)

    pd = sub.add_parser("down", help="stop and disable the systemd service")
    _add_common_flags(pd)
    pd.set_defaults(fn=cmd_down, audited=True)

    plp = sub.add_parser("list-plugins", help="list configured plugin specs")
    _add_common_flags(plp)
    plp.set_defaults(fn=cmd_list_plugins)

    ps = sub.add_parser("scan", help="one-shot health scan (no daemon)")
    _add_common_flags(ps)
    ps.add_argument("--accelerator-type", default="")
    ps.add_argument("--strict", action="store_true", help="exit 1 on any unhealthy check")
    ps.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable results instead of the table")
    ps.set_defaults(fn=cmd_scan)

    pfs = sub.add_parser(
        "fleet-scan",
        help="accelerated sweep over many hosts' ICI history DBs",
    )
    pfs.add_argument("dbs", nargs="+", help="per-host tpud state DB files")
    pfs.add_argument("--window", type=float, default=3600.0,
                     help="scan window in seconds")
    pfs.add_argument("--flap-threshold", type=int, default=3)
    pfs.add_argument("--crc-threshold", type=int, default=100)
    pfs.add_argument("--json", action="store_true", dest="as_json",
                     help="print the full result as JSON")
    pfs.set_defaults(fn=cmd_fleet_scan)

    pr = sub.add_parser("run", help="run the daemon")
    _add_common_flags(pr)
    pr.add_argument("--port", type=int, default=cfgmod.DEFAULT_PORT)
    pr.add_argument("--db-in-memory", action="store_true")
    pr.add_argument("--no-tls", action="store_true")
    pr.add_argument("--accelerator-type", default="")
    pr.add_argument("--expected-chip-count", type=int, default=0)
    pr.add_argument("--plugin-specs", default="", help="path to plugins.yaml")
    pr.add_argument("--endpoint", default="", help="control-plane endpoint")
    pr.add_argument("--token", default="", help="control-plane token")
    pr.add_argument("--disable-components", default="",
                    help="comma-separated component names to disable")
    pr.add_argument("--pprof", action="store_true",
                    help="enable /admin/pprof debug endpoints")
    pr.set_defaults(fn=cmd_run, audited=True)

    pi = sub.add_parser("inject-fault", help="inject a synthetic fault via kmsg")
    _add_common_flags(pi)
    pi.add_argument("--name", help="catalogued TPU error name (e.g. tpu_hbm_ecc_uncorrectable)")
    pi.add_argument("--chip-id", type=int, default=0)
    pi.add_argument("--detail", default="")
    pi.add_argument("--kernel-message", default="", help="raw kernel message instead of --name")
    pi.add_argument("--repeat", type=int, default=1,
                    help="burst: write the fault N times (flap modelling)")
    pi.add_argument("--interval-seconds", type=float, default=0.0,
                    help="spacing between burst writes")
    pi.set_defaults(fn=cmd_inject_fault, audited=True)

    pst = sub.add_parser("status", help="query the running daemon")
    pst.add_argument("--port", type=int, default=cfgmod.DEFAULT_PORT)
    pst.add_argument("--no-tls", action="store_true", help="daemon runs with --no-tls")
    pst.add_argument("--json", action="store_true", dest="as_json",
                     help="machine-readable status")
    pst.set_defaults(fn=cmd_status)

    pc = sub.add_parser("compact", help="VACUUM the state DB (daemon stopped)")
    _add_common_flags(pc)
    pc.set_defaults(fn=cmd_compact, audited=True)

    ph = sub.add_parser("set-healthy", help="clear a component's sticky state")
    _add_common_flags(ph)  # data-dir locates the audit trail
    ph.add_argument("--port", type=int, default=cfgmod.DEFAULT_PORT)
    ph.add_argument("--no-tls", action="store_true", help="daemon runs with --no-tls")
    ph.add_argument("--component", required=True)
    ph.set_defaults(fn=cmd_set_healthy, audited=True)

    pm = sub.add_parser("metadata", help="dump the metadata table")
    _add_common_flags(pm)
    pm.set_defaults(fn=cmd_metadata)

    phy = sub.add_parser(
        "history", help="health-transition timeline + availability from the ledger"
    )
    _add_common_flags(phy)
    phy.add_argument("--component", default="", help="filter to one component")
    phy.add_argument("--since-hours", type=float, default=24.0,
                     help="lookback window in hours (default 24)")
    phy.add_argument("--limit", type=int, default=256,
                     help="max transitions to show (0 = all)")
    phy.add_argument("--json", action="store_true", dest="as_json",
                     help="machine-readable timeline + availability")
    phy.set_defaults(fn=cmd_history)

    prm = sub.add_parser(
        "remediation",
        help="remediation audit ledger: policy decisions and repair attempts",
    )
    _add_common_flags(prm)
    prm.add_argument("--component", default="", help="filter to one component")
    prm.add_argument("--action", default="",
                     help="filter by action (e.g. reboot_system)")
    prm.add_argument("--outcome", default="",
                     help="filter by outcome (e.g. dry_run, executed)")
    prm.add_argument("--since-hours", type=float, default=24.0,
                     help="lookback window in hours (default 24)")
    prm.add_argument("--limit", type=int, default=256,
                     help="max attempts to show (0 = all)")
    prm.add_argument("--json", action="store_true", dest="as_json",
                     help="machine-readable attempts + summary")
    prm.set_defaults(fn=cmd_remediation)

    pch = sub.add_parser(
        "chaos", help="run declarative chaos campaigns against the daemon"
    )
    csub = pch.add_subparsers(dest="chaos_cmd", required=True)
    cr = csub.add_parser("run", help="execute a scenario; nonzero exit on FAIL")
    cr.add_argument("scenario",
                    help="shipped scenario name or a scenario file path")
    cr.add_argument("--port", type=int, default=cfgmod.DEFAULT_PORT)
    cr.add_argument("--no-tls", action="store_true")
    cr.add_argument("--no-wait", action="store_true",
                    help="launch on the daemon's pool and return immediately")
    cr.add_argument("--timeout", type=float, default=330.0,
                    help="HTTP timeout for the waited campaign (seconds)")
    cr.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable campaign result")
    cr.set_defaults(fn=cmd_chaos, audited=True)
    cl = csub.add_parser("list", help="list scenarios and past campaign results")
    cl.add_argument("--port", type=int, default=cfgmod.DEFAULT_PORT)
    cl.add_argument("--no-tls", action="store_true")
    cl.add_argument("--limit", type=int, default=10)
    cl.add_argument("--timeout", type=float, default=30.0)
    cl.add_argument("--json", action="store_true", dest="as_json")
    cl.set_defaults(fn=cmd_chaos)

    ppr = sub.add_parser(
        "predict",
        help="predictive health: per-component precursor scores",
    )
    ppr.add_argument("--component", default="", help="filter to one component")
    ppr.add_argument("--history", type=int, default=0,
                     help="append the last N score points per component")
    ppr.add_argument("--calibration", action="store_true",
                     help="show learned per-class threshold calibration")
    ppr.add_argument("--refit", action="store_true",
                     help="with --calibration: re-fit from the ledger first")
    ppr.add_argument("--port", type=int, default=cfgmod.DEFAULT_PORT)
    ppr.add_argument("--no-tls", action="store_true")
    ppr.add_argument("--timeout", type=float, default=30.0)
    ppr.add_argument("--json", action="store_true", dest="as_json",
                     help="machine-readable scores + status")
    ppr.set_defaults(fn=cmd_predict)

    pfa = sub.add_parser(
        "fabric",
        help="ICI fabric health: mesh-wide per-link sweep matrix",
    )
    pfa.add_argument("--link", default="",
                     help="append history for one link (e.g. c0-c1/x)")
    pfa.add_argument("--since", type=float, default=0.0,
                     help="history unix-timestamp floor")
    pfa.add_argument("--limit", type=int, default=0,
                     help="max history rows to append")
    pfa.add_argument("--port", type=int, default=cfgmod.DEFAULT_PORT)
    pfa.add_argument("--no-tls", action="store_true")
    pfa.add_argument("--timeout", type=float, default=30.0)
    pfa.add_argument("--json", action="store_true", dest="as_json",
                     help="machine-readable matrix + status")
    pfa.set_defaults(fn=cmd_fabric)

    pse = sub.add_parser(
        "session", help="control-plane session / outbox health"
    )
    ssub = pse.add_subparsers(dest="session_cmd", required=True)
    sst = ssub.add_parser(
        "status", help="connection, circuit-breaker, and outbox state"
    )
    sst.add_argument("--port", type=int, default=cfgmod.DEFAULT_PORT)
    sst.add_argument("--no-tls", action="store_true")
    sst.add_argument("--timeout", type=float, default=30.0)
    sst.add_argument("--json", action="store_true", dest="as_json")
    sst.set_defaults(fn=cmd_session)

    pmi = sub.add_parser("machine-info", help="print machine info JSON")
    pmi.add_argument("--accelerator-type", default="")
    pmi.set_defaults(fn=cmd_machine_info)

    prl = sub.add_parser("release", help="release signing (ed25519)")
    rsub = prl.add_subparsers(dest="release_cmd", required=True)
    r1 = rsub.add_parser("gen-root-key")
    r1.add_argument("--dir", default=".")
    r2 = rsub.add_parser("gen-signing-key")
    r2.add_argument("--dir", default=".")
    r3 = rsub.add_parser("sign-key")
    r3.add_argument("--root-key", required=True)
    r3.add_argument("--signing-pub", required=True)
    r4 = rsub.add_parser("sign-package")
    r4.add_argument("--signing-key", required=True)
    r4.add_argument("--package", required=True)
    r5 = rsub.add_parser("verify-package")
    r5.add_argument("--signing-pub", required=True)
    r5.add_argument("--package", required=True)
    r5.add_argument("--sig", default="")
    r5.add_argument("--root-pub", default="")
    r5.add_argument("--key-sig", default="")
    prl.set_defaults(fn=cmd_release)

    pup = sub.add_parser("update", help="set or check the target version")
    _add_common_flags(pup)
    pup.add_argument("--check", action="store_true")
    pup.add_argument("--target-version", default="")
    pup.add_argument("--install", action="store_true",
                     help="download, verify, and install --target-version now")
    pup.add_argument("--base-url", default="")
    pup.add_argument("--install-dir", default="")
    pup.add_argument("--signing-pub", default="")
    pup.add_argument("--root-pub", default="")
    pup.set_defaults(fn=cmd_update, audited=True)

    pcp = sub.add_parser("custom-plugins", help="validate a plugin specs file")
    pcp.add_argument("file")
    pcp.set_defaults(fn=cmd_custom_plugins)

    prg = sub.add_parser("run-plugin-group", help="run plugins with a tag once")
    prg.add_argument("file")
    prg.add_argument("--tag", default="")
    prg.set_defaults(fn=cmd_run_plugin_group)

    pn = sub.add_parser("notify", help="record a lifecycle notification")
    _add_common_flags(pn)
    pn.add_argument("phase", choices=["startup", "shutdown"])
    pn.set_defaults(fn=cmd_notify, audited=True)

    pmg = sub.add_parser(
        "manager", help="standalone dev control plane (serve / drive)"
    )
    msub = pmg.add_subparsers(dest="manager_cmd", required=True)
    ms = msub.add_parser("serve", help="run a manager process")
    ms.add_argument("--port", type=int, default=15135)
    ms.add_argument("--grpc-port", type=int, default=15136)
    ms.add_argument("--session-token", default="")
    ms.add_argument("--admin-token", default="")
    ms.add_argument("--data-dir", default="",
                    help="persist the fleet rollup journal here "
                         "(default: in-memory)")
    ms.add_argument("--shards", type=int, default=0,
                    help="ingest/rollup shard count "
                         "(default: 8; agents hash to shards by stable "
                         "crc32 slots, so this is safe to change between "
                         "restarts)")
    ms.add_argument("--peer-id", default="",
                    help="stable peer id in the manager peer set (also "
                         "used as instance_id; required with --peers)")
    ms.add_argument("--peers", action="append", default=[],
                    metavar="ID=ENDPOINT[|GRPC]",
                    help="full peer map incl. this manager's own entry; "
                         "repeatable. Enables federation (docs/fleet.md)")
    ms.add_argument("--replication-interval", type=float, default=0.0,
                    help="journal replication tick seconds (0 = "
                         "federation_replication_interval_seconds default)")
    ms.add_argument("--probe-interval", type=float, default=0.0,
                    help="peer health probe seconds (0 = "
                         "federation_probe_interval_seconds default)")
    ms.add_argument("--fanout-timeout", type=float, default=0.0,
                    help="per-peer scatter-gather seconds (0 = "
                         "federation_fanout_timeout_seconds default)")
    ms.add_argument("--dead-after-probes", type=int, default=0,
                    help="consecutive failed probes before a peer is "
                         "declared dead (0 = federation_dead_after_probes "
                         "default)")
    ms.add_argument("--no-auto-adopt", action="store_true",
                    help="never auto-adopt a dead peer's replicated "
                         "cohort (overrides federation_auto_adopt)")
    ms.set_defaults(fn=cmd_manager)
    mm = msub.add_parser("machines", help="list connected agents")
    mm.add_argument("--endpoint", default="http://127.0.0.1:15135")
    mm.add_argument("--admin-token", default="")
    mm.set_defaults(fn=cmd_manager)
    mr = msub.add_parser("request", help="issue one request to an agent")
    mr.add_argument("machine_id")
    mr.add_argument("method")
    mr.add_argument("--params", default="", help="JSON object of parameters")
    mr.add_argument("--endpoint", default="http://127.0.0.1:15135")
    mr.add_argument("--admin-token", default="")
    mr.add_argument("--timeout", type=float, default=30.0)
    mr.set_defaults(fn=cmd_manager)

    pfl = sub.add_parser(
        "fleet", help="fleet observability via a manager's operator API"
    )
    fsub = pfl.add_subparsers(dest="fleet_cmd", required=True)

    def _fleet_common(sp) -> None:
        sp.add_argument("--endpoint", default="http://127.0.0.1:15135")
        sp.add_argument("--admin-token", default="")
        sp.set_defaults(fn=cmd_fleet)

    fr = fsub.add_parser("rollup", help="fleet-wide rollup aggregates")
    _fleet_common(fr)
    ff = fsub.add_parser(
        "fabric", help="fleet-wide ICI link matrix: degraded links since ts"
    )
    ff.add_argument("--since", type=float, default=0.0,
                    help="unix-timestamp floor for degraded-since")
    _fleet_common(ff)
    fp = fsub.add_parser(
        "predict",
        help="fleet-ranked predictive pane: top-K series by decayed risk",
    )
    fp.add_argument("--top", type=int, default=20,
                    help="how many ranked (agent, component) rows")
    _fleet_common(fp)
    fa = fsub.add_parser("agents", help="paginated per-agent rollups")
    fa.add_argument("--offset", type=int, default=0)
    fa.add_argument("--limit", type=int, default=100)
    fa.add_argument("--peer", default="",
                    help="only agents owned by this peer id (cohort "
                         "placement view; federated managers only)")
    _fleet_common(fa)
    fpe = fsub.add_parser(
        "peers",
        help="the manager peer map: ring, health, rendezvous cohorts, "
             "replication watermarks",
    )
    _fleet_common(fpe)
    fh = fsub.add_parser(
        "history", help="one agent's journaled records, newest first"
    )
    fh.add_argument("machine_id")
    fh.add_argument("--since", type=float, default=0.0,
                    help="unix-timestamp floor")
    fh.add_argument("--offset", type=int, default=0)
    fh.add_argument("--limit", type=int, default=100)
    _fleet_common(fh)
    ft = fsub.add_parser(
        "traces", help="fleet records stitched to one check's trace"
    )
    ft.add_argument("correlation_id")
    _fleet_common(ft)

    return p


def main(argv: Optional[List[str]] = None) -> int:
    import os

    args = build_parser().parse_args(argv)
    log_setup(getattr(args, "log_level", "info"))
    # privileged CLI actions are audited into the data dir like the
    # daemon's own; read-only commands (scan, list-plugins, status, ...)
    # must not touch the data dir at all
    if getattr(args, "audited", False) and hasattr(args, "data_dir"):
        cfg = _build_config(args)
        if not cfg.db_in_memory:
            try:
                set_audit_logger(
                    AuditLogger(os.path.join(cfg.resolved_data_dir(),
                                             cfgmod.AUDIT_LOG_FILE))
                )
            except OSError:
                pass  # unwritable data dir: act unaudited rather than fail
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
