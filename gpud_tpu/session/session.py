"""Control-plane session — bidirectional channel to the fleet manager.

Reference: pkg/session (SURVEY §3.3). Protocol v1: two long-lived chunked
HTTP streams against ``<endpoint>/api/v1/session`` —
- writer: POST with ``X-TPUD-Session-Type: write``, chunked request body
  carrying newline-delimited JSON responses up (reference:
  session.go:525-575),
- reader: POST with type ``read``, streaming newline-delimited JSON
  requests down (reference: session.go:619+).

Each frame is ``{"req_id": str, "data": {...}}``. The keep-alive loop
reconnects both streams with exponential backoff + jitter, and drains the
reader channel on reconnect (reference: session_keepalive.go:11,
session_reconnect.go). Auth rides headers: machine id, token, machine
proof (reference: session.go:486-510).

Every network-touching function is injectable for tests
(reference pattern: session.go:262-296 timeAfterFunc/jitterFunc/
startReaderFunc).
"""

from __future__ import annotations

import json
import queue
import random
import threading
import time
from typing import Callable, Dict, Optional

from gpud_tpu.log import get_logger
from gpud_tpu.metrics.registry import counter
from gpud_tpu.version import __version__

logger = get_logger(__name__)

CHANNEL_CAP = 20          # reference: session.go:420-423
PIPE_INTERVAL = 3.0       # reference: server.go:616
BACKOFF_INITIAL = 1.0
BACKOFF_MAX = 60.0
BACKOFF_FACTOR = 2.0
# while auth-parked, how often to re-check whether the token changed
AUTH_RECHECK_INTERVAL = 5.0
# rate limit on the Warning event emitted when a session channel drops a
# frame (the counter still counts every drop)
FRAME_DROP_EVENT_INTERVAL = 30.0
# while the circuit breaker is open, cap each wait slice so stop() and
# token changes stay responsive
CIRCUIT_WAIT_SLICE = 1.0

_c_frames_dropped = counter(
    "tpud_session_frames_dropped_total",
    "frames dropped by a full session channel, by direction (read = "
    "manager requests, write = agent responses/outbox deliveries)",
)

# anchored so incidental digits ("port=4013") and local OS errors
# ("[Errno 13] Permission denied") never classify as auth failures
import re as _re

_AUTH_ERROR_RE = _re.compile(
    r"(\b40[13]\b"
    r"|unauthenticated"
    r"|unauthorized"
    r"|permission_denied"     # grpc enum spelling only, not OS errors
    r"|invalid token"
    r"|bad token"             # v2 HelloAck rejection vocabulary
    r"|invalid machine proof)",
    _re.IGNORECASE,
)


def is_auth_error(reason) -> bool:
    """Classify a connect failure as an auth failure (revoked/invalid
    token) vs a network blip (reference: session_reconnect.go:38-226 +
    session_v2.go:359 classify Unauthenticated/401). Prefers structured
    fields (a pre-classified ``auth_error`` attribute, HTTP status, grpc
    code); text matching is anchored."""
    explicit = getattr(reason, "auth_error", None)
    if explicit is not None:
        return bool(explicit)
    resp = getattr(reason, "response", None)
    if resp is not None:
        code = getattr(resp, "status_code", None)
        if code in (401, 403):
            return True
        if code is not None:
            return False  # a definite non-auth HTTP status
    code_fn = getattr(reason, "code", None)
    if callable(code_fn):
        try:  # grpc.RpcError
            name = getattr(code_fn(), "name", "")
            if name in ("UNAUTHENTICATED", "PERMISSION_DENIED"):
                return True
            if name:
                return False  # a definite non-auth grpc code
        except Exception:  # noqa: BLE001
            pass
    return bool(_AUTH_ERROR_RE.search(str(reason)))

HEADER_SESSION_TYPE = "X-TPUD-Session-Type"
HEADER_MACHINE_ID = "X-TPUD-Machine-ID"
HEADER_TOKEN = "Authorization"
HEADER_MACHINE_PROOF = "X-TPUD-Machine-Proof"
HEADER_VERSION = "X-TPUD-Version"


class Frame:
    """One wire frame (reference: session.go Body{ReqID, Data})."""

    def __init__(self, req_id: str = "", data: Optional[dict] = None) -> None:
        self.req_id = req_id
        self.data = data or {}
        self._encoded: Optional[str] = None

    def to_json(self) -> str:
        # cached: the serve loop encodes once to validate serializability;
        # the transport writer reuses that encoding (frames are not
        # mutated after construction)
        if self._encoded is None:
            self._encoded = json.dumps({"req_id": self.req_id, "data": self.data})
        return self._encoded

    @classmethod
    def from_json(cls, line: str) -> Optional["Frame"]:
        try:
            d = json.loads(line)
        except ValueError:
            return None
        if not isinstance(d, dict):
            return None
        return cls(req_id=str(d.get("req_id", "")), data=d.get("data") or {})


class Session:
    """reference: session.NewSession (session.go:342)."""

    def __init__(
        self,
        endpoint: str,
        machine_id: str,
        token: str = "",
        machine_proof: str = "",
        dispatch_fn: Optional[Callable[[dict], dict]] = None,
        start_reader_fn=None,
        start_writer_fn=None,
        jitter_fn: Callable[[float], float] = None,
        time_sleep_fn: Callable[[float], bool] = None,
        audit_logger=None,
        protocol: str = "auto",
        v2_target: str = "",
    ) -> None:
        self.endpoint = endpoint.rstrip("/")
        # split-port deployments (e.g. the standalone dev control plane
        # serves HTTP and gRPC on different ports) advertise the gRPC
        # target apart from the HTTP endpoint. Resolution: explicit param
        # > TPUD_SESSION_V2_TARGET env > derived from endpoint. May carry
        # a scheme ("http://host:p" pins plaintext, "https://" pins TLS);
        # bare host:port inherits the endpoint's scheme.
        import os as _os

        self.v2_target = v2_target or _os.environ.get(
            "TPUD_SESSION_V2_TARGET", ""
        )
        self.machine_id = machine_id
        self.token = token
        self.machine_proof = machine_proof
        self.dispatch_fn = dispatch_fn or (lambda req: {"error": "no dispatcher"})

        self.reader: "queue.Queue[Frame]" = queue.Queue(maxsize=CHANNEL_CAP)
        self.writer: "queue.Queue[Frame]" = queue.Queue(maxsize=CHANNEL_CAP)

        self._stop = threading.Event()
        self._threads = []
        self._reconnect_signal = threading.Event()
        self._connected = threading.Event()
        self.reconnect_count = 0
        self.last_connect_error: str = ""
        # injectable like jitter_fn/time_sleep_fn: tests shrink it so the
        # full-queue path doesn't cost 5s of wall clock per probe
        self.send_timeout = 5.0
        # auth-failure classification (reference: session_reconnect.go
        # 38-226): a revoked token parks the reconnect loop instead of
        # hammering the control plane with the normal backoff forever
        self.auth_failed = False
        self.on_auth_failure: Optional[Callable[[str], None]] = None
        # fires after every successful connect with the credential that
        # worked — the server persists the endpoint+token pair here, so
        # only credentials the control plane actually accepted are recorded
        self.on_connected: Optional[Callable[[], None]] = None
        # set by the server's auth-failure handler after it promotes the
        # boot-flag token once; guards against credential ping-pong
        self.flag_token_tried = False
        # optional connect-path circuit breaker (session/outbox.py): the
        # server injects one so a hard-down manager stops costing connect
        # attempts; None = classic backoff-only behavior (tests, tools)
        self.circuit = None
        # frame-drop visibility (tpud_session_frames_dropped_total): the
        # server wires an event emitter here; calls are rate-limited to
        # one per direction per FRAME_DROP_EVENT_INTERVAL
        self.on_frame_dropped: Optional[Callable[[str, str], None]] = None
        self._last_drop_note: Dict[str, float] = {}
        # structured auth classification of last_connect_error: transports
        # classify mid-stream failures while the exception object is live
        # (HTTP status / grpc code) instead of regexing the formatted
        # string later; None = unclassified, fall back to is_auth_error()
        self._last_reason_auth: Optional[bool] = None
        # connect attempts ever made (chaos proves the open circuit keeps
        # this flat)
        self.connect_attempts = 0

        # protocol auto: try v2 gRPC, fall back to legacy v1 dual streams
        # (reference: session_v2.go:49-80); injected transports pin v1
        self.protocol = "v1" if start_reader_fn is not None else protocol
        self.active_protocol = ""

        # injectables
        self.start_reader_fn = start_reader_fn or self._http_reader
        self.start_writer_fn = start_writer_fn or self._http_writer
        self.jitter_fn = jitter_fn or (lambda b: b * (0.5 + random.random()))
        # returns True if stop was requested during the sleep
        self.time_sleep_fn = time_sleep_fn or (lambda s: self._stop.wait(s))
        self.audit_logger = audit_logger

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        for name, target in (
            ("tpud-session-keepalive", self._keep_alive),
            ("tpud-session-serve", self._serve),
        ):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        self._reconnect_signal.set()
        for t in self._threads:
            t.join(timeout=3.0)
        self._threads.clear()

    @property
    def connected(self) -> bool:
        return self._connected.is_set()

    def _apply_peer(self, spec: str) -> None:
        """Retarget this session at the breaker's current peer. Accepts
        a bare endpoint, ``endpoint|grpc_target``, or the full
        ``peer_id=endpoint[|grpc_target]`` manager spec; a no-op when
        the spec is empty or already the active target. Only the
        keep-alive thread calls this, between connects."""
        if not spec:
            return
        raw = spec.strip()
        _head, sep, tail = raw.partition("=")
        if sep and "://" in tail:
            raw = tail  # peer_id=endpoint form: the id is routing-only
        endpoint, _, grpc_target = raw.partition("|")
        endpoint = endpoint.strip().rstrip("/")
        grpc_target = grpc_target.strip()
        if not endpoint:
            return
        if endpoint == self.endpoint and (
            not grpc_target or grpc_target == self.v2_target
        ):
            return
        logger.warning(
            "session failing over: %s -> %s", self.endpoint, endpoint
        )
        self.endpoint = endpoint
        self.v2_target = grpc_target
        # the new peer negotiates its own transport: a v1-only previous
        # peer must not pin the replacement to v1
        self._v2_failed = False
        self._v2_skip_cycles = 0

    # -- keep-alive / reconnect (reference: session_keepalive.go,
    #    session_reconnect.go) -------------------------------------------
    def _keep_alive(self) -> None:
        backoff = BACKOFF_INITIAL
        while not self._stop.is_set():
            cb = self.circuit
            if cb is not None and not cb.allow():
                # circuit open: no network attempt at all until the
                # cooldown elapses (the connect-attempt counter must stay
                # flat); wake in bounded slices so stop() stays responsive
                wait = min(
                    max(cb.seconds_until_probe(), 0.05), CIRCUIT_WAIT_SLICE
                )
                if self.time_sleep_fn(wait):
                    return
                continue
            if cb is not None:
                # HA failover: the breaker owns which manager to dial
                # (it rotates current_peer() on every trip to open);
                # retarget BEFORE the attempt so the immediate failover
                # probe already lands on the new peer
                self._apply_peer(cb.current_peer())
            self._drain_reader()
            self._reconnect_signal.clear()
            self._last_reason_auth = None
            self.connect_attempts += 1
            try:
                stops = self._connect()
            except Exception as e:  # noqa: BLE001
                self.last_connect_error = str(e)
                logger.warning("session connect failed: %s", e)
                auth = is_auth_error(e)
                if cb is not None and not auth:
                    # auth rejections park below — counting them toward
                    # the circuit would double-suppress the token path
                    cb.record_failure()
                if auth:
                    if self._park_on_auth_failure(str(e)):
                        return
                    backoff = BACKOFF_INITIAL
                    continue
                if cb is not None and cb.state != "closed":
                    # the failure tripped (or re-tripped) the breaker:
                    # its cooldown is now the single pacing authority.
                    # Sleeping the exponential backoff on top would
                    # stack two waits and stall recovery long after the
                    # manager is back (a failed half-open probe with
                    # backoff grown to minutes is the worst case)
                    backoff = BACKOFF_INITIAL
                    continue
                if self.time_sleep_fn(self.jitter_fn(backoff)):
                    return
                backoff = min(backoff * BACKOFF_FACTOR, BACKOFF_MAX)
                continue
            if cb is not None:
                cb.record_success()
            self._connected.set()
            if self.on_connected is not None:
                try:
                    self.on_connected()
                except Exception:  # noqa: BLE001
                    logger.exception("on_connected callback failed")
            backoff = BACKOFF_INITIAL
            self._reconnect_signal.wait()
            self._connected.clear()
            self.reconnect_count += 1
            for stop in stops:
                try:
                    if stop:
                        stop()
                except Exception:  # noqa: BLE001
                    pass
            if self._stop.is_set():
                return
            # a 401/Unauthenticated may also arrive mid-stream via
            # signal_reconnect's reason rather than a connect exception;
            # prefer the transport's structured classification (v1 HTTP
            # status / v2 grpc code captured while the exception was live)
            # over regexing the formatted string
            auth = self._last_reason_auth
            if auth is None:
                auth = is_auth_error(self.last_connect_error)
            if auth:
                if self._park_on_auth_failure(self.last_connect_error):
                    return
                backoff = BACKOFF_INITIAL
                continue
            if self.time_sleep_fn(self.jitter_fn(backoff)):
                return
            backoff = min(backoff * BACKOFF_FACTOR, BACKOFF_MAX)

    def _park_on_auth_failure(self, reason: str) -> bool:
        """Suspend reconnecting until the token changes (new token via
        updateToken/FIFO) or the session stops. Returns True when stop was
        requested (caller should exit the keep-alive loop)."""
        self.auth_failed = True
        failed_token = self.token
        logger.warning(
            "session auth failure (%s); suspending reconnect until the "
            "token changes", reason,
        )
        if self.on_auth_failure is not None:
            try:
                self.on_auth_failure(reason)
            except Exception:  # noqa: BLE001
                logger.exception("on_auth_failure callback failed")
        while not self._stop.is_set() and self.token == failed_token:
            if self.time_sleep_fn(AUTH_RECHECK_INTERVAL):
                return True
        self.auth_failed = False
        return self._stop.is_set()

    def _connect(self):
        """Open the transport per protocol preference; returns stop fns."""
        skip = getattr(self, "_v2_skip_cycles", 0)
        if skip > 0:
            self._v2_skip_cycles = skip - 1
            if self._v2_skip_cycles == 0:
                self._v2_failed = False  # cooldown elapsed: re-probe v2
        if self.protocol == "v2" or (
            self.protocol == "auto" and not getattr(self, "_v2_failed", False)
        ):
            try:
                from gpud_tpu.session.v2.client import start_v2_transport

                stop = start_v2_transport(self)
                self.active_protocol = "v2"
                return [stop]
            except Exception as e:  # noqa: BLE001
                if self.protocol == "v2":
                    raise
                # back off from v2 for a number of reconnect cycles rather
                # than forever: a transient UNAVAILABLE during a control-
                # plane rolling restart must not pin the daemon to v1 for
                # its whole lifetime
                self._v2_skip_cycles = 10
                self._v2_failed = True
                logger.info("session v2 unavailable (%s); using legacy v1", e)
        stops = [self.start_reader_fn(self), self.start_writer_fn(self)]
        self.active_protocol = "v1"
        return stops

    def signal_reconnect(self, reason: str = "", auth: Optional[bool] = None) -> None:
        """``auth`` carries the transport's structured classification of
        the failure (computed from the live exception's HTTP status/grpc
        code); None = unknown, the keep-alive loop falls back to text
        matching via ``is_auth_error``."""
        if reason:
            self.last_connect_error = reason
            self._last_reason_auth = auth
        self._reconnect_signal.set()

    def _drain_reader(self) -> None:
        while True:
            try:
                self.reader.get_nowait()
            except queue.Empty:
                return

    # -- serve loop (reference: session_serve.go:137) ----------------------
    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                frame = self.reader.get(timeout=0.5)
            except queue.Empty:
                continue
            if frame is None:  # sentinel/garbage must not kill the loop
                continue
            try:
                resp = self.dispatch_fn(frame.data)
            except Exception as e:  # noqa: BLE001
                logger.exception("request dispatch failed")
                resp = {"error": str(e)}
            # a dispatcher bug returning non-JSON-serializable data must
            # become an error response HERE — discovered later inside the
            # transport writer it would crash the pump mid-stream instead.
            # to_json() caches, so the writer pays no second serialization.
            out = Frame(req_id=frame.req_id, data=resp)
            try:
                out.to_json()
            except (TypeError, ValueError):
                logger.exception("dispatch result not serializable")
                out = Frame(
                    req_id=frame.req_id,
                    data={"error": "internal: dispatch result not serializable"},
                )
            self.send(out)

    def send(self, frame: Frame) -> bool:
        try:
            self.writer.put(frame, timeout=self.send_timeout)
            return True
        except queue.Full:
            self.note_frame_dropped(
                "write", "session writer channel full; dropping frame"
            )
            return False

    def note_frame_dropped(self, direction: str, detail: str) -> None:
        """Account one dropped frame: the counter counts every drop; the
        Warning event hook (server-wired) is rate-limited per direction so
        a sustained overflow doesn't flood the event store."""
        _c_frames_dropped.inc(labels={"direction": direction})
        logger.warning("%s", detail)
        hook = self.on_frame_dropped
        if hook is None:
            return
        now = time.monotonic()
        last = self._last_drop_note.get(direction)
        if last is not None and now - last < FRAME_DROP_EVENT_INTERVAL:
            return
        self._last_drop_note[direction] = now
        try:
            hook(direction, detail)
        except Exception:  # noqa: BLE001
            logger.exception("on_frame_dropped hook failed")

    # -- HTTP transport (requests-based; replaced in tests) ----------------
    def _headers(self, session_type: str) -> Dict[str, str]:
        h = {
            HEADER_SESSION_TYPE: session_type,
            HEADER_MACHINE_ID: self.machine_id,
            HEADER_VERSION: __version__,
            "Content-Type": "application/x-ndjson",
        }
        if self.token:
            h[HEADER_TOKEN] = f"Bearer {self.token}"
        if self.machine_proof:
            h[HEADER_MACHINE_PROOF] = self.machine_proof
        return h

    def _http_reader(self, _self) -> Callable[[], None]:
        """Opens the read stream: requests arriving as ndjson lines."""
        import requests

        resp = requests.post(
            f"{self.endpoint}/api/v1/session",
            headers=self._headers("read"),
            stream=True,
            timeout=(10, None),
        )
        resp.raise_for_status()
        stopped = threading.Event()

        def pump():
            try:
                for line in resp.iter_lines(decode_unicode=True):
                    if stopped.is_set() or self._stop.is_set():
                        return
                    if not line:
                        continue
                    frame = Frame.from_json(line)
                    if frame is not None:
                        try:
                            self.reader.put(frame, timeout=5.0)
                        except queue.Full:
                            self.note_frame_dropped(
                                "read",
                                "reader channel full; dropping request",
                            )
                # graceful server-side close is also a disconnect: without a
                # reconnect the session would look connected but be deaf
                if not stopped.is_set():
                    self.signal_reconnect("read stream closed")
            except Exception as e:  # noqa: BLE001
                if not stopped.is_set():
                    self.signal_reconnect(f"read stream: {e}", auth=is_auth_error(e))

        t = threading.Thread(target=pump, name="tpud-session-reader", daemon=True)
        t.start()

        def stop():
            stopped.set()
            resp.close()

        return stop

    def _http_writer(self, _self) -> Callable[[], None]:
        """Opens the write stream: a chunked POST whose body is produced
        from the writer queue (reference: io.Pipe up, session.go:525-575)."""
        import requests

        stopped = threading.Event()

        def body_gen():
            while not stopped.is_set() and not self._stop.is_set():
                try:
                    frame = self.writer.get(timeout=PIPE_INTERVAL)
                except queue.Empty:
                    yield b"\n"  # keep-alive blank line each pipe interval
                    continue
                yield (frame.to_json() + "\n").encode()

        def run():
            try:
                resp = requests.post(
                    f"{self.endpoint}/api/v1/session",
                    headers=self._headers("write"),
                    data=body_gen(),
                    timeout=(10, None),
                )
                resp.raise_for_status()
                # the POST returning at all means the server ended the
                # write stream — mute session without a reconnect otherwise
                if not stopped.is_set():
                    self.signal_reconnect("write stream closed")
            except Exception as e:  # noqa: BLE001
                if not stopped.is_set():
                    self.signal_reconnect(
                        f"write stream: {e}", auth=is_auth_error(e)
                    )

        t = threading.Thread(target=run, name="tpud-session-writer", daemon=True)
        t.start()

        def stop():
            stopped.set()

        return stop
