"""Session wire codec: batched outbox frames, delta encoding, compression.

``bench.py --outbox`` drains the local journal at ~245k frames/sec, but
until this module every record crossed the session as its own JSON frame
with its own ack round-trip — the wire, not storage, was the bottleneck
(ROADMAP item 2). Three layers close the gap, each independently
degradable:

- **Batch frames**: ``SessionOutbox.replay_once`` packs up to
  ``replay_batch`` records into one ``{"outbox_batch": {...}}`` frame;
  the manager ingests the batch and answers a single cumulative
  ``outboxAck`` watermark (the ``MAX(acked_seq, ?)`` SQL watermark
  absorbs it for free), collapsing N ack round-trips into 1.
- **Delta encoding** (:class:`DeltaEncoder` / :class:`DeltaDecoder`):
  most health transitions and metric gauges differ from the previous
  record of the same (kind, component) stream in 2–3 fields, so records
  carry a top-level dict diff against the stream's previous payload,
  with a full keyframe every ``keyframe_interval`` records and whenever
  the encoder resets (reconnect, send failure). The decoder applies
  diffs exactly; a delta arriving without its keyframe base raises
  :class:`DeltaDecodeError` so the manager acks only the decoded prefix
  and the agent redelivers keyframe-anchored.
- **Optional compression + binary framing** on the v2 tunnel at
  negotiated revision >= 3: every ``Frame.data`` /
  ``Result.payload_json`` byte string carries a 1-byte codec prefix
  (``j`` = raw JSON, ``z`` = zlib JSON, ``m`` = msgpack, ``M`` = zlib
  msgpack); payloads under ``compress_min_bytes`` — or that zlib fails
  to shrink — ship uncompressed. msgpack is used when importable (it
  serializes several times faster than ``json`` and ~25% smaller) and
  degrades to JSON framing when absent — both peers run this module, so
  a decoder always understands every prefix its build can emit. Rev-2
  peers negotiate down and see plain JSON bytes, so cross-revision
  fleets interoperate (docs/session.md).

Byte accounting rides ``tpud_session_wire_bytes_total{direction,codec}``
and the ``tpud_session_wire_compression_ratio`` gauge (raw JSON bytes
over on-wire bytes, cumulative since process start).
"""

from __future__ import annotations

import json
import threading
import zlib
from typing import Dict, List, Optional, Tuple

from gpud_tpu.metrics.registry import counter, gauge

try:  # the container bakes msgpack in; degrade to JSON framing without it
    import msgpack as _msgpack
except ImportError:  # pragma: no cover - exercised only on slim installs
    _msgpack = None

# rev-3 wire framing: 1-byte codec prefix on every payload byte string
PREFIX_JSON = b"j"
PREFIX_ZLIB = b"z"
PREFIX_MSGPACK = b"m"
PREFIX_ZLIB_MSGPACK = b"M"

DEFAULT_KEYFRAME_INTERVAL = 64    # full payload every K records per stream
DEFAULT_COMPRESS_MIN_BYTES = 512  # don't zlib tiny payloads (header > win)
COMPRESS_LEVEL = 1                # throughput-biased: the wire bench gates
                                  # frames/sec as well as bytes/frame, and
                                  # level 1 already captures most of the
                                  # repetition win on delta-encoded batches

BATCH_KEY = "outbox_batch"
BATCH_VERSION = 1

_c_wire_bytes = counter(
    "tpud_session_wire_bytes_total",
    "session payload bytes crossing the wire codec, by direction "
    "(egress/ingress) and codec (json/zlib/msgpack)",
)
_g_wire_ratio = gauge(
    "tpud_session_wire_compression_ratio",
    "cumulative raw-JSON bytes over on-wire bytes for egress payloads "
    "(1.0 = no win; higher is better)",
)

_stats_mu = threading.Lock()
_raw_egress_bytes = 0
_wire_egress_bytes = 0

# process-wide knobs, set once from config at server startup
# (configure()); module defaults serve tests and standalone tools
_compress_min_bytes = DEFAULT_COMPRESS_MIN_BYTES


def configure(compress_min_bytes: Optional[int] = None) -> None:
    """Apply config knobs (server startup; see config.py
    ``session_wire_compress_min_bytes``)."""
    global _compress_min_bytes
    if compress_min_bytes is not None:
        _compress_min_bytes = max(0, int(compress_min_bytes))


def _record_egress(raw_len: int, wire_len: int, codec: str) -> None:
    global _raw_egress_bytes, _wire_egress_bytes
    _c_wire_bytes.inc(wire_len, {"direction": "egress", "codec": codec})
    with _stats_mu:
        _raw_egress_bytes += raw_len
        _wire_egress_bytes += wire_len
        if _wire_egress_bytes:
            _g_wire_ratio.set(_raw_egress_bytes / _wire_egress_bytes)


def codec_stats() -> Dict:
    """Cumulative egress byte accounting (outboxStatus / bench)."""
    with _stats_mu:
        raw, wire = _raw_egress_bytes, _wire_egress_bytes
    return {
        "raw_egress_bytes": raw,
        "wire_egress_bytes": wire,
        "compression_ratio": round(raw / wire, 3) if wire else 1.0,
        "compress_min_bytes": _compress_min_bytes,
    }


class WireCodecError(ValueError):
    """Undecodable wire payload (unknown prefix, corrupt zlib body)."""


class DeltaDecodeError(ValueError):
    """A delta record arrived without its keyframe base — the decoder
    lost sync (new connection, dropped keyframe). The ingester acks only
    the decoded prefix; the agent's stall fallback redelivers the rest
    keyframe-anchored (outbox.reset_delivery / redeliver_after)."""


# -- rev-3 payload framing ---------------------------------------------------

def pack_obj(obj) -> bytes:
    """Object → compact serialized bytes, NO codec prefix: msgpack when
    available, else compact JSON. For single-process storage (the outbox
    journal column) where :func:`unpack_obj` is the only reader — wire
    traffic uses the prefix-framed :func:`encode_payload` instead."""
    if _msgpack is not None:
        return _msgpack.packb(obj, use_bin_type=True, default=str)
    return json.dumps(obj, separators=(",", ":"), default=str).encode("utf-8")


def unpack_obj(raw):
    """Inverse of :func:`pack_obj`; also reads legacy JSON text rows (a
    journal written before the msgpack column encoding, or by a build
    without msgpack). Raises ValueError on garbage."""
    if isinstance(raw, bytes):
        if _msgpack is not None:
            try:
                return _msgpack.unpackb(raw, raw=False, strict_map_key=False)
            except Exception:  # noqa: BLE001 - fall through to JSON sniff
                pass
        return json.loads(raw)
    return json.loads(raw)


def unpack_many(raws: List) -> List:
    """Bulk :func:`unpack_obj` — the replay hot path reads thousands of
    journal rows per batch, and a streaming Unpacker decodes them in one
    C-level pass instead of one Python call per row. Falls back to
    row-by-row decoding when any row isn't clean msgpack (legacy JSON
    text, or a JSON-bytes row from a build without msgpack — those yield
    a different object count, which the length check catches because a
    journaled payload is always a dict, never a 1-byte document)."""
    if _msgpack is not None and raws:
        try:
            unp = _msgpack.Unpacker(raw=False, strict_map_key=False)
            unp.feed(b"".join(raws))  # TypeError on str rows -> fallback
            objs = list(unp)
            if len(objs) == len(raws):
                return objs
        except Exception:  # noqa: BLE001 - any decode trouble -> fallback
            pass
    return [unpack_obj(r) for r in raws]


def encode_payload(obj, min_bytes: Optional[int] = None) -> bytes:
    """Object → prefix-framed wire bytes (rev >= 3 only — rev-2 peers
    expect bare JSON). msgpack body when available, JSON otherwise; zlib
    applies above ``min_bytes`` and only when it actually shrinks the
    payload."""
    if _msgpack is not None:
        raw = _msgpack.packb(obj, use_bin_type=True, default=str)
        plain, packed = PREFIX_MSGPACK, PREFIX_ZLIB_MSGPACK
        codec = "msgpack"
    else:
        raw = json.dumps(obj, separators=(",", ":"), default=str).encode("utf-8")
        plain, packed = PREFIX_JSON, PREFIX_ZLIB
        codec = "json"
    floor = _compress_min_bytes if min_bytes is None else min_bytes
    if len(raw) >= floor:
        z = zlib.compress(raw, COMPRESS_LEVEL)
        if len(z) + 1 < len(raw):
            out = packed + z
            _record_egress(len(raw), len(out), "zlib")
            return out
    out = plain + raw
    _record_egress(len(raw), len(out), codec)
    return out


def decode_payload(buf: bytes):
    """Prefix-framed wire bytes → object (inverse of encode_payload)."""
    if not buf:
        raise WireCodecError("empty wire payload")
    prefix, body = buf[:1], buf[1:]
    if prefix in (PREFIX_ZLIB, PREFIX_ZLIB_MSGPACK):
        try:
            raw = zlib.decompress(body)
        except zlib.error as e:
            raise WireCodecError(f"corrupt zlib payload: {e}") from e
        _c_wire_bytes.inc(len(buf), {"direction": "ingress", "codec": "zlib"})
        packed = prefix == PREFIX_ZLIB_MSGPACK
    elif prefix in (PREFIX_JSON, PREFIX_MSGPACK):
        raw = body
        packed = prefix == PREFIX_MSGPACK
        _c_wire_bytes.inc(
            len(buf),
            {"direction": "ingress",
             "codec": "msgpack" if packed else "json"},
        )
    else:
        raise WireCodecError(f"unknown wire codec prefix {prefix!r}")
    if packed:
        if _msgpack is None:
            raise WireCodecError(
                "msgpack-framed payload but msgpack is not installed"
            )
        try:
            return _msgpack.unpackb(raw, raw=False, strict_map_key=False)
        except Exception as e:  # noqa: BLE001 - msgpack raises many types
            raise WireCodecError(f"corrupt msgpack payload: {e}") from e
    try:
        return json.loads(raw)
    except ValueError as e:
        raise WireCodecError(f"wire payload is not JSON: {e}") from e


# -- delta codec -------------------------------------------------------------

# sentinel for "key absent": unequal (by identity) to every JSON value,
# including None, at C comparison speed
_MISSING = object()


def stream_of(kind: str, payload) -> str:
    """Delta stream key: records delta against the previous payload of
    the same (kind, component) — the repetitive axis of the telemetry."""
    component = ""
    if isinstance(payload, dict):
        component = str(payload.get("component", ""))
    return f"{kind}:{component}"


class DeltaEncoder:
    """Stateful per-stream delta encoder (agent side; NOT thread-safe —
    the outbox serializes access under its own lock).

    ``encode_record`` emits a positional array — field names would be
    re-packed and re-parsed for every record on the hot drain path:

    - keyframe: ``[seq, ts, kind, key, stream, payload]`` (length 6)
    - delta: ``[seq, ts, kind, key, stream, set, del]`` (length 7),
      a top-level dict diff against the stream's previous payload where
      changed/added keys are replaced wholesale (nested values are not
      recursed), ``set`` is the changed-key map (or None) and ``del``
      the removed-key list (or None)

    ``reset()`` forgets all stream state so the next record per stream
    is a keyframe — called on reconnect and on transport send failure,
    because the peer's decoder state is unknown from that point on.

    The encoder keeps a REFERENCE to each payload as the stream's diff
    base (no defensive copy — this sits on the hot replay path, bench.py
    --wire): callers must not mutate a payload after handing it in.
    ``SessionOutbox.replay_once`` satisfies this by construction — every
    row is freshly deserialized from the journal.
    """

    def __init__(self, keyframe_interval: int = DEFAULT_KEYFRAME_INTERVAL) -> None:
        self.keyframe_interval = max(1, int(keyframe_interval))
        # stream → (previous payload, records since last keyframe)
        self._streams: Dict[str, Tuple[Dict, int]] = {}

    def reset(self) -> None:
        self._streams.clear()

    def encode_record(
        self, seq: int, ts: float, kind: str, dedupe_key: str, payload
    ) -> List:
        if not isinstance(payload, dict):
            # non-dict payloads never delta; drop any stale stream base
            stream = f"{kind}:"
            self._streams.pop(stream, None)
            return [seq, ts, kind, dedupe_key, stream, payload]
        stream = f"{kind}:{payload.get('component', '')}"
        prev = self._streams.get(stream)
        if prev is None or prev[1] + 1 >= self.keyframe_interval:
            self._streams[stream] = (payload, 0)
            return [seq, ts, kind, dedupe_key, stream, payload]
        base, since = prev
        get = base.get
        changed = {
            k: v for k, v in payload.items() if get(k, _MISSING) != v
        }
        removed = None
        # keys-view equality is one C-level set compare; the per-key scan
        # only runs when the key sets actually diverged
        if base.keys() != payload.keys():
            rm = [k for k in base if k not in payload]
            if rm:
                removed = rm
        self._streams[stream] = (payload, since + 1)
        return [seq, ts, kind, dedupe_key, stream, changed or None, removed]


class DeltaDecoder:
    """Exact inverse of :class:`DeltaEncoder` (manager side, one per
    connection — a fresh connection starts with keyframes because the
    agent resets its encoder on reconnect).

    Like the encoder, decoded payloads are kept by REFERENCE as diff
    bases: callers must treat the returned payload as read-only."""

    def __init__(self) -> None:
        self._streams: Dict[str, Dict] = {}

    def reset(self) -> None:
        self._streams.clear()

    def decode_record(self, rec) -> Tuple[int, float, str, str, object]:
        """Record array → ``(seq, ts, kind, dedupe_key, payload)``.

        Raises :class:`DeltaDecodeError` on a malformed record or a
        delta without a base. Only ``seq`` is coerced (the ack watermark
        does arithmetic on it); the other fields ride through as the
        peer sent them — both ends run this module, so the types are
        right by construction, and a hot drain decodes hundreds of
        thousands of records.
        """
        try:
            n = len(rec)
            seq = rec[0]
            if type(seq) is not int:
                seq = int(seq)
            ts, kind, key, stream = rec[1], rec[2], rec[3], rec[4]
        except (KeyError, IndexError, TypeError, ValueError) as e:
            raise DeltaDecodeError(f"malformed wire record: {e}") from e
        if n == 6:  # keyframe
            payload = rec[5]
            if isinstance(payload, dict):
                self._streams[stream] = payload
            else:
                self._streams.pop(stream, None)
            return seq, ts, kind, key, payload
        if n != 7:
            raise DeltaDecodeError(
                f"wire record of length {n} (seq {seq})"
            )
        base = self._streams.get(stream)
        if base is None:
            raise DeltaDecodeError(
                f"delta for stream {stream!r} without a keyframe base "
                f"(seq {seq})"
            )
        payload = dict(base)
        s = rec[5]
        if s:
            payload.update(s)
        dels = rec[6]
        if dels:
            for k in dels:
                payload.pop(k, None)
        self._streams[stream] = payload
        return seq, ts, kind, key, payload


# -- batch frames ------------------------------------------------------------

def build_batch(records: List[List]) -> Dict:
    """Encoded records → the ``Frame.data`` dict of one delivery batch."""
    return {
        BATCH_KEY: {
            "v": BATCH_VERSION,
            "first_seq": records[0][0] if records else 0,
            "last_seq": records[-1][0] if records else 0,
            "count": len(records),
            "records": records,
        }
    }


def parse_batch(data) -> Optional[Dict]:
    """Frame data → the batch dict, or None when it isn't a batch frame
    (legacy per-record payloads, operator responses)."""
    if isinstance(data, dict):
        batch = data.get(BATCH_KEY)
        if isinstance(batch, dict):
            return batch
    return None
